"""Length-prefixed TCP transport
(reference: src/traceml_ai/transport/tcp_transport.py:21-268).

Frames: 4-byte big-endian length + codec body (see utils/msgpack_codec).
One ``send_batch`` call encodes a *list* of payloads into ONE frame and one
``sendall`` — the per-tick batching contract that keeps syscall count O(1)
per sampler tick.

Differences from the reference, chosen for the TPU build:

* the server is a **single selector-driven thread** (accept + read for all
  clients) instead of thread-per-client — hundreds of ranks on a pod slice
  must not mean hundreds of threads in the aggregator;
* the receive path drains complete frames in O(bytes) with a rolling
  buffer offset (the reference ships an O(N) drain too, proved by its
  bench tests/benchmarks/bench_tcp_drain.py);
* the selector thread only **splits frames** — msgpack decode happens on
  the consumer's thread (``drain()`` returns raw frames;
  ``decode_frames``/``drain_decoded`` do the decode), so one rank sending
  a huge batch can never stall accepts/reads for every other rank.

Frame bodies carry telemetry envelopes in schema v1 (row-list) or
schema v2 (columnar struct-of-arrays) — layout and negotiation are
documented in docs/developer_guide/wire-schema-v2.md.

The client is best-effort and NEVER raises into training code: lazy
connect, drop-on-failure, bounded reconnect backoff
(reference contract: tcp_transport.py:182-268).
"""

from __future__ import annotations

import os
import random
import selectors
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from traceml_tpu.transport import compression
from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

# fault-injection harness (no-op unless TRACEML_FAULT_PLAN is set; the
# module is stdlib-only and its fire() is one None check when inactive)
try:
    from traceml_tpu.dev import chaos as _chaos
except Exception:  # pragma: no cover
    _chaos = None

_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 256 * 1024 * 1024  # sanity bound against corrupt lengths

# optional C fast path (traceml_tpu/native/framing.c); None → pure Python
try:
    from traceml_tpu.native import get_framing

    _native = get_framing()
except Exception:  # pragma: no cover
    _native = None


class _ClientBuffer:
    """Incremental frame decoder with O(total bytes) drain (C fast path
    when the native extension built; identical framing either way)."""

    __slots__ = ("buf", "offset")

    def __init__(self) -> None:
        self.buf = bytearray()
        self.offset = 0  # consumed prefix

    def feed(self, data: bytes) -> List[bytes]:
        self.buf.extend(data)
        if _native is not None:
            # Pass the bytearray itself (y* accepts any buffer object) —
            # bytes(self.buf) would copy the whole rolling buffer per recv,
            # degrading a large multi-recv frame to O(buffered bytes/recv).
            frames, consumed = _native.drain_frames(
                self.buf, self.offset, MAX_FRAME_BYTES
            )
            self.offset = consumed
        else:
            frames = []
            while True:
                avail = len(self.buf) - self.offset
                if avail < _LEN.size:
                    break
                (n,) = _LEN.unpack_from(self.buf, self.offset)
                if n > MAX_FRAME_BYTES:
                    raise ValueError(f"frame length {n} exceeds bound")
                if avail < _LEN.size + n:
                    break
                start = self.offset + _LEN.size
                frames.append(bytes(self.buf[start : start + n]))
                self.offset = start + n
        # Compact once consumed prefix dominates — amortized O(1) per byte.
        if self.offset > 65536 and self.offset * 2 > len(self.buf):
            del self.buf[: self.offset]
            self.offset = 0
        return frames


def encode_frame(payload: Any) -> bytes:
    body = msgpack_codec.encode(payload)
    if _native is not None:
        return _native.pack_frames([body])
    return _LEN.pack(len(body)) + body


class TCPServer:
    """Aggregator-side ingest server.

    Raw frames are appended to an internal thread-safe queue; the
    aggregator loop blocks on :meth:`wait_for_data`, pulls frames with
    :meth:`drain`, and decodes them on its own thread via
    :meth:`decode_frames` (reference: tcp_transport.py:119-178).  Callers
    that don't care about the split can use :meth:`drain_decoded`.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        uds_path: Optional[str] = None,
    ) -> None:
        self._host = host
        self._requested_port = port
        self._sock: Optional[socket.socket] = None
        # optional extra AF_UNIX listener on the same selector (the uds
        # transport tier, docs/developer_guide/native-transport.md);
        # peers accepted there are tagged "uds:<n>"
        self._uds_path = uds_path
        self._uds_sock: Optional[socket.socket] = None
        self._uds_accepts = 0
        self._selector: Optional[selectors.DefaultSelector] = None
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._wake_r, self._wake_w = socket.socketpair()
        self._lock = threading.Lock()
        # (peer, frame) tuples: the peer tag ("ip:port" at accept) lets
        # the consumer attribute corrupt frames to the client that sent
        # them instead of one server-wide counter
        self._pending: List[Tuple[str, bytes]] = []
        self._data_event = threading.Event()
        self._clients: Dict[int, _ClientBuffer] = {}
        self._peers: Dict[int, str] = {}
        # shm ring registry polled on the serve tick (attach_ring_registry);
        # written before start() or from the serve thread only
        self._rings = None
        self._stopped = False
        self.port: Optional[int] = None
        self.frames_received = 0
        self.decode_errors = 0
        # frames by arrival path ("tcp" | "uds" | "shm"): the transport
        # observability strip in ingest_stats.json reads this
        self.frames_by_transport: Dict[str, int] = {}
        # compressed-carrier accounting (decode-side of the zstd tier)
        self.compressed_envelopes = 0
        self.compressed_bytes_in = 0
        self.decompressed_bytes = 0
        self.decompress_errors = 0
        # per-peer count of frames that arrived but could not be decoded
        # (body corruption) or desynced the stream (length corruption);
        # the connection survives body corruption — only a framing
        # desync still evicts that one client
        self.corrupt_frame_drops: Dict[str, int] = {}
        # deepest the undrained-frame buffer ever got: a proxy for how
        # far the consumer fell behind the selector thread
        self.pending_hwm = 0

    def attach_ring_registry(self, registry) -> None:
        """Attach a :class:`~traceml_tpu.transport.shm_ring.ShmRingRegistry`
        the serve loop polls each tick (call before :meth:`start`)."""
        self._rings = registry

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._stopped:
            raise RuntimeError(
                "TCPServer is single-use: construct a new instance after stop()"
            )
        if self._thread is not None:
            return
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._requested_port))
        sock.listen(128)
        sock.setblocking(False)
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._selector = selectors.DefaultSelector()
        self._selector.register(sock, selectors.EVENT_READ, ("accept", None))
        self._selector.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        if self._uds_path:
            try:
                uds = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    os.unlink(self._uds_path)
                except OSError:
                    pass
                uds.bind(self._uds_path)
                uds.listen(128)
                uds.setblocking(False)
                self._uds_sock = uds
                self._selector.register(
                    uds, selectors.EVENT_READ, ("accept_uds", None)
                )
            except OSError as exc:
                # the TCP listener is the golden path; a UDS bind failure
                # (path too long, stale dir perms) degrades, not aborts
                get_error_log().warning("uds listener bind failed", exc)
                self._uds_sock = None
        self._running.set()
        self._thread = threading.Thread(
            target=self._serve, name="traceml-tcp-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop and release every fd.  A stopped server is single-use."""
        if self._thread is None:
            return
        self._stopped = True
        self._running.clear()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._thread.join(timeout=5)
        self._thread = None
        try:
            if self._selector:
                for key in list(self._selector.get_map().values()):
                    try:
                        self._selector.unregister(key.fileobj)
                        if key.fileobj not in (self._sock, self._wake_r):
                            key.fileobj.close()
                    except Exception:
                        pass
                self._selector.close()
        except Exception:
            pass
        self._clients.clear()
        self._peers.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._uds_sock is not None:
            try:
                self._uds_sock.close()
            except OSError:
                pass
            self._uds_sock = None
            try:
                os.unlink(self._uds_path)
            except (OSError, TypeError):
                pass
        if self._rings is not None:
            try:
                self._rings.close()
            except Exception:
                pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    # -- consumer API --------------------------------------------------
    def wait_for_data(self, timeout: float) -> bool:
        fired = self._data_event.wait(timeout)
        if fired:
            self._data_event.clear()
        return fired

    def drain(self, max_frames: Optional[int] = None) -> List[bytes]:
        """Pull raw frames accumulated by the selector thread.

        With ``max_frames`` set, hands over at most that many frames and
        leaves the rest pending (the data event stays observable via
        :meth:`pending_frames`), so one drain call can't hold the caller
        hostage decoding an unbounded backlog.
        """
        return [frame for _peer, frame in self.drain_tagged(max_frames)]

    def drain_tagged(
        self, max_frames: Optional[int] = None
    ) -> List[Tuple[str, bytes]]:
        """:meth:`drain`, keeping each frame's peer tag ("ip:port") so
        the consumer can attribute decode failures per client."""
        with self._lock:
            if max_frames is None or len(self._pending) <= max_frames:
                out = self._pending
                self._pending = []
            else:
                out = self._pending[:max_frames]
                del self._pending[:max_frames]
        return out

    def pending_frames(self) -> int:
        """Frames buffered by the selector thread, awaiting drain()."""
        with self._lock:
            return len(self._pending)

    def decode_frames(self, frames: List[bytes]) -> List[Any]:
        """Decode raw frames into a flat payload list on the CALLER's
        thread (batch frames are flattened); bumps ``decode_errors``."""
        payloads, errors = msgpack_codec.decode_batch(frames)
        if errors:
            self.decode_errors += errors
            get_error_log().warning(
                f"dropped {errors} undecodable frame(s) during drain"
            )
        return self._unwrap_compressed(payloads, "unknown")

    def decode_tagged(self, tagged: List[Tuple[str, bytes]]) -> List[Any]:
        """Per-frame decode of :meth:`drain_tagged` output.  A corrupt
        frame is skipped (its whole batch of envelopes is lost — msgpack
        cannot partially decode) and counted against the peer that sent
        it in ``corrupt_frame_drops``; the connection stays up."""
        payloads: List[Any] = []
        for peer, frame in tagged:
            try:
                decoded = msgpack_codec.decode(frame)
            except msgpack_codec.CodecError:
                self.decode_errors += 1
                self._count_corrupt(peer)
                continue
            if isinstance(decoded, list):
                payloads.extend(self._unwrap_compressed(decoded, peer))
            else:
                payloads.extend(self._unwrap_compressed([decoded], peer))
        return payloads

    def _unwrap_compressed(self, payloads: List[Any], peer: str) -> List[Any]:
        """Restore compressed carrier envelopes in place (consumer
        thread).  Downstream of this point the pipeline sees payloads
        byte-identical to the uncompressed arm; a corrupt carrier is
        dropped like any other undecodable body, attributed to its
        peer."""
        out: List[Any] = []
        for payload in payloads:
            if not compression.is_compressed_payload(payload):
                out.append(payload)
                continue
            z_len = len(payload.get("z") or b"")
            try:
                inner = compression.unwrap_payload(payload)
            except compression.CompressionError:
                self.decompress_errors += 1
                self.decode_errors += 1
                self._count_corrupt(peer)
                continue
            self.compressed_envelopes += 1
            self.compressed_bytes_in += z_len
            self.decompressed_bytes += payload.get("n") or 0
            out.append(inner)
        return out

    def _count_corrupt(self, peer: str) -> None:
        # called from the consumer thread; _read (selector thread) also
        # mutates this dict, so both sides take the lock
        with self._lock:
            n = self.corrupt_frame_drops.get(peer, 0) + 1
            self.corrupt_frame_drops[peer] = n
        get_error_log().warning(
            f"undecodable frame from {peer} skipped "
            f"({n} corrupt frame(s) from this client so far)"
        )

    def drain_decoded(self) -> List[Any]:
        """Convenience: :meth:`drain` + :meth:`decode_frames`."""
        return self.decode_frames(self.drain())

    # -- server thread -------------------------------------------------
    def _serve(self) -> None:
        assert self._selector is not None and self._sock is not None
        # with a ring registry attached the select timeout drops so the
        # ring poll below stays sub-tick without any futex/eventfd
        # machinery — rings piggyback on the existing selector tick
        timeout = 0.05 if self._rings is not None else 0.5
        while self._running.is_set():
            try:
                events = self._selector.select(timeout=timeout)
            except OSError:
                break
            for key, _mask in events:
                kind, _ = key.data
                if kind == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                elif kind == "accept":
                    self._accept()
                elif kind == "accept_uds":
                    self._accept_uds()
                else:
                    self._read(key.fileobj)
            if self._rings is not None:
                self._poll_rings()

    def _poll_rings(self) -> None:
        """Drain every attached shm ring into the pending queue (serve
        thread only; frames are tagged "shm:<rank>")."""
        try:
            tagged = self._rings.poll()
        except Exception as exc:  # registry scan/attach trouble
            get_error_log().warning("shm ring poll failed", exc)
            return
        if not tagged:
            return
        with self._lock:
            self.frames_received += len(tagged)
            self.frames_by_transport["shm"] = (
                self.frames_by_transport.get("shm", 0) + len(tagged)
            )
            self._pending.extend(tagged)
            if len(self._pending) > self.pending_hwm:
                self.pending_hwm = len(self._pending)
        self._data_event.set()

    def _accept(self) -> None:
        assert self._sock is not None and self._selector is not None
        try:
            while True:
                conn, addr = self._sock.accept()
                conn.setblocking(False)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                fileno = conn.fileno()
                self._clients[fileno] = _ClientBuffer()
                try:
                    self._peers[fileno] = f"{addr[0]}:{addr[1]}"
                except (TypeError, IndexError):
                    self._peers[fileno] = "unknown"
                self._selector.register(conn, selectors.EVENT_READ, ("client", None))
        except BlockingIOError:
            return
        except OSError:
            return

    def _accept_uds(self) -> None:
        assert self._uds_sock is not None and self._selector is not None
        try:
            while True:
                conn, _addr = self._uds_sock.accept()
                conn.setblocking(False)
                fileno = conn.fileno()
                self._clients[fileno] = _ClientBuffer()
                # AF_UNIX peers have no address; number them at accept
                self._uds_accepts += 1
                self._peers[fileno] = f"uds:{self._uds_accepts}"
                self._selector.register(
                    conn, selectors.EVENT_READ, ("client", None)
                )
        except (BlockingIOError, OSError):
            return

    def _read(self, conn: socket.socket) -> None:
        assert self._selector is not None
        fileno = conn.fileno()
        try:
            data = conn.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            try:
                self._selector.unregister(conn)
            except Exception:
                pass
            self._clients.pop(fileno, None)
            self._peers.pop(fileno, None)
            try:
                conn.close()
            except OSError:
                pass
            return
        buf = self._clients.get(fileno)
        if buf is None:
            return
        peer = self._peers.get(fileno, "unknown")
        try:
            frames = buf.feed(data)
        except ValueError as exc:
            # a corrupt LENGTH field desyncs the stream — nothing after
            # it can be reframed, so this one client is evicted (and the
            # loss attributed to it); a corrupt BODY with intact framing
            # survives to decode_tagged, which skips just that frame
            get_error_log().warning(f"dropping client with bad frame: {exc}")
            with self._lock:
                self.corrupt_frame_drops[peer] = (
                    self.corrupt_frame_drops.get(peer, 0) + 1
                )
            try:
                self._selector.unregister(conn)
            except Exception:
                pass
            self._clients.pop(fileno, None)
            self._peers.pop(fileno, None)
            try:
                conn.close()
            except OSError:
                pass
            return
        if not frames:
            return
        # NO decode here: this is the selector thread, shared by every
        # client.  Frames are handed to the consumer as-is.
        kind = "uds" if peer.startswith("uds:") else "tcp"
        with self._lock:
            self.frames_received += len(frames)
            self.frames_by_transport[kind] = (
                self.frames_by_transport.get(kind, 0) + len(frames)
            )
            for frame in frames:
                self._pending.append((peer, frame))
            if len(self._pending) > self.pending_hwm:
                self.pending_hwm = len(self._pending)
        self._data_event.set()


class TCPClient:
    """Best-effort sender: never raises, lazily connects, drops on failure.

    Reconnect policy: capped exponential backoff with full jitter.
    ``reconnect_backoff`` is the BASE delay (kwarg name kept for
    back-compat with callers tuning it); consecutive dial failures
    double the window up to ``backoff_cap``, and the actual wait is
    drawn uniformly from [window/2, window] so a thousand ranks losing
    one aggregator never re-dial in lockstep.  Any successful dial
    resets the window to zero (the first retry after a blip is
    immediate).
    """

    #: transport kind reported in producer stats / transport_hello
    kind = "tcp"

    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: float = 2.0,
        reconnect_backoff: float = 1.0,
        backoff_cap: float = 15.0,
    ) -> None:
        self._host = host
        self._port = port
        self._timeout = connect_timeout
        self._backoff_base = max(0.001, float(reconnect_backoff))
        self._backoff_cap = max(self._backoff_base, float(backoff_cap))
        self._backoff_cur = 0.0  # jittered wait before the next dial
        self._fail_streak = 0
        self._connected_once = False
        self.reconnects = 0  # successful dials after the first
        self._sock: Optional[socket.socket] = None
        self._last_fail = 0.0
        self._lock = threading.Lock()
        # Serializes dialers; held WITHOUT self._lock during the blocking
        # create_connection so close() / a concurrent sender on an
        # established socket never waits behind a stalled connect.
        self._connect_lock = threading.Lock()
        self._gen = 0  # bumped by close(); a dial that straddles it is discarded
        # reusable frame buffer: steady-state sends assemble the length
        # prefix + body into one persistent bytearray instead of
        # allocating a fresh frame per tick.  Guarded by its own lock
        # (ordering: _framebuf_lock → _lock) so frame assembly — cheap
        # concatenation of pre-encoded bodies — never waits behind a
        # stalled sendall from the socket lock's perspective alone.
        self._framebuf = bytearray()
        self._framebuf_lock = threading.Lock()
        self.batches_sent = 0
        self.batches_dropped = 0

    def _dial(self) -> socket.socket:
        """Open one connected socket (raises OSError on failure).  The
        transport-specific seam: :class:`UDSClient` overrides only this."""
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        return sock

    def _note_dial_failure_locked(self) -> None:
        self._last_fail = time.monotonic()
        self._fail_streak += 1
        window = min(
            self._backoff_cap,
            self._backoff_base * (2 ** (self._fail_streak - 1)),
        )
        self._backoff_cur = random.uniform(window / 2.0, window)

    def _ensure_connected(self) -> Optional[socket.socket]:
        with self._lock:
            if self._sock is not None:
                return self._sock
            if time.monotonic() - self._last_fail < self._backoff_cur:
                return None
            gen = self._gen
        with self._connect_lock:
            with self._lock:
                if self._sock is not None:
                    return self._sock
                if self._gen != gen:
                    return None
            try:
                sock = self._dial()
            except OSError:
                with self._lock:
                    self._note_dial_failure_locked()
                return None
            try:
                sock.settimeout(self._timeout)
            except OSError:
                pass
            with self._lock:
                if self._gen != gen:
                    # close() raced the dial; don't resurrect the socket
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return None
                self._sock = sock
                self._fail_streak = 0
                self._backoff_cur = 0.0
                if self._connected_once:
                    self.reconnects += 1
                self._connected_once = True
                return sock

    def send_batch(self, payloads: List[Any]) -> bool:
        """Encode ``payloads`` as ONE frame, one sendall. True on success.

        Members may be :class:`msgpack_codec.EncodedPayload` — their
        pre-encoded bodies are spliced into the batch array with zero
        re-encode (the producer's single-encode contract; see
        docs/developer_guide/rank-producer-path.md) — or plain objects,
        encoded here.  Encoding happens before the socket lock is taken
        — a large batch being msgpack'd must not block a concurrent
        close() or sender.
        """
        if not payloads:
            return True
        try:
            body = msgpack_codec.encode_batch(payloads)
        except Exception:
            with self._lock:
                self.batches_dropped += 1
            return False
        return self.send_encoded_body(body)

    def send_encoded_body(self, body: bytes) -> bool:
        """Send an already-assembled wire body as one frame.  The replay
        path (transport/spool.py) splices spooled raw envelope bytes
        into a batch body itself and ships it through here — same
        framing, same counters, same failure semantics as send_batch."""
        fault = _chaos.fire("client.send") if _chaos is not None else None
        if fault is not None:
            if fault.action == "stall":
                time.sleep(float(fault.arg or 0.2))
                fault = None
            elif fault.action == "reset":
                with self._lock:
                    self._teardown_locked()
                    self.batches_dropped += 1
                return False
        if self._ensure_connected() is None:
            with self._lock:
                self.batches_dropped += 1
            return False
        with self._framebuf_lock:
            buf = self._framebuf
            del buf[:]
            buf += _LEN.pack(len(body))
            buf += body
            if fault is not None and fault.action == "corrupt":
                # flip one byte past the length prefix: framing stays
                # intact, the receiver's decode fails (per-client
                # corrupt_frame_drops path, connection survives)
                idx = 4 + (len(body) // 2)
                buf[idx] ^= 0xFF
            with self._lock:
                if self._sock is None:  # torn down between connect and send
                    self.batches_dropped += 1
                    return False
                try:
                    if fault is not None and fault.action == "truncate":
                        # ship a prefix then reset: receiver-side stream
                        # desync (bad length next), client evicted there
                        self._sock.sendall(bytes(buf[: max(5, len(buf) // 2)]))
                        self.batches_dropped += 1
                        self._teardown_locked()
                        return False
                    self._sock.sendall(buf)
                    self.batches_sent += 1
                    return True
                except Exception:
                    self.batches_dropped += 1
                    self._teardown_locked()
                    return False

    def _teardown_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._last_fail = time.monotonic()

    def close(self) -> None:
        """Drop the current socket (a later send_batch may redial)."""
        with self._lock:
            self._gen += 1
            self._teardown_locked()


class UDSClient(TCPClient):
    """Unix-domain-socket variant of the best-effort sender.

    Same framing, batching, backoff, chaos point (``client.send``), and
    durable-sender integration as TCP — only the dial differs, so the
    whole send path (including fault injection and replay splicing)
    is exercised identically on both stream transports.
    """

    kind = "uds"

    def __init__(
        self,
        path: str,
        connect_timeout: float = 2.0,
        reconnect_backoff: float = 1.0,
        backoff_cap: float = 15.0,
    ) -> None:
        super().__init__(
            host="",
            port=0,
            connect_timeout=connect_timeout,
            reconnect_backoff=reconnect_backoff,
            backoff_cap=backoff_cap,
        )
        self._path = str(path)

    def _dial(self) -> socket.socket:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(self._timeout)
            sock.connect(self._path)
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            raise
        return sock
