"""Overhead-governor policy tests.

The governor is the TPU answer to the reference's fixed "<1% overhead"
claim: observation cost is runtime-dependent (local probe ≈ µs, tunneled
PJRT probe ≈ RPC), so the sampling schedule must adapt.  These tests pin
the policy: cheap probes + realistic steps → full sampling; expensive
probes or tiny steps → stride growth, inline sweeps off, resolver
cadence floor.
"""

import threading

from traceml_tpu.sdk.instrumentation import trace_step
from traceml_tpu.sdk.state import reset_state_for_tests
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.overhead_governor import (
    OverheadGovernor,
    get_governor,
    reset_governor_for_tests,
)


def teardown_module():
    reset_governor_for_tests()
    reset_state_for_tests()


class TestPolicy:
    def test_cheap_probes_realistic_steps_full_sampling(self):
        g = OverheadGovernor(budget=0.01)
        g.observe_probe(20e-6, 10)  # 2 µs/probe
        for _ in range(10):
            g.observe_step(0.150)  # 150 ms steps
        assert g.marker_stride == 1
        assert g.allow_inline_sweep()
        assert all(g.begin_step() for _ in range(8))

    def test_rpc_probes_grow_stride_and_disable_inline(self):
        g = OverheadGovernor(budget=0.01)
        for _ in range(20):
            g.observe_probe(300e-6, 1)  # RPC-priced probe
            g.observe_step(0.001)  # 1 ms dispatch-bound steps
        # per-marker ≈ 15µs + 3×300µs ≈ 0.92ms; budget share 10µs → ~92
        assert g.marker_stride > 20
        assert not g.allow_inline_sweep()
        sampled = sum(g.begin_step() for _ in range(g.marker_stride * 3))
        assert sampled == 3

    def test_tiny_steps_alone_grow_stride(self):
        g = OverheadGovernor(budget=0.01)
        g.observe_probe(2e-6, 1)
        for _ in range(10):
            g.observe_step(100e-6)  # 0.1 ms steps: fixed 15µs > 1µs budget
        assert g.marker_stride > 1

    def test_stride_clamped(self):
        g = OverheadGovernor(budget=0.001)
        for _ in range(30):
            g.observe_probe(5e-3, 1)
            g.observe_step(1e-4)
        assert g.marker_stride <= 256

    def test_resolver_floor_scales_with_probe_cost(self):
        g = OverheadGovernor(budget=0.01)
        for _ in range(30):
            g.observe_probe(400e-6, 1)
        assert g.resolver_min_delay() >= 0.02
        g2 = OverheadGovernor(budget=0.01)
        g2.observe_probe(2e-6, 1)
        assert g2.resolver_min_delay() < 0.002

    def test_starvation_artifacts_clamped_not_discarded(self):
        """A sample above the ceiling is clamped, not ignored: a
        descheduled poller's 40 ms artifact cannot poison the EMA past
        the ceiling, but a runtime whose probes are GENUINELY that slow
        must still drive the governor into full backoff (discarding
        would freeze the maximum-overhead configuration — the failure
        direction must be over-throttling, never blindness)."""
        g = OverheadGovernor(budget=0.01)
        g.observe_probe(0.04, 1)  # 40 ms "probe": artifact or disaster
        assert g.probe_cost_ema <= 20e-3 + 1e-9  # bounded by the ceiling
        assert g.probe_cost_ema > 1e-3  # but definitely not ignored
        # sustained slow probes → inline sweeps off, resolver backs off
        for _ in range(30):
            g.observe_probe(0.04, 1)
        assert not g.allow_inline_sweep()
        assert g.resolver_min_delay() == 0.1  # capped floor

    def test_resolver_floor_capped(self):
        g = OverheadGovernor(budget=0.001)
        for _ in range(50):
            g.observe_probe(10e-3, 1)  # worst believable probe cost
        assert g.resolver_min_delay() <= 0.1

    def test_budget_env_override(self, monkeypatch):
        monkeypatch.setenv("TRACEML_OVERHEAD_BUDGET", "0.05")
        g = OverheadGovernor()
        assert g.budget == 0.05

    def test_snapshot_shape(self):
        g = OverheadGovernor()
        g.observe_step(0.01)
        snap = g.snapshot()
        assert set(snap) == {
            "budget", "probe_cost_ema_us", "step_ema_ms",
            "marker_stride", "inline_sweep",
        }

    def test_thread_safe_observations(self):
        g = OverheadGovernor()

        def pound():
            for _ in range(500):
                g.observe_probe(10e-6, 2)
                g.observe_step(0.01)

        ts = [threading.Thread(target=pound) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert g.marker_stride >= 1


class TestHotPathIntegration:
    def test_unsampled_steps_emit_hostonly_rows(self):
        """With stride>1 the envelope still flows, just without device
        markers — the window builder then selects the host clock."""
        st = reset_state_for_tests()
        gov = reset_governor_for_tests(budget=0.01)
        # force an expensive-probe regime before any steps run
        for _ in range(30):
            gov.observe_probe(1e-3, 1)
            gov.observe_step(1e-3)
        stride = gov.marker_stride
        assert stride > 1

        class Ready:
            size = 1

            def is_ready(self):
                return True

        batches = []
        st.on_batch_flushed.append(batches.append)
        for _ in range(stride * 2):
            with trace_step(st) as ts:
                ts.mark(Ready())
        with_marker = sum(
            1
            for b in batches
            for ev in b.events
            if ev.name == T.STEP_TIME and ev.marker is not None
        )
        assert with_marker == 2  # one marked step per stride cycle
        assert len(batches) == stride * 2  # every step still produced rows
        reset_governor_for_tests()
        reset_state_for_tests()

    def test_gate_resets_after_unsampled_step(self):
        """Out-of-step instrumentation (eval loops) must never inherit an
        unsampled step's gate (code-review finding)."""
        from traceml_tpu.sdk.wrappers import wrap_forward

        st = reset_state_for_tests()
        gov = reset_governor_for_tests(budget=0.01)
        for _ in range(30):
            gov.observe_probe(1e-3, 1)
            gov.observe_step(1e-3)
        assert gov.marker_stride > 1

        class Ready:
            size = 1

            def is_ready(self):
                return True

        with trace_step(st):
            pass  # an unsampled step (stride > 1, tick 1)
        assert st.sample_markers is True  # reset on exit

        captured = []
        st.buffer.add = lambda ev: captured.append(ev)  # type: ignore
        fwd = wrap_forward(lambda: Ready(), state=st)
        fwd()  # out-of-step: must carry a marker
        assert captured and captured[-1].marker is not None
        reset_governor_for_tests()
        reset_state_for_tests()

    def test_chokepoint_drops_markers_on_unsampled_step(self):
        """publish_region_marker is the single gate: any site's marker
        (h2d, trace_time, Lightning) is dropped on an unsampled step."""
        from traceml_tpu.sdk.wrappers import publish_region_marker
        from traceml_tpu.utils.timing import DeviceMarker, TimeEvent

        st = reset_state_for_tests()

        class Ready:
            def is_ready(self):
                return False  # pending: would need resolver probes

        st.tls.in_step = True
        st.sample_markers = False
        ev = TimeEvent("x", 1)
        ev.marker = DeviceMarker([Ready()])
        publish_region_marker(ev, st)
        assert ev.marker is None  # dropped, never submitted
        st.tls.in_step = False
        reset_state_for_tests()

    def test_marker_skipped_when_gate_off(self):
        st = reset_state_for_tests()
        st.sample_markers = False

        class Ready:
            size = 1

            def is_ready(self):
                raise AssertionError("probe must not run when gate is off")

        with trace_step(st) as ts:
            st.sample_markers = False  # enter() recomputed it; force off
            ts.mark(Ready())  # must be inert, not raise
        reset_governor_for_tests()
        reset_state_for_tests()
