"""Standalone fleet-router process entry
(docs/developer_guide/federation.md).

Launched as ``python -m traceml_tpu.federation`` by ``traceml
fleet-router`` with TRACEML_FLEET_* env config.  Binds the router HTTP
server (port 0 → ephemeral, the bound port is advertised via
``fleet_router_ready.json`` in ``TRACEML_FLEET_STATE_DIR``), then runs
until SIGTERM/SIGINT — the same ready-file + signal contract as the
aggregator child, so launcher/process.py supervision applies
unchanged.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import traceback
from pathlib import Path

from traceml_tpu.config import flags
from traceml_tpu.federation.router import FleetRouter
from traceml_tpu.utils.atomic_io import atomic_write_json
from traceml_tpu.utils.error_log import get_error_log

READY_FILE = "fleet_router_ready.json"


def main() -> int:
    stop_evt = threading.Event()

    def _on_signal(signum, frame):  # noqa: ANN001
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    from traceml_tpu.utils.orphan_watch import arm_parent_death_watch

    arm_parent_death_watch(stop_evt.set)

    state_dir = Path(flags.FLEET_STATE_DIR.get_str() or ".")
    try:
        router = FleetRouter(
            shard_spec=flags.FLEET_SHARDS.get_str(),
            host=flags.FLEET_HOST.get_str() or "127.0.0.1",
            port=flags.FLEET_PORT.get_int(0),
            cache_ttl=flags.FLEET_CACHE_TTL.get_float(0.5),
            probe_s=flags.FLEET_PROBE_S.get_float(2.0),
            hop_compress=flags.TRANSPORT_COMPRESS.get_str(),
        )
        if not router.ring.shards:
            print(
                "[TraceML] fleet-router: no shards configured "
                "(set TRACEML_FLEET_SHARDS)",
                file=sys.stderr,
            )
            return 2
        router.start()
        assert router.port is not None
        atomic_write_json(
            state_dir / READY_FILE,
            {
                "port": router.port,
                "host": router.host,
                "pid": os.getpid(),
                "shards": router.ring.shards,
            },
        )
        print(
            f"[TraceML] fleet router: http://{router.host}:{router.port}/"
            f"fleet ({len(router.ring.shards)} shards)"
        )
        while not stop_evt.wait(0.25):
            pass
        router.stop()
        return 0
    except Exception as exc:
        get_error_log().error("fleet router fatal", exc)
        try:
            path = state_dir / "fleet_router_error.log"
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(
                    "".join(
                        traceback.format_exception(
                            type(exc), exc, exc.__traceback__
                        )
                    )
                )
        except Exception:
            pass
        return 1


if __name__ == "__main__":
    sys.exit(main())
