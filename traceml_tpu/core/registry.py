"""Small named-factory registry (reference: src/traceml_ai/core/registry.py:18-97).

Used for sampler specs, diagnostic domains, projection writers and display
drivers.  Deliberately tiny: register by key, optionally with metadata, look
up or iterate in registration order.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class RegistryError(KeyError):
    """Raised on duplicate registration or missing key."""


class Registry:
    """Thread-safe, ordered name → value registry."""

    def __init__(self, name: str = "registry") -> None:
        self._name = name
        self._lock = threading.Lock()
        self._items: Dict[str, Any] = {}

    @property
    def name(self) -> str:
        return self._name

    def register(self, key: str, value: Any, *, overwrite: bool = False) -> Any:
        with self._lock:
            if key in self._items and not overwrite:
                raise RegistryError(
                    f"{self._name}: key {key!r} already registered"
                )
            self._items[key] = value
        return value

    def decorator(self, key: str) -> Callable[[Any], Any]:
        """``@registry.decorator("name")`` registration sugar."""

        def _wrap(value: Any) -> Any:
            self.register(key, value)
            return value

        return _wrap

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._items.get(key, default)

    def require(self, key: str) -> Any:
        with self._lock:
            if key not in self._items:
                raise RegistryError(
                    f"{self._name}: unknown key {key!r}; "
                    f"known: {sorted(self._items)}"
                )
            return self._items[key]

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def items(self) -> List[Tuple[str, Any]]:
        with self._lock:
            return list(self._items.items())

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._items

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)
