"""Vectorized gate arm for the serving diagnosis pack.

Lifts the two remaining per-element scalar scans — the backlog-share
count over the queue-depth slot series and ReplicaSkewRule's per-replica
tokens/s median / min / lag filter — into numpy reductions that match
the scalar arm bit-for-bit (integer counts and float64 medians are
exact; lagging-replica masks evaluate the identical ``(med − v) / med``
float arithmetic elementwise).

``enabled()`` is the pack's kill-switch gate
(``TRACEML_VECTOR_DIAGNOSIS=0`` forces the scalar reference arm); a
helper that cannot reproduce its loop returns ``None`` and counts a
fallback instead of logging per tick.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from traceml_tpu.utils.columnar import (
    note_vector_fallback,
    vector_diagnosis_enabled,
)

DOMAIN = "serving"


def enabled() -> bool:
    return vector_diagnosis_enabled()


def backlog_share(queue_depth: List[float]) -> Optional[float]:
    """Share of window seqs with a non-empty queue (an integer count
    over the slot series — exact).  ``None`` → scalar arm."""
    if not queue_depth:
        return 0.0
    try:
        arr = np.asarray(queue_depth)
        return int((arr > 0).sum()) / len(queue_depth)
    except Exception:
        note_vector_fallback(DOMAIN)
        return None


def replica_skew(
    per_rank: Dict[int, Dict[str, float]],
    skew_warn: float,
) -> Optional[Tuple[float, float, List[int]]]:
    """ReplicaSkewRule's per-replica scan: (median tokens/s, min
    tokens/s, lagging replicas sorted).  Caller guards ``len >= 2`` and
    ``med > 0``.  ``None`` → scalar arm."""
    try:
        ranks = np.asarray(list(per_rank), dtype=np.int64)
        vals = np.asarray(
            [
                float(v.get("tokens_per_s", 0.0) or 0.0)
                for v in per_rank.values()
            ],
            dtype=np.float64,
        )
        med = float(np.median(vals))
        worst = float(np.min(vals))
        if med > 0.0:
            lag = np.sort(ranks[(med - vals) / med >= skew_warn]).tolist()
        else:
            lag = []
        return med, worst, lag
    except Exception:
        note_vector_fallback(DOMAIN)
        return None
