"""Shared diagnostic contracts
(reference: src/traceml_ai/diagnostics/common.py:24-215).

``DiagnosticResult.issues`` is always non-empty — when nothing fires,
the domain emits a HEALTHY info issue — and ``diagnosis`` is the
top-ranked issue after :func:`sort_issues` (severity → score →
breadth).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Protocol, Sequence

SEVERITY_INFO = "info"
SEVERITY_WARNING = "warning"
SEVERITY_CRITICAL = "critical"

_SEVERITY_ORDER = {SEVERITY_CRITICAL: 2, SEVERITY_WARNING: 1, SEVERITY_INFO: 0}

STATUS_OK = "ok"
STATUS_ISSUE = "issue"


@dataclasses.dataclass
class DiagnosticIssue:
    kind: str  # e.g. "INPUT_BOUND", "COMPUTE_STRAGGLER"
    severity: str = SEVERITY_INFO
    status: str = STATUS_ISSUE
    summary: str = ""
    action: str = ""
    metric: Optional[str] = None  # canonical metric name
    phase: Optional[str] = None  # phase key (input/h2d/.../residual)
    score: float = 0.0  # rule-specific magnitude (higher = worse)
    share_pct: Optional[float] = None  # phase share of step (0..1)
    skew_pct: Optional[float] = None  # cross-rank skew (0..1+)
    ranks: List[int] = dataclasses.field(default_factory=list)
    evidence: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d


def healthy_issue(domain: str, summary: str = "") -> DiagnosticIssue:
    return DiagnosticIssue(
        kind="HEALTHY",
        severity=SEVERITY_INFO,
        status=STATUS_OK,
        summary=summary or f"No {domain} issues detected in the analyzed window.",
    )


def sort_issues(issues: Sequence[DiagnosticIssue]) -> List[DiagnosticIssue]:
    """severity desc → score desc → breadth (#ranks) desc → kind asc."""
    return sorted(
        issues,
        key=lambda i: (
            -_SEVERITY_ORDER.get(i.severity, 0),
            -(i.score or 0.0),
            -len(i.ranks),
            i.kind,
        ),
    )


@dataclasses.dataclass
class DiagnosticResult:
    domain: str
    issues: List[DiagnosticIssue]

    def __post_init__(self) -> None:
        if not self.issues:
            self.issues = [healthy_issue(self.domain)]
        self.issues = sort_issues(self.issues)

    @property
    def diagnosis(self) -> DiagnosticIssue:
        return self.issues[0]

    @property
    def healthy(self) -> bool:
        return self.diagnosis.status == STATUS_OK

    def to_dict(self) -> Dict[str, Any]:
        return {
            "domain": self.domain,
            "diagnosis": self.diagnosis.to_dict(),
            "issues": [i.to_dict() for i in self.issues],
        }


class DiagnosticRule(Protocol):
    """A rule inspects a domain context and yields issues (possibly none)."""

    def evaluate(self, ctx: Any) -> List[DiagnosticIssue]: ...


def run_rules(domain: str, rules: Sequence[DiagnosticRule], ctx: Any) -> DiagnosticResult:
    issues: List[DiagnosticIssue] = []
    for rule in rules:
        try:
            issues.extend(rule.evaluate(ctx) or [])
        except Exception:
            # a broken rule must never take down the report
            continue
    return DiagnosticResult(domain=domain, issues=issues)
