"""Step-memory thresholds
(reference: src/traceml_ai/diagnostics/step_memory/policy.py:13-93)."""

from __future__ import annotations

import dataclasses

MiB = 1024 * 1024
GiB = 1024 * MiB


@dataclasses.dataclass(frozen=True)
class StepMemoryPolicy:
    pressure_warn: float = 0.92  # used / capacity
    pressure_critical: float = 0.97
    imbalance_warn: float = 0.20  # cross-rank skew
    imbalance_critical: float = 0.30
    imbalance_pressure_gate: float = 0.5  # only interesting when ≥50% full
    # creep heuristics (reference: trend.py:31-57, policy.py:27 — the
    # ≥800-row gate, 512 MiB / 1 GiB delta bars, worst/median growth and
    # slope bars, and the ≤2% peak-pullback weak-recovery tolerance)
    creep_min_steps: int = 800
    creep_min_delta_bytes: int = 512 * MiB
    creep_min_growth_pct: float = 0.06        # worst rank must clear this
    creep_median_growth_pct: float = 0.04     # cluster-wide when median clears
    creep_min_slope_pct_per_100: float = 0.015   # worst rank, rel. to mean
    creep_median_slope_pct_per_100: float = 0.010
    creep_pullback_max: float = 0.02          # deeper dip ⇒ allocator recovered
    creep_short_window: int = 100
    creep_long_window: int = 400
    creep_confirmed_delta_bytes: int = 1 * GiB


DEFAULT_POLICY = StepMemoryPolicy()
