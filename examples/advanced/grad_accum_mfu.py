"""Gradient accumulation with a correct MFU declaration
(reference role: examples/advanced/bert_gradient_accum.py — the
grad-accum pattern, TPU-first).

Gradient accumulation dispatches N micro-batch programs per optimizer
step, so the auto cost-analysis of ONE dispatch under-counts the step's
FLOPs by N×.  Declare the SUM with ``set_step_flops`` — the MFU
numerator is the whole optimizer step:

    python examples/advanced/grad_accum_mfu.py --accum 4 --steps 40

Works anywhere (CPU backend included); on a TPU host the MFU line in
the final summary becomes meaningful against the chip's bf16 peak.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import traceml_tpu
from traceml_tpu.runtime import lifecycle
from traceml_tpu.runtime.settings import settings_from_env

HIDDEN, BATCH, CLASSES = 512, 32, 10


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--accum", type=int, default=4)
    parser.add_argument("--steps", type=int, default=40)
    args = parser.parse_args()

    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.normal(0, 0.02, (HIDDEN, HIDDEN)), jnp.float32)
    w2 = jnp.asarray(rng.normal(0, 0.02, (HIDDEN, CLASSES)), jnp.float32)
    params = {"w1": w1, "w2": w2}
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    def loss_fn(params, x, y):
        h = jax.nn.gelu(x @ params["w1"])
        logits = h @ params["w2"]
        return -jnp.mean(jnp.sum(
            jax.nn.one_hot(y, CLASSES) * jax.nn.log_softmax(logits), -1))

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # One micro-batch's model FLOPs from the lowered program, then the
    # DECLARED step FLOPs = accum × micro.  (The optimizer-apply
    # program is a negligible O(params) addition and is intentionally
    # not counted.)
    x0 = jnp.zeros((BATCH, HIDDEN))
    y0 = jnp.zeros((BATCH,), jnp.int32)
    micro = grad_fn.lower(params, x0, y0).compile().cost_analysis()
    if isinstance(micro, (list, tuple)):  # older jax returns [dict]
        micro = micro[0] if micro else {}
    micro_flops = float((micro or {}).get("flops", 0.0))

    def batches(n):
        for _ in range(n):
            yield (
                rng.normal(size=(BATCH, HIDDEN)).astype(np.float32),
                rng.integers(0, CLASSES, size=(BATCH,)),
            )

    settings = settings_from_env()
    lifecycle.start_aggregator(settings)
    lifecycle.start_runtime(settings)
    traceml_tpu.init(mode="manual")
    if micro_flops:
        traceml_tpu.set_step_flops(micro_flops * args.accum)
    try:
        it = iter(traceml_tpu.wrap_dataloader(batches(args.steps * args.accum)))
        for _ in range(args.steps):
            with traceml_tpu.trace_step():
                grads_sum = None
                for _ in range(args.accum):
                    x, y = next(it)
                    x, y = jax.device_put(x), jax.device_put(y)
                    loss, grads = grad_fn(params, x, y)
                    grads_sum = grads if grads_sum is None else jax.tree.map(
                        jnp.add, grads_sum, grads)
                grads_mean = jax.tree.map(
                    lambda g: g / args.accum, grads_sum)
                params, opt_state = apply(params, opt_state, grads_mean)
        print(f"done: loss {float(loss):.4f}")
        summary = traceml_tpu.summary()
        eff_keys = {
            k: v for k, v in summary.items()
            if any(s in k for s in ("flops", "mfu", "tflops", "step_time"))
        }
        print("summary keys:", eff_keys or sorted(summary)[:6])
        print("full efficiency block lands in final_summary.json "
              "(sections.step_time.global.efficiency)")
    finally:
        lifecycle.stop_runtime()
        lifecycle.stop_aggregator(finalize=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
