"""Diagnostics rail (reference role: nicegui_sections/
model_diagnostics_section.py — overall pill + per-source severity rows).

Color buckets come from each finding's OWN severity field — never
re-parsed from status text — so new diagnosis kinds color correctly
with no change here (the reference documents the same stance at
model_diagnostics_section.py:20-23).
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">Diagnostics</h2><span class="sp"></span>
  <span id="diag-pill"></span></div>
<div id="findings"><span class="muted">no findings yet</span></div>
"""

_JS = r"""
const SEV_RANK={critical:2,warning:1,info:0};
function render_diagnostics(d){
  const el=document.getElementById("findings");
  const pill=document.getElementById("diag-pill");
  const fs=d.findings||[];
  if(!fs.length){
    el.innerHTML='<span class="muted">no findings yet</span>';
    pill.innerHTML="";return}
  const worst=fs.reduce((a,f)=>
    (SEV_RANK[f.severity]||0)>(SEV_RANK[a.severity]||0)?f:a,fs[0]);
  pill.innerHTML=`<span class="sevpill"
    style="background:${SEV[worst.severity]||"#555"}">${esc(worst.severity)}</span>`;
  el.innerHTML=fs.map(f=>`<div class="finding sev-${esc(f.severity)}">
    <b>${esc(f.domain)}/${esc(f.kind)}</b>
    <span class="muted">[${esc(f.severity)}]</span>
    ${f.confidence_label?`<span class="muted">· ${esc(f.confidence_label)} confidence</span>`:""}
    <br>${esc(f.summary)}
    ${f.action?`<br><span class="muted">→ ${esc(f.action)}</span>`:""}</div>`).join("")}
"""

SECTION = Section(
    id="diagnostics",
    title="Diagnostics",
    html=_HTML,
    js=_JS,
    contract=(
        "findings.severity",
        "findings.domain",
        "findings.kind",
        "findings.summary",
        "findings.action",
        "findings.confidence_label",
    ),
)
