"""The aggregator
(reference: src/traceml_ai/aggregator/trace_aggregator.py:89-586).

Owns the TCP ingest server, the SQLite writer, the final-summary
service, and a display driver.  Event-driven loop: block on
``wait_for_data`` (bounded by the render interval), split telemetry from
control messages, ingest, rate-limited UI tick + summary poll.

Shutdown (``stop()``): settle late telemetry until every expected rank
sent ``rank_finished`` or the deadline passes (writing a
``finalization_warning.json`` naming missing ranks), budgeted SQLite
finalize, then generate the final summary and write artifacts.

Fault tolerance (docs/developer_guide/fault-tolerance.md): every
envelope and control message feeds the rank liveness tracker
(``rank_status.json``, ACTIVE→STALE→LOST); a restarted aggregator
re-seeds finished ranks and last-seen from that file, and the SQLite
writer's per-lane seq table dedups the ranks' reconnect replay.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Set

from traceml_tpu.aggregator.display_drivers import resolve_display_driver
from traceml_tpu.aggregator.liveness import RankLivenessTracker
from traceml_tpu.aggregator.session_registry import SessionRegistry
from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.aggregator.summary_service import FinalSummaryService
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.sdk import protocol
from traceml_tpu.telemetry.control import (
    MESH_TOPOLOGY,
    PRODUCER_STATS,
    RANK_FINISHED,
    RANK_HEARTBEAT,
    TRANSPORT_HELLO,
    control_kind,
    is_control_message,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope, normalize_telemetry_envelope
from traceml_tpu.transport.select import server_transport_config
from traceml_tpu.transport.tcp_transport import TCPServer
from traceml_tpu.utils.atomic_io import atomic_write_json
from traceml_tpu.utils.error_log import get_error_log

_RENDER_INTERVAL = 0.5
_SETTLE_POLL = 0.1
# max frames decoded per _drain_once call: a backlog burst (slow UI
# tick, hundreds of ranks reconnecting) is worked off in bounded slices
# so the loop can interleave UI ticks instead of decoding for seconds
_DRAIN_BATCH_FRAMES = 512


class TraceMLAggregator:
    def __init__(self, settings: TraceMLSettings) -> None:
        self.settings = settings
        # transport tier: in auto mode the ingest server also stands up
        # a UDS listener and polls same-host shm rings; TRACEML_TRANSPORT
        # =tcp yields exactly the plain pre-transport-tier TCPServer
        # (docs/developer_guide/native-transport.md)
        transport_cfg = server_transport_config(settings)
        self.server = TCPServer(
            host=settings.aggregator.bind_host,
            port=settings.aggregator.port,
            uds_path=transport_cfg.get("uds_path"),
        )
        self.ring_registry = None
        if transport_cfg.get("enable_rings"):
            try:
                from traceml_tpu.transport.shm_ring import ShmRingRegistry

                self.ring_registry = ShmRingRegistry(settings.session_dir)
                self.server.attach_ring_registry(self.ring_registry)
            except Exception as exc:
                get_error_log().warning("shm ring registry unavailable", exc)
        self.db_path = settings.session_dir / "telemetry.sqlite"
        self.writer = SQLiteWriter(
            self.db_path, summary_window_rows=settings.summary_window_rows
        )
        self.display = resolve_display_driver(settings.mode)
        # serving tier: the display driver reads THROUGH this registry,
        # so one aggregator process can serve sibling sessions under the
        # same logs_dir (fleet index + per-session publishers)
        self.registry = SessionRegistry(
            settings.logs_dir,
            default_session=settings.session_id,
            max_sessions=settings.serve_max_sessions,
        )
        self.summary_service = FinalSummaryService(
            settings,
            generate=self.generate_final_summary,
            settle=self.settle_telemetry,
        )
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._finished_ranks: Set[int] = set()
        self._seen_ranks: Set[int] = set()
        self.liveness = RankLivenessTracker()
        # latest producer_stats snapshot per rank (publisher self-
        # observability: collect/encode/flush cost, idle-tick ratio)
        self._producer_stats: Dict[int, Dict[str, Any]] = {}
        # per-rank transport_hello announcements (kind + codec chosen)
        self._transport_hellos: Dict[int, Dict[str, Any]] = {}
        # _drain_lock now guards ONLY the frame handoff (server.drain +
        # ticket issue); decode runs unlocked and ingest is ordered by
        # ticket under _ingest_cond — see _drain_once
        self._drain_lock = threading.Lock()
        self._ingest_cond = threading.Condition()
        self._drain_ticket = 0
        self._ingest_next = 0
        # shm durable-consumption watermarks: ring tails advance only
        # after the writer settles the envelopes drained up to a cursor
        # snapshot, so an aggregator kill -9 between drain and commit
        # re-delivers the window to the next incarnation (seq dedup
        # absorbs the overlap).  Guarded by _ingest_cond; the drained-
        # frame counter by _drain_lock.
        self._shm_frames_drained = 0
        self._ring_watermarks: "deque" = deque()
        self._last_drain_frames = 0
        self._last_ui_tick = 0.0
        self._last_stats_write = 0.0
        # periodic ingest_stats.json cadence (instance attr so tests and
        # embedders can tighten it)
        self._stats_interval = 5.0
        self.envelopes_ingested = 0
        self.started = False
        self.port: Optional[int] = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self.started:
            return
        self.started = True
        get_error_log().set_path(self.settings.session_dir / "aggregator_error.log")
        self.settings.session_dir.mkdir(parents=True, exist_ok=True)
        self._reseed_from_prior_run()
        self.server.start()
        self.port = self.server.port
        self.writer.start()
        try:
            self.display.start(self)
        except Exception as exc:
            get_error_log().warning("display start failed", exc)
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="traceml-aggregator", daemon=True
        )
        self._thread.start()

    def stop(self, finalize_timeout: Optional[float] = None) -> None:
        if not self.started:
            return
        self.started = False
        budget = (
            finalize_timeout
            if finalize_timeout is not None
            else self.settings.finalize_timeout_sec
        )
        deadline = time.monotonic() + max(1.0, budget)
        try:
            self._settle_end_of_run(deadline)
        except Exception as exc:
            get_error_log().warning("end-of-run settle failed", exc)
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.server.stop()
        try:
            self.display.stop()
        except Exception as exc:
            get_error_log().warning("display stop failed", exc)
        try:
            self.registry.close()
        except Exception as exc:
            get_error_log().warning("session registry close failed", exc)
        ok = self.writer.finalize(timeout=max(5.0, deadline - time.monotonic()))
        if not ok:
            get_error_log().warning("sqlite finalize incomplete within budget")
        # self-metrics for the summary meta (reference parity: SQLite
        # writer counters enqueued/dropped/written, now with queue /
        # group-commit / prune detail)
        try:
            self._write_ingest_stats(final=True)
        except Exception as exc:
            get_error_log().warning("ingest stats write failed", exc)
        try:
            if not self.generate_final_summary():
                atomic_write_json(
                    self.settings.session_dir / "finalization_error.json",
                    {"error": "final summary generation failed", "ts": time.time()},
                )
        except Exception as exc:
            get_error_log().error("final summary at shutdown failed", exc)
            atomic_write_json(
                self.settings.session_dir / "finalization_error.json",
                {"error": str(exc), "ts": time.time()},
            )

    def _reseed_from_prior_run(self) -> None:
        """Crash-resume: a restarted aggregator (same session dir) picks
        up where its predecessor left off.  The SQLite writer re-seeds
        its partition counts and seq-dedup table from the reopened DB;
        here we restore what only lived in aggregator memory — which
        ranks already finished, and their liveness history — from the
        last persisted ``rank_status.json``."""
        path = self.settings.session_dir / "rank_status.json"
        if not path.exists():
            return
        try:
            snap = json.loads(path.read_text())
        except (OSError, ValueError) as exc:
            get_error_log().warning("rank_status reseed failed", exc)
            return
        if snap.get("session_id") not in (None, self.settings.session_id):
            return  # stale file from a different session sharing the dir
        self.liveness.seed(snap)
        ranks = snap.get("ranks")
        if isinstance(ranks, dict):
            for rank_s, info in ranks.items():
                try:
                    rank = int(rank_s)
                except (TypeError, ValueError):
                    continue
                self._seen_ranks.add(rank)
                if isinstance(info, dict) and info.get("finished"):
                    self._finished_ranks.add(rank)

    # -- ingest ----------------------------------------------------------
    def _drain_once(self, max_frames: Optional[int] = _DRAIN_BATCH_FRAMES) -> int:
        # Three stages, pipelined across callers (aggregator loop and the
        # summary-service thread via settle_telemetry):
        #   1. frame handoff under _drain_lock (cheap list splice + a
        #      monotonically increasing ticket),
        #   2. msgpack decode with NO lock held — the expensive part, so
        #      settle_telemetry never blocks behind another caller's
        #      decode slice; concurrent slices decode in parallel,
        #   3. ingest in ticket order under _ingest_cond, preserving the
        #      seed's strict frame ordering into the writer queues.
        with self._drain_lock:
            frames = self.server.drain_tagged(max_frames)
            ticket = self._drain_ticket
            self._drain_ticket += 1
            cursors = None
            if self.ring_registry is not None and frames:
                shm_n = sum(1 for tag, _f in frames if tag.startswith("shm:"))
                if shm_n:
                    self._shm_frames_drained += shm_n
                    # newest ring-cursor snapshot fully covered by the
                    # frames this (and earlier) drain slices pulled out
                    cursors = self.ring_registry.take_marks(
                        self._shm_frames_drained
                    )
        payloads: List[Any] = []
        try:
            if frames:
                # tagged decode: a corrupt frame is counted against its
                # peer and skipped instead of poisoning the whole batch
                payloads = self.server.decode_tagged(frames)
        finally:
            n = 0
            with self._ingest_cond:
                while ticket != self._ingest_next:
                    self._ingest_cond.wait(1.0)
                try:
                    for p in payloads:
                        self._chaos_ingest_hook()
                        if is_control_message(p):
                            self._handle_control(p)
                            continue
                        env = normalize_telemetry_envelope(p)
                        if env is None:
                            continue
                        self._seen_ranks.add(env.global_rank)
                        self.liveness.observe(
                            env.global_rank,
                            progress=env.sampler == "step_time",
                        )
                        self.writer.ingest(env)
                        n += 1
                    self.envelopes_ingested += n
                    self._last_drain_frames = len(frames)
                    if cursors:
                        # ticket ordering guarantees envelopes_ingested
                        # now covers every frame drained before this
                        # cursor snapshot — commit the tails once the
                        # writer has settled that many envelopes
                        self._ring_watermarks.append(
                            (self.envelopes_ingested, cursors)
                        )
                finally:
                    # the ticket advances even when decode/ingest raised,
                    # or every later caller would deadlock at the gate
                    self._ingest_next += 1
                    self._ingest_cond.notify_all()
        return n

    @staticmethod
    def _chaos_ingest_hook() -> None:
        """Fault-injection point: fires ``aggregator.ingest`` once per
        drained payload (kill9 rules SIGKILL this process inside fire —
        the chaos e2e suite uses that to crash the aggregator at a
        deterministic envelope count)."""
        try:
            from traceml_tpu.dev import chaos

            if chaos.active():
                chaos.fire("aggregator.ingest")
        except ImportError:  # pragma: no cover
            pass

    def _commit_rings(self) -> None:
        """Advance shm ring tails for every watermark the writer has
        settled (see _ring_watermarks).  Cheap when nothing is eligible;
        called from the loop tick and after each force_flush."""
        if self.ring_registry is None:
            return
        with self._ingest_cond:
            if not self._ring_watermarks:
                return
            settled = self.writer.settled_envelopes()
            cursors = None
            while self._ring_watermarks and self._ring_watermarks[0][0] <= settled:
                cursors = self._ring_watermarks.popleft()[1]
        if cursors:
            self.ring_registry.commit(cursors)

    def _drain_all(self) -> int:
        """Drain to empty in bounded slices (settle/shutdown path: no UI
        between batches, but each slice stays interruptible by the GIL)."""
        total = self._drain_once()
        while self._last_drain_frames >= _DRAIN_BATCH_FRAMES:  # tracelint: unguarded(single int read; a stale value only defers or adds one bounded drain slice)
            total += self._drain_once()
        return total

    def _write_ingest_stats(self, final: bool = False) -> None:
        """Self-metrics snapshot — written periodically from the loop
        (every ``_stats_interval`` seconds) so a live observer sees
        backpressure building, not just the post-mortem at stop()."""
        wstats = self.writer.stats()
        with self._ingest_cond:
            ingested = self.envelopes_ingested
        atomic_write_json(
            self.settings.session_dir / "ingest_stats.json",
            {
                "envelopes_ingested": ingested,
                "frames_received": self.server.frames_received,
                "decode_errors": self.server.decode_errors,
                "corrupt_frame_drops": dict(self.server.corrupt_frame_drops),
                "pending_frames_hwm": self.server.pending_hwm,
                "rows_written": self.writer.written,
                "rows_enqueued": self.writer.enqueued,
                "rows_dropped": self.writer.dropped,
                "enqueued_by_domain": wstats["enqueued_by_domain"],
                "dropped_by_domain": wstats["dropped_by_domain"],
                "unknown_domain_drops": wstats["unknown_domain_drops"],
                "drop_warnings": wstats["drop_warnings"],
                "replay_duplicates": wstats["replay_duplicates"],
                "queues": wstats["queues"],
                "group_commit": wstats["group_commit"],
                "prune": wstats["prune"],
                "finished_ranks": sorted(self._finished_ranks),
                "producers": {
                    str(rank): stats
                    for rank, stats in sorted(self._producer_stats.items())
                },
                "transports": self._transport_stats(),
                "final": final,
                "ts": time.time(),
            },
        )
        self._write_rank_status()

    def _transport_stats(self) -> Dict[str, Any]:
        """Transport-tier observability: frames per arrival path, the
        decompression counters, shm ring registry health, and each
        rank's announced (kind, codec)."""
        out: Dict[str, Any] = {
            "frames_by_kind": dict(self.server.frames_by_transport),
            "compression": {
                "envelopes": self.server.compressed_envelopes,
                "bytes_in": self.server.compressed_bytes_in,
                "bytes_decoded": self.server.decompressed_bytes,
                "errors": self.server.decompress_errors,
            },
            "ranks": {
                str(rank): hello
                for rank, hello in sorted(self._transport_hellos.items())
            },
        }
        if self.ring_registry is not None:
            out["shm"] = self.ring_registry.stats()
        return out

    def _write_rank_status(self) -> None:
        """Persist the liveness snapshot.  Written on the stats cadence
        and at settle-end; readers (report, web payload, a restarted
        aggregator) use the states as written — re-deriving them after
        the run would mark every silent-because-done rank LOST."""
        snap = self.liveness.snapshot()
        snap["session_id"] = self.settings.session_id
        snap["expected_world_size"] = self.expected_world_size()
        atomic_write_json(self.settings.session_dir / "rank_status.json", snap)

    def _handle_control(self, payload: Dict[str, Any]) -> None:
        kind = control_kind(payload)
        if kind == RANK_FINISHED:
            meta = payload.get("meta") or {}
            rank = meta.get("global_rank", meta.get("rank"))
            try:
                rank = int(rank)
            except (TypeError, ValueError):
                # a garbled marker must NOT default to rank 0 — that
                # falsely settles rank 0 and can unblock shutdown with
                # real telemetry still in flight; drop it loudly instead
                get_error_log().warning(
                    f"rank_finished with invalid global_rank {rank!r}; dropped"
                )
                return
            self._finished_ranks.add(rank)
            self.liveness.mark_finished(rank)
        elif kind == RANK_HEARTBEAT:
            meta = payload.get("meta") or {}
            try:
                rank = int(meta.get("global_rank", meta.get("rank")))
            except (TypeError, ValueError):
                return
            self._seen_ranks.add(rank)
            self.liveness.observe(rank)
        elif kind == PRODUCER_STATS:
            meta = payload.get("meta") or {}
            stats = payload.get("stats")
            if not isinstance(stats, dict):
                return
            try:
                rank = int(meta.get("global_rank", meta.get("rank")))
            except (TypeError, ValueError):
                return
            # later snapshots are cumulative — keep only the latest
            self._producer_stats[rank] = stats
            self.liveness.observe(rank)
        elif kind == TRANSPORT_HELLO:
            meta = payload.get("meta") or {}
            try:
                rank = int(meta.get("global_rank", meta.get("rank")))
            except (TypeError, ValueError):
                return
            self._seen_ranks.add(rank)
            self.liveness.observe(rank)
            # keep-latest: a restarted rank may re-announce with a
            # different tier (e.g. fell back from shm to tcp)
            hello = {
                "transport": payload.get("transport"),
                "compression": payload.get("compression"),
            }
            if payload.get("fallback_from"):
                hello["fallback_from"] = payload.get("fallback_from")
            self._transport_hellos[rank] = hello
        elif kind == MESH_TOPOLOGY:
            meta = payload.get("meta") or {}
            topo = payload.get("topology")
            if not isinstance(topo, dict):
                return
            try:
                rank = int(meta.get("global_rank", meta.get("rank")))
            except (TypeError, ValueError):
                return
            self._seen_ranks.add(rank)
            self.liveness.observe(rank)
            # persist through the normal writer path: the control meta is
            # already identity-shaped, and carrying NO seq bypasses the
            # writer's dedup lane (spool replay may re-deliver this;
            # readers keep the latest row per rank, so appends are
            # idempotent at read time)
            try:
                env_meta = dict(meta)
                env_meta.pop("seq", None)
                env_meta["sampler"] = "mesh_topology"
                row = {
                    "timestamp": float(payload.get("timestamp") or time.time()),
                    "source": str(topo.get("source") or "mesh"),
                    "axes_json": json.dumps(topo.get("axes") or []),
                    "coords_json": json.dumps(topo.get("coords")),
                }
                self.writer.ingest(
                    TelemetryEnvelope(
                        meta=env_meta, tables={"mesh_topology": [row]}
                    )
                )
            except Exception as exc:
                get_error_log().warning("mesh_topology persist failed", exc)

    # -- loop ------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.server.wait_for_data(_RENDER_INTERVAL)
                # re-loop until the backlog is gone, giving the UI a
                # chance to tick between bounded decode batches — the
                # loop never parks in wait_for_data with frames pending
                while True:
                    self._drain_once()
                    self._commit_rings()
                    now = time.monotonic()
                    if now - self._last_ui_tick >= _RENDER_INTERVAL:
                        self._last_ui_tick = now
                        self.summary_service.poll()
                        try:
                            self.display.tick(self)
                        except Exception as exc:
                            get_error_log().warning("display tick failed", exc)
                    if now - self._last_stats_write >= self._stats_interval:
                        self._last_stats_write = now
                        try:
                            self._write_ingest_stats()
                        except Exception as exc:
                            get_error_log().warning(
                                "periodic ingest stats write failed", exc
                            )
                    if (
                        self._last_drain_frames < _DRAIN_BATCH_FRAMES  # tracelint: unguarded(single int read; a stale value only defers backlog catch-up to the next loop tick)
                        or self._stop_evt.is_set()
                    ):
                        break
            except Exception as exc:  # keep the loop alive
                get_error_log().warning("aggregator loop error", exc)
                time.sleep(0.1)

    # -- settle / finalize ------------------------------------------------
    def expected_world_size(self) -> int:
        if self.settings.expected_world_size:
            return self.settings.expected_world_size
        return max(len(self._seen_ranks), 1)

    def settle_telemetry(self, timeout: float = 5.0) -> None:
        """Drain whatever is in flight and wait for it to be committed
        (reference: trace_aggregator.py:518)."""
        deadline = time.monotonic() + timeout
        self._drain_all()
        self.writer.force_flush(timeout=max(0.5, deadline - time.monotonic()))
        self._commit_rings()

    def _settle_end_of_run(self, deadline: float) -> None:
        """Wait for all expected rank_finished markers or the deadline
        (reference: trace_aggregator.py:440-499)."""
        expected = self.expected_world_size()
        while time.monotonic() < deadline:
            self._drain_all()
            if len(self._finished_ranks) >= expected:
                break
            time.sleep(_SETTLE_POLL)
        self._drain_all()
        self.writer.force_flush(timeout=max(1.0, deadline - time.monotonic()))
        self._commit_rings()
        missing = sorted(
            set(range(expected)) - self._finished_ranks
        )
        if missing:
            # per-missing-rank liveness verdicts ride along: the report
            # distinguishes a rank that died mid-run (LOST, telemetry
            # data gap) from one that merely lost its finish marker
            now = time.time()
            atomic_write_json(
                self.settings.session_dir / "finalization_warning.json",
                {
                    "missing_ranks": missing,
                    "missing_rank_states": {
                        str(r): self.liveness.state_of(r, now) for r in missing
                    },
                    "finished_ranks": sorted(self._finished_ranks),
                    "expected_world_size": expected,
                    "ts": now,
                },
            )

    # -- summary ----------------------------------------------------------
    def generate_final_summary(self) -> bool:
        """Build final_summary artifacts from the SQLite DB."""
        from traceml_tpu.reporting.final import generate_summary

        return generate_summary(
            db_path=self.db_path,
            session_dir=self.settings.session_dir,
            settings=self.settings,
        )


def write_ready_file(
    settings: TraceMLSettings,
    port: int,
    display_port: Optional[int] = None,
) -> None:
    """The launcher polls this to learn the bound ports (ingest always;
    the dashboard's HTTP port when a browser driver is serving)."""
    payload: Dict[str, Any] = {
        "port": port,
        "pid": __import__("os").getpid(),
        "ts": time.time(),
    }
    if display_port is not None:
        payload["display_port"] = display_port
    atomic_write_json(
        settings.session_dir / "aggregator_ready.json", payload
    )
