"""Dashboard theme layer (reference role: nicegui_sections/theme.py —
a single source of truth for chrome tokens, functional data-viz colors,
and shared chart/format helpers; rebuilt for the dependency-free
dashboard with our own dark "machine-room" design rather than the
reference's brand).

Split of responsibilities mirrors the reference:
* chrome tokens + component CSS live HERE and nowhere else;
* FUNCTIONAL colors (phase + severity) encode meaning shared with the
  CLI renderers — sections must not re-hue them;
* shared JS helpers (escaping, formatting, staleness, sparkline paths,
  tooltip) are emitted once and used by every section's render fn.

Security note carried from browser.py: every telemetry-derived string
is escaped via ``esc()`` before interpolation — the ingest port is
unauthenticated, so payload strings are treated as hostile.
"""

from __future__ import annotations

# --- chrome tokens (ours: deep-space glass, ice accent) -------------------
BG = "#0d0f16"
INK = "#e9ecf5"
MUTED = "#8d93a8"
ACCENT = "#5ad1e6"          # ice cyan — hero metric color
ACCENT_DEEP = "#2b9ec7"
VIOLET = "#9d7bff"
BORDER = "rgba(233,236,245,0.10)"
GOOD = "#4ade80"
CARD = "rgba(26,29,44,0.72)"

# --- functional palette (shared meaning with the CLI renderers) -----------
# phase key → (ribbon label, color); order = canonical step composition
PHASES = [
    ("input", "IN", "#e74c3c"),
    ("h2d", "H2D", "#e67e22"),
    ("forward", "FWD", "#2d7dd2"),
    ("backward", "BWD", "#2255a4"),
    ("optimizer", "OPT", "#7d3dd2"),
    ("compute", "CMP", "#2d7dd2"),
    ("compile", "XLA", "#f1c40f"),
    ("collective", "ICI", "#16a085"),
    ("checkpoint", "CKPT", "#8e5a2b"),
    ("residual", "RES", "#95a5a6"),
]
SEV = {"info": "#2d7dd2", "warning": "#e67e22", "critical": "#c0392b"}

CSS = """
:root{
  --bg:#0d0f16; --ink:#e9ecf5; --muted:#8d93a8; --accent:#5ad1e6;
  --accent-deep:#2b9ec7; --violet:#9d7bff; --border:rgba(233,236,245,0.10);
  --good:#4ade80; --warn:#e67e22; --crit:#c0392b;
  --mono:"SF Mono",Menlo,Consolas,"Liberation Mono",monospace;
  --sans:system-ui,-apple-system,"Segoe UI",sans-serif;
}
*{box-sizing:border-box}
body{font-family:var(--sans);margin:0;color:var(--ink);min-height:100vh;
  background-color:var(--bg);
  background-image:
    radial-gradient(rgba(233,236,245,0.03) 1px,transparent 1px),
    radial-gradient(900px 480px at 8% -10%,rgba(90,209,230,0.10),transparent 55%),
    radial-gradient(800px 520px at 102% -6%,rgba(157,123,255,0.09),transparent 52%);
  background-size:26px 26px,100% 100%,100% 100%;background-attachment:fixed}
.wrap{max-width:1380px;margin:0 auto;padding:20px 24px;display:flex;
  flex-direction:column;gap:14px}
.grid{display:flex;gap:14px;flex-wrap:wrap;align-items:stretch}
.cell{min-width:300px;display:flex;flex-direction:column}
.card{background:linear-gradient(175deg,rgba(30,34,52,0.82),rgba(22,25,38,0.72));
  border:1px solid var(--border);border-radius:16px;padding:16px 18px;
  box-shadow:inset 0 1px 0 rgba(233,236,245,0.06),0 8px 22px rgba(0,0,0,0.35);
  backdrop-filter:blur(18px);transition:box-shadow .25s,transform .25s;
  min-width:0;width:100%}
.card:hover{transform:translateY(-1px);
  box-shadow:inset 0 1px 0 rgba(233,236,245,0.09),0 14px 30px rgba(0,0,0,0.45)}
@keyframes rise{from{opacity:0;transform:translateY(14px)}to{opacity:1;transform:none}}
.reveal{animation:rise .6s cubic-bezier(.2,.7,.2,1) both}
.d1{animation-delay:.06s}.d2{animation-delay:.12s}.d3{animation-delay:.18s}
.ctitle{font-size:.95rem;font-weight:600;margin:0}
.chead{display:flex;align-items:center;gap:10px;margin-bottom:.55rem}
.chead .sp{flex:1}
.cmeta{font-family:var(--mono);font-size:.72rem;color:var(--muted)}
.muted{color:var(--muted);font-size:.82rem}
.wm{font-weight:700;font-size:1.25rem;letter-spacing:-.01em}
.wm b{color:var(--accent);font-weight:700}
.eyebrow{font-family:var(--mono);font-style:italic;font-size:.72rem;
  color:var(--accent);background:rgba(90,209,230,0.10);
  border:1px solid rgba(90,209,230,0.25);padding:2px 10px;border-radius:999px}
.livedot{width:8px;height:8px;border-radius:999px;background:var(--good);
  animation:pulse 2.4s infinite}
@keyframes pulse{0%{box-shadow:0 0 0 0 rgba(74,222,128,.5)}
  70%{box-shadow:0 0 0 6px rgba(74,222,128,0)}100%{box-shadow:0 0 0 0 rgba(74,222,128,0)}}
table{border-collapse:collapse;width:100%;font-size:.85rem}
th,td{text-align:left;padding:.28rem .5rem;border-bottom:1px solid rgba(233,236,245,0.07)}
th{font-family:var(--mono);font-size:.68rem;letter-spacing:.08em;
  text-transform:uppercase;color:var(--muted);font-weight:600}
td.num,th.num{text-align:right;font-variant-numeric:tabular-nums}
.badge{font-family:var(--mono);font-size:.68rem;border-radius:999px;
  padding:.12rem .5rem;background:rgba(233,236,245,0.08)}
.badge.stale{background:rgba(230,126,34,0.16);color:#ffd27f;
  border:1px solid rgba(230,126,34,0.35)}
.sev-info{border-left:4px solid var(--accent-deep)}
.sev-warning{border-left:4px solid var(--warn)}
.sev-critical{border-left:4px solid var(--crit)}
.finding{margin:.3rem 0;padding:.5rem .65rem;border-radius:10px;
  background:rgba(233,236,245,0.05)}
.meter{background:rgba(233,236,245,0.08);border-radius:3px;width:110px;
  height:11px;display:inline-block;vertical-align:middle;overflow:hidden}
.meter>i{display:block;height:100%;background:var(--accent-deep)}
.meter>i.warn{background:var(--warn)}.meter>i.crit{background:var(--crit)}
pre{white-space:pre-wrap;font-size:.78rem;color:#b8e0c8;margin:0;
  font-family:var(--mono)}
.err{color:#f0a0a0}
svg.chart{width:100%;height:120px;background:rgba(10,12,20,0.55);
  border-radius:8px}
svg.spark{width:100%;height:64px;background:rgba(10,12,20,0.55);
  border-radius:8px}
.legend{display:flex;flex-wrap:wrap;gap:.15rem .8rem}
.legend span{font-family:var(--mono);font-size:.7rem;color:var(--muted);
  cursor:default}
.legend span.toggle{cursor:pointer;user-select:none}
.legend span.off{opacity:.32;text-decoration:line-through}
.legend i{display:inline-block;width:9px;height:9px;border-radius:2px;
  margin-right:.3rem;vertical-align:middle}
/* phase ribbon (the hero signature) */
.ribbon{display:flex;width:100%;height:30px;border-radius:10px;
  overflow:hidden;border:1px solid rgba(233,236,245,0.08);
  box-shadow:inset 0 1px 0 rgba(255,255,255,.08)}
.pseg{height:100%;transition:width .6s cubic-bezier(.4,0,.2,1);display:flex;
  align-items:center;justify-content:center;min-width:0;overflow:hidden}
.seglab{font-family:var(--mono);font-size:.62rem;font-weight:600;
  color:rgba(255,255,255,.95);white-space:nowrap;
  text-shadow:0 1px 1px rgba(0,0,0,.35)}
.verdict{font-size:1.12rem;font-weight:500;letter-spacing:-.005em;margin:.7rem 0 .2rem}
.sevpill{font-family:var(--mono);font-size:.66rem;font-weight:600;
  padding:2px 8px;border-radius:999px;text-transform:uppercase;
  letter-spacing:.06em;color:#fff}
/* KPI tiles */
.kpis{display:flex;gap:9px;flex-wrap:wrap;margin-top:.7rem}
.kpi{position:relative;background:rgba(233,236,245,0.045);
  border:1px solid rgba(233,236,245,0.07);border-radius:11px;
  padding:9px 12px 8px;min-width:104px;flex:1}
.kpi::before{content:'';position:absolute;left:0;top:0;height:100%;width:3px;
  border-radius:3px 0 0 3px;background:var(--acc,var(--accent));opacity:.85}
.klab{font-family:var(--mono);font-size:.62rem;letter-spacing:.09em;
  text-transform:uppercase;color:var(--accent);font-weight:600}
.kval{font-family:var(--mono);font-size:1.1rem;font-weight:600;
  font-variant-numeric:tabular-nums;margin-top:3px;line-height:1.1}
.kunit{font-size:.62em;color:var(--muted);font-weight:500;margin-left:2px}
.heat td{font-family:var(--mono);font-size:.78rem}
#tip{position:fixed;display:none;pointer-events:none;z-index:50;
  background:rgba(16,18,28,0.96);border:1px solid var(--border);
  border-radius:8px;padding:.35rem .55rem;font-family:var(--mono);
  font-size:.72rem;max-width:280px}
"""

# shared JS helpers — emitted ONCE by pages.py, before section scripts
HELPERS_JS = r"""
const COLORS={input:"#e74c3c",h2d:"#e67e22",forward:"#2d7dd2",
backward:"#2255a4",optimizer:"#7d3dd2",compute:"#2d7dd2",
compile:"#f1c40f",collective:"#16a085",checkpoint:"#8e5a2b",
residual:"#95a5a6"};
const SEV={info:"#2d7dd2",warning:"#e67e22",critical:"#c0392b"};
// telemetry strings (hostnames, diagnosis text, phase/rank keys) arrive
// from an unauthenticated ingest port — escape EVERY interpolation.
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmtB=n=>{if(n==null||isNaN(n))return"n/a";
  const u=["B","KiB","MiB","GiB","TiB"];let i=0;
  while(n>=1024&&i<u.length-1){n/=1024;i++}return n.toFixed(i?2:0)+" "+u[i]};
const fmtMs=v=>v==null?"n/a":(v<1?(v*1000).toFixed(0)+" µs":
  v<1000?v.toFixed(1)+" ms":(v/1000).toFixed(2)+" s");
const pct=v=>v==null?"—":(v*100).toFixed(1)+"%";
const rankColor=ri=>`hsl(${(ri*67)%360},70%,62%)`;
function badge(el,serverTs,latestTs){
  const e=document.getElementById(el);if(!e)return;
  if(latestTs==null){e.innerHTML='<span class="badge">no data</span>';return}
  const age=serverTs-latestTs;
  e.innerHTML=age>5?`<span class="badge stale">${age.toFixed(0)}s stale</span>`
                   :'<span class="badge">live</span>'}
function meter(frac,warn,crit){
  if(frac==null)return"—";
  const cls=frac>=crit?"crit":frac>=warn?"warn":"";
  const w=Math.min(100,frac*100).toFixed(0);
  return`<span class="meter"><i class="${cls}" style="width:${w}%"></i></span>
    <span class="muted">${(frac*100).toFixed(0)}%</span>`}
function kpiTile(key,label,acc){
  return`<div class="kpi" style="--acc:${esc(acc)}"><span class="klab">${esc(label)}</span>
    <div class="kval" id="kpi-${esc(key)}">—</div></div>`}
function setKpi(key,num,unit){
  const e=document.getElementById("kpi-"+key);if(!e)return;
  e.innerHTML=num==null?"—":`${esc(num)}<span class="kunit">${esc(unit||"")}</span>`}
// shared crosshair tooltip: sections attach via hookTip(svg, fn(frac)->html)
const tip=(()=>{let el=null;return{
  show(html,x,y){if(!el)el=document.getElementById("tip");if(!el)return;
    el.innerHTML=html;el.style.display="block";
    el.style.left=Math.min(x+14,window.innerWidth-300)+"px";
    el.style.top=(y+12)+"px"},
  hide(){if(!el)el=document.getElementById("tip");
    if(el)el.style.display="none"}}})();
function hookTip(svgId,htmlAt){
  const svg=document.getElementById(svgId);if(!svg||svg._tipped)return;
  svg._tipped=true;
  svg.addEventListener("mousemove",ev=>{
    const r=svg.getBoundingClientRect();
    const frac=Math.max(0,Math.min(1,(ev.clientX-r.left)/r.width));
    const html=htmlAt(frac);
    if(html)tip.show(html,ev.clientX,ev.clientY);else tip.hide()});
  svg.addEventListener("mouseleave",()=>tip.hide())}
function sparkPath(series,w,h,max,pad){
  const m=max||Math.max(1,...series);
  return series.map((v,i)=>`${(i/(series.length-1||1))*w},${
    (h-(pad||2))-(v/m)*(h-2*(pad||2))}`).join(" ")}
"""


def head() -> str:
    return f"<style>{CSS}</style>"
