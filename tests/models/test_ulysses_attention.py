"""Ulysses (all-to-all sequence-parallel) attention vs the reference
and vs ring attention on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from traceml_tpu.ops.attention import causal_attention_reference
from traceml_tpu.ops.ring_attention import make_ring_attention
from traceml_tpu.ops.ulysses_attention import (
    make_ulysses_attention,
    ulysses_attention,
)
from traceml_tpu.parallel.mesh import make_mesh


def _qkv(B, S, H, D, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) * 0.4 for k in ks)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_ulysses_matches_reference(p):
    if len(jax.devices()) < p:
        pytest.skip("not enough devices")
    mesh = make_mesh({"context": p}, devices=jax.devices()[:p])
    q, k, v = _qkv(B=2, S=128, H=8, D=32)
    ref = causal_attention_reference(q, k, v)
    fn = make_ulysses_attention(mesh, "context")
    with mesh:
        out = fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ulysses_agrees_with_ring():
    """The two sequence-parallel strategies compute the same function."""
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=96, H=4, D=16, seed=5)
    with mesh:
        ring = make_ring_attention(mesh, "context")(q, k, v)
        uly = make_ulysses_attention(mesh, "context")(q, k, v)
    np.testing.assert_allclose(
        np.asarray(uly), np.asarray(ring), atol=2e-5, rtol=2e-5
    )


def test_ulysses_causality_across_shards():
    """Perturbing the LAST shard's keys must not change earlier
    positions' outputs."""
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=64, H=4, D=16, seed=3)
    fn = make_ulysses_attention(mesh, "context")
    with mesh:
        out1 = fn(q, k, v)
        k2 = k.at[:, 48:].add(7.0)  # future-only perturbation
        out2 = fn(q, k2, v)
    np.testing.assert_allclose(
        np.asarray(out1[:, :48]), np.asarray(out2[:, :48]),
        atol=1e-6, rtol=1e-6,
    )
    assert not np.allclose(np.asarray(out1[:, 48:]), np.asarray(out2[:, 48:]))


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=64, H=3, D=16)  # 3 heads, 4-way axis
    fn = make_ulysses_attention(mesh, "context")
    with pytest.raises(Exception, match="divisible|ulysses"):
        with mesh:
            fn(q, k, v)


def test_ulysses_differentiable():
    """Gradients flow through both all_to_alls (training path)."""
    mesh = make_mesh({"context": 2}, devices=jax.devices()[:2])
    q, k, v = _qkv(B=1, S=32, H=2, D=8, seed=9)

    fn = make_ulysses_attention(mesh, "context")

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(causal_attention_reference(q, k, v) ** 2)

    with mesh:
        g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), atol=2e-4, rtol=2e-4
    )


def test_ulysses_bf16_stays_close_to_ring():
    """bf16 inputs: the f32 p·v accumulation keeps ulysses within
    bf16-level tolerance of ring attention."""
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=128, H=4, D=16, seed=11, dtype=jnp.bfloat16)
    with mesh:
        ring = make_ring_attention(mesh, "context")(q, k, v)
        uly = make_ulysses_attention(mesh, "context")(q, k, v)
    np.testing.assert_allclose(
        np.asarray(uly, dtype=np.float32),
        np.asarray(ring, dtype=np.float32),
        atol=2e-2, rtol=2e-2,
    )
