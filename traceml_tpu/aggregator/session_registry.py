"""Session registry: one aggregator process serving N sessions
(docs/developer_guide/serving-tier.md).

The registry maps validated session ids under one ``logs_dir`` to
serving-tier publishers (``renderers/serving.publisher_for`` — lazily
opened, keyed, LRU-bounded, so an idle session costs nothing and a
burst of sessions can't exhaust sqlite connections), and builds the
fleet index served at ``GET /api/sessions``: per session the rank
liveness summary, the primary diagnosis, and the last-update stamp.

Session ids come from URLs on the (unauthenticated) display port, so
they are validated against a strict charset BEFORE touching the
filesystem — both on lookup and during directory discovery; a hostile
directory name under ``logs_dir`` is skipped, never echoed.
"""

from __future__ import annotations

import re
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from traceml_tpu.renderers.serving import SessionPublisher, publisher_for

# no leading dot (also excludes "." / ".."), no separators — a session id
# must stay a single path component
_SESSION_ID_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9._\-]{0,127}$")


def valid_session_id(session_id: Any) -> bool:
    return bool(
        isinstance(session_id, str) and _SESSION_ID_RE.match(session_id)
    )


class SessionRegistry:
    """Thread-safe (shared by every HTTP handler thread)."""

    def __init__(
        self,
        logs_dir: Path,
        default_session: Optional[str] = None,
        window_steps: int = 150,
        max_sessions: int = 8,
        fleet_cache_ttl: float = 0.0,
    ) -> None:
        self.logs_dir = Path(logs_dir)
        self.default_session = default_session
        self.window_steps = window_steps
        self.max_sessions = max(1, int(max_sessions))
        #: whole-index reuse window — the federation rollup polls
        #: ``/api/sessions`` per shard per interval, multiplied by
        #: routers; 0 keeps the historical rebuild-every-call behavior
        self.fleet_cache_ttl = max(0.0, float(fleet_cache_ttl))
        self._lock = threading.Lock()
        # sessions opened THROUGH this registry — close() only touches
        # these, never publishers some other registry/test opened
        self._open: Dict[str, SessionPublisher] = {}
        # explicit shard locations (register()) — the aggregator context
        # may bind its own session to a DB outside logs_dir/<sid>/
        self._db_overrides: Dict[str, Path] = {}
        self._dir_overrides: Dict[str, Path] = {}
        # per-session entry cache keyed by an artifact stamp (mtimes +
        # sizes + live publisher token) — invalidation is the stamp
        # changing, so a TTL-cached index never shows an update later
        # than the artifacts it was built from
        self._entry_cache: Dict[str, Tuple[tuple, Dict[str, Any]]] = {}
        self._index_cache: Optional[Tuple[float, Dict[str, Any]]] = None
        self.entry_builds = 0  # observability: cache-effectiveness tests

    def register(
        self,
        session_id: str,
        db_path: Path,
        session_dir: Optional[Path] = None,
    ) -> None:
        """Pin a session to an explicit DB shard (and artifact dir),
        overriding the ``logs_dir/<sid>/`` convention.  Used by the
        display driver for the session its context already bound."""
        if not valid_session_id(session_id):
            raise KeyError(session_id)
        with self._lock:
            self._db_overrides[session_id] = Path(db_path)
            if session_dir is not None:
                self._dir_overrides[session_id] = Path(session_dir)
            # the binding changes where artifacts are read from — any
            # cached entry/index for this session is now misaddressed
            self._entry_cache.pop(session_id, None)
            self._index_cache = None

    # -- lookup ----------------------------------------------------------

    def resolve(self, session_id: Optional[str]) -> Optional[str]:
        """Requested session id → validated id (default when omitted),
        or None when invalid/unknown-default."""
        if session_id is None or session_id == "":
            session_id = self.default_session
        if not valid_session_id(session_id):
            return None
        return session_id

    def db_path(self, session_id: str) -> Path:
        with self._lock:
            override = self._db_overrides.get(session_id)
        if override is not None:
            return override
        return self.logs_dir / session_id / "telemetry.sqlite"

    def session_dir(self, session_id: str) -> Path:
        with self._lock:
            override = self._dir_overrides.get(session_id)
        if override is not None:
            return override
        return self.logs_dir / session_id

    def publisher(self, session_id: str) -> SessionPublisher:
        """The session's publisher (opened lazily; LRU-bounded by the
        serving-tier cache).  Caller must pass a validated id."""
        if not valid_session_id(session_id):
            raise KeyError(session_id)
        pub = publisher_for(
            self.db_path(session_id),
            session_id,
            window_steps=self.window_steps,
            max_publishers=self.max_sessions,
        )
        with self._lock:
            self._open[session_id] = pub
        return pub

    # -- fleet index -----------------------------------------------------

    def sessions(self) -> List[str]:
        """Valid session ids under logs_dir that have produced telemetry
        (DB shard or rank-status file), plus the default session even
        before its first write.  Invalid directory names are skipped —
        defense in depth ahead of client-side escaping."""
        found = set()
        try:
            for entry in self.logs_dir.iterdir():
                if not valid_session_id(entry.name):
                    continue
                if not entry.is_dir():
                    continue
                if (entry / "telemetry.sqlite").exists() or (
                    entry / "rank_status.json"
                ).exists():
                    found.add(entry.name)
        except OSError:
            pass
        if self.default_session and valid_session_id(self.default_session):
            found.add(self.default_session)
        return sorted(found)

    def _session_entry(self, session_id: str) -> Dict[str, Any]:
        from traceml_tpu.reporting.loaders import load_rank_status
        from traceml_tpu.sdk.protocol import get_final_summary_json_path
        from traceml_tpu.utils.atomic_io import read_json

        session_dir = self.session_dir(session_id)
        db = self.db_path(session_id)
        entry: Dict[str, Any] = {
            "session": session_id,
            "db_exists": db.exists(),
            "last_update_ts": None,
            "ranks": {},
            "finished": False,
            "primary_diagnosis": None,
        }
        try:
            entry["last_update_ts"] = db.stat().st_mtime
        except OSError:
            pass
        status = load_rank_status(session_dir)
        if status and isinstance(status.get("ranks"), dict):
            counts: Dict[str, int] = {}
            for info in status["ranks"].values():
                state = (info or {}).get("state") or "?"
                counts[state] = counts.get(state, 0) + 1
            entry["ranks"] = counts
            if status.get("ts"):
                entry["last_update_ts"] = status["ts"]
        summary_path = get_final_summary_json_path(session_dir)
        if summary_path.exists():
            entry["finished"] = True
            summary = read_json(summary_path)
            if isinstance(summary, dict):
                primary = summary.get("primary_diagnosis")
                if isinstance(primary, dict):
                    entry["primary_diagnosis"] = {
                        k: primary.get(k)
                        for k in ("kind", "severity", "summary")
                    }
                mesh = ((summary.get("meta") or {}).get("topology") or {}).get(
                    "mesh"
                )
                if mesh:
                    entry["mesh"] = mesh
                # workload kind for the fleet page: a serving section only
                # exists when the session recorded serving telemetry
                sections = summary.get("sections")
                if isinstance(sections, dict):
                    kinds = []
                    if (sections.get("step_time") or {}).get("status") == "OK":
                        kinds.append("training")
                    if "serving" in sections:
                        kinds.append("serving")
                    if kinds:
                        entry["workload"] = "+".join(kinds)
        else:
            # live session: peek at an already-open publisher's diagnosis
            # fragment — the index never force-opens a publisher (that
            # would let a fleet listing thrash the LRU bound)
            with self._lock:
                pub = self._open.get(session_id)
            if pub is not None and not pub.closed:
                diag = pub.fragment("diagnosis") or {}
                issue = diag.get("diagnosis")
                if isinstance(issue, dict):
                    entry["primary_diagnosis"] = {
                        k: issue.get(k)
                        for k in ("kind", "severity", "summary")
                    }
                mesh = (pub.fragment("meta") or {}).get("mesh")
                if mesh:
                    entry["mesh"] = mesh
                kinds = []
                if (pub.fragment("step_time") or {}).get("step_time"):
                    kinds.append("training")
                if (pub.fragment("serving") or {}).get("serving"):
                    kinds.append("serving")
                if kinds:
                    entry["workload"] = "+".join(kinds)
        return entry

    def _entry_stamp(self, session_id: str) -> tuple:
        """Cheap invalidation key for one session's index entry: the
        (mtime_ns, size) of each artifact the entry is derived from,
        plus the open publisher's version token for live sessions —
        any write that could change the entry changes the stamp."""
        from traceml_tpu.sdk.protocol import get_final_summary_json_path

        session_dir = self.session_dir(session_id)
        parts: list = []
        for path in (
            session_dir / "rank_status.json",
            get_final_summary_json_path(session_dir),
            self.db_path(session_id),
        ):
            try:
                st = path.stat()
                parts.append((st.st_mtime_ns, st.st_size))
            except OSError:
                parts.append(None)
        with self._lock:
            pub = self._open.get(session_id)
        if pub is not None and not pub.closed:
            try:
                parts.append(pub.poll())
            except Exception:
                parts.append(None)
        else:
            parts.append(None)
        return tuple(parts)

    def _entry_cached(self, session_id: str) -> Dict[str, Any]:
        stamp = self._entry_stamp(session_id)
        with self._lock:
            cached = self._entry_cache.get(session_id)
        if cached is not None and cached[0] == stamp:
            return cached[1]
        entry = self._session_entry(session_id)
        with self._lock:
            self.entry_builds += 1
            self._entry_cache[session_id] = (stamp, entry)
        return entry

    def fleet_index(self) -> Dict[str, Any]:
        now = time.monotonic()
        if self.fleet_cache_ttl > 0.0:
            with self._lock:
                cached_index = self._index_cache
            if (
                cached_index is not None
                and (now - cached_index[0]) <= self.fleet_cache_ttl
            ):
                return cached_index[1]
        index = {
            "version": 1,
            "ts": time.time(),
            "default_session": self.default_session
            if valid_session_id(self.default_session)
            else None,
            "sessions": [
                self._entry_cached(sid) for sid in self.sessions()
            ],
        }
        if self.fleet_cache_ttl > 0.0:
            with self._lock:
                self._index_cache = (now, index)
        return index

    def close(self) -> None:
        with self._lock:
            pubs = list(self._open.values())
            self._open.clear()
        for pub in pubs:
            pub.close()
