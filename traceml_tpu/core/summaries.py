"""Summary-section contracts (reference: src/traceml_ai/core/summaries.py:12-45).

A summary section is the unit of the final report: it has a key, a schema
payload (JSON-safe dict) and a status.  Failed sections degrade to a
schema-valid NO_DATA payload rather than breaking the report
(reference: reporting/final.py:752-798).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

STATUS_OK = "OK"
STATUS_NO_DATA = "NO_DATA"
STATUS_ERROR = "ERROR"


@dataclasses.dataclass
class SummarySection:
    key: str
    title: str
    status: str = STATUS_OK
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        # Reserved fields win over payload keys of the same name, so a
        # telemetry row carrying its own "status" can never mask a
        # STATUS_ERROR section marker.
        out: Dict[str, Any] = dict(self.payload)
        out["key"] = self.key
        out["title"] = self.title
        out["status"] = self.status
        if self.error is not None:
            out["error"] = self.error
        return out


@dataclasses.dataclass
class SummaryResult:
    sections: Dict[str, SummarySection] = dataclasses.field(default_factory=dict)

    def add(self, section: SummarySection) -> None:
        self.sections[section.key] = section

    def to_dict(self) -> Dict[str, Any]:
        return {k: s.to_dict() for k, s in self.sections.items()}
