"""Minimal torch training loop under TraceML-TPU (CPU or torch-xla).

Run:  traceml-tpu run --mode cli examples/quickstart/pytorch_minimal.py
"""

import torch
import torch.nn as nn
from torch.utils.data import DataLoader, TensorDataset

import traceml_tpu

traceml_tpu.init(mode="auto")

model = nn.Sequential(nn.Linear(64, 256), nn.Tanh(), nn.Linear(256, 1))
opt = torch.optim.Adam(model.parameters(), lr=1e-3)
loss_fn = nn.MSELoss()
loader = DataLoader(
    TensorDataset(torch.randn(2048, 64), torch.randn(2048, 1)), batch_size=16
)

for epoch in range(3):
    for x, y in loader:
        with traceml_tpu.trace_step():
            opt.zero_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()
print("final loss:", float(loss))
