"""serving projection → ``serving_samples``.

One row per (replica, window): the per-window aggregates the serving
sampler emits — request counts, queue depth, prefill/decode time split,
TTFT / end-to-end latency percentiles, KV-cache headroom — plus the
packed per-request populations (``ttft_ms_list`` / ``e2e_ms_list`` /
``tokens_list``).  The packed lists are what make cross-window
percentiles exact: the ragged window build (utils/columnar.py
``RaggedEventColumns``) re-ranks the raw populations instead of
averaging row-level p99s.  ``step`` is the replica's monotone window
sequence number, so watermark retention and the (rank × step) cube
work unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "serving_samples"
RETENTION_TABLES = (TABLE,)


def accepts_sampler(name: str) -> bool:
    return name == "serving"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            step INTEGER,
            timestamp REAL,
            requests_enqueued INTEGER,
            requests_completed INTEGER,
            requests_active INTEGER,
            queue_depth INTEGER,
            decode_tokens INTEGER,
            prefill_ms REAL,
            decode_ms REAL,
            tokens_per_s REAL,
            batch_occupancy REAL,
            ttft_p50_ms REAL,
            ttft_p95_ms REAL,
            ttft_p99_ms REAL,
            e2e_p50_ms REAL,
            e2e_p95_ms REAL,
            e2e_p99_ms REAL,
            kv_bytes INTEGER,
            kv_limit_bytes INTEGER,
            kv_headroom REAL,
            ttft_ms_list TEXT,
            e2e_ms_list TEXT,
            tokens_list TEXT
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_rank_step "
        f"ON {TABLE} (session_id, global_rank, step)"
    )


def insert_sql(table: str) -> str:
    return (
        f"INSERT INTO {TABLE} (session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid, step, timestamp,"
        " requests_enqueued, requests_completed, requests_active, queue_depth,"
        " decode_tokens, prefill_ms, decode_ms, tokens_per_s, batch_occupancy,"
        " ttft_p50_ms, ttft_p95_ms, ttft_p99_ms, e2e_p50_ms, e2e_p95_ms,"
        " e2e_p99_ms, kv_bytes, kv_limit_bytes, kv_headroom, ttft_ms_list,"
        " e2e_ms_list, tokens_list)"
        " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    ident = identity_tuple(env)
    tables: Dict[str, List[Tuple]] = {}
    v = env.column_view("serving")
    if v:
        steps = v.ints("step")
        ts = v.floats("timestamp")
        enq = v.ints("requests_enqueued")
        done = v.ints("requests_completed")
        active = v.ints("requests_active")
        qdepth = v.ints("queue_depth")
        dtok = v.ints("decode_tokens")
        pre_ms = v.floats("prefill_ms")
        dec_ms = v.floats("decode_ms")
        tps = v.floats("tokens_per_s")
        occ = v.floats("batch_occupancy")
        t50 = v.floats("ttft_p50_ms")
        t95 = v.floats("ttft_p95_ms")
        t99 = v.floats("ttft_p99_ms")
        e50 = v.floats("e2e_p50_ms")
        e95 = v.floats("e2e_p95_ms")
        e99 = v.floats("e2e_p99_ms")
        kvb = v.ints("kv_bytes")
        kvl = v.ints("kv_limit_bytes")
        kvh = v.floats("kv_headroom")
        ttft_l = v.strs("ttft_ms_list", "")
        e2e_l = v.strs("e2e_ms_list", "")
        tok_l = v.strs("tokens_list", "")
        tables[TABLE] = [
            ident
            + (
                steps[i],
                ts[i],
                enq[i] or 0,
                done[i] or 0,
                active[i] or 0,
                qdepth[i] or 0,
                dtok[i] or 0,
                pre_ms[i] or 0.0,
                dec_ms[i] or 0.0,
                tps[i] or 0.0,
                occ[i] or 0.0,
                t50[i] or 0.0,
                t95[i] or 0.0,
                t99[i] or 0.0,
                e50[i] or 0.0,
                e95[i] or 0.0,
                e99[i] or 0.0,
                kvb[i] if kvb[i] is not None else -1,
                kvl[i] if kvl[i] is not None else -1,
                kvh[i] if kvh[i] is not None else -1.0,
                ttft_l[i],
                e2e_l[i],
                tok_l[i],
            )
            for i in range(len(v))
        ]
    return tables
