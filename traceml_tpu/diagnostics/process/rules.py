"""Per-process rules
(reference: src/traceml_ai/diagnostics/process/rules.py:35-347,
policy.py:14-41).  The reference's reserved/allocated "overhang" rule is
a CUDA-caching-allocator concept; its TPU analogue is the gap between
the allocator peak and current bytes (freed-but-held headroom), kept as
``DEVICE_MEMORY_OVERHANG``.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Mapping, Sequence

from traceml_tpu.diagnostics.common import (
    confidence_from,
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    DiagnosticIssue,
)
from traceml_tpu.utils.formatting import fmt_bytes


@dataclasses.dataclass(frozen=True)
class ProcessPolicy:
    rss_warn_bytes: int = 48 * 1024**3
    rss_critical_bytes: int = 96 * 1024**3
    # per-process CPU tiers (psutil counts per-core: 400 == 4 cores busy)
    # (reference: process/rules.py:35-347 High/VeryHigh CPU tiers)
    cpu_warn_pct: float = 90.0 * 4
    cpu_critical_pct: float = 90.0 * 8
    device_mem_skew_warn: float = 0.20
    device_mem_skew_critical: float = 0.30
    skew_pressure_gate: float = 0.5
    overhang_ratio: float = 2.0  # peak / current
    overhang_min_frac: float = 0.30  # peak ≥ 30% of capacity


DEFAULT_POLICY = ProcessPolicy()


@dataclasses.dataclass
class ProcessContext:
    # global_rank → process rows
    procs: Dict[int, List[Dict[str, Any]]]
    # (global_rank, device_id) → device rows
    devices: Dict[tuple, List[Dict[str, Any]]]
    policy: ProcessPolicy = DEFAULT_POLICY


def build_process_context(
    proc_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    device_rows: Mapping[tuple, Sequence[Mapping[str, Any]]],
    policy: ProcessPolicy = DEFAULT_POLICY,
) -> ProcessContext:
    return ProcessContext(
        procs={int(k): list(v) for k, v in proc_rows.items()},
        devices={k: list(v) for k, v in device_rows.items()},
        policy=policy,
    )


class HighProcessRSSRule:
    def evaluate(self, ctx: ProcessContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for rank, rows in ctx.procs.items():
            if not rows:
                continue
            rss = rows[-1].get("rss_bytes")
            if not rss or rss < p.rss_warn_bytes:
                continue
            severity = (
                SEVERITY_CRITICAL if rss >= p.rss_critical_bytes else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_PROCESS_RSS",
                    severity=severity,
                    summary=f"Rank {rank} process RSS is {fmt_bytes(rss)}.",
                    action=(
                        "Host memory in the training process: shrink host-side "
                        "caches, avoid retaining numpy copies of device data."
                    ),
                    metric="process_rss",
                    score=float(rss),
                    ranks=[rank],
                )
            )
        return issues


class RankDeviceMemoryImbalanceRule:
    def evaluate(self, ctx: ProcessContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        per_rank: Dict[int, float] = {}
        pressure = 0.0
        for (rank, _dev), rows in ctx.devices.items():
            if not rows:
                continue
            last = rows[-1]
            used = float(last.get("memory_used_bytes") or 0)
            per_rank[rank] = per_rank.get(rank, 0.0) + used
            total = last.get("memory_total_bytes")
            if used and total:
                pressure = max(pressure, used / float(total))
        if len(per_rank) < 2 or pressure < p.skew_pressure_gate:
            return []
        med = statistics.median(per_rank.values())
        if med <= 0:
            return []
        worst = max(per_rank, key=lambda r: per_rank[r])
        skew = (per_rank[worst] - med) / med
        if skew < p.device_mem_skew_warn:
            return []
        severity = (
            SEVERITY_CRITICAL
            if skew >= p.device_mem_skew_critical
            else SEVERITY_WARNING
        )
        return [
            DiagnosticIssue(
                kind="RANK_DEVICE_MEMORY_IMBALANCE",
                severity=severity,
                summary=(
                    f"Rank {worst} uses {skew * 100:.0f}% more device memory "
                    f"than the median rank."
                ),
                action="Check sharding spec symmetry and rank-0-only buffers.",
                metric="process_device_mem_skew",
                score=skew,
                confidence=confidence_from(skew, p.device_mem_skew_warn),
                skew_pct=skew,
                ranks=[worst],
            )
        ]


class DeviceMemoryOverhangRule:
    def evaluate(self, ctx: ProcessContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        issues = []
        for (rank, dev), rows in ctx.devices.items():
            if not rows:
                continue
            last = rows[-1]
            cur = float(last.get("memory_used_bytes") or 0)
            peak = float(last.get("memory_peak_bytes") or 0)
            total = last.get("memory_total_bytes")
            if not total or cur <= 0 or peak <= 0:
                continue
            if peak / cur >= p.overhang_ratio and peak / float(total) >= p.overhang_min_frac:
                issues.append(
                    DiagnosticIssue(
                        kind="DEVICE_MEMORY_OVERHANG",
                        severity=SEVERITY_WARNING,
                        summary=(
                            f"Rank {rank} chip {dev}: allocator peak "
                            f"{fmt_bytes(peak)} is ≥{p.overhang_ratio:.0f}× the "
                            f"steady-state {fmt_bytes(cur)} — a transient "
                            "allocation spike dominates the footprint."
                        ),
                        action=(
                            "Find the spike (often eval/checkpoint or the "
                            "first compiled step) and shave it: remat the "
                            "spiky computation or stage it."
                        ),
                        metric="device_mem_overhang",
                        score=peak / cur,
                        ranks=[rank],
                        evidence={"device_id": dev},
                    )
                )
        return issues


class HighProcessCPURule:
    """HIGH_PROCESS_CPU — a training process burning many host cores
    (reference: process/rules.py:35-347 with VeryHigh tier).  Uses a
    recent mean so one psutil spike doesn't fire it."""

    def evaluate(self, ctx: ProcessContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for rank, rows in ctx.procs.items():
            vals = [
                float(r["cpu_pct"])
                for r in rows[-30:]
                if r.get("cpu_pct") is not None
            ]
            if not vals:
                continue
            cpu = statistics.mean(vals)
            if cpu < p.cpu_warn_pct:
                continue
            severity = (
                SEVERITY_CRITICAL if cpu >= p.cpu_critical_pct else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_PROCESS_CPU",
                    severity=severity,
                    summary=(
                        f"Rank {rank} process burns {cpu:.0f}% CPU "
                        f"(~{cpu / 100:.1f} cores, recent mean)."
                    ),
                    action=(
                        "A compute-hungry training process starves its own "
                        "dataloader workers and the dispatch thread: move "
                        "preprocessing into workers, check for busy-wait "
                        "loops, cap intra-op threads."
                    ),
                    metric="process_cpu_pct",
                    score=cpu / 100.0,
                    ranks=[rank],
                )
            )
        return issues


DEFAULT_RULES = (
    HighProcessRSSRule(),
    HighProcessCPURule(),
    RankDeviceMemoryImbalanceRule(),
    DeviceMemoryOverhangRule(),
)
