"""Tracer-overhead benchmark — the headline metric.

Runs the flagship decoder LM for N steps twice on the real device:

* **untraced** — plain ``jax.jit`` training loop;
* **traced**   — the FULL observability stack: ``init(auto)`` patches,
  ``wrap_step_fn`` (AOT compile attribution), ``trace_step`` envelopes,
  step-memory edges, the runtime agent's sampler thread, and telemetry
  shipped over a real TCP socket to an in-process aggregator sink.

Prints ONE JSON line::

    {"metric": "tracer_step_overhead_pct", "value": <pct>, "unit": "%",
     "vs_baseline": <pct / 1.0>}

``vs_baseline`` is the ratio against the reference's published claim of
"under 1% overhead" (reference README.md:44); the driver target is <2%
(BASELINE.md).  Lower is better; <1.0 beats the reference's claim.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

WARMUP_STEPS = 5
MEASURE_STEPS = 60
_PROBE_TIMEOUT_S = 90


def _device_probe_ok() -> bool:
    """Probe device availability in a SUBPROCESS with a timeout.

    The TPU tunnel can wedge hard enough that ``jax.devices()`` blocks
    for minutes inside C++ (unkillable from Python threads).  Probing in
    a child process keeps this script — and the driver calling it —
    responsive; on probe failure the benchmark re-execs itself on the
    CPU backend so it always emits its one JSON line.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=_PROBE_TIMEOUT_S,
            capture_output=True,
        )
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _reexec_on_cpu() -> int:
    import os
    import subprocess

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TRACEML_BENCH_NO_PROBE"] = "1"
    print(
        "[bench] device backend unreachable; falling back to CPU proxy",
        file=sys.stderr,
    )
    proc = subprocess.run([sys.executable, __file__], env=env)
    return proc.returncode


def _build(cfg_override=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from traceml_tpu.models import ModelConfig, init_train_state, make_train_step

    platform = jax.default_backend()
    if cfg_override is not None:
        cfg = cfg_override
    elif platform == "tpu":
        cfg = ModelConfig(
            vocab_size=16384, hidden=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, max_seq_len=512,
        )
        batch, seq = 8, 512
    else:  # CPU fallback keeps bench runnable anywhere
        cfg = ModelConfig(
            vocab_size=2048, hidden=256, n_layers=2, n_heads=4,
            n_kv_heads=2, max_seq_len=256,
        )
    if platform != "tpu":
        batch, seq = 4, 128
    elif cfg_override is not None:
        batch, seq = 4, 128

    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    train_step = make_train_step(model, tx)
    rng = np.random.default_rng(0)
    batches = [
        jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        for _ in range(8)
    ]
    return model, state, tx, train_step, batches


def _run_loop(step_fn, state, batches, n_steps, bracket=None):
    """Time n_steps; returns (median_step_s, final_state)."""
    import jax

    times = []
    for i in range(n_steps):
        tokens = batches[i % len(batches)]
        t0 = time.perf_counter()
        if bracket is not None:
            with bracket():
                state, metrics = step_fn(state, tokens)
        else:
            state, metrics = step_fn(state, tokens)
        # per-step sync: measures true per-step cost including device
        # time; identical in both arms so the delta is tracer overhead
        jax.block_until_ready(metrics["loss"])
        times.append(time.perf_counter() - t0)
    return statistics.median(times), state


def main() -> int:
    import os

    if os.environ.get("TRACEML_BENCH_NO_PROBE") != "1" and not _device_probe_ok():
        return _reexec_on_cpu()
    import jax

    # ---- build BOTH arms, then measure in INTERLEAVED rounds ----------
    # (sequential arms are biased by machine-load drift; per-round
    # paired deltas with a median are robust to it)
    model, state, tx, train_step, batches = _build()
    plain = jax.jit(train_step, donate_argnums=(0,))
    _, state = _run_loop(plain, state, batches, WARMUP_STEPS)  # compile+warm

    import traceml_tpu
    from traceml_tpu.aggregator.trace_aggregator import TraceMLAggregator
    from traceml_tpu.runtime.identity import RuntimeIdentity
    from traceml_tpu.runtime.runtime import TraceMLRuntime
    from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings
    import tempfile

    tmp = Path(tempfile.mkdtemp(prefix="traceml_bench_"))
    agg_settings = TraceMLSettings(
        session_id="bench", logs_dir=tmp, mode="summary",
        aggregator=AggregatorEndpoint(port=0), expected_world_size=1,
        finalize_timeout_sec=10.0,
    )
    agg = TraceMLAggregator(agg_settings)
    agg.start()
    rt_settings = TraceMLSettings(
        session_id="bench", logs_dir=tmp, mode="summary",
        aggregator=AggregatorEndpoint(port=agg.port or 0),
        sampler_interval_sec=1.0,
    )
    runtime = TraceMLRuntime(rt_settings, RuntimeIdentity(global_rank=0))
    runtime.start()
    traceml_tpu.init(mode="auto")

    model2, state2, tx2, train_step2, batches2 = _build()
    traced = traceml_tpu.wrap_step_fn(train_step2, donate_argnums=(0,))
    _, state2 = _run_loop(
        traced, state2, batches2, WARMUP_STEPS, bracket=traceml_tpu.trace_step
    )

    rounds = 5
    steps_per_round = max(10, MEASURE_STEPS // rounds)
    deltas = []
    u_all, t_all = [], []
    for _ in range(rounds):
        u, state = _run_loop(plain, state, batches, steps_per_round)
        t, state2 = _run_loop(
            traced, state2, batches2, steps_per_round,
            bracket=traceml_tpu.trace_step,
        )
        u_all.append(u)
        t_all.append(t)
        deltas.append((t - u) / u * 100.0)
    runtime.stop()
    agg.stop(finalize_timeout=5.0)

    untraced_s = statistics.median(u_all)
    traced_s = statistics.median(t_all)
    overhead_pct = max(0.0, statistics.median(deltas))
    print(
        f"[bench] untraced {untraced_s * 1000:.2f} ms/step, "
        f"traced {traced_s * 1000:.2f} ms/step on {jax.default_backend()} "
        f"(per-round deltas: {[round(d, 1) for d in deltas]})",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "tracer_step_overhead_pct",
                "value": round(overhead_pct, 3),
                "unit": "%",
                "vs_baseline": round(overhead_pct / 1.0, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
