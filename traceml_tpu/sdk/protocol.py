"""Summary file-IPC protocol
(reference: src/traceml_ai/sdk/protocol.py:48-229).

The worker and the aggregator share only the session directory; the
final-summary request/response is a pair of atomic JSON files in
``<session>/control/``, and the artifacts live at canonical paths.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.utils.atomic_io import atomic_write_json, read_json

REQUEST_FILE = "final_summary_request.json"
RESPONSE_FILE = "final_summary_response.json"
SUMMARY_JSON = "final_summary.json"
SUMMARY_TXT = "final_summary.txt"
SUMMARY_HTML = "final_summary.html"


def control_dir(session_dir: Path) -> Path:
    return Path(session_dir) / "control"


def request_path(session_dir: Path) -> Path:
    return control_dir(session_dir) / REQUEST_FILE


def response_path(session_dir: Path) -> Path:
    return control_dir(session_dir) / RESPONSE_FILE


def get_final_summary_json_path(session_dir: Path) -> Path:
    return Path(session_dir) / SUMMARY_JSON


def get_final_summary_txt_path(session_dir: Path) -> Path:
    return Path(session_dir) / SUMMARY_TXT


def get_final_summary_html_path(session_dir: Path) -> Path:
    return Path(session_dir) / SUMMARY_HTML


def write_summary_request(session_dir: Path, requester_rank: int = 0) -> None:
    atomic_write_json(
        request_path(session_dir),
        {"requested_at": time.time(), "requester_rank": requester_rank},
    )


def read_summary_request(session_dir: Path) -> Optional[Dict[str, Any]]:
    return read_json(request_path(session_dir))


def write_summary_response(
    session_dir: Path, ok: bool, error: Optional[str] = None
) -> None:
    atomic_write_json(
        response_path(session_dir),
        {"completed_at": time.time(), "ok": ok, "error": error},
    )


def read_summary_response(session_dir: Path) -> Optional[Dict[str, Any]]:
    return read_json(response_path(session_dir))


def clear_request(session_dir: Path) -> None:
    try:
        request_path(session_dir).unlink()
    except OSError:
        pass
