"""Wire contract between per-rank runtimes and the aggregator
(reference: src/traceml_ai/telemetry/)."""

from traceml_tpu.telemetry.envelope import (  # noqa: F401
    SCHEMA_V2,
    SCHEMA_VERSION,
    ColumnView,
    SenderIdentity,
    TelemetryEnvelope,
    build_columnar_envelope,
    build_telemetry_envelope,
    columns_to_rows,
    is_columnar_table,
    normalize_telemetry_envelope,
    rows_to_columns,
)
from traceml_tpu.telemetry.control import (  # noqa: F401
    CONTROL_KEY,
    RANK_FINISHED,
    build_rank_finished,
    is_control_message,
    control_kind,
)
