"""Human-readable units (reference: src/traceml_ai/utils/formatting.py)."""

from __future__ import annotations

from typing import Optional

_BYTE_UNITS = ["B", "KiB", "MiB", "GiB", "TiB", "PiB"]


def fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    n = float(n)
    sign = "-" if n < 0 else ""
    n = abs(n)
    for unit in _BYTE_UNITS:
        if n < 1024.0 or unit == _BYTE_UNITS[-1]:
            if unit == "B":
                return f"{sign}{n:.0f} {unit}"
            return f"{sign}{n:.2f} {unit}"
        n /= 1024.0
    return f"{sign}{n:.2f} PiB"


def fmt_ms(ms: Optional[float]) -> str:
    if ms is None:
        return "n/a"
    if ms < 1.0:
        return f"{ms * 1000:.0f} µs"
    if ms < 1000.0:
        return f"{ms:.1f} ms"
    s = ms / 1000.0
    if s < 60:
        return f"{s:.2f} s"
    m, s = divmod(s, 60.0)
    return f"{int(m)}m{s:04.1f}s"


def fmt_pct(frac: Optional[float], *, digits: int = 1) -> str:
    if frac is None:
        return "n/a"
    return f"{frac * 100:.{digits}f}%"


def fmt_count(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    n = float(n)
    for thresh, suffix in ((1e9, "B"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= thresh:
            return f"{n / thresh:.1f}{suffix}"
    return f"{n:.0f}"
