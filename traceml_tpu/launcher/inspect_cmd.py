"""``traceml-tpu inspect`` — decode per-rank msgpack backups
(reference: launcher/commands.py:580-616).

Handles both backup frame formats (see database/database_writer.py):
legacy per-row files print one JSON object per row; envelope files
(v2, ``envelopes.msgpack``) carry multiple tables per frame, so each
row is printed with a ``table`` field naming its origin.

``--domain`` filters to one telemetry domain (table name, e.g.
``collectives``); collectives rows additionally get a derived
``overlap_efficiency`` column (``1 − exposed_ms/duration_ms``, 1.0 for
zero-duration rows) so overlap quality is readable straight off the
backups.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.database.database_writer import iter_backup_tables


def _enrich_row(table: Optional[str], row: Dict[str, Any]) -> Dict[str, Any]:
    """Derived columns per domain.  Collectives: overlap efficiency."""
    if table == "collectives" or (table is None and "exposed_ms" in row):
        try:
            dur = float(row.get("duration_ms", 0.0) or 0.0)
            exp = float(row.get("exposed_ms", 0.0) or 0.0)
            row = dict(row)
            row["overlap_efficiency"] = (
                round(1.0 - exp / dur, 4) if dur > 0 else 1.0
            )
        except Exception:
            pass
    return row


def run_inspect(
    path: Path, limit: int = 20, domain: Optional[str] = None
) -> int:
    path = Path(path)
    files = []
    if path.is_file():
        files = [path]
    elif path.is_dir():
        files = sorted(path.rglob("*.msgpack"))
    if not files:
        print(f"no .msgpack backups under {path}")
        return 1
    matched = 0
    for f in files:
        printed_header = False
        n = 0
        for table, row in iter_backup_tables(f):
            # legacy per-row files carry no table tag; fall back to the
            # file stem so --domain still works on old backups
            effective = table if table is not None else f.stem
            if domain is not None and effective != domain:
                continue
            if not printed_header:
                print(f"── {f}")
                printed_header = True
            row = _enrich_row(effective, row)
            if table is None:
                print(json.dumps(row, default=str))
            else:
                print(json.dumps({"table": table, **row}, default=str))
            matched += 1
            n += 1
            if n >= limit:
                print(f"… (showing first {limit})")
                break
        if domain is None and not printed_header:
            print(f"── {f}")
    if domain is not None and matched == 0:
        print(f"no rows for domain {domain!r} under {path}")
        return 1
    return 0
