"""live_metrics(): the per-step, in-process tracker projection
(vs summary(), which is final-summary file IPC)."""

import tempfile
import time
from pathlib import Path

import jax
import jax.numpy as jnp


def test_live_metrics_without_runtime():
    import traceml_tpu

    out = traceml_tpu.live_metrics()
    # fail-open: just the step counter, never raises
    assert set(out) <= {"traceml/live/step"}


def test_live_metrics_with_runtime(tmp_path):
    import traceml_tpu
    from traceml_tpu.runtime import lifecycle
    from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings

    settings = TraceMLSettings(
        session_id="live", logs_dir=tmp_path, mode="summary",
        aggregator=AggregatorEndpoint(port=1),  # nowhere; client fails open
        sampler_interval_sec=0.1,
    )
    rt = lifecycle.start_runtime(settings)
    assert rt is not None
    try:
        traceml_tpu.init(mode="auto")
        fn = traceml_tpu.wrap_step_fn(lambda x: (x * 2).sum())
        x = jnp.ones((64, 64))
        for _ in range(6):
            with traceml_tpu.trace_step():
                out = fn(x)
            jax.block_until_ready(out)
            time.sleep(0.02)
        deadline = time.monotonic() + 5
        metrics = {}
        while time.monotonic() < deadline:
            metrics = traceml_tpu.live_metrics()
            if "traceml/live/step_time_ms" in metrics:
                break
            time.sleep(0.1)
        assert metrics["traceml/live/step"] >= 6
        assert metrics["traceml/live/step_time_ms"] > 0
        assert "traceml/live/compute_time_ms" in metrics
        # every value is a plain scalar (logger-safe)
        assert all(isinstance(v, (int, float)) for v in metrics.values())
    finally:
        lifecycle.stop_runtime()
