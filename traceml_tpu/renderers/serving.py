"""Serving tier: version-keyed payload publication for N viewers
(docs/developer_guide/serving-tier.md).

One :class:`SessionPublisher` per live session owns the session's
``LiveComputer`` and a cache of serialized payload fragments keyed on the
snapshot store's per-domain ``data_version`` counters.  However many
dashboard tabs, delta pollers, or SSE streams are attached, each fragment
is rebuilt and JSON-encoded at most once per version change:

- ``poll()`` refreshes the store (rate-limited by ``min_poll_interval``
  so M concurrent viewers collapse to ~1 store refresh per interval),
  rebuilds only fragments whose dep versions advanced, and bumps a
  fragment's published version only when its serialized bytes actually
  changed (content compare — a store write that doesn't alter the view
  publishes nothing).
- the **version token** ``"{PAYLOAD_VERSION}:v.v.v..."`` carries every
  fragment's published version in ``FRAGMENT_ORDER`` position.  Clients
  echo it back (``?since=`` or SSE ``Last-Event-ID``) and receive only
  fragments whose version differs — after ANY gap, a stale token simply
  selects more fragments, so reconnect resume needs no server-side
  event log.
- delta and full bodies are assembled by splicing the cached
  per-fragment bytes (no re-serialization); the full body and its gzip
  form are additionally cached for ``full_ttl`` seconds so every viewer
  inside one UI tick shares identical bytes.

Publishers live in a keyed, LRU-bounded module cache (``publisher_for``)
— the replacement for the old ``web_payload._computers`` global that
closed every cached computer whenever a *different* session polled.
"""

from __future__ import annotations

import gzip
import json
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

from traceml_tpu.renderers.compute import LiveComputer
from traceml_tpu.renderers.web_payload import (
    FRAGMENT_DEPS,
    FRAGMENT_ORDER,
    PAYLOAD_VERSION,
    build_fragment,
)

#: responses smaller than this are not worth gzipping
GZIP_MIN_BYTES = 256


def parse_token(token: Optional[str]) -> Optional[Dict[str, int]]:
    """Version token → {fragment: version}, or None when absent/garbled/
    from another payload generation (caller then serves everything)."""
    if not token:
        return None
    try:
        gen, sep, rest = token.partition(":")
        if not sep or int(gen) != PAYLOAD_VERSION:
            return None
        parts = rest.split(".")
        if len(parts) != len(FRAGMENT_ORDER):
            return None
        return {n: int(v) for n, v in zip(FRAGMENT_ORDER, parts)}
    except (TypeError, ValueError):
        return None


class SessionPublisher:
    """Owns one session's computer + serialized-fragment cache; thread-safe
    (every HTTP handler thread of the serving tier reads through it)."""

    def __init__(
        self, db_path: Path, session: str, window_steps: int = 150
    ) -> None:
        self.db_path = Path(db_path)
        self.session = session
        self.window_steps = window_steps
        self._computer = LiveComputer(self.db_path, window_steps=window_steps)
        self._cond = threading.Condition(threading.RLock())
        #: minimum seconds between store refreshes — M viewers polling in
        #: one interval share a single refresh (tests/benches may set 0)
        self.min_poll_interval = 0.2
        #: assembled full body reuse window (~one UI tick); bounds how
        #: stale the ``ts`` stamp shared between viewers can get, well
        #: under the dashboard's 5 s staleness badge threshold
        self.full_ttl = 0.5
        self._last_poll = 0.0
        self._frag_versions: Dict[str, int] = {n: 0 for n in FRAGMENT_ORDER}
        self._frag_objs: Dict[str, Dict[str, Any]] = {}
        self._frag_bytes: Dict[str, bytes] = {}
        self._computed_deps: Dict[str, Tuple[int, ...]] = {}
        # [token, built_at_monotonic, raw, gzip-or-None]
        self._full_cache: Optional[list] = None
        self._closed = False
        self.stats: Dict[str, Any] = {
            "polls": 0,
            "builds": {n: 0 for n in FRAGMENT_ORDER},
            "publishes": {n: 0 for n in FRAGMENT_ORDER},
            "full_assemblies": 0,
        }

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    @property
    def token(self) -> str:
        with self._cond:
            return f"{PAYLOAD_VERSION}:" + ".".join(
                str(self._frag_versions[n]) for n in FRAGMENT_ORDER
            )

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._computer.close()

    # -- publication -----------------------------------------------------

    def poll(self, force: bool = False) -> str:
        """Refresh the store and republish any fragment whose content
        changed.  Rate-limited; returns the current version token."""
        with self._cond:
            if self._closed:
                return self.token
            now = time.monotonic()
            if (
                not force
                and self._frag_bytes
                and now - self._last_poll < self.min_poll_interval
            ):
                return self.token
            self._last_poll = now
            self.stats["polls"] += 1
            payload, versions = self._computer.payload_with_versions()
            changed = False
            for name in FRAGMENT_ORDER:
                deps = FRAGMENT_DEPS.get(name)
                if deps is not None:
                    at = tuple(versions[d] for d in deps)
                    if self._computed_deps.get(name) == at:
                        continue
                elif name == "header" and name in self._frag_bytes:
                    continue  # constant after first build
                obj = build_fragment(
                    name, payload, session=self.session, db_path=self.db_path
                )
                t0 = time.perf_counter_ns()
                raw = json.dumps(obj).encode("utf-8")
                ser_ns = time.perf_counter_ns() - t0
                try:  # profiling is garnish — never fail a publish over it
                    self._computer._store.tick_profile.note_stage(
                        name, "serialize", ser_ns
                    )
                except Exception:
                    pass
                self.stats["builds"][name] += 1
                if deps is not None:
                    self._computed_deps[name] = at
                if raw != self._frag_bytes.get(name):
                    self._frag_objs[name] = obj
                    self._frag_bytes[name] = raw
                    self._frag_versions[name] += 1
                    self.stats["publishes"][name] += 1
                    changed = True
            if changed:
                self._full_cache = None
                self._cond.notify_all()
            return self.token

    def _changed_names(self, since: Optional[str]) -> list:
        since_v = parse_token(since)
        if since_v is None:
            return [n for n in FRAGMENT_ORDER if n in self._frag_bytes]
        return [
            n
            for n in FRAGMENT_ORDER
            if n in self._frag_bytes
            and since_v.get(n) != self._frag_versions[n]
        ]

    def wait_for_change(self, since: Optional[str], timeout: float) -> bool:
        """Block until some fragment's version differs from ``since`` (or
        timeout).  The publisher is pull-driven, so this re-polls in
        slices rather than waiting purely on the condition."""
        deadline = time.monotonic() + timeout
        slice_s = max(self.min_poll_interval, 0.02)
        while True:
            self.poll()
            with self._cond:
                if self._closed or self._changed_names(since):
                    return not self._closed
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(slice_s, remaining))

    # -- response bodies -------------------------------------------------

    def delta_body(
        self, since: Optional[str]
    ) -> Tuple[Optional[bytes], str]:
        """(delta JSON bytes or None when nothing moved, current token).

        The body is spliced from the cached per-fragment bytes:
        ``{"token": ..., "ts": ..., "fragments": {name: <cached>, ...}}``.
        """
        self.poll()
        with self._cond:
            token = self.token
            changed = self._changed_names(since)
            if not changed:
                return None, token
            head = json.dumps(
                {"token": token, "ts": time.time()}
            ).encode("utf-8")
            parts = [
                b'"' + n.encode("ascii") + b'": ' + self._frag_bytes[n]
                for n in changed
            ]
            body = (
                head[:-1]
                + b', "fragments": {'
                + b", ".join(parts)
                + b"}}"
            )
            return body, token

    def _assemble_full(self) -> bytes:
        # historical flat key order: version, session, ts, <domains...>;
        # inner bytes are obj_bytes[1:-1] joined with json's default
        # ", " separator — byte-identical to a single json.dumps
        parts = [self._frag_bytes["header"][1:-1]]
        parts.append(json.dumps({"ts": time.time()}).encode("utf-8")[1:-1])
        for name in FRAGMENT_ORDER:
            if name == "header":
                continue
            inner = self._frag_bytes[name][1:-1]
            if inner:  # meta serializes to {} when absent — skip
                parts.append(inner)
        return b"{" + b", ".join(parts) + b"}"

    def full_body(
        self, accept_gzip: bool = False
    ) -> Tuple[bytes, str, Optional[str]]:
        """(body bytes, version token, content-encoding or None).  The
        assembled body (and its gzip form) is shared by every viewer for
        ``full_ttl`` seconds — only the ``ts`` stamp goes stale."""
        self.poll()
        with self._cond:
            token = self.token
            now = time.monotonic()
            if (
                self._full_cache is None
                or self._full_cache[0] != token
                or now - self._full_cache[1] > self.full_ttl
            ):
                self._full_cache = [token, now, self._assemble_full(), None]
                self.stats["full_assemblies"] += 1
            cache = self._full_cache
            if accept_gzip and len(cache[2]) >= GZIP_MIN_BYTES:
                if cache[3] is None:
                    cache[3] = gzip.compress(cache[2], mtime=0)
                return cache[3], token, "gzip"
            return cache[2], token, None

    def fragment(self, name: str) -> Optional[Dict[str, Any]]:
        """Current cached object for one fragment (fleet index peeks at
        ``diagnosis`` without assembling a whole payload)."""
        self.poll()
        with self._cond:
            return self._frag_objs.get(name)

    def full_payload_dict(self) -> Dict[str, Any]:
        """The flat payload as a dict (``build_web_payload`` compat) —
        composed from the cached fragment objects, same key order as the
        assembled JSON body."""
        self.poll()
        with self._cond:
            out: Dict[str, Any] = dict(self._frag_objs["header"])
            out["ts"] = time.time()
            for name in FRAGMENT_ORDER:
                if name != "header":
                    out.update(self._frag_objs[name])
            return out


# -- keyed, LRU-bounded publisher cache ----------------------------------
# Replaces web_payload's old module-global that supported exactly one
# session per process (different db_path → close EVERYTHING).  Keyed on
# (db_path, session, window_steps); the least-recently-used publisher is
# closed when the bound is exceeded.  An evicted publisher that a request
# thread still holds serves that one response from its closed computer
# (degraded, not crashed) — the next request re-fetches through the cache.

_publishers: "OrderedDict[Tuple[str, str, int], SessionPublisher]" = (
    OrderedDict()
)
_publishers_lock = threading.Lock()
_max_publishers = 8


def set_max_publishers(n: int) -> None:
    global _max_publishers
    with _publishers_lock:
        _max_publishers = max(1, int(n))


def publisher_for(
    db_path: Path,
    session: str,
    window_steps: int = 150,
    max_publishers: Optional[int] = None,
) -> SessionPublisher:
    key = (str(Path(db_path)), session, int(window_steps))
    evicted = []
    with _publishers_lock:
        pub = _publishers.get(key)
        if pub is not None and not pub.closed:
            _publishers.move_to_end(key)
            return pub
        pub = SessionPublisher(
            Path(db_path), session, window_steps=window_steps
        )
        _publishers[key] = pub
        limit = (
            max(1, int(max_publishers))
            if max_publishers is not None
            else _max_publishers
        )
        while len(_publishers) > limit:
            _, old = _publishers.popitem(last=False)
            evicted.append(old)
    for old in evicted:
        old.close()
    return pub


def close_all_publishers() -> None:
    """Close and drop every cached publisher (tests / aggregator stop)."""
    with _publishers_lock:
        pubs = list(_publishers.values())
        _publishers.clear()
    for pub in pubs:
        pub.close()
