"""Transport selection: shm ring / UDS / TCP, automatic with override.

Policy (``TRACEML_TRANSPORT``, declared in config/flags.py):

``auto``  same-host detect (the aggregator connect host is loopback) →
          shm ring; else UDS when an explicit socket path was given;
          else TCP.  Any fast-path setup failure falls through to the
          next tier and ultimately to TCP — the pure-Python TCP path is
          the golden fallback, mirroring the ColumnarFallback pattern.
``shm``   force the ring (setup failure still falls back to TCP rather
          than dropping telemetry).
``uds``   force the Unix-domain stream.
``tcp``   force plain TCP — byte-for-byte the pre-transport-tier
          behavior: no UDS listener, no ring registry, no compression
          unless explicitly forced, 0.5 s selector tick.

Compression (``TRACEML_TRANSPORT_COMPRESS``): ``auto`` enables the best
available codec only on a cross-host TCP link (loopback and same-host
fast paths gain nothing from shrinking bytes that never leave the
machine); an explicit codec name forces it on any stream transport;
shm frames are never compressed (the ring IS the same host).

The selection is rank-side; the aggregator side mirrors it with
:func:`server_transport_config` so both ends of the contract read the
same flags.  Everything here is cheap and fail-open: a broken fast
path must degrade to TCP, never into training code.
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

from traceml_tpu.transport import compression
from traceml_tpu.transport.tcp_transport import TCPClient, UDSClient
from traceml_tpu.utils.error_log import get_error_log

_LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1", "0.0.0.0")


def is_same_host(connect_host: str) -> bool:
    """True when the aggregator is reachable without leaving the machine
    (the launcher's default single-host topology)."""
    return str(connect_host).strip().lower() in _LOOPBACK_HOSTS


def default_uds_path(session_dir: Path) -> str:
    """Deterministic socket path both ends derive from the session dir.

    Short (AF_UNIX paths are capped at ~107 bytes and pytest tmp session
    dirs routinely blow past that) and collision-free per (session, uid)
    via digest.
    """
    digest = hashlib.sha1(
        f"{Path(session_dir).resolve()}:{os.getuid()}".encode()
    ).hexdigest()[:12]
    return f"/tmp/traceml-{digest}.sock"


def choose_transport(
    transport: str, connect_host: str, uds_path: Optional[str]
) -> str:
    """Resolve the configured transport mode to a concrete kind."""
    mode = (transport or "auto").strip().lower()
    if mode in ("tcp", "uds", "shm"):
        return mode
    if is_same_host(connect_host):
        return "shm"
    if uds_path:
        return "uds"
    return "tcp"


def resolve_compression(
    transport_kind: str, requested: str, connect_host: str = ""
) -> Optional[str]:
    """The codec the publisher should wrap envelopes with, or None."""
    req = (requested or "auto").strip().lower()
    if req in ("", "0", "false", "off", "none"):
        return None
    if transport_kind == "shm":
        # same-page-cache delivery: compressing would only add CPU
        return None
    if req in ("auto", "1", "true", "yes", "on"):
        # auto: only a genuinely cross-host TCP link pays per byte —
        # loopback TCP (incl. the forced TRACEML_TRANSPORT=tcp arm)
        # stays byte-identical to the pre-transport-tier wire
        if transport_kind != "tcp" or is_same_host(connect_host):
            return None
        return compression.resolve_codec("auto")
    return compression.resolve_codec(req)


def create_transport_client(
    settings: Any, global_rank: int
) -> Tuple[Optional[TCPClient], Dict[str, Any]]:
    """Build the rank-side telemetry client for ``settings``.

    Returns ``(client, info)`` where ``info`` carries ``kind``,
    ``compression`` (codec name or None), and ``fallback_from`` when a
    fast path failed setup and the tier below took over.  The client
    quacks like :class:`TCPClient` for everything the publisher and
    DurableSender touch.
    """
    connect_host = settings.aggregator.connect_host
    port = settings.aggregator.port
    if not port:
        return None, {"kind": None, "compression": None}
    kind = choose_transport(
        getattr(settings, "transport", "auto"),
        connect_host,
        getattr(settings, "uds_path", None),
    )
    info: Dict[str, Any] = {"kind": kind, "compression": None}
    client: Optional[TCPClient] = None
    if kind == "shm":
        try:
            from traceml_tpu.transport import shm_ring

            shm_dir = getattr(settings, "shm_dir", None)
            path = shm_ring.ring_segment_path(
                settings.session_dir,
                global_rank,
                Path(shm_dir) if shm_dir else None,
            )
            client = shm_ring.ShmRingClient(  # type: ignore[assignment]
                path,
                capacity=getattr(settings, "shm_ring_bytes", None),
                session_dir=settings.session_dir,
                global_rank=global_rank,
            )
        except Exception as exc:
            # fallback-on-attach-failure: degrade to the golden path
            get_error_log().warning(
                "shm ring setup failed; falling back to tcp", exc
            )
            info["fallback_from"] = "shm"
            kind = "tcp"
            info["kind"] = "tcp"
    if kind == "uds":
        path = getattr(settings, "uds_path", None) or default_uds_path(
            settings.session_dir
        )
        client = UDSClient(path)
    elif client is None:
        client = TCPClient(connect_host, port)
        info["kind"] = kind = "tcp"
    info["compression"] = resolve_compression(
        kind, getattr(settings, "transport_compress", "auto"), connect_host
    )
    return client, info


def server_transport_config(settings: Any) -> Dict[str, Any]:
    """The aggregator-side mirror of the selection: which extra
    listeners/registries the ingest server should stand up.

    ``tcp`` mode returns the empty config — the server is then
    byte-for-byte the pre-transport-tier TCPServer.
    """
    mode = (getattr(settings, "transport", "auto") or "auto").strip().lower()
    out: Dict[str, Any] = {"uds_path": None, "enable_rings": False}
    if mode == "tcp":
        return out
    if mode in ("auto", "uds"):
        out["uds_path"] = getattr(settings, "uds_path", None) or default_uds_path(
            settings.session_dir
        )
    if mode in ("auto", "shm"):
        out["enable_rings"] = True
    return out
