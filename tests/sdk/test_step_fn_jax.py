"""Real-JAX integration tests for wrap_step_fn on the CPU backend."""

import time

import pytest

from traceml_tpu.sdk import state as state_mod
from traceml_tpu.sdk.instrumentation import trace_step
from traceml_tpu.sdk.step_fn import wrap_step_fn
from traceml_tpu.utils.timing import (
    COMPILE_TIME,
    COMPUTE_TIME,
    GLOBAL_STEP_QUEUE,
    STEP_TIME,
)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_state():
    st = state_mod.reset_state_for_tests()
    from traceml_tpu.utils.step_memory import FakeMemoryBackend, StepMemoryTracker

    st.mem_tracker = StepMemoryTracker(FakeMemoryBackend([[]]))
    GLOBAL_STEP_QUEUE.drain()
    yield st
    GLOBAL_STEP_QUEUE.drain()


def _loss_fn(w, x):
    return jnp.sum((x @ w) ** 2)


def test_compile_then_hit_emits_phases(fresh_state):
    step = wrap_step_fn(lambda w, x: (w - 0.01 * jax.grad(_loss_fn)(w, x),))
    w = jnp.ones((8, 8))
    x = jnp.ones((4, 8))
    with trace_step():
        (w,) = step(w, x)
    with trace_step():
        (w,) = step(w, x)
    batches = GLOBAL_STEP_QUEUE.drain()
    assert len(batches) == 2
    names0 = [e.name for e in batches[0].events]
    names1 = [e.name for e in batches[1].events]
    # first step: compile + compute + envelope; second: no compile
    assert COMPILE_TIME in names0
    assert COMPUTE_TIME in names0
    assert STEP_TIME in names0
    assert COMPILE_TIME not in names1
    assert COMPUTE_TIME in names1
    assert step.compile_count >= 1
    comp = next(e for e in batches[0].events if e.name == COMPILE_TIME)
    assert comp.meta["backend_compile_ms"] > 0
    assert "fun_name" in comp.meta


def test_recompile_on_new_shape(fresh_state):
    step = wrap_step_fn(lambda w, x: (w @ w) * x.sum())
    w = jnp.ones((64, 64))
    xa = jnp.ones((2, 4))
    xb = jnp.ones((3, 4))
    with trace_step():
        step(w, xa)
    with trace_step():
        step(w, xb)  # new shape → recompile
    with trace_step():
        step(w, xa)  # cache hit (and xa/xb ops already compiled)
    batches = GLOBAL_STEP_QUEUE.drain()

    def compiles(b):
        return [e for e in b.events if e.name == COMPILE_TIME]

    assert compiles(batches[0]), "first call must emit a compile event"
    assert compiles(batches[1]), "new input shape must emit a compile event"
    assert not compiles(batches[2]), "cache hit must not emit compile events"


def test_markers_resolve_and_device_times_appear(fresh_state):
    step = wrap_step_fn(lambda w: (w @ w).sum())
    w = jnp.ones((64, 64))
    with trace_step():
        step(w)
    batch = GLOBAL_STEP_QUEUE.drain()[0]
    deadline = time.monotonic() + 5
    while not batch.resolved() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert batch.resolved()
    compute = next(e for e in batch.events if e.name == COMPUTE_TIME)
    assert compute.device_ready_at is not None
    step_ev = next(e for e in batch.events if e.name == STEP_TIME)
    # step envelope inherits the last dispatch's marker via mark_step_outputs
    assert step_ev.marker is not None


def test_prejitted_fn_accepted(fresh_state):
    # a fresh heavy shape so its compile clears the emission threshold
    jitted = jax.jit(lambda x: jnp.tanh(x @ x).sum() * 2)
    step = wrap_step_fn(jitted)
    with trace_step():
        out = step(jnp.ones((96, 96)))
    assert float(out) != 0.0
    batch = GLOBAL_STEP_QUEUE.drain()[0]
    names = [e.name for e in batch.events]
    assert COMPUTE_TIME in names
    # pre-jitted fns get compile attribution through the listener too
    assert COMPILE_TIME in names


def test_wrapper_survives_broken_compile_tracker(fresh_state, monkeypatch):
    import traceml_tpu.instrumentation.compile_tracker as ct

    monkeypatch.setattr(ct, "install_compile_tracker", lambda: False)
    step = wrap_step_fn(lambda x: x + 1)
    x = jnp.ones((4,))
    with trace_step():
        out = step(x)
    assert float(out[0]) == 2.0


def test_donate_argnums_passthrough(fresh_state):
    step = wrap_step_fn(lambda w, x: w + x, donate_argnums=(0,))
    w = jnp.ones((8,))
    with trace_step():
        out = step(w, jnp.ones((8,)))
    assert float(out[0]) == 2.0


def test_h2d_patch_times_device_put(fresh_state):
    import numpy as np

    from traceml_tpu.instrumentation.patches.jax_h2d_patch import (
        patch_jax_h2d,
        unpatch_jax_h2d,
    )
    from traceml_tpu.utils.timing import H2D_TIME

    st = fresh_state
    try:
        assert patch_jax_h2d()
        with trace_step():
            arr = jax.device_put(np.ones((16, 16)))
            _ = arr.sum()
        batch = GLOBAL_STEP_QUEUE.drain()[0]
        names = [e.name for e in batch.events]
        assert H2D_TIME in names
        # device→device put must NOT be timed as h2d
        with trace_step():
            jax.device_put(arr)
        batch2 = GLOBAL_STEP_QUEUE.drain()[0]
        assert H2D_TIME not in [e.name for e in batch2.events]
    finally:
        unpatch_jax_h2d()


def test_h2d_patch_inert_under_jit(fresh_state):
    from traceml_tpu.instrumentation.patches.jax_h2d_patch import (
        patch_jax_h2d,
        unpatch_jax_h2d,
    )

    st = fresh_state
    try:
        patch_jax_h2d()

        @jax.jit
        def f(x):
            return jax.device_put(x) + 1  # tracer → passthrough

        with trace_step():
            out = f(jnp.ones((4,)))
        assert float(out[0]) == 2.0
    finally:
        unpatch_jax_h2d()
