"""Vectorized gate arm for the step_memory diagnosis pack.

Memory series reads (``latest_pressure`` / ``last_used``) are O(1)
tail-row lookups on the int-column rings, so the per-series loops stay
scalar; what vectorizes is ImbalanceRule's cross-rank aggregation —
median / first-argmax worst rank / skew over the per-rank byte map —
with ``np.median`` matching ``statistics.median`` and ``np.argmax``
matching the scalar first-max tie-break bit-for-bit.

``enabled()`` is the pack's kill-switch gate
(``TRACEML_VECTOR_DIAGNOSIS=0`` forces the scalar reference arm); the
helper returns ``None`` and counts a fallback instead of logging when
it cannot reproduce the scalar loop.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from traceml_tpu.utils.columnar import (
    note_vector_fallback,
    vector_diagnosis_enabled,
)

DOMAIN = "step_memory"


def enabled() -> bool:
    return vector_diagnosis_enabled()


def median_worst_skew(
    per_rank: Dict[int, float],
) -> Optional[Tuple[float, int, float]]:
    """ImbalanceRule's cross-rank scan: (median bytes, worst rank via
    first-max tie-break, skew vs the median).  Caller guards
    ``len >= 2``; a non-positive median returns skew 0.0 and the caller
    bails exactly like the scalar arm.  ``None`` → scalar arm."""
    try:
        ranks = list(per_rank)
        vals = np.asarray(list(per_rank.values()), dtype=np.float64)
        med = float(np.median(vals))
        widx = int(np.argmax(vals))
        worst_rank = ranks[widx]
        skew = ((float(vals[widx]) - med) / med) if med > 0 else 0.0
        return med, worst_rank, skew
    except Exception:
        note_vector_fallback(DOMAIN)
        return None
