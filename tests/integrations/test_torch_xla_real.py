"""Real-package torch-xla smoke — runs ONLY where torch_xla is actually
installed (the CI e2e lane attempts a guarded CPU-wheel install; this
image has no network, so locally these skip).  The fake-backed e2e and
the FAKES.md contract tests carry the behavior coverage; this file
exists so the day a real wheel is present, the patch surfaces are
exercised against it with zero extra wiring (VERDICT r4 item 4).
"""

import importlib.util
import os
import sys
from pathlib import Path

import pytest

FAKES = str(Path(__file__).resolve().parents[1] / "fakes")


def _real_torch_xla_present() -> bool:
    spec = importlib.util.find_spec("torch_xla")
    if spec is None or spec.origin is None:
        return False
    return not spec.origin.startswith(FAKES)


pytestmark = pytest.mark.skipif(
    not _real_torch_xla_present(),
    reason="real torch_xla not installed (guarded CI install only)",
)

if _real_torch_xla_present():
    # must be set BEFORE the first device op initializes the PJRT
    # runtime, and for EVERY test in this module (the CI lane sets
    # jax-CPU knobs, not torch-xla's)
    os.environ.setdefault("PJRT_DEVICE", "CPU")


def test_real_patch_mark_step_installs_and_reverts():
    from traceml_tpu.instrumentation.torch_xla_support import (
        patch_mark_step,
        unpatch_mark_step,
    )

    import torch_xla.core.xla_model as xm

    assert patch_mark_step()
    assert hasattr(xm.mark_step, "_traceml_original")
    unpatch_mark_step()
    assert not hasattr(xm.mark_step, "_traceml_original")


def test_real_memory_backend_shape():
    from traceml_tpu.instrumentation.torch_xla_support import XlaMemoryBackend

    try:
        rows = XlaMemoryBackend().sample()
    except RuntimeError as exc:
        pytest.skip(f"torch_xla runtime exposes no devices here: {exc}")
    if not rows:
        # sample() fails open per device; CPU wheels commonly raise
        # from get_memory_info (TPU-only in many versions) — that is a
        # real-runtime limitation, not a backend bug
        pytest.skip("get_memory_info unavailable on this runtime/device")
    for row in rows:
        assert row["current_bytes"] >= 0
        assert {"device_id", "device_kind", "peak_bytes"} <= set(row)


def test_real_identity_calls():
    import torch_xla.core.xla_model as xm

    assert isinstance(xm.get_ordinal(), int)
    if "torch_xla.runtime" in sys.modules or importlib.util.find_spec(
        "torch_xla.runtime"
    ):
        import torch_xla.runtime as xr

        assert isinstance(xr.world_size(), int)
