"""Step-memory diagnosis entrypoint
(reference: src/traceml_ai/diagnostics/step_memory/api.py:136-754)."""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from traceml_tpu.diagnostics.common import DiagnosticResult, run_rules
from traceml_tpu.diagnostics.step_memory.policy import DEFAULT_POLICY, StepMemoryPolicy
from traceml_tpu.diagnostics.step_memory.rules import (
    DEFAULT_RULES,
    build_memory_context,
    build_memory_context_from_columns,
)
from traceml_tpu.utils.columnar import MemoryColumns

DOMAIN = "step_memory"


def diagnose_rank_rows(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
) -> DiagnosticResult:
    ctx = build_memory_context(rank_rows, policy)
    return run_rules(DOMAIN, DEFAULT_RULES, ctx)


def diagnose_columns(
    rank_columns: Mapping[int, MemoryColumns],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
) -> DiagnosticResult:
    """Columnar fast path: diagnose straight from the snapshot store's
    per-rank memory ring buffers (no row-dict walk)."""
    ctx = build_memory_context_from_columns(rank_columns, policy)
    return run_rules(DOMAIN, DEFAULT_RULES, ctx)
