"""The bench's device-timing physicality gate.

A tunneled PJRT client can report buffers ready on enqueue, which makes
``block_until_ready`` a no-op and turns "step time" into dispatch
throughput — the resulting overhead ratio is tunnel-latency noise, not a
tracer measurement.  ``bench.py`` refuses to certify any device timing
whose implied FLOP/s exceeds what one chip can physically sustain.
"""

import bench


class _Leaf:
    def __init__(self, size):
        self.size = size


class _State:
    def __init__(self, n_params):
        self.params = {"w": _Leaf(n_params)}


def test_impossible_throughput_rejected():
    # 150M params, 8192 tokens → ~7.4 TFLOP/step; 5 ms (ABOVE the
    # min-step floor, so this exercises the FLOP/s branch, not the
    # floor) implies ~1.5 PFLOP/s — past any single chip
    flops = bench._step_flops(_State(150_000_000), [_Batch(16, 512)])
    assert flops == 6.0 * 150_000_000 * 16 * 512
    assert 5e-3 >= bench._DEVICE_MIN_STEP_S
    assert not bench._device_measurement_physical(5e-3, flops)


def test_realistic_throughput_accepted():
    # the same step at 40 ms implies ~185 TFLOP/s — a real chip
    flops = bench._step_flops(_State(150_000_000), [_Batch(16, 512)])
    assert bench._device_measurement_physical(40e-3, flops)


def test_sub_floor_steps_rejected_even_if_flops_ok():
    # tiny model, tiny step: physically possible FLOP/s but far below
    # the noise floor where a % overhead claim means anything
    flops = bench._step_flops(_State(1_000), [_Batch(1, 8)])
    assert not bench._device_measurement_physical(1e-3, flops)


class _Batch:
    def __init__(self, b, s):
        self.shape = (b, s)
