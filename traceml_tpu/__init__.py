"""traceml-tpu — TPU-native training observability.

A ground-up, TPU-first framework with the capabilities of TraceML
(reference: /root/reference/src/traceml_ai): wrap an unmodified JAX
(Flax/Optax/pjit) or torch training script, split every training step into
phases (input wait, h2d/infeed, compute, compile, optimizer, residual),
sample per-chip memory and host counters, ship per-rank telemetry to an
out-of-process aggregator, and emit rule-based diagnoses plus a
``final_summary.json`` artifact.

The public API is a lazy facade (reference: src/traceml_ai/__init__.py:50-61)
so that ``import traceml_tpu`` never imports jax/torch eagerly — import cost
and fail-open behavior matter more than convenience here.
"""

from traceml_tpu.version import __version__

# NOTE: grows as the SDK lands; every symbol here must resolve via api.py.
_API_SYMBOLS = (
    "init",
    "start",
    "trace_step",
    "trace_time",
    "summary",
    "final_summary",
    "live_metrics",
    "wrap_dataloader",
    "wrap_step_fn",
    "wrap_h2d",
    "wrap_forward",
    "wrap_backward",
    "wrap_optimizer",
    "wrap_collective",
    "instrument_collective",
    "patch_lax_collectives",
    "record_collective",
    "wrap_checkpoint",
    "instrument_generate",
    "record_request_enqueued",
    "record_prefill_start",
    "record_prefill_end",
    "record_decode_token",
    "record_request_finished",
    "current_step",
    "enable_ici_stats",
    "request_profile",
    "set_step_flops",
    "set_step_tokens",
)

__all__ = list(_API_SYMBOLS) + ["__version__"]


def __getattr__(name):
    if name in _API_SYMBOLS:
        from traceml_tpu import api

        return getattr(api, name)
    raise AttributeError(f"module 'traceml_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_API_SYMBOLS))
