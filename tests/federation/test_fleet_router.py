"""Fleet-router correctness: delta replay through the router
byte-identical to direct shard access, edge-cache semantics, hostile-id
rejection at the edge, shard-down degradation, SSE resume across a
router restart, and hop compression
(docs/developer_guide/federation.md)."""

from __future__ import annotations

import http.client
import json
import time
import types
import zlib

import pytest

from traceml_tpu.aggregator.display_drivers.browser import (
    BrowserDisplayDriver,
    wait_until_ready,
)
from traceml_tpu.federation.router import FleetRouter
from traceml_tpu.renderers import serving

from tests.display.test_browser_driver import _make_session_db
from tests.display.test_serving_delta import (
    _read_sse_event,
    _write_rows,
)


@pytest.fixture(autouse=True)
def _fresh_publishers():
    serving.close_all_publishers()
    yield
    serving.close_all_publishers()


def _start_shard(logs_dir, session="dash"):
    """One aggregator shard: a browser driver over logs_dir/<session>."""
    session_dir = logs_dir / session
    session_dir.mkdir(parents=True, exist_ok=True)
    if not (session_dir / "telemetry.sqlite").exists():
        _make_session_db(session_dir)
    db = session_dir / "telemetry.sqlite"
    ctx = types.SimpleNamespace(
        db_path=db,
        settings=types.SimpleNamespace(
            session_id=session,
            session_dir=session_dir,
            logs_dir=logs_dir,
            serve_max_sessions=8,
        ),
    )
    driver = BrowserDisplayDriver(port=0)
    driver.sse_wait_slice = 0.02
    driver.sse_heartbeat_sec = 0.2
    driver.start(ctx)
    assert driver.port and wait_until_ready("127.0.0.1", driver.port, 5.0)
    serving.publisher_for(db, session).min_poll_interval = 0
    return driver, db


def _start_router(ports, cache_ttl=0.0, probe=True, **kw):
    router = FleetRouter(
        shards=[f"127.0.0.1:{p}" for p in ports],
        cache_ttl=cache_ttl,
        probe_s=600.0,  # tests drive probes explicitly
        **kw,
    )
    router.start()
    assert router.port
    if probe:
        for shard in router.ring.shards:
            router.health.probe(shard)
    return router


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _triple(result):
    """(status, body, token) — the client-visible serving contract."""
    status, headers, body = result
    return status, body, headers.get("X-TraceML-Token")


def _canon_triple(result):
    """Like _triple but with the per-build ``ts`` stamp stripped from the
    JSON body — delta bodies are rebuilt per request with a fresh ts
    (full bodies are cached per version and stay byte-compared)."""
    status, body, token = _triple(result)
    if body:
        payload = {
            k: v for k, v in json.loads(body).items() if k != "ts"
        }
        body = json.dumps(payload, sort_keys=True)
    return status, body, token


# -- delta replay equivalence ----------------------------------------------

def test_replay_through_router_matches_direct(tmp_path):
    """Full → writes → delta → dropped rounds → garbled token: at every
    step the router's answer is byte-identical to the shard's."""
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port])
    try:
        q = "/api/live?session=dash"
        direct = _triple(_get(driver.port, q))
        routed = _triple(_get(router.port, q))
        assert routed == direct
        token = direct[2]

        # version advances; delta from the old token (deltas are
        # rebuilt per request with a fresh ts — compare canonical form)
        _write_rows(db, step0=40)
        dq = f"{q}&since={token}"
        direct_d = _canon_triple(_get(driver.port, dq))
        routed_d = _canon_triple(_get(router.port, dq))
        assert routed_d == direct_d
        assert direct_d[0] == 200

        # dropped rounds: two more writes, client still at the OLD token
        _write_rows(db, step0=45)
        _write_rows(db, step0=50)
        direct_d2 = _canon_triple(_get(driver.port, dq))
        routed_d2 = _canon_triple(_get(router.port, dq))
        assert routed_d2 == direct_d2

        # garbled token ⇒ full serve (all fragments), identically on
        # both paths — still a per-request delta body, so canonical form
        gq = f"{q}&since=garbage!!token"
        direct_g = _canon_triple(_get(driver.port, gq))
        routed_g = _canon_triple(_get(router.port, gq))
        assert routed_g == direct_g
        assert "header" in json.loads(direct_g[1])["fragments"]

        # idle delta: 204 + token on both paths
        cur = routed_g[2]
        iq = f"{q}&since={cur}"
        assert _triple(_get(router.port, iq)) == _triple(
            _get(driver.port, iq)
        )
    finally:
        router.stop()
        driver.stop()


def test_summary_through_router_matches_direct(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port])
    try:
        q = "/api/summary?session=dash"
        # not ready yet: same 404 body through both paths
        assert _get(router.port, q)[0] == 404
        (tmp_path / "dash" / "final_summary.json").write_text(
            json.dumps({"primary_diagnosis": {
                "kind": "ok", "severity": "info", "summary": "fine"}})
        )
        direct = _triple(_get(driver.port, q))
        routed = _triple(_get(router.port, q))
        assert routed[0] == direct[0] == 200
        assert routed[1] == direct[1]
    finally:
        router.stop()
        driver.stop()


# -- edge cache ------------------------------------------------------------

def test_viewer_count_does_not_multiply_upstream_fetches(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port], cache_ttl=30.0)
    try:
        base = router.upstream_fetches
        results = [
            _get(router.port, "/api/live?session=dash") for _ in range(12)
        ]
        assert router.upstream_fetches == base + 1
        assert len({r[2] for r in results}) == 1  # all the same bytes
        assert results[0][1]["X-TraceML-Edge-Cache"] == "miss"
        assert results[-1][1]["X-TraceML-Edge-Cache"] == "hit"

        # client-side If-None-Match answered at the edge, no upstream
        token = results[0][1]["X-TraceML-Token"]
        status, headers, body = _get(
            router.port, "/api/live?session=dash",
            headers={"If-None-Match": f'"{token}"'},
        )
        assert status == 304 and body == b""
        assert router.upstream_fetches == base + 1

        # deltas at the same since-token also collapse to one fetch
        for _ in range(8):
            _get(router.port, f"/api/live?session=dash&since={token}")
        assert router.upstream_fetches == base + 2
    finally:
        router.stop()
        driver.stop()


def test_expired_entry_revalidates_with_if_none_match(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port], cache_ttl=0.05)
    try:
        base = router.upstream_fetches
        first = _get(router.port, "/api/live?session=dash")
        time.sleep(0.1)
        # unchanged upstream: a 304 renews the entry — header exchange,
        # no body
        second = _get(router.port, "/api/live?session=dash")
        assert second[1]["X-TraceML-Edge-Cache"] == "revalidated"
        assert second[2] == first[2]
        assert router.upstream_fetches == base + 2
        assert router.cache.stats()["revalidations"] == 1

        # advanced upstream: revalidation misses, new body replaces
        _write_rows(db, step0=40)
        time.sleep(0.1)
        third = _get(router.port, "/api/live?session=dash")
        assert third[1]["X-TraceML-Edge-Cache"] == "miss"
        assert third[1]["X-TraceML-Token"] != first[1]["X-TraceML-Token"]
    finally:
        router.stop()
        driver.stop()


# -- hostile input ---------------------------------------------------------

def test_hostile_session_ids_rejected_before_any_proxying(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port], probe=False)
    try:
        base = router.upstream_fetches
        hostile = [
            "../../../etc/passwd",
            "..%2F..%2Fetc%2Fpasswd",
            "<script>alert(1)</script>",
            "a" * 200,
            ".hidden",
            "",
        ]
        for sid in hostile:
            for route in ("/api/live", "/api/summary", "/api/stream"):
                status, _, _ = _get(
                    router.port, f"{route}?session={sid}"
                )
                assert status == 404, (route, sid)
        # no session param at all
        assert _get(router.port, "/api/live")[0] == 404
        assert router.upstream_fetches == base, (
            "hostile ids must never reach a shard"
        )
        # an over-long since token is refused, not proxied or cached
        status, _, _ = _get(
            router.port, "/api/live?session=dash&since=" + "x" * 500
        )
        assert status == 404
        assert router.upstream_fetches == base
    finally:
        router.stop()
        driver.stop()


# -- shard-down degradation ------------------------------------------------

def test_dead_shard_degrades_to_stale_rows_and_stale_cache(tmp_path):
    shard_a, _ = _start_shard(tmp_path / "a", session="alpha")
    shard_b, _ = _start_shard(tmp_path / "b", session="beta")
    router = _start_router([shard_a.port, shard_b.port], cache_ttl=0.05)
    b_name = f"127.0.0.1:{shard_b.port}"
    try:
        # warm: both sessions visible, beta's live body cached
        status, _, body = _get(router.port, "/api/fleet")
        fleet = json.loads(body)
        sids = {r["session"] for r in fleet["sessions"]}
        assert status == 200 and sids == {"alpha", "beta"}
        live = _get(router.port, "/api/live?session=beta")
        assert live[0] == 200

        shard_b.stop()
        for _ in range(3):  # past the is_down threshold
            router.health.probe(b_name)
        assert router.health.is_down(b_name)

        time.sleep(0.1)  # expire the fleet + live cache entries
        status, _, body = _get(router.port, "/api/fleet")
        assert status == 200, "a dead shard must not error the page"
        fleet = json.loads(body)
        rows = {r["session"]: r for r in fleet["sessions"]}
        assert rows["beta"]["stale"] is True, (
            "dead shard's sessions degrade to marked-stale rows"
        )
        assert rows["alpha"]["stale"] is False
        shard_rows = {r["shard"]: r for r in fleet["shards"]}
        assert shard_rows[b_name]["alive"] is False

        # the federated page itself stays 200 (502-free contract)
        status, _, page = _get(router.port, "/fleet")
        assert status == 200 and b"federated fleet" in page

        # cached live body served stale-marked, not 50x
        status, headers, _ = _get(router.port, "/api/live?session=beta")
        assert status == 200
        assert headers.get("X-TraceML-Stale") == "1"
        assert headers["X-TraceML-Edge-Cache"] == "stale"

        # a session that was never cached on the dead shard: clean 503
        status, _, _ = _get(
            router.port, "/api/summary?session=beta"
        )
        assert status == 503
    finally:
        router.stop()
        shard_a.stop()
        shard_b.stop()


# -- SSE through the router ------------------------------------------------

def test_sse_resume_across_router_restart(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port])
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", router.port, timeout=10
        )
        conn.request("GET", "/api/stream?session=dash")
        resp = conn.getresponse()
        assert resp.status == 200
        first = _read_sse_event(resp)
        assert first["event"] == "fragment"
        token = first["id"]
        assert json.loads(first["data"])
        conn.close()

        # the router dies and a NEW one takes the same address — no
        # state to migrate, the client's Last-Event-ID carries resume
        port = router.port
        router.stop()
        _write_rows(db, step0=40)
        router = _start_router([driver.port], port=port)
        assert router.port == port

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request(
            "GET", "/api/stream?session=dash",
            headers={"Last-Event-ID": token},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        resumed = _read_sse_event(resp)
        assert resumed["event"] == "fragment"
        assert resumed["id"] != token
        delta = json.loads(resumed["data"])
        # a resume is a delta, not a replay: only advanced fragments
        assert "step_time" in delta["fragments"]
        conn.close()
    finally:
        router.stop()
        driver.stop()


# -- hop compression -------------------------------------------------------

def test_shard_compresses_hop_when_asked(tmp_path):
    driver, db = _start_shard(tmp_path)
    try:
        plain = _get(driver.port, "/api/live?session=dash")
        status, headers, body = _get(
            driver.port, "/api/live?session=dash",
            headers={"X-TraceML-Hop-Compress": "zlib"},
        )
        assert status == 200
        assert headers["Content-Encoding"] == "x-traceml-zlib"
        orig = int(headers["X-TraceML-Orig-Len"])
        restored = zlib.decompress(body)
        assert len(restored) == orig
        assert restored == plain[2]
        assert len(body) < orig
    finally:
        driver.stop()


def test_hop_compressed_bytes_identical_through_router(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port], hop_compress="zlib")
    try:
        assert router.hop_codec in ("zlib", "zstd")
        direct = _triple(_get(driver.port, "/api/live?session=dash"))
        routed = _triple(_get(router.port, "/api/live?session=dash"))
        assert routed == direct
    finally:
        router.stop()
        driver.stop()


# -- rollup / fleet API ----------------------------------------------------

def test_fleet_rollup_merges_both_shards(tmp_path):
    shard_a, _ = _start_shard(tmp_path / "a", session="alpha")
    shard_b, _ = _start_shard(tmp_path / "b", session="beta")
    router = _start_router([shard_a.port, shard_b.port])
    try:
        status, headers, body = _get(router.port, "/api/fleet")
        assert status == 200
        fleet = json.loads(body)
        assert fleet["totals"]["sessions"] == 2
        by_sid = {r["session"]: r["shard"] for r in fleet["sessions"]}
        assert by_sid["alpha"] == f"127.0.0.1:{shard_a.port}"
        assert by_sid["beta"] == f"127.0.0.1:{shard_b.port}"
        # the learned location map routes to the REAL owner even when
        # the ring would guess otherwise
        for sid, shard in by_sid.items():
            assert router.owner_of(sid) == shard
        # /api/sessions aliases the rollup for fleet-page compatibility
        status, _, body = _get(router.port, "/api/sessions")
        assert status == 200
        assert {r["session"] for r in json.loads(body)["sessions"]} == {
            "alpha", "beta"
        }
    finally:
        router.stop()
        shard_a.stop()
        shard_b.stop()


def test_healthz_reports_role_and_shards(tmp_path):
    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port])
    try:
        status, _, body = _get(router.port, "/healthz")
        data = json.loads(body)
        assert status == 200 and data["ok"] is True
        assert data["role"] == "fleet-router"
        assert data["shards"][0]["alive"] is True
        assert "cache" in data
    finally:
        router.stop()
        driver.stop()


def test_concurrent_cold_misses_coalesce_to_one_upstream_fetch(tmp_path):
    """A thundering herd on one uncached key is single-flighted: the
    first request fetches, the rest wait on it and serve from cache —
    the shard sees exactly one body-moving fetch."""
    import threading

    driver, db = _start_shard(tmp_path)
    router = _start_router([driver.port], cache_ttl=60.0)
    try:
        before = router.upstream_fetches_200
        results = []
        results_lock = threading.Lock()
        gate = threading.Barrier(16)

        def hit():
            gate.wait()
            got = _triple(_get(router.port, "/api/live?session=dash"))
            with results_lock:
                results.append(got)

        threads = [threading.Thread(target=hit) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 16
        assert {r[0] for r in results} == {200}
        # every follower saw the leader's body, byte for byte
        assert len({r[1] for r in results}) == 1
        assert len({r[2] for r in results}) == 1
        assert router.upstream_fetches_200 - before == 1
    finally:
        router.stop()
        driver.stop()
