"""Device-memory section (reference role: nicegui_sections/
step_memory_section.py — worst/median series + KPI stats).

Per-rank pressure table with history sparklines as before, plus the
reference section's stat treatment: a KPI strip (current worst / p95 /
growth trend) computed client-side from the same payload the table
reads — presentation math only; pressure and growth themselves come
from the renderer views (single source of truth).
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">Device memory</h2><span class="sp"></span>
  <span id="mem-badge"></span></div>
<div class="kpis" id="mem-kpis" style="margin:.1rem 0 .6rem"></div>
<div id="memory"></div>
"""

_JS = r"""
let memBuilt=false;
function buildMem(){
  document.getElementById("mem-kpis").innerHTML=
    kpiTile("mem-worst","WORST PRESSURE","var(--crit)")+
    kpiTile("mem-total","TOTAL CURRENT","var(--accent)")+
    kpiTile("mem-growth","MAX GROWTH","#f1c40f");
  memBuilt=true}
function render_memory(d){
  if(!memBuilt)buildMem();
  const m=d.memory;badge("mem-badge",d.ts,m&&m.latest_ts);
  const el=document.getElementById("memory");
  if(!m||!m.ranks||!m.ranks.length){
    el.innerHTML='<span class="muted">no memory telemetry</span>';return}
  const pressures=m.ranks.map(s=>s.pressure).filter(v=>v!=null);
  setKpi("mem-worst",pressures.length?
    (Math.max(...pressures)*100).toFixed(0):null,"%");
  setKpi("mem-total",fmtB(m.total_current_bytes).split(" ")[0],
    fmtB(m.total_current_bytes).split(" ")[1]);
  const growths=m.ranks.map(s=>s.growth_bytes).filter(v=>v!=null);
  const gmax=growths.length?Math.max(...growths):null;
  setKpi("mem-growth",gmax==null?null:
    (gmax>=0?"+":"−")+fmtB(Math.abs(gmax)).split(" ")[0],
    gmax==null?"":fmtB(Math.abs(gmax)).split(" ")[1]);
  let rows=`<table><tr><th class="num">rank</th><th>device</th>
    <th class="num">current</th><th class="num">step peak</th>
    <th class="num">limit</th><th>pressure</th><th class="num">growth</th><th>history</th></tr>`;
  for(const s of m.ranks){
    const hist=s.history||[];const hmax=Math.max(1,...hist);
    const spark=hist.length>1?`<svg width="100" height="18" viewBox="0 0 100 18">
      <polyline fill="none" stroke="var(--accent-deep)" stroke-width="1"
        points="${sparkPath(hist,100,18,hmax)}"/></svg>`:"—";
    const g=s.growth_bytes;
    const worst=s.rank===m.worst_pressure_rank?' style="color:#ffd27f"':"";
    rows+=`<tr><td class="num"${worst}>${esc(s.rank)}</td><td>${esc(s.device_kind)}</td>
      <td class="num">${fmtB(s.current_bytes)}</td>
      <td class="num">${fmtB(s.step_peak_bytes)}</td>
      <td class="num">${fmtB(s.limit_bytes)}</td>
      <td>${meter(s.pressure,0.92,0.97)}</td>
      <td class="num">${g?(g>0?"+":"-")+fmtB(Math.abs(g)):"—"}</td>
      <td>${spark}</td></tr>`}
  el.innerHTML=rows+"</table>"}
"""

SECTION = Section(
    id="memory",
    title="Device memory",
    html=_HTML,
    js=_JS,
    contract=(
        "ts",
        "memory.latest_ts",
        "memory.ranks.rank",
        "memory.ranks.device_kind",
        "memory.ranks.current_bytes",
        "memory.ranks.step_peak_bytes",
        "memory.ranks.limit_bytes",
        "memory.ranks.pressure",
        "memory.ranks.growth_bytes",
        "memory.ranks.history",
        "memory.worst_pressure_rank",
        "memory.total_current_bytes",
    ),
)
