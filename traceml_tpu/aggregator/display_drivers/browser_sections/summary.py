"""Final-summary card + rank-0 output (reference role: the
final-summary surface the reference routes through its summary display
driver; here a card that appears when the run finalizes, polled from
``/api/summary`` every 5th tick).
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="card reveal" id="summary" style="display:none"></div>
"""

_JS = r"""
let summaryLoaded=false,summaryTick=0;
async function render_summary(d){
  if(summaryLoaded||(summaryTick++%5))return;
  try{
    const r=await fetch("/api/summary");if(!r.ok)return;
    const s=await r.json();if(!s||!s.sections)return;
    summaryLoaded=true;drawSummary(s)
  }catch(e){}}
function drawSummary(s){
  const el=document.getElementById("summary");
  const p=s.primary_diagnosis||{};
  const secs=s.sections||{};
  const chips=Object.keys(secs).map(k=>
    `<span class="badge">${esc(k)}: ${esc((secs[k]||{}).status||"—")}</span>`).join(" ");
  const topo=(s.meta||{}).topology||{};
  const eff=((secs.step_time||{}).global||{}).efficiency;
  el.style.display="";
  el.innerHTML=`<div class="chead"><h2 class="ctitle">Final summary</h2>
    <span class="badge">run finished</span></div>
    <div class="finding sev-${esc(p.severity||"info")}">
      <b>${esc(p.kind||"NO_DATA")}</b>
      <span class="muted">[${esc(p.severity||"")}]</span><br>${esc(p.summary||"")}
      ${p.action?`<br><span class="muted">→ ${esc(p.action)}</span>`:""}</div>
    <div style="margin:.4rem 0">${chips}</div>
    <div class="muted">world ${esc(topo.world_size!=null?topo.world_size:"?")}
      · mode ${esc(topo.mode||"?")}
      ${eff&&eff.achieved_tflops_median!=null?` · ${Number(eff.achieved_tflops_median).toFixed(1)} TFLOP/s`+
        (eff.mfu_median!=null?` · MFU ${(eff.mfu_median*100).toFixed(0)}%`:""):""}</div>`}
"""

SECTION = Section(
    id="summary",
    title="Final summary",
    html=_HTML,
    js=_JS,
    contract=(),  # reads /api/summary (final_summary.json), not /api/live
)

OUTPUT_SECTION = Section(
    id="output",
    title="Rank 0 output",
    html="""
<div class="chead"><h2 class="ctitle">Rank 0 output</h2><span class="sp"></span></div>
<pre id="stdout"></pre>
""",
    js=r"""
function render_output(d){
  document.getElementById("stdout").textContent=
    (d.stdout||[]).map(l=>l.line).join("\n")}
""",
    contract=("stdout.line",),
)
