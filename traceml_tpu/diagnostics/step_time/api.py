"""Step-time diagnosis entrypoint
(reference: src/traceml_ai/diagnostics/step_time/api.py +
utils/step_time_window.py diagnose_step_time_window:510)."""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
    SEVERITY_INFO,
    run_rules,
)
from traceml_tpu.diagnostics.step_time.policy import policy_for
from traceml_tpu.diagnostics.step_time.rules import DEFAULT_RULES, build_context
from traceml_tpu.utils.step_time_window import StepTimeWindow, build_step_time_window

DOMAIN = "step_time"


def diagnose_window(
    window: Optional[StepTimeWindow],
    mode: str = "summary",
    efficiency: Optional[Mapping[str, Any]] = None,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    """``efficiency`` is the section's MFU block (mfu_median etc.) when
    model FLOPs were declared — feeds the LowMfuRule.  ``topology`` is
    the captured :class:`~traceml_tpu.utils.topology.MeshTopology` (or
    None): fired issues whose ranks map onto a host / mesh-axis / DCN
    grouping gain an ``attribution`` block; None leaves the result
    byte-identical to the pre-topology contract."""
    policy = policy_for(mode)
    if window is None or window.n_steps < policy.min_steps:
        return DiagnosticResult(
            domain=DOMAIN,
            issues=[
                DiagnosticIssue(
                    kind="INSUFFICIENT_STEP_TIME_DATA",
                    severity=SEVERITY_INFO,
                    status="ok",
                    summary=(
                        "Not enough aligned steps for a reliable step-time "
                        f"diagnosis (have {0 if window is None else window.n_steps}, "
                        f"need {policy.min_steps})."
                    ),
                )
            ],
        )
    ctx = build_context(window, policy, efficiency=efficiency)
    result = run_rules(DOMAIN, DEFAULT_RULES, ctx)
    result = _prefer_cause_over_symptom(result)
    if topology is not None:
        from traceml_tpu.diagnostics.attribution import attach_attribution
        from traceml_tpu.utils.step_time_window import STEP_KEY

        step = window.metric(STEP_KEY)
        result = attach_attribution(
            result, topology, step.per_rank_avg_ms if step else None
        )
    return result


#: kinds that EXPLAIN idleness — when one fires at the symptom's
#: severity or above, it is the actionable verdict and must outrank it
_CAUSE_KINDS = (
    "INPUT_BOUND", "COMPILE_BOUND", "RESIDUAL_HEAVY",
    "INPUT_STRAGGLER", "COMPUTE_STRAGGLER", "H2D_STRAGGLER",
    "COLLECTIVE_STRAGGLER", "RESIDUAL_STRAGGLER", "STRAGGLER",
)
_SYMPTOM_KINDS = ("LOW_DEVICE_UTILIZATION",)
_SEV_RANK = {"info": 0, "warning": 1, "critical": 2}


def _prefer_cause_over_symptom(result: DiagnosticResult) -> DiagnosticResult:
    """LOW_DEVICE_UTILIZATION is a SYMPTOM (the chip idles); when a
    same-or-higher-severity cause fired in the same window (the input
    pipeline, a recompile storm, a straggler), the cause is the
    actionable verdict — an idle chip with a named reason must not win
    the severity→score sort just because ``1 − occupancy`` is a big
    number (found in r4 verification: a 150-step input_bound run
    promoted the symptom over INPUT_BOUND)."""
    issues = result.issues
    causes = [i for i in issues if i.kind in _CAUSE_KINDS]
    if not causes:
        return result
    changed = False
    for issue in issues:
        if issue.kind not in _SYMPTOM_KINDS:
            continue
        sev = _SEV_RANK.get(issue.severity, 0)
        peers = [
            c for c in causes if _SEV_RANK.get(c.severity, 0) >= sev
        ]
        if not peers:
            continue
        best = max(peers, key=lambda c: c.score or 0.0)
        # sort is severity → score: nudge the symptom just under its
        # best explaining cause so the cause leads the result
        issue.score = min(issue.score, (best.score or 0.0) - 1e-6)
        issue.evidence.setdefault("explained_by", best.kind)
        changed = True
    if not changed:
        return result
    return DiagnosticResult(domain=result.domain, issues=issues)


def diagnose_rank_rows(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    mode: str = "summary",
    max_steps: int = 200,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    window = build_step_time_window(rank_rows, max_steps=max_steps)
    return diagnose_window(window, mode=mode, topology=topology)
