"""Background device-marker resolver.

The reference resolves CUDA events on the 1 Hz sampler thread because the
events carry exact device timestamps (timing.py:66).  On TPU the
readiness *observation time* IS the timestamp, so resolution cadence
bounds timing accuracy.  This daemon polls pending
:class:`~traceml_tpu.utils.timing.DeviceMarker`s at millisecond cadence
while work is in flight and parks when idle — ~hundreds of cheap local
PJRT ``is_ready()`` calls per second, no device sync, no GIL-heavy work.

This replaces the reference's CUDA event pool (cuda_event_pool.py): there
is nothing to pool — markers are just array refs — but the *resolution
service* is the shared infrastructure both designs need.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.overhead_governor import get_governor
from traceml_tpu.utils.timing import DeviceMarker

_DEFAULT_INTERVAL = 0.002  # 2 ms poll while young markers are pending
_IDLE_TIMEOUT = 0.25  # park after this long with nothing pending
_FINE_WINDOW_S = 0.020  # markers younger than this get the fine cadence
_MAX_BACKOFF_S = 0.025  # cadence ceiling for long-running markers


def _poll_batch(pending: List[DeviceMarker]) -> tuple:
    """Poll a batch of markers and feed the governor ONE probe-cost
    sample: the batch MINIMUM per-poll duration — robust to the polling
    thread being descheduled mid-poll (a starved poller measures its own
    starvation, not the probe).  No-op polls of already-resolved markers
    and exception-path polls are excluded from the sample.  Returns
    (#resolved-by-this-batch, min_probe_dt | None).  Shared by
    sweep_inline (main thread) and the resolver loop."""
    resolved = 0
    best = None
    for m in pending:
        was_resolved = m.resolved
        t0 = time.perf_counter()
        try:
            if m.poll():
                resolved += 1
        except Exception:
            continue  # poll() fails open; a raise says nothing of cost
        if was_resolved:
            continue  # fast-path no-op poll: not a probe-cost sample
        dt = time.perf_counter() - t0
        if best is None or dt < best:
            best = dt
    # this is THE signal that detects RPC-priced is_ready (tunneled
    # PJRT) and turns inline sweeping off / stretches the marker stride
    if best is not None:
        get_governor().observe_probe(best, 1)
    return resolved, best


#: consecutive inline-sweep wins before step-end submits go quiet
_QUIET_AFTER_WINS = 3


class MarkerResolver:
    def __init__(self, poll_interval: float = _DEFAULT_INTERVAL) -> None:
        self._interval = poll_interval
        self._pending: List[DeviceMarker] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Adaptive quiet mode: in a bracketed hot loop, sweep_inline()
        # at each step boundary stamps the step-end marker before this
        # thread ever touches it — so waking the thread per submit only
        # buys two context-switch preemptions of the training thread per
        # step (measured ~2-3% of a 12 ms step on a 1-core host, the
        # short-step bench lane).  After a few consecutive inline wins,
        # step-end submits stop waking the thread; the idle-timeout scan
        # (≤ _IDLE_TIMEOUT) remains the backstop for a loop that stalls,
        # and any marker the THREAD ends up resolving decays the counter
        # so non-bracketed loops get the eager wake back immediately.
        self._inline_wins = 0

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="traceml-marker-resolver", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    def submit(self, marker: DeviceMarker) -> None:
        if marker.resolved or marker.submitted:
            return
        marker.submitted = True
        with self._lock:
            self._pending.append(marker)
        quiet = (
            getattr(marker, "step_end_hint", False)
            and self._inline_wins >= _QUIET_AFTER_WINS
        )
        if not quiet:
            self._wake.set()
        # Lazy-start so merely importing the sdk never spawns threads.
        if self._thread is None or not self._thread.is_alive():
            self.start()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def sweep_inline(self, max_n: int = 64) -> int:
        """Opportunistic poll on the CALLER thread; returns #resolved.

        Called at step boundaries (trace_step.__enter__): in a hot
        training loop the GIL can starve the resolver thread for tens of
        ms, so the main thread stamps the previous step's markers itself
        — the stamp error is then bounded by one inter-step gap instead
        of the resolver's scheduling luck.  Cost: a handful of local
        ``is_ready()`` calls, microseconds.
        """
        if not self._pending:  # tracelint: unguarded(emptiness probe on the hot step path; a racing append is swept next step)
            return 0
        # (unlocked fast path: hot loops with the governor subsampling
        # usually have no pending markers)
        with self._lock:
            pending = list(self._pending[:max_n])
        if not pending:
            return 0
        resolved, _ = _poll_batch(pending)
        if resolved:
            self._inline_wins = min(self._inline_wins + resolved, 50)
            with self._lock:
                self._pending = [m for m in self._pending if not m.resolved]
        return resolved

    def _delay_for(self, age_s: float, step_end_hint: bool = False) -> float:
        """Per-marker poll schedule.

        Every resolver wakeup PREEMPTS the training thread on a
        saturated host (context switch + cache pollution — measured
        ~2-4% of a 190 ms step at a 30-wakeup/step schedule on a
        1-core host), so wakeups are spent where a stamp can land:

        * **step-end markers** (``step_end_hint``: the fused
          compute/envelope marker) in the long-lifetime regime
          (governor's marker-lifetime EMA ≥ 20 ms — the observed
          dispatch→readiness duration of previous step-end markers, NOT
          the step envelope, which also contains pre-dispatch host
          time): sleep straight to ~85% of the expected lifetime, then
          poll at 2% of it — ≤ ~8 wakeups/step, relative stamp error
          ≤ 2%, and in bracketed loops sweep_inline() at the next step
          boundary stamps first anyway;
        * **intra-step phase markers** (h2d, collective, user regions)
          and the short-step/unknown regime: fine cadence — poll every
          2 ms while young, back off to 10% of age (relative error
          ≤10%, absolute ≤25 ms).  Phase markers resolve quickly, so
          the fine window costs a handful of wakeups, and delaying them
          to step end would collapse the intra-step device edges
          (regression caught by the straggler scenario E2Es).
        """
        if step_end_hint:
            ema = get_governor().marker_lifetime_ema
            if ema is not None:
                # sleep straight toward the expected completion window at
                # ANY lifetime scale — short steps included (a ~12 ms step
                # fine-polled at 2 ms costs ~6 main-thread preemptions per
                # step on a 1-core host, the dominant tracer cost in the
                # short-step bench lane); in bracketed loops
                # sweep_inline() at the next boundary stamps first anyway
                if age_s < 0.85 * ema:
                    return max(self._interval, 0.85 * ema - age_s)
                # capped like the non-hint path: a marker wedged behind a
                # stall (blocking checkpoint, retrace) must not push its
                # own poll cadence — and hence its stamp error —
                # unboundedly (the stalled lifetime is EMA-rejected, so
                # the schedule cannot self-correct mid-stall)
                return min(
                    _MAX_BACKOFF_S,
                    max(self._interval, 0.02 * ema, 0.1 * (age_s - ema)),
                )
        if age_s < _FINE_WINDOW_S:
            return self._interval
        return min(_MAX_BACKOFF_S, max(self._interval, 0.1 * age_s))

    def _run(self) -> None:
        import time as _time

        try:
            while not self._stop.is_set():
                with self._lock:
                    pending = list(self._pending)
                if not pending:
                    fired = self._wake.wait(timeout=_IDLE_TIMEOUT)
                    if fired:
                        self._wake.clear()
                    continue
                thread_resolved, _ = _poll_batch(pending)
                if thread_resolved:
                    # inline sweeping is NOT keeping up (unbracketed
                    # loop, stall) — restore eager wakes
                    self._inline_wins = max(
                        0, self._inline_wins - 2 * thread_resolved
                    )
                now = _time.perf_counter()
                with self._lock:
                    # Identity-based prune: concurrent submits and
                    # sweep_inline() prunes both mutate _pending, so a
                    # slice-by-stale-length merge would drop markers.
                    self._pending = [m for m in self._pending if not m.resolved]
                    unresolved = list(self._pending)
                if unresolved:
                    delay = min(
                        self._delay_for(
                            now - m.dispatched_at,
                            getattr(m, "step_end_hint", False),
                        )
                        for m in unresolved
                    )
                else:
                    delay = self._interval
                # expensive-probe floor: keep this thread's probe duty
                # cycle within the overhead budget (RPC-priced is_ready
                # through a tunneled PJRT client must not hammer the
                # channel the main thread dispatches on)
                delay = max(delay, get_governor().resolver_min_delay())
                # waiting on _wake (not _stop) lets a fresh submit
                # re-tighten the cadence mid-backoff
                fired = self._wake.wait(timeout=delay)
                if fired:
                    self._wake.clear()
        except Exception as exc:  # pragma: no cover
            get_error_log().error("marker resolver crashed", exc)


_resolver = MarkerResolver()


def get_marker_resolver() -> MarkerResolver:
    return _resolver
