"""HTML writer + window-builder edge cases."""

import jax.numpy as jnp  # noqa: F401  (keeps jax platform pinned first)

from traceml_tpu.reporting.html.writer import render_html_summary
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import build_step_time_window


def test_html_renders_minimal_and_odd_payloads():
    html = render_html_summary({"meta": {}, "primary_diagnosis": {}, "sections": {}})
    assert "<html" in html
    payload = {
        "meta": {"session_id": "<script>x</script>", "topology": {}},
        "primary_diagnosis": {"kind": "INPUT_BOUND", "severity": "critical",
                              "summary": "a & b < c"},
        "sections": {
            "step_time": {
                "status": "OK",
                "issues": [{"kind": "K", "severity": "warning", "summary": "s"}],
                "global": {
                    "n_steps": 3, "clock": "host",
                    "phases": {"step_time": {"median_ms": 1.0,
                                             "share_of_step": None,
                                             "worst_rank": 0,
                                             "skew_pct": 0.0}},
                    "step_series_ms": {"0": [1.0, 2.0, 1.5]},
                },
            }
        },
    }
    html = render_html_summary(payload)
    assert "&lt;script&gt;" in html  # escaped, not injected
    assert "a &amp; b &lt; c" in html
    assert "<polyline" in html


def test_html_renders_round2_sections():
    """Occupancy, per-rank matrix, memory pressure/growth, system nodes
    + cluster, process table, telemetry footer."""
    payload = {
        "meta": {"session_id": "s", "topology": {"world_size": 2},
                 "telemetry_stats": {"envelopes_ingested": 10}},
        "primary_diagnosis": {"kind": "HEALTHY", "severity": "info",
                              "summary": "ok"},
        "sections": {
            "step_time": {
                "status": "OK", "issues": [],
                "global": {
                    "n_steps": 40, "clock": "device",
                    "median_occupancy": 0.83,
                    "steady_state": {"median_ms": 90.0,
                                     "warmup_inflation_pct": 0.1,
                                     "warmup_steps_excluded": 10},
                    "phases": {
                        "step_time": {"median_ms": 100.0, "share_of_step": None,
                                      "worst_rank": 1, "skew_pct": 0.0},
                        "compute": {"median_ms": 80.0, "share_of_step": 0.8,
                                    "worst_rank": 1, "skew_pct": 0.0},
                    },
                    "per_rank": {
                        "0": {"avg_ms": {"step_time": 100.0, "compute": 80.0},
                              "occupancy": 0.85, "steps_seen": 40},
                        "1": {"avg_ms": {"step_time": 101.0, "compute": 81.0},
                              "occupancy": 0.81, "steps_seen": 40},
                    },
                },
            },
            "step_memory": {
                "status": "OK", "issues": [],
                "global": {
                    "per_rank": {"0": {"current_bytes": 4 << 30,
                                       "step_peak_bytes": 5 << 30,
                                       "limit_bytes": 16 << 30,
                                       "pressure": 0.31,
                                       "growth_bytes": 1 << 20}},
                    "rollup": {"total_current_bytes": 4 << 30,
                               "max_peak_bytes": 5 << 30},
                },
            },
            "system": {
                "status": "OK", "issues": [],
                "global": {
                    "nodes": {"0": {"hostname": "a", "cpu_pct_mean": 20.0,
                                    "cpu_pct_max": 40.0,
                                    "memory_used_bytes": 1, "memory_total_bytes": 2,
                                    "load_1m": 0.5},
                              "1": {"hostname": "b", "cpu_pct_mean": 80.0,
                                    "cpu_pct_max": 95.0,
                                    "memory_used_bytes": 1, "memory_total_bytes": 2,
                                    "load_1m": 2.0}},
                    "cluster": {"n_nodes": 2, "cpu_pct_min": 20.0,
                                "cpu_pct_median": 50.0, "cpu_pct_max": 80.0,
                                "busiest_node": "b"},
                },
            },
            "process": {
                "status": "OK", "issues": [],
                "global": {"per_rank": {"0": {"pid": 7, "cpu_pct_mean": 50.0,
                                              "cpu_pct_max": 90.0,
                                              "rss_bytes": 1 << 30,
                                              "rss_peak_bytes": 2 << 30,
                                              "num_threads": 8}}},
            },
        },
    }
    html = render_html_summary(payload)
    # occupancy + steady state render as KPI tiles now
    assert "chip busy" in html and ">83<" in html
    assert "steady state" in html
    assert "Per-rank breakdown" in html
    assert "31%" in html  # memory pressure
    assert "cluster: 2 nodes" in html
    assert "busiest b" in html
    assert "Processes" in html
    assert "envelopes_ingested 10" in html


def _row(step, clock="device", with_device=True, step_ms=100.0):
    ev = {"cpu_ms": step_ms, "count": 1,
          "device_ms": step_ms if with_device else None}
    return {"step": step, "clock": clock,
            "events": {T.STEP_TIME: ev}}


def test_window_mixed_device_coverage_falls_back_to_host():
    rows = {
        0: [_row(s) for s in range(1, 31)],
        # rank 1 lost device timing on one step (late stamp excluded)
        1: [_row(s, with_device=(s != 15)) for s in range(1, 31)],
    }
    w = build_step_time_window(rows)
    assert w.clock == "host"
    assert w.metric("step_time").median_ms == 100.0


def test_window_single_step_and_disjoint_ranks():
    # single common step
    rows = {0: [_row(5)], 1: [_row(5)]}
    w = build_step_time_window(rows)
    assert w.n_steps == 1
    assert w.steps == [5]
    # disjoint steps → no window
    rows = {0: [_row(1)], 1: [_row(2)]}
    assert build_step_time_window(rows) is None


def test_compare_accepts_session_dirs(tmp_path):
    import json

    from traceml_tpu.reporting.compare.command import compare_summaries

    for name, step in (("a", 100.0), ("b", 130.0)):
        d = tmp_path / name
        d.mkdir()
        (d / "final_summary.json").write_text(json.dumps({
            "meta": {"session_id": name},
            "primary_diagnosis": {"kind": "X", "severity": "info"},
            "sections": {"step_time": {"global": {"phases": {
                "step_time": {"median_ms": step}}}}},
        }))
    payload = compare_summaries(tmp_path / "a", tmp_path / "b")
    assert payload["verdict"] in ("REGRESSION", "LIKELY_REGRESSION")


def test_html_kpis_rollup_and_efficiency(tmp_path):
    """r4 additions: MFU/efficiency KPI tiles, the verdict's evidence
    line, per-section status chips, and the median→worst spread bars
    from the uniform rollup all render."""
    payload = {
        "meta": {"session_id": "k", "topology": {"world_size": 2}},
        "primary_diagnosis": {
            "kind": "INPUT_STRAGGLER", "severity": "critical",
            "summary": "rank 1 lags", "action": "look at rank 1",
            "ranks": [1],
            "evidence": {"score": 0.42, "statistic": "median"},
        },
        "sections": {
            "step_time": {
                "status": "OK", "issues": [],
                "diagnosis": {"kind": "INPUT_STRAGGLER"},
                "global": {
                    "clock": "device", "n_steps": 50,
                    "phases": {
                        "step_time": {"median_ms": 100.0, "worst_ms": 160.0,
                                      "worst_rank": 1, "skew_pct": 0.6,
                                      "share_of_step": None},
                        "input": {"median_ms": 20.0, "worst_ms": 80.0,
                                  "worst_rank": 1, "skew_pct": 3.0,
                                  "share_of_step": 0.2},
                    },
                    "efficiency": {
                        "flops_per_step": 2.5e12, "flops_source": "manual",
                        "achieved_tflops_median": 25.0, "mfu_median": 0.41,
                        "peak_tflops": 459.0, "peak_flops": 4.59e14,
                        "device_kind": "TPU v5p", "device_count": 4,
                    },
                    "rollup": {
                        "index_by": "global_rank",
                        "window": {"steps_analyzed": 50},
                        "average": {"step_time": 110.0, "input": 35.0},
                        "median": {"step_time": {"value": 100.0, "idx": "0"},
                                   "input": {"value": 20.0, "idx": "0"}},
                        "worst": {"step_time": {"value": 160.0, "idx": "1"},
                                  "input": {"value": 80.0, "idx": "1"}},
                    },
                },
            },
        },
    }
    html = render_html_summary(payload)
    assert "MFU" in html and ">41<" in html
    assert "TFLOP/step" in html and "TPU v5p" in html
    assert "score=0.42" in html and "statistic=median" in html
    assert "step_time: OK" in html  # status chip
    assert "Cross-rank spread" in html
    assert "r0/r1" in html  # rollup median/worst rank pairing
