"""Ulysses-style sequence parallelism: all-to-all head scattering.

The second canonical long-context strategy (alongside
``ops/ring_attention.py``): instead of rotating K/V blocks around a
ring, ONE ``all_to_all`` re-shards the activations from
sequence-sharded to head-sharded, every device runs ordinary full
attention for its head slice, and a second ``all_to_all`` restores the
sequence sharding (DeepSpeed-Ulysses recipe; public pattern).

Trade-offs vs ring attention on TPU:

* **Communication**: 2 all-to-alls of the full activations per layer
  (O(S·H·D/P) bytes each, one shot over ICI) vs P−1 ppermute hops of
  K/V.  All-to-all rides the ICI fabric well and needs no per-block
  software pipeline, but cannot overlap with attention math the way
  the ring's hop-per-block does.
* **Memory**: full sequence length is materialized locally for the
  head slice → the S² score matrix exists per head slice.  Ring keeps
  O(S_local²) blocks only.  Ulysses therefore suits moderate S with
  many heads; ring suits extreme S.
* **Constraint**: the head count must divide by the axis size
  (heads-per-device = H/P); ring has no such constraint.

Usage inside ``shard_map`` over a mesh with a sequence axis::

    out = ulysses_attention(q, k, v, axis_name="context")

with q,k,v the LOCAL (B, S_local, H, D) shards, sequence-ordered by
mesh position (same contract as ring_attention).  Causality is exact:
after the first all-to-all each device sees the FULL sequence, so a
standard causal mask applies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from traceml_tpu.utils import jax_compat
from traceml_tpu.utils.jax_compat import shard_map


def _full_causal_attention(q, k, v):
    """Ordinary causal attention on full-sequence local tensors.

    q,k,v: (B, S, h, D) → (B, S, h, D); f32 softmax accumulation.
    After the head-scatter this is PLAIN causal self-attention, so the
    pallas flash kernel applies unchanged on TPU —
    ``ops.attention.causal_attention`` dispatches to it (with the jnp
    reference as the fail-open path) exactly as in the dense model.
    """
    from traceml_tpu.ops.attention import causal_attention

    return causal_attention(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Causal attention over a sequence-sharded axis via all-to-all.

    q,k,v: local (B, S_local, H, D); H must be divisible by the axis
    size.  Returns the local (B, S_local, H, D) output shard.
    """
    P = jax_compat.axis_size(axis_name)
    B, S_loc, H, D = q.shape
    if H % P != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the "
            f"sequence-axis size ({P}); use ring_attention otherwise"
        )

    def seq_to_heads(x):
        # (B, S_loc, H, D) → (B, P·S_loc, H/P, D): trade the sequence
        # shard for a head shard.  split_axis=2 (heads), concat_axis=1
        # (sequence); tiled=True splits/joins in place rather than
        # adding a mesh dimension.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        # inverse: (B, P·S_loc, H/P, D) → (B, S_loc, H, D)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    q_full = seq_to_heads(q)
    k_full = seq_to_heads(k)
    v_full = seq_to_heads(v)
    out_full = _full_causal_attention(q_full, k_full, v_full)
    return heads_to_seq(out_full).astype(q.dtype)


def make_ulysses_attention(mesh, axis_name: str = "context"):
    """Convenience: a jitted global-array Ulysses attention over ``mesh``.

    Same contract as ``make_ring_attention``: GLOBAL (B, S, H, D)
    arrays sequence-sharded over ``axis_name`` in and out.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)

    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name)

    return jax.jit(
        shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )
