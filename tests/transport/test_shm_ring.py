"""Shm ring transport: the SPSC ring contract (commit-by-head-advance,
wraparound, generation flips) plus golden decoded-envelope equality
against the TCP arm (docs/developer_guide/native-transport.md).

The ring tests run against whatever append/drain implementation is
active (native _ring.so or the pure-Python twins) — the bytes in the
segment are the contract, so a parity test crosses the two directly.
"""

import os
import time

import pytest

from traceml_tpu.transport import TCPClient, TCPServer, UDSClient
from traceml_tpu.transport.compression import EnvelopeCompressor
from traceml_tpu.transport.shm_ring import (
    MIN_RING_BYTES,
    RING_HDR,
    ShmRingClient,
    ShmRingConsumer,
    ShmRingRegistry,
    init_ring_buffer,
    py_ring_append,
    py_ring_drain,
    scan_ring_descriptors,
    validate_ring_buffer,
)
from traceml_tpu.utils import msgpack_codec


def _payloads(n, rank=0):
    return [
        {
            "meta": {
                "seq": 1000 + i,
                "session_id": "s",
                "sampler": "step_time",
                "global_rank": rank,
            },
            "data": {"step": i, "values": [float(i)] * 64},
        }
        for i in range(n)
    ]


def _ring(tmp_path, name="seg.ring", **kw):
    return ShmRingClient(tmp_path / name, capacity=MIN_RING_BYTES, **kw)


# -- ring byte contract --------------------------------------------------


def test_client_consumer_roundtrip(tmp_path):
    client = _ring(tmp_path)
    consumer = ShmRingConsumer(client.path, 0)
    try:
        payloads = _payloads(5)
        assert client.send_batch(payloads)
        frames = consumer.drain()
        assert len(frames) == 1
        assert msgpack_codec.decode(frames[0]) == payloads
        assert client.frames_sent == 1
        assert consumer.frames == 1
    finally:
        client.close()
        consumer.close()


def test_wraparound_many_batches(tmp_path):
    """Total traffic several times the capacity: frames must straddle
    the wrap point repeatedly and still decode byte-identically."""
    client = _ring(tmp_path)
    consumer = ShmRingConsumer(client.path, 0)
    try:
        sent = []
        for i in range(200):
            batch = _payloads(3, rank=i)
            assert client.send_batch(batch), f"iteration {i}"
            sent.append(batch)
            if i % 7 == 0:  # drain at an offset-shifting cadence
                for frame in consumer.drain():
                    assert msgpack_codec.decode(frame) == sent.pop(0)
        for frame in consumer.drain():
            assert msgpack_codec.decode(frame) == sent.pop(0)
        assert sent == []
    finally:
        client.close()
        consumer.close()


def test_native_python_parity_both_directions():
    """native append → python drain and python append → native drain
    over a wrapping ring: the segment bytes are the contract."""
    from traceml_tpu.native import get_ring

    native = get_ring()
    if native is None:
        pytest.skip("native ring extension unavailable")
    capacity = 1024
    frames = [bytes([i]) * (150 + i) for i in range(40)]

    for direction in ("native_to_py", "py_to_native"):
        buf = bytearray(RING_HDR + capacity)
        init_ring_buffer(buf, capacity, producer_gen=1)
        got = []
        for frame in frames:
            if direction == "native_to_py":
                assert native.ring_append(buf, frame)
                got.extend(py_ring_drain(buf, capacity, 0))
            else:
                assert py_ring_append(buf, capacity, frame)
                got.extend(native.ring_drain(buf, 0))
        assert got == frames, direction


def test_torn_write_is_never_drained(tmp_path):
    """Garbage past head (a producer killed mid-memcpy) is invisible;
    the next committed frame drains cleanly over it."""
    client = _ring(tmp_path)
    consumer = ShmRingConsumer(client.path, 0)
    try:
        # fake a torn write: bytes in free space, head NOT advanced
        mm = client._mm
        mm[RING_HDR : RING_HDR + 64] = b"\xde\xad\xbe\xef" * 16
        assert consumer.readable() == 0
        assert consumer.drain() == []
        payloads = _payloads(2)
        assert client.send_batch(payloads)
        frames = consumer.drain()
        assert len(frames) == 1
        assert msgpack_codec.decode(frames[0]) == payloads
    finally:
        client.close()
        consumer.close()


def test_full_ring_fails_send_then_recovers(tmp_path):
    client = _ring(tmp_path)
    consumer = ShmRingConsumer(client.path, 0)
    try:
        big = b"x" * (MIN_RING_BYTES // 3)
        assert client.send_encoded_body(big)
        assert client.send_encoded_body(big)
        assert not client.send_encoded_body(big)  # full: fail, don't block
        assert client.ring_full_drops == 1
        assert client.batches_dropped == 1
        assert len(consumer.drain()) == 2
        assert client.send_encoded_body(big)  # space reclaimed
    finally:
        client.close()
        consumer.close()


def test_frame_larger_than_ring_is_refused(tmp_path):
    client = _ring(tmp_path)
    try:
        assert not client.send_encoded_body(b"x" * (MIN_RING_BYTES + 1))
        assert client.batches_dropped == 1
    finally:
        client.close()


def test_consumer_reattach_fails_exactly_one_send(tmp_path):
    """Aggregator restart semantics: the first attach is free; a
    RE-attach (fresh consumer_gen) fails ONE send so the durable layer
    replays its unacked window, then sends flow again."""
    client = _ring(tmp_path)
    first = ShmRingConsumer(client.path, 0)
    try:
        assert client.send_batch(_payloads(1))
        assert client.consumer_gen_flips == 0

        first.close()
        second = ShmRingConsumer(client.path, 0)  # the "restarted" aggregator
        try:
            assert not client.send_batch(_payloads(1))  # the one failed send
            assert client.consumer_gen_flips == 1
            assert client.reconnects == 1
            assert client.send_batch(_payloads(1))  # and recovery
            # pre-restart frames survived in the ring: the new consumer
            # drains them too (ring doubles as a replay window)
            assert len(second.drain()) >= 2
        finally:
            second.close()
    finally:
        client.close()


def test_corrupt_header_rejected(tmp_path):
    client = _ring(tmp_path)
    client.close()
    with open(tmp_path / "seg.ring", "r+b") as f:
        f.write(b"\x00\x00\x00\x00")  # torn magic
    with pytest.raises(ValueError, match="magic"):
        ShmRingConsumer(tmp_path / "seg.ring", 0)


def test_validate_rejects_invariant_violations():
    capacity = 1024
    buf = bytearray(RING_HDR + capacity)
    init_ring_buffer(buf, capacity, producer_gen=1)
    assert validate_ring_buffer(buf) == capacity
    # head < tail is impossible under the commit protocol → corruption
    import struct

    struct.pack_into("<Q", buf, 16, 5)
    struct.pack_into("<Q", buf, 24, 99)
    with pytest.raises(ValueError, match="invariant"):
        validate_ring_buffer(buf)


# -- descriptor discovery + registry -------------------------------------


def test_descriptor_scan_and_registry_attach(tmp_path):
    session = tmp_path / "session"
    client = ShmRingClient(
        tmp_path / "seg.ring",
        capacity=MIN_RING_BYTES,
        session_dir=session,
        global_rank=3,
    )
    try:
        descs = scan_ring_descriptors(session)
        assert len(descs) == 1
        assert descs[0]["global_rank"] == 3
        assert descs[0]["path"] == str(client.path)

        registry = ShmRingRegistry(session)
        payloads = _payloads(2, rank=3)
        assert client.send_batch(payloads)
        tagged = registry.poll()
        assert [tag for tag, _ in tagged] == ["shm:3"]
        assert msgpack_codec.decode(tagged[0][1]) == payloads
        stats = registry.stats()
        assert stats["rings_attached"] == 1
        assert stats["frames"] == 1
        registry.close()
        # cumulative counters survive close (final ingest_stats write)
        assert registry.stats()["frames"] == 1
    finally:
        client.close()


def test_registry_quarantines_corrupt_segment(tmp_path):
    session = tmp_path / "session"
    client = ShmRingClient(
        tmp_path / "seg.ring",
        capacity=MIN_RING_BYTES,
        session_dir=session,
        global_rank=0,
    )
    client.close()
    with open(tmp_path / "seg.ring", "r+b") as f:
        f.write(b"XXXX")
    registry = ShmRingRegistry(session)
    assert registry.poll() == []
    stats = registry.stats()
    assert stats["attach_failures"] == 1
    assert stats["quarantined"] == 1
    assert stats["rings_attached"] == 0
    registry.close()


# -- golden decoded-envelope equality across transports ------------------


def _drain_server(server, n, timeout=10.0):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        server.wait_for_data(0.1)
        got.extend(server.drain_decoded())
    return got


def _send_via_tcp(tmp_path, payloads):
    server = TCPServer()
    server.start()
    try:
        client = TCPClient("127.0.0.1", server.port)
        assert client.send_batch(payloads)
        got = _drain_server(server, len(payloads))
        client.close()
        return got
    finally:
        server.stop()


def _send_via_uds(tmp_path, payloads):
    sock = str(tmp_path / "u.sock")
    server = TCPServer(uds_path=sock)
    server.start()
    try:
        client = UDSClient(sock)
        assert client.send_batch(payloads)
        got = _drain_server(server, len(payloads))
        client.close()
        return got
    finally:
        server.stop()


def _send_via_shm(tmp_path, payloads):
    session = tmp_path / "shm_session"
    server = TCPServer()
    server.attach_ring_registry(ShmRingRegistry(session))
    server.start()
    try:
        client = ShmRingClient(
            tmp_path / "golden.ring",
            capacity=MIN_RING_BYTES,
            session_dir=session,
            global_rank=0,
        )
        assert client.send_batch(payloads)
        got = _drain_server(server, len(payloads))
        client.close()
        return got
    finally:
        server.stop()


def _send_via_compressed_tcp(tmp_path, payloads):
    server = TCPServer()
    server.start()
    try:
        client = TCPClient("127.0.0.1", server.port)
        compressor = EnvelopeCompressor("zlib", min_bytes=0)
        wrapped = [
            compressor.wrap(msgpack_codec.preencode(p)) for p in payloads
        ]
        assert compressor.envelopes_compressed > 0  # arm actually compressed
        assert client.send_batch(wrapped)
        got = _drain_server(server, len(payloads))
        assert server.compressed_envelopes > 0
        client.close()
        return got
    finally:
        server.stop()


def test_golden_equality_across_transport_arms(tmp_path):
    """Every transport arm must hand the ingest pipeline the SAME
    decoded payload list — transports move bytes, never reshape them."""
    payloads = _payloads(6)
    golden = _send_via_tcp(tmp_path, payloads)
    assert golden == payloads
    assert _send_via_uds(tmp_path, payloads) == golden
    assert _send_via_shm(tmp_path, payloads) == golden
    if msgpack_codec.preencode({}).raw is not None:
        assert _send_via_compressed_tcp(tmp_path, payloads) == golden


# -- chaos points --------------------------------------------------------


def test_chaos_shm_write_corrupt_drops_one_batch(tmp_path):
    """A corrupt fault on shm.write flips a byte INSIDE the committed
    body: the ring framing survives, the server's per-frame decode
    drops just that batch and keeps the ring attached."""
    from traceml_tpu.dev import chaos

    chaos._reset_for_tests('[{"point": "shm.write", "action": "corrupt"}]')
    session = tmp_path / "session"
    try:
        client = ShmRingClient(
            tmp_path / "seg.ring",
            capacity=MIN_RING_BYTES,
            session_dir=session,
            global_rank=0,
        )
        registry = ShmRingRegistry(session)
        first = _payloads(2)
        assert client.send_batch(first)  # fault fires on this publish
        good = _payloads(3)
        assert client.send_batch(good)
        tagged = registry.poll()
        assert len(tagged) == 2
        decoded = []
        for _tag, frame in tagged:
            try:
                decoded.append(msgpack_codec.decode(frame))
            except msgpack_codec.CodecError:
                decoded.append(None)  # flip broke msgpack structure
        # the flip corrupted the first batch (undecodable or wrong
        # values) while ring framing kept the NEXT frame intact
        assert decoded[0] != first
        assert decoded[1] == good
        client.close()
        registry.close()
    finally:
        chaos._reset_for_tests(None)


def test_chaos_shm_attach_corrupt_quarantines(tmp_path):
    from traceml_tpu.dev import chaos

    chaos._reset_for_tests('[{"point": "shm.attach", "action": "corrupt"}]')
    session = tmp_path / "session"
    try:
        client = ShmRingClient(
            tmp_path / "seg.ring",
            capacity=MIN_RING_BYTES,
            session_dir=session,
            global_rank=0,
        )
        registry = ShmRingRegistry(session)
        assert registry.poll() == []
        assert registry.stats()["attach_failures"] == 1
        client.close()
        registry.close()
    finally:
        chaos._reset_for_tests(None)
