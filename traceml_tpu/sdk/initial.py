"""``init``/``start`` — patch policy
(reference: src/traceml_ai/sdk/initial.py:12-33, 81-125, 128-175, 192-276).

Modes:

* ``auto``      — apply every applicable patch (jax h2d; torch
  dataloader/forward/backward/optimizer when torch is importable),
* ``manual``    — none; user calls the wrappers,
* ``selective`` — explicit per-patch booleans.

Idempotent; a re-``init`` with a *conflicting* mode raises (the one place
the SDK is allowed to raise — silently switching patch policy mid-run
would corrupt the phase stream).
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Optional

from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils.error_log import get_error_log

VALID_MODES = ("auto", "manual", "selective")


@dataclasses.dataclass(frozen=True)
class TraceMLInitConfig:
    mode: str = "auto"
    patch_dataloader: bool = True
    patch_forward: bool = True
    patch_backward: bool = True
    patch_optimizer: bool = True
    patch_h2d: bool = True
    patch_checkpoint: bool = True
    traced_model: object = None


class TraceMLInitError(RuntimeError):
    pass


def _torch_loaded() -> bool:
    return "torch" in sys.modules


def _jax_loaded() -> bool:
    return "jax" in sys.modules


def init(
    mode: str = "auto",
    prefer_jax: Optional[bool] = None,
    prefer_torch: Optional[bool] = None,
    **kwargs,
) -> TraceMLInitConfig:
    """Apply the requested patch policy.  Safe to call more than once
    with the same mode; conflicting re-init raises.

    ``prefer_jax`` / ``prefer_torch``: apply that framework's
    instrumentation even if it isn't imported yet (the executor sets
    these from the script's static analysis; default = only touch a
    framework the process already loaded, so neither job type pays the
    other stack's import).
    """
    if mode not in VALID_MODES:
        raise TraceMLInitError(f"mode must be one of {VALID_MODES}, got {mode!r}")
    st = get_state()
    if st.initialized:
        if st.patch_mode != mode:
            raise TraceMLInitError(
                f"traceml already initialized with mode={st.patch_mode!r}; "
                f"re-init with mode={mode!r} conflicts"
            )
        return TraceMLInitConfig(mode=mode, **kwargs)

    cfg = TraceMLInitConfig(mode=mode, **kwargs)
    applied = []
    want_jax = _jax_loaded() if prefer_jax is None else bool(prefer_jax)
    if want_jax:
        # Ecosystem compat shim: chex (via optax) references
        # jax.core.Tracer at import time, which fails UNLESS the
        # submodule was imported first (submodule import sets the
        # attribute, bypassing jax's deprecation __getattr__).  Our
        # executor initializes tracing before the user script imports
        # its stack, so do the import here to keep user imports
        # order-independent.
        try:
            import jax.core  # noqa: F401
        except Exception as exc:
            get_error_log().warning("jax.core compat import failed", exc)
        # process-wide compile attribution (cheap listener; compile
        # visibility is core telemetry, not a patch)
        try:
            from traceml_tpu.instrumentation.compile_tracker import (
                install_compile_tracker,
            )

            if install_compile_tracker():
                applied.append("compile_tracker")
        except Exception as exc:
            get_error_log().warning("compile tracker failed", exc)
    if mode != "manual":
        # per-patch kwargs are honored in every non-manual mode ("auto"
        # defaults them all True; passing patch_x=False narrows it).
        want = cfg
        if want_jax and want.patch_h2d:
            try:
                from traceml_tpu.instrumentation.patches.jax_h2d_patch import (
                    patch_jax_h2d,
                )

                if patch_jax_h2d():
                    applied.append("jax_h2d")
            except Exception as exc:
                get_error_log().warning("jax h2d patch failed", exc)
        if want.patch_checkpoint:
            try:
                from traceml_tpu.instrumentation.orbax_patch import (
                    install_orbax_patch,
                )

                outcome = install_orbax_patch()  # now, or on first import
                if outcome != "noop":
                    applied.append(f"orbax_checkpoint[{outcome}]")
            except Exception as exc:
                get_error_log().warning("orbax patch failed", exc)
        # torch-xla lazy-barrier timing: mark_step wall time IS the
        # device execution + collective wait for the step (BASELINE
        # BERT-base / Llama FSDP configs run through this path).
        # Armed UNCONDITIONALLY (like orbax, not inside want_torch): the
        # executor inits before the script imports torch, so framework
        # preference can be unknown here; arming is cheap, self-gating
        # (noop when torch_xla isn't even installed), and never imports
        # torch_xla on the user's behalf.
        try:
            from traceml_tpu.instrumentation.torch_xla_support import (
                install_torch_xla_patch,
            )

            outcome = install_torch_xla_patch()
            if outcome != "noop":
                applied.append(f"torch_xla_mark_step[{outcome}]")
        except Exception as exc:
            get_error_log().warning("torch-xla mark_step patch failed", exc)
        # Torch-side patches: when torch is already imported, or the
        # executor's static analysis says this is a torch job.
        want_torch = (
            _torch_loaded() if prefer_torch is None else bool(prefer_torch)
        )
        if want_torch:
            from traceml_tpu.instrumentation.dataloader import (
                patch_torch_dataloader,
            )
            from traceml_tpu.instrumentation.patches.torch_patches import (
                install_torch_optimizer_hooks,
                patch_torch_backward,
                patch_torch_forward,
                set_traced_model,
            )

            if want.patch_dataloader and patch_torch_dataloader():
                applied.append("torch_dataloader")
            if want.patch_forward and patch_torch_forward():
                applied.append("torch_forward")
            if want.patch_backward and patch_torch_backward():
                applied.append("torch_backward")
            if want.patch_optimizer and install_torch_optimizer_hooks():
                applied.append("torch_optimizer")
            if cfg.traced_model is not None:
                set_traced_model(cfg.traced_model)
    st.initialized = True
    st.patch_mode = mode
    get_error_log().info(f"traceml init mode={mode} patches={applied}")
    return cfg


# alias (reference exposes both init and start)
start = init


def shutdown_patches() -> None:
    """Remove every patch (tests / clean embedding)."""
    st = get_state()
    try:
        from traceml_tpu.instrumentation.patches.jax_h2d_patch import unpatch_jax_h2d

        unpatch_jax_h2d()
    except Exception:
        pass
    try:
        from traceml_tpu.instrumentation.dataloader import unpatch_torch_dataloader
        from traceml_tpu.instrumentation.patches.torch_patches import (
            unpatch_all_torch,
        )

        unpatch_torch_dataloader()
        unpatch_all_torch()
    except Exception:
        pass
    try:
        from traceml_tpu.instrumentation.orbax_patch import (
            remove_orbax_hook,
            unpatch_orbax,
        )

        unpatch_orbax()
        remove_orbax_hook()
    except Exception:
        pass
    try:
        from traceml_tpu.instrumentation.torch_xla_support import (
            remove_torch_xla_hook,
            unpatch_mark_step,
        )

        unpatch_mark_step()
        remove_torch_xla_hook()
    except Exception:
        pass
    st.initialized = False
    st.patch_mode = None
