"""LiveSnapshotStore: cursor semantics, trim lockstep, data_version.

The store's contract (docs/developer_guide/live-read-path.md): after any
sequence of incremental refreshes its accessors return EXACTLY what a
fresh full load through ``reporting/loaders.py`` would — including after
the writer's retention trim deleted rows the store still held — and
``data_version`` only ever moves forward.
"""

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.reporting import loaders
from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T


def _ident(rank=0, node=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank % 4,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=100 + rank,
    )


def _step_rows(start, n, base_ms=50.0):
    return [
        {
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": base_ms, "device_ms": base_ms, "count": 1},
                T.COMPUTE_TIME: {
                    "cpu_ms": 1.0, "device_ms": base_ms * 0.9, "count": 1,
                },
            },
        }
        for s in range(start, start + n)
    ]


def _mem_rows(start, n):
    return [
        {"step": s, "timestamp": float(s), "device_id": 0, "device_kind": "tpu",
         "current_bytes": 100 + s, "peak_bytes": 200 + s,
         "step_peak_bytes": 150 + s, "limit_bytes": 1000, "backend": "fake"}
        for s in range(start, start + n)
    ]


def _assert_matches_full_load(store, db):
    assert store.step_time_rows() == loaders.load_step_time_rows(
        db, max_steps_per_rank=store.window_steps
    )
    assert store.step_memory_rows() == loaders.load_step_memory_rows(
        db, max_rows_per_rank=store.memory_rows_per_rank
    )
    assert store.system_rows() == loaders.load_system_rows(
        db, max_rows=store.max_system_rows
    )
    assert store.process_rows() == loaders.load_process_rows(
        db, max_rows=store.max_process_rows
    )
    assert store.stdout_tail() == loaders.load_stdout_tail(db)
    assert store.model_stats() == loaders.load_model_stats(db)
    assert store.topology() == loaders.load_topology(db)


def test_incremental_refreshes_match_full_load(tmp_path):
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=60)

    versions_seen = [store.data_version]
    for batch in range(4):
        start = 1 + batch * 10
        for rank, node in ((0, 0), (1, 1)):
            w.ingest(build_telemetry_envelope(
                "step_time", {"step_time": _step_rows(start, 10)},
                _ident(rank, node),
            ))
            w.ingest(build_telemetry_envelope(
                "step_memory", {"step_memory": _mem_rows(start, 10)},
                _ident(rank, node),
            ))
        w.ingest(build_telemetry_envelope(
            "system",
            {"system": [{"timestamp": float(batch), "cpu_pct": 10.0 + batch,
                         "memory_used_bytes": 1, "memory_total_bytes": 2,
                         "memory_pct": 50.0}],
             "system_device": [{"timestamp": float(batch), "device_id": 0,
                                "device_kind": "tpu", "memory_used_bytes": 5,
                                "memory_peak_bytes": 6,
                                "memory_total_bytes": 10}]},
            _ident(0, 0),
        ))
        w.ingest(build_telemetry_envelope(
            "process",
            {"process": [{"timestamp": float(batch), "cpu_pct": 5.0,
                          "rss_bytes": 10 + batch, "vms_bytes": 20,
                          "num_threads": 3}]},
            _ident(1, 1),
        ))
        w.ingest(build_telemetry_envelope(
            "stdout_stderr",
            {"stdout_stderr": [{"timestamp": float(batch), "stream": "stdout",
                                "line": f"batch {batch}"}]},
            _ident(0, 0),
        ))
        assert w.force_flush()
        changed = store.refresh()
        assert changed
        versions_seen.append(store.data_version)

    # strictly monotonic across changed refreshes
    assert versions_seen == sorted(set(versions_seen))
    # idle refresh: nothing changed, versions stable
    assert store.refresh() is False
    assert store.data_version == versions_seen[-1]

    _assert_matches_full_load(store, db)
    assert w.finalize()
    store.close()


def test_cursor_semantics_under_retention_trim(tmp_path):
    db = tmp_path / "t.sqlite"
    # tiny retention: keep 1.5 × 10 = 15 rows per (session, rank)
    w = SQLiteWriter(db, summary_window_rows=10, retention_factor=1.5)
    w.start()
    # store window larger than the retained row count, so matching the
    # fresh load REQUIRES trim-lockstep eviction from the deques
    store = LiveSnapshotStore(db, window_steps=50)

    versions = []
    for start in (1, 26, 51, 76):
        for rank in (0, 1):
            w.ingest(build_telemetry_envelope(
                "step_time", {"step_time": _step_rows(start, 25)},
                _ident(rank),
            ))
            w.ingest(build_telemetry_envelope(
                "step_memory", {"step_memory": _mem_rows(start, 25)},
                _ident(rank),
            ))
        assert w.force_flush()
        store.refresh()
        versions.append(store.data_version)

    # finalize runs the retention prune: only the newest 15 rows per
    # rank survive in SQLite, while the store still holds up to 50
    assert w.finalize()
    assert store.refresh() is True  # trim detected (eviction, no new rows)
    versions.append(store.data_version)
    assert versions == sorted(versions)

    st = store.step_time_rows()
    fresh = loaders.load_step_time_rows(db, max_steps_per_rank=50)
    assert st == fresh
    for rank, rows in st.items():
        steps = [r["step"] for r in rows]
        assert steps == sorted(set(steps)), "duplicate or unordered steps"
        assert len(rows) == 15  # exactly the retained rows, none resurrected
        assert steps[-1] == 100
        assert steps[0] == 86
    assert store.step_memory_rows() == loaders.load_step_memory_rows(
        db, max_rows_per_rank=store.memory_rows_per_rank
    )

    # a rank seen before the trim stays visible in topology even though
    # DISTINCT over the trimmed table would still return it here
    assert store.topology() == loaders.load_topology(db)

    # idle after trim: no further version movement
    assert store.refresh() is False
    assert store.data_version == versions[-1]
    store.close()


def test_store_connects_lazily_and_survives_missing_db(tmp_path):
    db = tmp_path / "nope.sqlite"
    store = LiveSnapshotStore(db)
    assert store.refresh() is False
    assert not store.connected
    assert store.step_time_rows() == {}
    assert store.topology() == {"mode": "unknown", "world_size": 0, "nodes": 0}

    # DB appears later: the same store picks it up
    w = SQLiteWriter(db)
    w.start()
    w.ingest(build_telemetry_envelope(
        "step_time", {"step_time": _step_rows(1, 5)}, _ident(0),
    ))
    assert w.force_flush()
    assert store.refresh() is True
    assert store.connected
    assert sorted(store.step_time_rows()) == [0]
    assert w.finalize()
    store.close()
