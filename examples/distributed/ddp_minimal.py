"""Multi-rank demo: run with  traceml-tpu run --nprocs 4 \
    examples/distributed/ddp_minimal.py

Each process is one rank (RANK/WORLD_SIZE from the launcher's env
contract); the final summary aggregates all ranks and reports cross-rank
skew.  On a real pod, the same script runs one process per host with
jax.distributed.initialize().
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

import traceml_tpu
from traceml_tpu.models.mlp import TinyMLP, make_mlp_train_step

traceml_tpu.init(mode="auto")
rank = int(os.environ.get("RANK", 0))

model = TinyMLP(hidden=256, depth=3)
init, train_step = make_mlp_train_step(model)
params, opt_state = init(jax.random.PRNGKey(rank), np.zeros((1, 64), np.float32))
step = traceml_tpu.wrap_step_fn(train_step)

rng = np.random.default_rng(rank)
for i in range(120):
    with traceml_tpu.trace_step():
        x = jax.device_put(rng.normal(size=(64, 64)).astype(np.float32))
        y = jax.device_put(rng.normal(size=(64, 1)).astype(np.float32))
        params, opt_state, loss = step(params, opt_state, x, y)

print(f"rank {rank} done, loss={float(loss):.4f}")
