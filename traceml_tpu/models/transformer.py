"""Flagship decoder-only LM (llama-style), TPU-first.

Design notes (why it looks the way it does):

* **MXU-friendly**: every hot op is a large batched matmul in bf16;
  static shapes everywhere; attention is einsum-based so XLA fuses the
  softmax chain and tiles onto the systolic array.
* **Sharding-native**: `param_shardings` maps every parameter to a
  `PartitionSpec` over the ("data","fsdp","tensor") mesh axes — embed /
  ffn / head dims shard over "tensor", everything shards over "fsdp"
  (ZeRO-style) on its largest remaining dim; XLA inserts the
  all-gathers/reduce-scatters (GSPMD), we never hand-roll collectives.
* **Remat**: optional `jax.checkpoint` over each block trades FLOPs for
  HBM, the standard long-context lever.
* **GQA + RoPE + RMSNorm + SwiGLU**: the contemporary decoder recipe,
  kept minimal and readable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from traceml_tpu.utils.jax_compat import shard_map


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_mult: float = 2.6667  # SwiGLU hidden = mult * hidden (rounded)
    max_seq_len: int = 1024
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # sequence/context parallelism for long sequences: "dense" runs the
    # fused jnp path and lets GSPMD partition it; "ring" / "ulysses"
    # wrap the matching ops/ kernel in shard_map over ``context_axis``
    # of ``mesh`` (set both), sharding attention BY SEQUENCE with exact
    # global causality — see ops/ring_attention.py /
    # ops/ulysses_attention.py for the trade-offs
    attention_impl: str = "dense"
    context_axis: Any = None     # mesh axis name, e.g. "context"
    mesh: Any = None             # jax.sharding.Mesh (shard_map needs it)

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads

    @property
    def ffn_hidden(self) -> int:
        # round to a multiple of 128 for MXU tiling
        h = int(self.hidden * self.ffn_mult)
        return max(128, (h + 127) // 128 * 128)

    @classmethod
    def tiny(cls) -> "ModelConfig":
        return cls(vocab_size=256, hidden=128, n_layers=2, n_heads=4,
                   n_kv_heads=2, max_seq_len=128)


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embeddings over the last dim of x: (..., seq, heads, head_dim)."""
    head_dim = x.shape[-1]
    half = head_dim // 2
    freqs = jnp.exp(
        -jnp.log(theta) * (2.0 * jnp.arange(half, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * freqs  # (..., seq, half)
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def seq_parallel_spec(cfg: "ModelConfig", batch_size: Optional[int] = None):
    """PartitionSpec for (B, S, heads, hd) q/k/v under seq parallelism.

    Derived from the mesh instead of hardcoded so no axis is silently
    replicated: batch shards over whichever of ``mesh.BATCH_AXES`` the
    mesh actually has (without this, every data-parallel group would
    all-gather the global batch at the shard_map boundary and
    redundantly compute full-batch attention — advisor r4); heads shard
    over "tensor" when the mesh has one and the head count divides,
    matching the column-parallel wq/wk/wv output layout so the
    shard_map boundary introduces no tensor-axis all-gather either.
    Attention is independent per batch element and per head, so both
    shardings are exact.

    Fallbacks keep previously-valid configs running (review r5): a
    ``batch_size`` not divisible by the batch axes' product (e.g. B=1
    eval on a training mesh) replicates batch as before, and heads stay
    unsharded when the ulysses all-to-all could not redistribute the
    per-shard head count over the context axis.
    """
    from jax.sharding import PartitionSpec as P

    from traceml_tpu.parallel.mesh import BATCH_AXES

    mesh_axes = tuple(cfg.mesh.axis_names)
    batch_axes = tuple(
        ax for ax in BATCH_AXES
        if ax in mesh_axes and ax != cfg.context_axis
    )
    if batch_axes and batch_size is not None:
        # keep the LARGEST dividing subset rather than all-or-nothing
        # (mesh {data:4, fsdp:2} with B=4 still shards over 'data') —
        # exhaustive over the ≤2 batch axes, because a greedy in-order
        # scan lets an earlier small axis block a later larger one
        # (mesh {data:2, fsdp:4} with B=4 must pick fsdp, not data)
        best, best_dp = (), 1
        for mask in range(1, 1 << len(batch_axes)):
            subset = tuple(
                ax for i, ax in enumerate(batch_axes) if mask >> i & 1
            )
            dp = 1
            for ax in subset:
                dp *= cfg.mesh.shape[ax]
            if batch_size % dp == 0 and (
                dp > best_dp or (dp == best_dp and len(subset) > len(best))
            ):
                best, best_dp = subset, dp
        batch_axes = best
    heads_axis = None
    if (
        "tensor" in mesh_axes
        and cfg.context_axis != "tensor"
        and cfg.n_heads % cfg.mesh.shape["tensor"] == 0
    ):
        local_heads = cfg.n_heads // cfg.mesh.shape["tensor"]
        if (
            cfg.attention_impl != "ulysses"
            or local_heads % cfg.mesh.shape[cfg.context_axis] == 0
        ):
            heads_axis = "tensor"
    return P(batch_axes or None, cfg.context_axis, heads_axis, None)


class Attention(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        B, S, H = x.shape
        hd = cfg.head_dim
        q = nn.Dense(cfg.n_heads * hd, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wq")(x)
        k = nn.Dense(cfg.n_kv_heads * hd, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wk")(x)
        v = nn.Dense(cfg.n_kv_heads * hd, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wv")(x)
        q = q.reshape(B, S, cfg.n_heads, hd)
        k = k.reshape(B, S, cfg.n_kv_heads, hd)
        v = v.reshape(B, S, cfg.n_kv_heads, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # GQA: repeat kv heads up to n_heads
        group = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
        out = self._attend(q, k, v)  # (B, S, heads, hd)
        out = out.reshape(B, S, cfg.n_heads * hd)
        return nn.Dense(H, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="wo")(out)

    def _attend(self, q, k, v):
        """Attention kernel dispatch per cfg.attention_impl.

        "dense": the fused jnp path — GSPMD partitions it (the pallas
        flash kernel substitutes on TPU).  "ring"/"ulysses": the op
        runs inside shard_map over cfg.context_axis with q/k/v sharded
        BY SEQUENCE (and by batch over the data-parallel axes — see
        seq_parallel_spec); RoPE was already applied on global
        positions, and both ops enforce global causality themselves.
        """
        cfg = self.cfg
        if cfg.attention_impl == "dense":
            from traceml_tpu.ops.attention import causal_attention

            return causal_attention(q, k, v)
        if cfg.attention_impl == "ring":
            from traceml_tpu.ops.ring_attention import ring_attention as op
        elif cfg.attention_impl == "ulysses":
            from traceml_tpu.ops.ulysses_attention import (
                ulysses_attention as op,
            )
        else:
            raise ValueError(
                f"unknown attention_impl {cfg.attention_impl!r} "
                "(dense | ring | ulysses)"
            )
        if cfg.mesh is None or cfg.context_axis is None:
            raise ValueError(
                f"attention_impl={cfg.attention_impl!r} requires cfg.mesh "
                "and cfg.context_axis (sequence-parallel attention runs "
                "inside shard_map); use attention_impl='dense' for "
                "single-mesh GSPMD partitioning"
            )
        spec = seq_parallel_spec(cfg, batch_size=q.shape[0])
        return shard_map(
            lambda a, b, c: op(a, b, c, cfg.context_axis),
            mesh=cfg.mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)


class MLP(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.ffn_hidden, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="w_gate")(x)
        up = nn.Dense(cfg.ffn_hidden, use_bias=False, dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype, name="w_up")(x)
        return nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="w_down")(
            nn.silu(gate) * up
        )


class Block(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        x = x + Attention(cfg, name="attn")(
            RMSNorm(dtype=cfg.dtype, name="attn_norm")(x), positions
        )
        x = x + MLP(cfg, name="mlp")(
            RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        )
        return x


class DecoderLM(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, S = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed")(tokens)
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=cfg.param_dtype, name="lm_head")(x)
        return logits


# -- sharding ------------------------------------------------------------


def param_shardings(params, mesh) -> Any:
    """Map every param leaf to a NamedSharding over (fsdp, tensor).

    Rules (scaling-book style):
    * 2D kernels: shard dim 0 over "fsdp"; dim 1 over "tensor" for
      column-parallel layers (wq/wk/wv/w_gate/w_up/lm_head) and dim 0
      over "tensor" + dim 1 over "fsdp" for row-parallel (wo/w_down).
    * embeddings: vocab over "fsdp", hidden over "tensor".
    * 1D scales: replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    col_parallel = ("wq", "wk", "wv", "w_gate", "w_up", "lm_head")
    row_parallel = ("wo", "w_down")

    def spec_for(path: Tuple[str, ...], leaf) -> Any:
        ndim = getattr(leaf, "ndim", 0)
        names = [p for p in path]
        if ndim <= 1:
            return NamedSharding(mesh, P())
        owner = next((n for n in names if n in col_parallel + row_parallel), None)
        if "embed" in names and ndim == 2:
            return NamedSharding(mesh, P("fsdp", "tensor"))
        if owner in col_parallel:
            return NamedSharding(mesh, P("fsdp", "tensor"))
        if owner in row_parallel:
            return NamedSharding(mesh, P("tensor", "fsdp"))
        return NamedSharding(mesh, P("fsdp"))

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    specs = []
    for path, leaf in flat:
        keys = tuple(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path
        )
        specs.append(spec_for(keys, leaf))
    return jax.tree_util.tree_unflatten(treedef, specs)


# -- training ------------------------------------------------------------


def loss_fn(params, apply_fn, tokens) -> jnp.ndarray:
    """Next-token cross entropy (inputs=tokens[:, :-1], targets=[:, 1:])."""
    logits = apply_fn({"params": params}, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def init_train_state(
    cfg: ModelConfig,
    rng: Optional[jax.Array] = None,
    learning_rate: float = 3e-4,
    mesh=None,
) -> Tuple[Any, Dict[str, Any], Any]:
    """Returns (model, state, optimizer).  state = {params, opt_state, step}.

    With a mesh, params and optimizer state are sharded per
    `param_shardings` (jax.device_put applies GSPMD layouts directly).
    """
    import optax

    model = DecoderLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    tokens = jnp.zeros((2, min(16, cfg.max_seq_len)), dtype=jnp.int32)
    params = model.init(rng, tokens)["params"]
    tx = optax.adamw(learning_rate, weight_decay=0.01)
    if mesh is not None:
        shardings = param_shardings(params, mesh)
        params = jax.device_put(params, shardings)
    opt_state = tx.init(params)
    state = {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}
    return model, state, tx


def make_train_step(model: DecoderLM, tx) -> Any:
    """The (un-jitted) functional train step: (state, tokens) → (state, metrics).

    Callers wrap it with ``traceml_tpu.wrap_step_fn`` (tracing + AOT
    compile attribution) or plain ``jax.jit``; donate state for in-place
    updates.
    """
    import optax

    def train_step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], model.apply, tokens
        )
        updates, opt_state = tx.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss}

    return train_step
