"""End-to-end tick pipeline: vectorized diagnosis arm vs scalar legacy.

The r20 diagnosis layer must pay for itself on the FULL serving-tier
tick — store refresh → incremental window build → diagnosis →
attribution → views → fragment serialization — not just on a rule
microbench.  Two ``SessionPublisher`` pipelines run over the SAME
session DB, one with ``TRACEML_VECTOR_DIAGNOSIS=1`` (vectorized gates +
per-(domain, version) diagnosis cache) and one with ``=0`` (the scalar
pre-change reference arm).  Interleaved min-of-N warm ticks, golden
byte-comparison of the served payload between arms BEFORE any timing:

* steady-state warm tick (heartbeat: a model_stats-only ingest
  re-dirties the step_time payload without advancing any diagnosis
  input — the serving tier's dominant tick shape between step bursts)
  at 1024 ranks × 240 steps: vectorized arm ≥ 3× faster than the
  scalar arm, the diagnosis cache hits, and ZERO rules evaluate;
* step-burst tick (one new step per rank lands between polls) is
  reported per arm as an informational metric — both arms share the
  irreducible refresh + ring-buffer-append + json.dumps floor there,
  so it is not the gated number;
* the per-stage tick profile (``TICK_STAGES``) for the vectorized arm
  is emitted as bench_common lines at the gate size.

The fixture is a clean straggler at scale (rank 0 slow in residual,
every other rank inflated by sync wait) so the straggler rules fire and
the scalar arm pays the per-rank window materialization the vector
gates avoid.  Results print as bench_common JSON lines (collected into
BENCH_LOCAL_r20.json at the repo root).
"""

import json
import os
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
import bench_common  # noqa: E402

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter  # noqa: E402
from traceml_tpu.renderers.serving import SessionPublisher  # noqa: E402
from traceml_tpu.samplers.serving_sampler import pack_floats  # noqa: E402
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_telemetry_envelope,
)
from traceml_tpu.utils import timing as T  # noqa: E402

pytestmark = pytest.mark.slow

BENCH = "tick_pipeline"
FLAG = "TRACEML_VECTOR_DIAGNOSIS"
WINDOW = 240
STEPS = 240
RANKS_PER_NODE = 8
SERVING_RANKS = 8
REPS = 5

ARMS = (("vector", "1"), ("legacy", "0"))


# -- synthetic session -----------------------------------------------------


def _ident(rank, world):
    node = rank // RANKS_PER_NODE
    return SenderIdentity(
        session_id="bench",
        global_rank=rank,
        local_rank=rank % RANKS_PER_NODE,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=1000 + rank,
    )


def _step_rows(rank, start, n):
    """Clean-straggler fixture at scale: rank 0 is slow in residual
    (backward small, unexplained time large), every other rank's step is
    inflated by sync wait (backward swallows the gap).  Straggler rules
    fire, so the scalar arm runs the component-delta attribution over
    every rank's materialized window."""
    rows = []
    slow = rank == 0
    for s in range(start, start + n):
        base = 200.0 + (s % 7) * 0.05 + (rank % 5) * 0.01
        backward = 60.0 if slow else 156.0 + (s % 3) * 0.01
        rows.append({
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": base, "device_ms": base, "count": 1},
                T.DATALOADER_NEXT: {
                    "cpu_ms": 4.0, "device_ms": None, "count": 1,
                },
                T.BACKWARD_TIME: {
                    "cpu_ms": backward, "device_ms": backward, "count": 1,
                },
            },
        })
    return rows


def _model_row(ts):
    return {
        "timestamp": ts, "flops_per_step": 1.2e12,
        "flops_source": "estimated", "device_kind": "tpu",
        "peak_flops": 1.97e14, "device_count": 1,
        "tokens_per_step": 4096.0,
    }


def _mem_rows(start, n):
    return [
        {"step": s, "timestamp": float(s), "device_id": 0,
         "device_kind": "tpu", "current_bytes": 1 << 30,
         "peak_bytes": (1 << 30) + s, "step_peak_bytes": 1 << 30,
         "limit_bytes": 16 << 30, "backend": "fake"}
        for s in range(start, start + n)
    ]


def _coll_rows(rank, start, n):
    """One poorly-overlapped all_reduce every 4th step — enough volume
    to keep the collectives rules honest without doubling the DB."""
    rows = []
    for s in range(start, start + n):
        if s % 4:
            continue
        dur = 12.0 + (rank % 7) * 0.25
        rows.append({
            "step": s, "timestamp": float(s), "op": "all_reduce",
            "dtype": "float32", "count": 2, "bytes": 1 << 22,
            "group_size": RANKS_PER_NODE, "duration_ms": dur,
            "exposed_ms": dur * 0.8,
        })
    return rows


def _srv_rows(rank, start, n):
    rows = []
    for s in range(start, start + n):
        if s % 4:
            continue
        rows.append({
            "step": s, "timestamp": float(s),
            "requests_enqueued": 4, "requests_completed": 3,
            "requests_active": 2, "queue_depth": 6 + (rank % 3),
            "decode_tokens": 128, "prefill_ms": 18.0,
            "decode_ms": 90.0 + rank, "tokens_per_s": 240.0 - rank,
            "batch_occupancy": 0.5,
            "kv_bytes": 1 << 30, "kv_limit_bytes": 2 << 30,
            "kv_headroom": 0.5,
            "ttft_ms_list": pack_floats([40.0, 55.0, 70.0]),
            "e2e_ms_list": pack_floats([200.0, 260.0, 320.0]),
            "tokens_list": "16,16,16",
        })
    return rows


def _seed_db(db, ranks, steps):
    w = SQLiteWriter(db)
    w.start()
    for rank in range(ranks):
        ident = _ident(rank, ranks)
        w.ingest(build_telemetry_envelope(
            "step_time",
            {
                "step_time": _step_rows(rank, 1, steps),
                "model_stats": [_model_row(1.0)],
            },
            ident,
        ))
        w.ingest(build_telemetry_envelope(
            "step_memory",
            {"step_memory": _mem_rows(max(1, steps - 59), min(steps, 60))},
            ident,
        ))
        w.ingest(build_telemetry_envelope(
            "collectives",
            {"collectives": _coll_rows(rank, 1, steps)},
            ident,
        ))
        if rank < SERVING_RANKS:
            w.ingest(build_telemetry_envelope(
                "serving", {"serving": _srv_rows(rank, 1, steps)}, ident,
            ))
        if rank % RANKS_PER_NODE == 0:
            w.ingest(build_telemetry_envelope(
                "system",
                {"system": [
                    {"timestamp": float(i), "cpu_pct": 30.0,
                     "memory_used_bytes": 8 << 30,
                     "memory_total_bytes": 32 << 30, "memory_pct": 25.0}
                    for i in range(4)
                ]},
                ident,
            ))
    assert w.force_flush()
    return w


# -- golden comparison -----------------------------------------------------


def _payload_bytes(pub):
    """Served payload canonicalized for cross-arm comparison: drop the
    wall-clock stamp and the profiler block (timings differ by arm by
    construction — every OTHER byte must match)."""
    obj = pub.full_payload_dict()
    obj.pop("ts", None)
    obj.pop("window_build", None)
    return json.dumps(obj, sort_keys=True).encode()


def _golden_compare(pubs):
    blobs = {}
    for name, flag in ARMS:
        os.environ[FLAG] = flag
        blobs[name] = _payload_bytes(pubs[name])
    assert blobs["vector"] == blobs["legacy"], (
        "vectorized arm changed served payload bytes"
    )


# -- timing ----------------------------------------------------------------


def _timed_poll(pub):
    t0 = time.perf_counter()
    pub.poll(force=True)
    return (time.perf_counter() - t0) * 1000.0


def _run_case(tmp_path, ranks, steps, emit_stages=False):
    saved = os.environ.get(FLAG)
    db = tmp_path / f"bench_{ranks}.sqlite"
    w = _seed_db(db, ranks, steps)
    pubs, cold_ms = {}, {}
    extra = {"ranks": ranks, "steps": steps, "window": WINDOW}
    try:
        for name, flag in ARMS:
            os.environ[FLAG] = flag
            pub = SessionPublisher(db, "bench", window_steps=WINDOW)
            pub.min_poll_interval = 0.0
            cold_ms[name] = _timed_poll(pub)
            pubs[name] = pub

        # identical served bytes before ANY timing is trusted
        _golden_compare(pubs)

        # step-burst ticks: one new step per rank lands, then each arm
        # polls the same dirty store (order alternates per rep) —
        # informational, both arms share the refresh/append/json floor
        burst = {name: [] for name, _ in ARMS}
        next_step = steps + 1
        for rep in range(REPS):
            for rank in range(ranks):
                w.ingest(build_telemetry_envelope(
                    "step_time",
                    {"step_time": _step_rows(rank, next_step, 1)},
                    _ident(rank, ranks),
                ))
            assert w.force_flush()
            order = ARMS if rep % 2 == 0 else ARMS[::-1]
            for name, flag in order:
                os.environ[FLAG] = flag
                burst[name].append(_timed_poll(pubs[name]))
            next_step += 1
        _golden_compare(pubs)  # arms still byte-identical after warmup

        # warm steady-state (heartbeat) ticks — the GATED number: a
        # model_stats-only ingest re-dirties the step_time payload (MFU
        # block) without advancing any diagnosis input.  The legacy arm
        # re-runs build → rules → views → dataclasses.asdict over all
        # ranks; the vectorized arm rides the window/table/diagnosis
        # caches and only rebuilds the MFU block + serialization
        times = {name: [] for name, _ in ARMS}
        prof = pubs["vector"]._computer.store.tick_profile
        hits0 = prof.counters.get("diag_cache_hits", 0)
        evals0 = prof.counters.get("rule_evals", 0)
        for rep in range(REPS):
            w.ingest(build_telemetry_envelope(
                "step_time",
                {"model_stats": [_model_row(1000.0 + rep)]},
                _ident(0, ranks),
            ))
            assert w.force_flush()
            order = ARMS if rep % 2 == 0 else ARMS[::-1]
            for name, flag in order:
                os.environ[FLAG] = flag
                times[name].append(_timed_poll(pubs[name]))
        _golden_compare(pubs)
        vec_ms = min(times["vector"])
        leg_ms = min(times["legacy"])
        # every vector-arm heartbeat tick must have hit the diagnosis
        # cache and evaluated ZERO rules (the ISSUE acceptance)
        hit_ticks = prof.counters.get("diag_cache_hits", 0) - hits0
        rule_evals = prof.counters.get("rule_evals", 0) - evals0
        assert hit_ticks >= REPS, prof.counters
        assert rule_evals == 0, prof.counters

        for name, _ in ARMS:
            bench_common.emit(
                BENCH, "cold_tick", cold_ms[name], "ms", arm=name, **extra
            )
            bench_common.emit(
                BENCH, "step_burst_tick", min(burst[name]), "ms",
                arm=name, **extra,
            )
            bench_common.emit(
                BENCH, "warm_tick", min(times[name]), "ms",
                arm=name, **extra,
            )
        speedup = leg_ms / max(vec_ms, 1e-6)
        burst_speedup = min(burst["legacy"]) / max(min(burst["vector"]), 1e-6)
        bench_common.emit(BENCH, "speedup_warm_tick", speedup, "x", **extra)
        bench_common.emit(
            BENCH, "speedup_step_burst", burst_speedup, "x", **extra
        )

        if emit_stages:
            snap = prof.snapshot()
            ticks = max(1, snap["ticks"])
            for domain in sorted(snap["stage_ns"]):
                for stage, ns in sorted(snap["stage_ns"][domain].items()):
                    bench_common.emit(
                        BENCH, "stage_ms", ns / ticks / 1e6, "ms",
                        domain=domain, stage=stage, **extra,
                    )
            for key in ("diag_cache_hits", "diag_cache_misses", "rule_evals"):
                bench_common.emit(
                    BENCH, key, snap["counters"].get(key, 0), "count", **extra
                )
        return {"vector_ms": vec_ms, "legacy_ms": leg_ms,
                "burst": burst, "speedup": speedup}
    finally:
        if saved is None:
            os.environ.pop(FLAG, None)
        else:
            os.environ[FLAG] = saved
        for pub in pubs.values():
            pub.close()
        w.finalize()


@pytest.mark.parametrize("ranks", [128, 1024])
def test_tick_pipeline_bench(tmp_path, ranks):
    res = _run_case(tmp_path, ranks, STEPS, emit_stages=(ranks == 1024))
    if ranks == 1024:
        # the acceptance floor (ISSUE r20): total warm pipeline tick,
        # vectorized arm ≥ 3× the scalar pre-change arm
        assert res["speedup"] >= 3.0, res


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        for ranks in (128, 1024):
            _run_case(
                Path(d), ranks, STEPS, emit_stages=(ranks == 1024)
            )
