"""Serving-domain diagnosis: QUEUE_SATURATED, KV_CACHE_PRESSURE,
DECODE_BOUND, REPLICA_SKEW (see diagnostics/DIAGNOSIS.md)."""

from traceml_tpu.diagnostics.serving.api import (  # noqa: F401
    DOMAIN,
    diagnose_serving_window,
)
from traceml_tpu.diagnostics.serving.policy import (  # noqa: F401
    LIVE_POLICY,
    SUMMARY_POLICY,
    ServingPolicy,
    policy_for,
)
from traceml_tpu.diagnostics.serving.rules import (  # noqa: F401
    DEFAULT_RULES,
    ServingContext,
    build_context,
)
