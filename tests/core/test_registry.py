import pytest

from traceml_tpu.core.registry import Registry, RegistryError


def test_register_get_require():
    r = Registry("t")
    r.register("a", 1)
    assert r.get("a") == 1
    assert r.require("a") == 1
    assert r.get("missing") is None
    assert r.get("missing", 42) == 42
    with pytest.raises(RegistryError):
        r.require("missing")


def test_duplicate_and_overwrite():
    r = Registry()
    r.register("a", 1)
    with pytest.raises(RegistryError):
        r.register("a", 2)
    r.register("a", 2, overwrite=True)
    assert r.get("a") == 2


def test_order_and_iteration():
    r = Registry()
    for k in ("z", "m", "a"):
        r.register(k, k.upper())
    assert r.keys() == ["z", "m", "a"]
    assert list(r) == ["z", "m", "a"]
    assert len(r) == 3
    assert "m" in r


def test_decorator():
    r = Registry()

    @r.decorator("fn")
    def fn():
        return 7

    assert r.get("fn") is fn
