"""Step-memory thresholds
(reference: src/traceml_ai/diagnostics/step_memory/policy.py:13-93)."""

from __future__ import annotations

import dataclasses

MiB = 1024 * 1024
GiB = 1024 * MiB


@dataclasses.dataclass(frozen=True)
class StepMemoryPolicy:
    pressure_warn: float = 0.92  # used / capacity
    pressure_critical: float = 0.97
    imbalance_warn: float = 0.20  # cross-rank skew
    imbalance_critical: float = 0.30
    imbalance_pressure_gate: float = 0.5  # only interesting when ≥50% full
    # creep heuristics (reference: trend.py:31-57, policy.py:27)
    creep_min_steps: int = 800
    creep_min_delta_bytes: int = 512 * MiB
    creep_min_growth_pct: float = 0.06
    creep_min_slope_per_100: float = 0.00015  # fraction of capacity
    creep_confirmed_delta_bytes: int = 1 * GiB


DEFAULT_POLICY = StepMemoryPolicy()
