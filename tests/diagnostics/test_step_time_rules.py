"""Step-time diagnosis tests with hand-built step rows
(reference style: tests/diagnostics/test_step_time.py:35-60)."""

from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows, diagnose_window
from traceml_tpu.utils.step_time_window import build_step_time_window
from traceml_tpu.utils import timing as T


def _row(step, step_ms, input_ms=0.0, h2d_ms=0.0, compute_ms=0.0,
         backward_ms=None, compile_ms=0.0, clock="device"):
    events = {
        T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
    }
    if input_ms:
        events[T.DATALOADER_NEXT] = {"cpu_ms": input_ms, "device_ms": None, "count": 1}
    if h2d_ms:
        events[T.H2D_TIME] = {"cpu_ms": 0.2, "device_ms": h2d_ms, "count": 1}
    if compute_ms:
        events[T.COMPUTE_TIME] = {"cpu_ms": 0.5, "device_ms": compute_ms, "count": 1}
    if backward_ms is not None:
        events[T.BACKWARD_TIME] = {"cpu_ms": backward_ms, "device_ms": backward_ms, "count": 1}
    if compile_ms:
        events[T.COMPILE_TIME] = {"cpu_ms": compile_ms, "device_ms": None, "count": 1}
    return {"step": step, "clock": clock, "events": events}


def _steady_rows(n, step_ms, **kw):
    return [_row(s, step_ms, **kw) for s in range(1, n + 1)]


def test_healthy_compute_bound():
    rows = {
        r: _steady_rows(60, 100.0, input_ms=3.0, compute_ms=92.0)
        for r in range(4)
    }
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "COMPUTE_BOUND"
    assert result.diagnosis.severity == "info"


def test_input_bound_fires():
    rows = {
        r: _steady_rows(60, 100.0, input_ms=45.0, compute_ms=50.0)
        for r in range(2)
    }
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "INPUT_BOUND"
    assert result.diagnosis.severity == "critical"  # 45% ≥ 0.40
    assert abs(result.diagnosis.share_pct - 0.45) < 0.01


def test_input_bound_warn_level():
    rows = {0: _steady_rows(60, 100.0, input_ms=33.0, compute_ms=60.0)}
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "INPUT_BOUND"
    assert result.diagnosis.severity == "warning"  # 0.30 ≤ 0.33 < 0.40


def test_input_straggler_on_one_rank():
    # ranks 0-2 healthy; rank 3's input wait is huge (reference demo:
    # rank input 254.5ms vs median 3.8ms)
    rows = {}
    for r in range(3):
        rows[r] = _steady_rows(60, 100.0, input_ms=4.0, compute_ms=90.0)
    rows[3] = _steady_rows(60, 280.0, input_ms=184.0, compute_ms=90.0)
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "INPUT_STRAGGLER"
    assert result.diagnosis.ranks == [3]
    assert result.diagnosis.score > 0.10


def test_clean_straggler_discounts_sync_wait():
    """Fast ranks' backward inflated by allreduce wait for the slow rank
    must NOT be flagged; the slow rank's compute must be."""
    rows = {}
    # rank 0 slow in backward-only (genuine compute straggler):
    # others wait inside backward (sync), so their backward is inflated too
    for r in range(4):
        if r == 0:
            rows[r] = _steady_rows(60, 200.0, input_ms=4.0, backward_ms=160.0)
        else:
            # non-sync work 40ms; backward = own 60 + wait 100 = 160
            rows[r] = _steady_rows(60, 200.0, input_ms=4.0, backward_ms=160.0)
    # identical ranks → no straggler at all (all the same)
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind != "COMPUTE_STRAGGLER"

    # now make rank 0 genuinely slower in non-sync (forward-equivalent
    # residual) — others' steps stretch via sync wait but clean-step
    # should isolate rank 0
    rows = {}
    for r in range(4):
        if r == 0:
            # 100ms residual-ish compute (in step, not in phases) + 60 bwd
            rows[r] = _steady_rows(60, 200.0, input_ms=4.0, backward_ms=60.0)
        else:
            # fast non-sync (44ms) but backward shows 60 own + 96 wait
            rows[r] = _steady_rows(60, 200.0, input_ms=4.0, backward_ms=156.0)
    result = diagnose_rank_rows(rows, mode="summary")
    # rank 0's clean step = 140 + 60 = 200; others: 44 + max(0,156-(196-44))=44+4=48+44=...
    # others clean: non_sync=44, clean_sync = max(0, 156 - (140-44)...
    assert result.diagnosis.kind in ("RESIDUAL_STRAGGLER", "STRAGGLER", "COMPUTE_STRAGGLER")
    assert result.diagnosis.ranks == [0]


def test_compile_bound_fires_on_recompile_storm():
    rows = {0: []}
    for s in range(1, 61):
        compile_ms = 400.0 if s % 3 == 0 else 0.0  # recompiling every 3 steps
        rows[0].append(_row(s, 100.0 + compile_ms, compute_ms=90.0, compile_ms=compile_ms))
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "COMPILE_BOUND"
    assert result.diagnosis.severity == "critical"


def test_residual_heavy():
    # step 100ms, only 60 accounted → 40% residual
    rows = {0: _steady_rows(60, 100.0, input_ms=5.0, compute_ms=55.0)}
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "RESIDUAL_HEAVY"
    assert result.diagnosis.severity == "critical"


def test_insufficient_data():
    rows = {0: _steady_rows(10, 100.0, compute_ms=90.0)}
    result = diagnose_rank_rows(rows, mode="summary")
    assert result.diagnosis.kind == "INSUFFICIENT_STEP_TIME_DATA"
    assert result.healthy


def test_clock_selection_falls_back_to_host():
    rows = {
        0: [_row(s, 100.0, compute_ms=90.0) for s in range(1, 61)],
        1: [_row(s, 100.0, compute_ms=90.0, clock="host") for s in range(1, 61)],
    }
    # rank 1 rows claim host clock → whole window must use host clock
    w = build_step_time_window(rows)
    assert w.clock == "host"


def test_window_suffix_alignment():
    rows = {
        0: [_row(s, 100.0, compute_ms=90.0) for s in range(1, 101)],
        1: [_row(s, 100.0, compute_ms=90.0) for s in range(41, 101)],
    }
    w = build_step_time_window(rows, max_steps=200)
    assert w.steps[0] == 41
    assert w.n_steps == 60


def test_diagnose_window_none():
    result = diagnose_window(None, mode="summary")
    assert result.diagnosis.kind == "INSUFFICIENT_STEP_TIME_DATA"


# -- evidence-derived confidence (r4) --------------------------------------

def test_confidence_from_formula():
    from traceml_tpu.diagnostics.common import confidence_from, confidence_label

    # at the bar, full window, single statistic → borderline
    at_bar = confidence_from(0.30, 0.30)
    assert 0.5 <= at_bar < 0.60
    assert confidence_label(at_bar) == "low"
    # at 2× the bar → high
    strong = confidence_from(0.60, 0.30)
    assert strong >= 0.85 and confidence_label(strong) == "high"
    # thin window scales down; disagreement scales down further
    assert confidence_from(0.60, 0.30, coverage=0.5) < strong
    assert confidence_from(0.60, 0.30, agreement=False) < strong
    # never exceeds 1
    assert confidence_from(100.0, 0.01) <= 1.0
    assert confidence_label(None) is None


def test_input_bound_confidence_scales_with_margin():
    def input_issue(input_ms, compute_ms):
        result = diagnose_rank_rows(
            {0: _steady_rows(60, 100.0, input_ms=input_ms,
                             compute_ms=compute_ms)},
            mode="summary",
        )
        return next(i for i in result.issues if i.kind == "INPUT_BOUND")

    weak = input_issue(33.0, 60.0)
    strong = input_issue(80.0, 15.0)
    assert weak.confidence is not None and strong.confidence is not None
    assert strong.confidence > weak.confidence
    assert strong.to_dict()["confidence_label"] in ("medium", "high")


def test_straggler_confidence_carries_agreement():
    rows = {r: _steady_rows(60, 100.0, compute_ms=95.0) for r in range(3)}
    rows[3] = _steady_rows(60, 420.0, compute_ms=410.0)
    diag = diagnose_rank_rows(rows, mode="summary").diagnosis
    assert diag.kind in ("COMPUTE_STRAGGLER", "STRAGGLER")
    # a persistent 4× straggler is seen by BOTH statistics → high
    assert diag.confidence is not None and diag.confidence >= 0.85
    assert diag.to_dict()["confidence_label"] == "high"


def test_symptom_never_outranks_its_cause():
    """LOW_DEVICE_UTILIZATION (symptom) must not beat a same-severity
    INPUT_BOUND (cause) in the severity→score sort, even when
    1 − occupancy is numerically larger than the input share (found in
    r4: a long input_bound run promoted the symptom)."""
    # heavy input, almost no device work → occupancy ~2%, input ~83%
    rows = {0: _steady_rows(60, 72.0, input_ms=60.0, compute_ms=1.4)}
    result = diagnose_rank_rows(rows, mode="summary")
    kinds = [i.kind for i in result.issues]
    assert "INPUT_BOUND" in kinds and "LOW_DEVICE_UTILIZATION" in kinds
    assert result.diagnosis.kind == "INPUT_BOUND"
    occ = next(i for i in result.issues
               if i.kind == "LOW_DEVICE_UTILIZATION")
    assert occ.evidence.get("explained_by") == "INPUT_BOUND"


def test_symptom_stands_alone_when_no_cause_fired():
    # low occupancy with NO dominant phase: nothing explains it →
    # the symptom keeps its own rank
    rows = {0: _steady_rows(60, 100.0, input_ms=10.0, compute_ms=9.0)}
    result = diagnose_rank_rows(rows, mode="summary")
    if result.diagnosis.kind == "LOW_DEVICE_UTILIZATION":
        assert "explained_by" not in result.diagnosis.evidence
