"""Telemetry envelope (reference: src/traceml_ai/telemetry/envelope.py:92-166).

Canonical shape on the wire::

    {
      "meta": {
        "schema": 1,
        "session_id": str,
        "sampler": str,                # e.g. "step_time"
        "timestamp": float,            # sender host unix time
        "rank": int,                   # == global_rank (back-compat alias)
        "global_rank": int,
        "local_rank": int,
        "world_size": int,
        "local_world_size": int,
        "node_rank": int,
        "hostname": str,
        "pid": int,
        "platform": str,               # "tpu" | "cpu" | "gpu"
        "device_kind": str,            # e.g. "TPU v5p"
      },
      "body": {"tables": {table_name: [row, ...]}}
    }

``normalize_telemetry_envelope`` accepts the canonical shape and a legacy
flat shape ``{"sampler":..., "tables":...}`` and always returns the
canonical one — the aggregator only ever sees canonical envelopes.
"""

from __future__ import annotations

import dataclasses
import socket
import os
import time
from typing import Any, Dict, List, Mapping, Optional

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class SenderIdentity:
    """Identity attached to every envelope a rank emits
    (reference: runtime/identity.py:88-131; extended with TPU fields)."""

    session_id: str = "unknown"
    global_rank: int = 0
    local_rank: int = 0
    world_size: int = 1
    local_world_size: int = 1
    node_rank: int = 0
    hostname: str = dataclasses.field(default_factory=socket.gethostname)
    pid: int = dataclasses.field(default_factory=os.getpid)
    platform: str = "cpu"
    device_kind: str = "unknown"

    def to_meta(self) -> Dict[str, Any]:
        return {
            "schema": SCHEMA_VERSION,
            "session_id": self.session_id,
            "rank": self.global_rank,
            "global_rank": self.global_rank,
            "local_rank": self.local_rank,
            "world_size": self.world_size,
            "local_world_size": self.local_world_size,
            "node_rank": self.node_rank,
            "hostname": self.hostname,
            "pid": self.pid,
            "platform": self.platform,
            "device_kind": self.device_kind,
        }


@dataclasses.dataclass
class TelemetryEnvelope:
    meta: Dict[str, Any]
    tables: Dict[str, List[Dict[str, Any]]]

    @property
    def sampler(self) -> str:
        return str(self.meta.get("sampler", "unknown"))

    @property
    def global_rank(self) -> int:
        return int(self.meta.get("global_rank", self.meta.get("rank", 0)))

    def to_wire(self) -> Dict[str, Any]:
        return {"meta": dict(self.meta), "body": {"tables": self.tables}}


def build_telemetry_envelope(
    sampler: str,
    tables: Mapping[str, List[Dict[str, Any]]],
    identity: Optional[SenderIdentity] = None,
    timestamp: Optional[float] = None,
) -> TelemetryEnvelope:
    identity = identity or SenderIdentity()
    meta = identity.to_meta()
    meta["sampler"] = sampler
    meta["timestamp"] = time.time() if timestamp is None else timestamp
    return TelemetryEnvelope(meta=meta, tables={k: list(v) for k, v in tables.items()})


def normalize_telemetry_envelope(payload: Any) -> Optional[TelemetryEnvelope]:
    """Coerce a decoded wire payload into a canonical envelope.

    Returns None for payloads that are not telemetry (e.g. control
    messages, garbage) — the caller decides what to do with those.
    """
    if not isinstance(payload, Mapping):
        return None
    if "meta" in payload and "body" in payload:
        meta = payload.get("meta")
        body = payload.get("body")
        if not isinstance(meta, Mapping) or not isinstance(body, Mapping):
            return None
        tables = body.get("tables")
        if not isinstance(tables, Mapping):
            return None
        meta = dict(meta)
        meta.setdefault("schema", SCHEMA_VERSION)
        meta.setdefault("global_rank", meta.get("rank", 0))
        meta.setdefault("rank", meta.get("global_rank", 0))
        return TelemetryEnvelope(
            meta=meta,
            tables={str(k): list(v) for k, v in tables.items() if isinstance(v, list)},
        )
    # Legacy flat shape: {"sampler": ..., "tables": {...}, **identity}
    if "tables" in payload and "sampler" in payload:
        tables = payload.get("tables")
        if not isinstance(tables, Mapping):
            return None
        meta = {
            k: v
            for k, v in payload.items()
            if k not in ("tables",) and not isinstance(v, (dict, list))
        }
        meta.setdefault("schema", SCHEMA_VERSION)
        meta.setdefault("global_rank", meta.get("rank", 0))
        meta.setdefault("rank", meta.get("global_rank", 0))
        meta.setdefault("timestamp", time.time())
        return TelemetryEnvelope(
            meta=meta,
            tables={str(k): list(v) for k, v in tables.items() if isinstance(v, list)},
        )
    return None
