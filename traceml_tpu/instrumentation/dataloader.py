"""Input-pipeline (dataloader) timing
(reference: src/traceml_ai/instrumentation/patches/dataloader_patch.py:8-34).

JAX has no canonical DataLoader class, so the primary surface is a
generic iterator wrapper: each ``next()`` is timed as
``dataloader_next`` — the input-wait phase that drives the INPUT_BOUND
and INPUT_STRAGGLER diagnoses.  For torch, an auto-patch replaces
``DataLoader.__iter__`` with the same wrapper.

Optionally the wrapper also moves each batch to device with timed
``device_put`` (``to_device=True``) — the recommended JAX pattern, since
an implicit transfer inside a jitted call cannot be attributed to h2d.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

from traceml_tpu.sdk.state import TraceState, get_state
from traceml_tpu.sdk.wrappers import publish_region_marker
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import DATALOADER_NEXT, H2D_TIME, timed_region

_PATCHED_FLAG = "_traceml_tpu_patched"


def _timed_device_put(batch: Any, state: TraceState, device: Any = None) -> Any:
    import jax

    region = timed_region(H2D_TIME, state.current_step, sink=state.buffer.add)
    with region as tr:
        out = (
            jax.device_put(batch) if device is None else jax.device_put(batch, device)
        )
        if state.markers_enabled():
            tr.mark(out)
    # shared chokepoint: envelope hand-off + governor gate + resolver
    # submission (sdk/wrappers.publish_region_marker)
    publish_region_marker(region.event, state)
    return out


class wrap_dataloader:
    """Iterate a dataloader with per-``next()`` input-wait timing.

    Duplicate-instrumentation guard: wrapping an already-wrapped iterator
    returns it unchanged (reference: sdk/wrappers.py duplicate guards).
    """

    def __new__(cls, iterable: Iterable, *args: Any, **kwargs: Any):
        if isinstance(iterable, wrap_dataloader):
            return iterable
        return super().__new__(cls)

    def __init__(
        self,
        iterable: Iterable,
        *,
        to_device: bool = False,
        device: Any = None,
        state: Optional[TraceState] = None,
    ) -> None:
        if getattr(self, "_init_done", False):
            return
        self._init_done = True
        self._iterable = iterable
        self._to_device = to_device
        self._device = device
        self._state = state or get_state()

    def __iter__(self) -> Iterator[Any]:
        st = self._state
        it = iter(self._iterable)
        while True:
            # Nested-timer guard: a DataLoader whose __iter__ was patched
            # would double-time `next()`; the TLS depth gate prevents it.
            if st.tls.dataloader_depth > 0:
                try:
                    batch = next(it)
                except StopIteration:
                    return
            else:
                st.tls.dataloader_depth += 1
                region = timed_region(
                    DATALOADER_NEXT, st.current_step, sink=None
                )
                try:
                    with region:
                        batch = next(it)
                except StopIteration:
                    return
                finally:
                    st.tls.dataloader_depth -= 1
                # Only record real batches (not the StopIteration probe).
                try:
                    st.buffer.add(region.event)
                except Exception as exc:
                    get_error_log().warning("dataloader event add failed", exc)
            if self._to_device:
                try:
                    batch = _timed_device_put(batch, st, self._device)
                except Exception as exc:
                    get_error_log().warning("dataloader device_put failed", exc)
            yield batch

    def __len__(self) -> int:
        return len(self._iterable)  # type: ignore[arg-type]


def patch_torch_dataloader() -> bool:
    """Replace ``torch.utils.data.DataLoader.__iter__`` with a timing
    generator (reference: dataloader_patch.py:8-34).  Idempotent."""
    try:
        from torch.utils.data import DataLoader
    except Exception:
        return False
    if getattr(DataLoader, _PATCHED_FLAG, False):
        return True
    original_iter = DataLoader.__iter__

    def patched_iter(self):  # noqa: ANN001
        st = get_state()
        it = original_iter(self)
        while True:
            if st.tls.dataloader_depth > 0:
                try:
                    yield next(it)
                except StopIteration:
                    return
                continue
            st.tls.dataloader_depth += 1
            region = timed_region(DATALOADER_NEXT, st.current_step, sink=None)
            try:
                with region:
                    batch = next(it)
            except StopIteration:
                return
            finally:
                st.tls.dataloader_depth -= 1
            try:
                st.buffer.add(region.event)
            except Exception:
                pass
            yield batch

    patched_iter._traceml_original = original_iter  # type: ignore[attr-defined]
    DataLoader.__iter__ = patched_iter
    setattr(DataLoader, _PATCHED_FLAG, True)
    return True


def unpatch_torch_dataloader() -> None:
    try:
        from torch.utils.data import DataLoader
    except Exception:
        return
    patched = DataLoader.__iter__
    original = getattr(patched, "_traceml_original", None)
    if original is not None:
        DataLoader.__iter__ = original
        setattr(DataLoader, _PATCHED_FLAG, False)
