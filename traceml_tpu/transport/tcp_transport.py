"""Length-prefixed TCP transport
(reference: src/traceml_ai/transport/tcp_transport.py:21-268).

Frames: 4-byte big-endian length + codec body (see utils/msgpack_codec).
One ``send_batch`` call encodes a *list* of payloads into ONE frame and one
``sendall`` — the per-tick batching contract that keeps syscall count O(1)
per sampler tick.

Differences from the reference, chosen for the TPU build:

* the server is a **single selector-driven thread** (accept + read for all
  clients) instead of thread-per-client — hundreds of ranks on a pod slice
  must not mean hundreds of threads in the aggregator;
* the receive path drains complete frames in O(bytes) with a rolling
  buffer offset (the reference ships an O(N) drain too, proved by its
  bench tests/benchmarks/bench_tcp_drain.py);
* the selector thread only **splits frames** — msgpack decode happens on
  the consumer's thread (``drain()`` returns raw frames;
  ``decode_frames``/``drain_decoded`` do the decode), so one rank sending
  a huge batch can never stall accepts/reads for every other rank.

Frame bodies carry telemetry envelopes in schema v1 (row-list) or
schema v2 (columnar struct-of-arrays) — layout and negotiation are
documented in docs/developer_guide/wire-schema-v2.md.

The client is best-effort and NEVER raises into training code: lazy
connect, drop-on-failure, bounded reconnect backoff
(reference contract: tcp_transport.py:182-268).
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 256 * 1024 * 1024  # sanity bound against corrupt lengths

# optional C fast path (traceml_tpu/native/framing.c); None → pure Python
try:
    from traceml_tpu.native import get_framing

    _native = get_framing()
except Exception:  # pragma: no cover
    _native = None


class _ClientBuffer:
    """Incremental frame decoder with O(total bytes) drain (C fast path
    when the native extension built; identical framing either way)."""

    __slots__ = ("buf", "offset")

    def __init__(self) -> None:
        self.buf = bytearray()
        self.offset = 0  # consumed prefix

    def feed(self, data: bytes) -> List[bytes]:
        self.buf.extend(data)
        if _native is not None:
            # Pass the bytearray itself (y* accepts any buffer object) —
            # bytes(self.buf) would copy the whole rolling buffer per recv,
            # degrading a large multi-recv frame to O(buffered bytes/recv).
            frames, consumed = _native.drain_frames(
                self.buf, self.offset, MAX_FRAME_BYTES
            )
            self.offset = consumed
        else:
            frames = []
            while True:
                avail = len(self.buf) - self.offset
                if avail < _LEN.size:
                    break
                (n,) = _LEN.unpack_from(self.buf, self.offset)
                if n > MAX_FRAME_BYTES:
                    raise ValueError(f"frame length {n} exceeds bound")
                if avail < _LEN.size + n:
                    break
                start = self.offset + _LEN.size
                frames.append(bytes(self.buf[start : start + n]))
                self.offset = start + n
        # Compact once consumed prefix dominates — amortized O(1) per byte.
        if self.offset > 65536 and self.offset * 2 > len(self.buf):
            del self.buf[: self.offset]
            self.offset = 0
        return frames


def encode_frame(payload: Any) -> bytes:
    body = msgpack_codec.encode(payload)
    if _native is not None:
        return _native.pack_frames([body])
    return _LEN.pack(len(body)) + body


class TCPServer:
    """Aggregator-side ingest server.

    Raw frames are appended to an internal thread-safe queue; the
    aggregator loop blocks on :meth:`wait_for_data`, pulls frames with
    :meth:`drain`, and decodes them on its own thread via
    :meth:`decode_frames` (reference: tcp_transport.py:119-178).  Callers
    that don't care about the split can use :meth:`drain_decoded`.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._host = host
        self._requested_port = port
        self._sock: Optional[socket.socket] = None
        self._selector: Optional[selectors.DefaultSelector] = None
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._wake_r, self._wake_w = socket.socketpair()
        self._lock = threading.Lock()
        self._pending: List[Any] = []
        self._data_event = threading.Event()
        self._clients: Dict[int, _ClientBuffer] = {}
        self._stopped = False
        self.port: Optional[int] = None
        self.frames_received = 0
        self.decode_errors = 0
        # deepest the undrained-frame buffer ever got: a proxy for how
        # far the consumer fell behind the selector thread
        self.pending_hwm = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._stopped:
            raise RuntimeError(
                "TCPServer is single-use: construct a new instance after stop()"
            )
        if self._thread is not None:
            return
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._requested_port))
        sock.listen(128)
        sock.setblocking(False)
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._selector = selectors.DefaultSelector()
        self._selector.register(sock, selectors.EVENT_READ, ("accept", None))
        self._selector.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        self._running.set()
        self._thread = threading.Thread(
            target=self._serve, name="traceml-tcp-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop and release every fd.  A stopped server is single-use."""
        if self._thread is None:
            return
        self._stopped = True
        self._running.clear()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._thread.join(timeout=5)
        self._thread = None
        try:
            if self._selector:
                for key in list(self._selector.get_map().values()):
                    try:
                        self._selector.unregister(key.fileobj)
                        if key.fileobj not in (self._sock, self._wake_r):
                            key.fileobj.close()
                    except Exception:
                        pass
                self._selector.close()
        except Exception:
            pass
        self._clients.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    # -- consumer API --------------------------------------------------
    def wait_for_data(self, timeout: float) -> bool:
        fired = self._data_event.wait(timeout)
        if fired:
            self._data_event.clear()
        return fired

    def drain(self, max_frames: Optional[int] = None) -> List[bytes]:
        """Pull raw frames accumulated by the selector thread.

        With ``max_frames`` set, hands over at most that many frames and
        leaves the rest pending (the data event stays observable via
        :meth:`pending_frames`), so one drain call can't hold the caller
        hostage decoding an unbounded backlog.
        """
        with self._lock:
            if max_frames is None or len(self._pending) <= max_frames:
                out = self._pending
                self._pending = []
            else:
                out = self._pending[:max_frames]
                del self._pending[:max_frames]
        return out

    def pending_frames(self) -> int:
        """Frames buffered by the selector thread, awaiting drain()."""
        with self._lock:
            return len(self._pending)

    def decode_frames(self, frames: List[bytes]) -> List[Any]:
        """Decode raw frames into a flat payload list on the CALLER's
        thread (batch frames are flattened); bumps ``decode_errors``."""
        payloads, errors = msgpack_codec.decode_batch(frames)
        if errors:
            self.decode_errors += errors
            get_error_log().warning(
                f"dropped {errors} undecodable frame(s) during drain"
            )
        return payloads

    def drain_decoded(self) -> List[Any]:
        """Convenience: :meth:`drain` + :meth:`decode_frames`."""
        return self.decode_frames(self.drain())

    # -- server thread -------------------------------------------------
    def _serve(self) -> None:
        assert self._selector is not None and self._sock is not None
        while self._running.is_set():
            try:
                events = self._selector.select(timeout=0.5)
            except OSError:
                break
            for key, _mask in events:
                kind, _ = key.data
                if kind == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                elif kind == "accept":
                    self._accept()
                else:
                    self._read(key.fileobj)

    def _accept(self) -> None:
        assert self._sock is not None and self._selector is not None
        try:
            while True:
                conn, _addr = self._sock.accept()
                conn.setblocking(False)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._clients[conn.fileno()] = _ClientBuffer()
                self._selector.register(conn, selectors.EVENT_READ, ("client", None))
        except BlockingIOError:
            return
        except OSError:
            return

    def _read(self, conn: socket.socket) -> None:
        assert self._selector is not None
        fileno = conn.fileno()
        try:
            data = conn.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            try:
                self._selector.unregister(conn)
            except Exception:
                pass
            self._clients.pop(fileno, None)
            try:
                conn.close()
            except OSError:
                pass
            return
        buf = self._clients.get(fileno)
        if buf is None:
            return
        try:
            frames = buf.feed(data)
        except ValueError as exc:
            get_error_log().warning(f"dropping client with bad frame: {exc}")
            try:
                self._selector.unregister(conn)
            except Exception:
                pass
            self._clients.pop(fileno, None)
            try:
                conn.close()
            except OSError:
                pass
            return
        if not frames:
            return
        # NO decode here: this is the selector thread, shared by every
        # client.  Frames are handed to the consumer as-is.
        self.frames_received += len(frames)
        with self._lock:
            self._pending.extend(frames)
            if len(self._pending) > self.pending_hwm:
                self.pending_hwm = len(self._pending)
        self._data_event.set()


class TCPClient:
    """Best-effort sender: never raises, lazily connects, drops on failure."""

    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: float = 2.0,
        reconnect_backoff: float = 1.0,
    ) -> None:
        self._host = host
        self._port = port
        self._timeout = connect_timeout
        self._backoff = reconnect_backoff
        self._sock: Optional[socket.socket] = None
        self._last_fail = 0.0
        self._lock = threading.Lock()
        # Serializes dialers; held WITHOUT self._lock during the blocking
        # create_connection so close() / a concurrent sender on an
        # established socket never waits behind a stalled connect.
        self._connect_lock = threading.Lock()
        self._gen = 0  # bumped by close(); a dial that straddles it is discarded
        # reusable frame buffer: steady-state sends assemble the length
        # prefix + body into one persistent bytearray instead of
        # allocating a fresh frame per tick.  Guarded by its own lock
        # (ordering: _framebuf_lock → _lock) so frame assembly — cheap
        # concatenation of pre-encoded bodies — never waits behind a
        # stalled sendall from the socket lock's perspective alone.
        self._framebuf = bytearray()
        self._framebuf_lock = threading.Lock()
        self.batches_sent = 0
        self.batches_dropped = 0

    def _ensure_connected(self) -> Optional[socket.socket]:
        with self._lock:
            if self._sock is not None:
                return self._sock
            if time.monotonic() - self._last_fail < self._backoff:
                return None
            gen = self._gen
        with self._connect_lock:
            with self._lock:
                if self._sock is not None:
                    return self._sock
                if self._gen != gen:
                    return None
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=self._timeout
                )
            except OSError:
                with self._lock:
                    self._last_fail = time.monotonic()
                return None
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self._timeout)
            except OSError:
                pass
            with self._lock:
                if self._gen != gen:
                    # close() raced the dial; don't resurrect the socket
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return None
                self._sock = sock
                return sock

    def send_batch(self, payloads: List[Any]) -> bool:
        """Encode ``payloads`` as ONE frame, one sendall. True on success.

        Members may be :class:`msgpack_codec.EncodedPayload` — their
        pre-encoded bodies are spliced into the batch array with zero
        re-encode (the producer's single-encode contract; see
        docs/developer_guide/rank-producer-path.md) — or plain objects,
        encoded here.  Encoding happens before the socket lock is taken
        — a large batch being msgpack'd must not block a concurrent
        close() or sender.
        """
        if not payloads:
            return True
        try:
            body = msgpack_codec.encode_batch(payloads)
        except Exception:
            self.batches_dropped += 1
            return False
        if self._ensure_connected() is None:
            self.batches_dropped += 1
            return False
        with self._framebuf_lock:
            buf = self._framebuf
            del buf[:]
            buf += _LEN.pack(len(body))
            buf += body
            with self._lock:
                if self._sock is None:  # torn down between connect and send
                    self.batches_dropped += 1
                    return False
                try:
                    self._sock.sendall(buf)
                    self.batches_sent += 1
                    return True
                except Exception:
                    self.batches_dropped += 1
                    self._teardown_locked()
                    return False

    def _teardown_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._last_fail = time.monotonic()

    def close(self) -> None:
        """Drop the current socket (a later send_batch may redial)."""
        with self._lock:
            self._gen += 1
            self._teardown_locked()
