"""Frozen runtime settings + the TRACEML_* env contract
(reference: src/traceml_ai/runtime/settings.py:26-82 and the env block
launcher/commands.py:292-341 — the ONLY contract between the launcher
and child processes).
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any, Dict, Optional

ENV_PREFIX = "TRACEML_"

# canonical env var names
ENV_SESSION_ID = "TRACEML_SESSION_ID"
ENV_LOGS_DIR = "TRACEML_LOGS_DIR"
ENV_MODE = "TRACEML_MODE"  # cli | summary
ENV_AGG_HOST = "TRACEML_AGGREGATOR_HOST"
ENV_AGG_BIND_HOST = "TRACEML_AGGREGATOR_BIND_HOST"
ENV_AGG_PORT = "TRACEML_AGGREGATOR_PORT"
ENV_SAMPLER_INTERVAL = "TRACEML_SAMPLER_INTERVAL_SEC"
ENV_MAX_STEPS = "TRACEML_TRACE_MAX_STEPS"
ENV_DISABLE = "TRACEML_DISABLE"
ENV_DISK_BACKUP = "TRACEML_DISK_BACKUP"
ENV_CAPTURE_STDERR = "TRACEML_CAPTURE_STDERR"
ENV_RUN_NAME = "TRACEML_RUN_NAME"
ENV_EXPECTED_WORLD_SIZE = "TRACEML_EXPECTED_WORLD_SIZE"
ENV_FINALIZE_TIMEOUT = "TRACEML_FINALIZE_TIMEOUT_SEC"
ENV_SUMMARY_WINDOW_ROWS = "TRACEML_SUMMARY_WINDOW_ROWS"
ENV_SERVE_MAX_SESSIONS = "TRACEML_SERVE_MAX_SESSIONS"
ENV_SCRIPT = "TRACEML_SCRIPT"
ENV_SCRIPT_ARGS = "TRACEML_SCRIPT_ARGS"


@dataclasses.dataclass(frozen=True)
class AggregatorEndpoint:
    """connect_host vs bind_host split for multi-node
    (reference: settings.py:36-49)."""

    connect_host: str = "127.0.0.1"
    bind_host: str = "127.0.0.1"
    port: int = 0


@dataclasses.dataclass(frozen=True)
class TraceMLSettings:
    session_id: str = "local"
    logs_dir: Path = Path("./traceml_logs")
    mode: str = "cli"  # cli | summary
    aggregator: AggregatorEndpoint = dataclasses.field(
        default_factory=AggregatorEndpoint
    )
    sampler_interval_sec: float = 1.0
    trace_max_steps: Optional[int] = None
    disabled: bool = False
    disk_backup: bool = False
    capture_stderr: bool = True
    run_name: Optional[str] = None
    expected_world_size: Optional[int] = None
    finalize_timeout_sec: float = 300.0
    summary_window_rows: int = 10000
    # serving tier: max concurrently-open session publishers (LRU bound
    # on sqlite connections) when one aggregator serves a fleet
    serve_max_sessions: int = 8

    @property
    def session_dir(self) -> Path:
        return Path(self.logs_dir) / self.session_id

    def rank_dir(self, global_rank: int) -> Path:
        return self.session_dir / f"rank_{global_rank}"

    def to_dict(self) -> Dict[str, object]:
        """JSON-safe dict (actor/subprocess hand-off)."""
        d = dataclasses.asdict(self)
        d["logs_dir"] = str(self.logs_dir)
        return d

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "TraceMLSettings":
        data = dict(data)
        agg = data.get("aggregator")
        if isinstance(agg, dict):
            data["aggregator"] = AggregatorEndpoint(**agg)
        if "logs_dir" in data:
            data["logs_dir"] = Path(str(data["logs_dir"]))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    @property
    def control_dir(self) -> Path:
        return self.session_dir / "control"


def _env_bool(env: Dict[str, str], name: str, default: bool) -> bool:
    v = env.get(name)
    if v is None:
        return default
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def settings_from_env(env: Optional[Dict[str, str]] = None) -> TraceMLSettings:
    e = dict(os.environ) if env is None else dict(env)

    def get(name: str, default: Any = None) -> Any:
        return e.get(name, default)

    max_steps = get(ENV_MAX_STEPS)
    expected_ws = get(ENV_EXPECTED_WORLD_SIZE)
    connect_host = get(ENV_AGG_HOST, "127.0.0.1")
    return TraceMLSettings(
        session_id=get(ENV_SESSION_ID, "local"),
        logs_dir=Path(get(ENV_LOGS_DIR, "./traceml_logs")),
        mode=get(ENV_MODE, "cli"),
        aggregator=AggregatorEndpoint(
            connect_host=connect_host,
            bind_host=get(ENV_AGG_BIND_HOST, connect_host),
            port=int(get(ENV_AGG_PORT, 0) or 0),
        ),
        sampler_interval_sec=float(get(ENV_SAMPLER_INTERVAL, 1.0) or 1.0),
        trace_max_steps=int(max_steps) if max_steps else None,
        disabled=_env_bool(e, ENV_DISABLE, False),
        disk_backup=_env_bool(e, ENV_DISK_BACKUP, False),
        capture_stderr=_env_bool(e, ENV_CAPTURE_STDERR, True),
        run_name=get(ENV_RUN_NAME) or None,
        expected_world_size=int(expected_ws) if expected_ws else None,
        finalize_timeout_sec=float(get(ENV_FINALIZE_TIMEOUT, 300.0) or 300.0),
        summary_window_rows=int(get(ENV_SUMMARY_WINDOW_ROWS, 10000) or 10000),
        serve_max_sessions=int(get(ENV_SERVE_MAX_SESSIONS, 8) or 8),
    )


def settings_to_env(s: TraceMLSettings) -> Dict[str, str]:
    """The launcher-side half of the contract."""
    env = {
        ENV_SESSION_ID: s.session_id,
        ENV_LOGS_DIR: str(s.logs_dir),
        ENV_MODE: s.mode,
        ENV_AGG_HOST: s.aggregator.connect_host,
        ENV_AGG_BIND_HOST: s.aggregator.bind_host,
        ENV_AGG_PORT: str(s.aggregator.port),
        ENV_SAMPLER_INTERVAL: str(s.sampler_interval_sec),
        ENV_CAPTURE_STDERR: "1" if s.capture_stderr else "0",
        ENV_FINALIZE_TIMEOUT: str(s.finalize_timeout_sec),
        ENV_SUMMARY_WINDOW_ROWS: str(s.summary_window_rows),
        ENV_SERVE_MAX_SESSIONS: str(s.serve_max_sessions),
    }
    if s.trace_max_steps is not None:
        env[ENV_MAX_STEPS] = str(s.trace_max_steps)
    if s.disabled:
        env[ENV_DISABLE] = "1"
    if s.disk_backup:
        env[ENV_DISK_BACKUP] = "1"
    if s.run_name:
        env[ENV_RUN_NAME] = s.run_name
    if s.expected_world_size is not None:
        env[ENV_EXPECTED_WORLD_SIZE] = str(s.expected_world_size)
    return env
