import jax
import jax.numpy as jnp
import numpy as np

from traceml_tpu.models.vit import ViT, ViTConfig, make_vit_train_step


def test_vit_forward_shapes():
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    images = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
    params = model.init(jax.random.PRNGKey(0), images)["params"]
    logits = model.apply({"params": params}, images)
    assert logits.shape == (2, cfg.n_classes)
    assert logits.dtype == jnp.float32


def test_vit_train_step_learns():
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    init, train_step = make_vit_train_step(model, learning_rate=5e-3)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, cfg.n_classes, (8,)), jnp.int32)
    state = init(jax.random.PRNGKey(0), images)
    step = jax.jit(train_step, donate_argnums=(0,))
    losses = []
    for _ in range(25):
        state, m = step(state, images, labels)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8  # memorizes the batch


def test_encoder_attention_is_bidirectional():
    """The non-causal path must let EARLY positions see LATE keys —
    checked pre-pool at the op level (a pooled logit check is vacuous:
    the perturbed position changes its own row under causal too)."""
    from traceml_tpu.ops.attention import attention_reference

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 8, 2, 8)), jnp.float32)
        for _ in range(3)
    )
    k2 = k.at[:, -1].add(2.0)  # perturb only the LAST key
    full1 = attention_reference(q, k, v, causal=False)
    full2 = attention_reference(q, k2, v, causal=False)
    causal1 = attention_reference(q, k, v, causal=True)
    causal2 = attention_reference(q, k2, v, causal=True)
    # non-causal: early rows change; causal: early rows must NOT
    assert not np.allclose(np.asarray(full1[:, 0]), np.asarray(full2[:, 0]))
    np.testing.assert_allclose(
        np.asarray(causal1[:, :-1]), np.asarray(causal2[:, :-1]), atol=1e-6
    )
