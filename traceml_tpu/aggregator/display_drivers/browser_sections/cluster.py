"""Cluster section: rollup table + per-rank heatmap (reference role:
the cluster rows of system_section + TPU-new cross-rank heatmap).

The heatmap colors each rank's metric by its ratio to the cross-rank
median; a zero median with a nonzero outlier (3 wedged ranks at 0% cpu,
1 spinning) is treated as "infinitely hot" so the outlier still flags.
Both cards hide themselves on single-rank runs.
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div id="cluster-card" style="display:none">
<div class="chead"><h2 class="ctitle">Cluster</h2>
  <span class="cmeta" id="cluster-sub"></span><span class="sp"></span></div>
<div id="cluster"></div></div>
<div id="heatmap-card" style="display:none;margin-top:.8rem">
<div class="chead"><h2 class="ctitle">Per-rank heatmap</h2>
  <span class="cmeta">relative to cross-rank median</span><span class="sp"></span></div>
<div id="heatmap"></div></div>
"""

_JS = r"""
function heatColor(ratio){
  if(ratio==null||isNaN(ratio))return"rgba(233,236,245,0.05)";
  const x=Math.max(0,Math.min(1,(ratio-0.85)/1.15));
  return`hsl(${(220-220*x).toFixed(0)},62%,${(26+x*14).toFixed(0)}%)`}
function render_cluster(d){
  const card=document.getElementById("cluster-card");
  const s=d.system;
  if(s&&s.is_cluster&&(s.rollups||[]).length){
    card.style.display="";
    document.getElementById("cluster-sub").textContent=
      `${s.nodes.length}/${s.expected_nodes} nodes`+
      (s.missing_nodes?` · ${esc(s.missing_nodes)} MISSING`:"");
    let cr=`<table><tr><th>metric</th><th class="num">min</th>
      <th class="num">median</th><th class="num">max</th><th>max node</th></tr>`;
    for(const r of s.rollups){
      cr+=`<tr><td>${esc(r.metric)}</td><td class="num">${r.min_value.toFixed(1)}</td>
        <td class="num">${r.median_value.toFixed(1)}</td>
        <td class="num">${r.max_value.toFixed(1)}</td><td>${esc(r.max_node)}</td></tr>`}
    document.getElementById("cluster").innerHTML=cr+"</table>"
  }else card.style.display="none";
  // per-rank heatmap assembled from step/memory/process payloads
  const hcard=document.getElementById("heatmap-card");
  const el=document.getElementById("heatmap");
  const ranks={};
  const st=d.step_time;
  if(st&&st.step_series)for(const r in st.step_series){
    const sr=st.step_series[r];if(!sr.length)continue;
    const tail=sr.slice(-8);
    (ranks[r]=ranks[r]||{}).step_ms=tail.reduce((a,b)=>a+b,0)/tail.length}
  if(d.memory&&d.memory.ranks)for(const m of d.memory.ranks)
    (ranks[m.rank]=ranks[m.rank]||{}).mem_pressure=m.pressure;
  if(d.process&&d.process.ranks)for(const p of d.process.ranks){
    (ranks[p.rank]=ranks[p.rank]||{}).cpu_pct=p.cpu_pct;
    ranks[p.rank].rss=p.rss_bytes}
  const ids=Object.keys(ranks).sort((a,b)=>a-b);
  if(ids.length<2){hcard.style.display="none";return}
  hcard.style.display="";
  const METRICS=["step_ms","mem_pressure","cpu_pct","rss"];
  const med={};
  for(const m of METRICS){
    const vs=ids.map(r=>ranks[r][m]).filter(v=>v!=null).sort((a,b)=>a-b);
    med[m]=vs.length?vs[Math.floor(vs.length/2)]:null}
  let html=`<table class="heat"><tr><th class="num">rank</th>`+
    METRICS.map(m=>`<th>${esc(m)}</th>`).join("")+`</tr>`;
  for(const r of ids){
    html+=`<tr><td class="num">${esc(r)}</td>`;
    for(const m of METRICS){
      const v=ranks[r][m];
      const ratio=(v==null||med[m]==null)?null:
        med[m]>0?v/med[m]:(v>0?2:1);
      const label=v==null?"—":(m==="rss"?fmtB(v):m==="mem_pressure"?pct(v):
        m==="cpu_pct"?v.toFixed(0)+"%":fmtMs(v));
      html+=`<td style="background:${heatColor(ratio)}">${label}
        ${ratio!=null&&ratio>1.15?`<span class="muted">(${ratio.toFixed(2)}×)</span>`:""}</td>`}
    html+="</tr>"}
  el.innerHTML=html+"</table>"}
"""

SECTION = Section(
    id="cluster",
    title="Cluster",
    html=_HTML,
    js=_JS,
    contract=(
        "system.is_cluster",
        "system.rollups.metric",
        "system.rollups.min_value",
        "system.rollups.median_value",
        "system.rollups.max_value",
        "system.rollups.max_node",
        "system.expected_nodes",
        "system.missing_nodes",
        "step_time.step_series",
        "memory.ranks.pressure",
        "process.ranks.cpu_pct",
        "process.ranks.rss_bytes",
    ),
)
