"""Collective-communication capture
(motivated by T3, arXiv:2401.16677 — fine-grained compute/collective
overlap tracking — and EQuARX, arXiv:2506.17615 — quantized AllReduce).

Two sources feed one bounded queue of per-collective records:

1. **Profiler trace events** (preferred, when a capture is running):
   :func:`extract_collectives_from_trace_events` maps XLA trace rows
   (``all-reduce``, ``all-gather``, ``reduce-scatter``, ``all-to-all``,
   ``collective-permute`` fusions) to canonical records, including the
   *exposed* portion of each collective — the span NOT covered by a
   concurrently running compute op.  A capture backend registers itself
   via :func:`register_trace_source`; none is required.

2. **Pure-Python fallback** (always available, mirrors the
   ColumnarFallback philosophy — correctness never depends on the
   profiler): :func:`instrument_collective` wraps a host-dispatched
   collective callable (gradient sync, manual ring hop), and
   :func:`patch_lax_collectives` wraps the eager ``jax.lax`` collective
   entry points.  Both time the host window, estimate bytes/dtype from
   the output pytree, and record the call as fully exposed unless the
   caller declares overlap — a host-blocking dispatch IS exposed comm.

Every record is a flat uniform dict (plays well with the r10 columnar
producer accumulators)::

    {"step", "ts", "op", "dtype", "bytes", "group_size",
     "duration_ms", "exposed_ms"}

Kill switch: ``TRACEML_COLLECTIVES=0`` turns every entry point into a
no-op (and unregisters the sampler — see runtime/sampler_registry.py).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from traceml_tpu.config import flags
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import BoundedDropQueue

# --- canonical op vocabulary ------------------------------------------------
OP_ALL_REDUCE = "all_reduce"
OP_ALL_GATHER = "all_gather"
OP_REDUCE_SCATTER = "reduce_scatter"
OP_ALL_TO_ALL = "all_to_all"
OP_P2P = "p2p"
OP_OTHER = "other"

OP_KINDS = (
    OP_ALL_REDUCE,
    OP_ALL_GATHER,
    OP_REDUCE_SCATTER,
    OP_ALL_TO_ALL,
    OP_P2P,
    OP_OTHER,
)

# XLA HLO / trace-event spellings → canonical kind.  Longest-prefix style
# matching happens in normalize_op; these are exact (lowered) aliases.
_OP_ALIASES: Dict[str, str] = {
    "all_reduce": OP_ALL_REDUCE,
    "all-reduce": OP_ALL_REDUCE,
    "allreduce": OP_ALL_REDUCE,
    "psum": OP_ALL_REDUCE,
    "pmean": OP_ALL_REDUCE,
    "pmax": OP_ALL_REDUCE,
    "pmin": OP_ALL_REDUCE,
    "cross-replica-sum": OP_ALL_REDUCE,
    "all_gather": OP_ALL_GATHER,
    "all-gather": OP_ALL_GATHER,
    "allgather": OP_ALL_GATHER,
    "reduce_scatter": OP_REDUCE_SCATTER,
    "reduce-scatter": OP_REDUCE_SCATTER,
    "reducescatter": OP_REDUCE_SCATTER,
    "psum_scatter": OP_REDUCE_SCATTER,
    "all_to_all": OP_ALL_TO_ALL,
    "all-to-all": OP_ALL_TO_ALL,
    "alltoall": OP_ALL_TO_ALL,
    "collective-permute": OP_P2P,
    "collective_permute": OP_P2P,
    "ppermute": OP_P2P,
    "send": OP_P2P,
    "recv": OP_P2P,
}

_QUEUE_MAX = 8192


def collectives_enabled() -> bool:
    return flags.COLLECTIVES.enabled()


# Global queue shared by the recorders above and CollectivesSampler.
GLOBAL_COLLECTIVES_QUEUE = BoundedDropQueue("collectives", maxsize=_QUEUE_MAX)


def normalize_op(name: Any) -> str:
    """Canonicalize an op spelling (HLO name, jax.lax name, user string)."""
    s = str(name or "").strip().lower()
    if s in _OP_ALIASES:
        return _OP_ALIASES[s]
    if s in OP_KINDS:
        return s
    # trace events carry suffixed HLO names ("all-reduce.17", fusion tags)
    for alias, kind in _OP_ALIASES.items():
        if s.startswith(alias):
            return kind
    return OP_OTHER


def bytes_of(tree: Any) -> Tuple[int, str]:
    """Best-effort (payload bytes, dtype) of a collective's output pytree.

    Dtype is taken from the largest leaf — for a fused sync that's the
    gradient payload, which is what ALLREDUCE_QUANTIZABLE cares about.
    """
    leaves: Sequence[Any]
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = [tree]
    total = 0
    dtype = ""
    best = -1
    for leaf in leaves:
        try:
            n = int(leaf.nbytes)
        except Exception:
            continue
        total += n
        if n > best:
            best = n
            dtype = str(getattr(leaf, "dtype", "") or "")
    return total, dtype


def _current_step() -> int:
    try:
        from traceml_tpu.sdk.state import get_state

        return int(get_state().current_step)
    except Exception:
        return 0


def record_collective(
    op: str,
    *,
    nbytes: int = 0,
    dtype: str = "",
    group_size: int = 1,
    duration_ms: float = 0.0,
    exposed_ms: Optional[float] = None,
    overlapped: bool = False,
    step: Optional[int] = None,
    ts: Optional[float] = None,
) -> bool:
    """Record one collective occurrence.  Never raises; returns whether
    the record was enqueued (False: disabled or queue full).

    ``exposed_ms`` is the portion of ``duration_ms`` NOT hidden behind
    compute.  When omitted it defaults from the coarse ``overlapped``
    flag: fully exposed (fallback, host-blocking dispatch) or fully
    hidden.  Profiler sources pass the measured value.
    """
    if not collectives_enabled():
        return False
    try:
        dur = max(0.0, float(duration_ms))
        if exposed_ms is None:
            exp = 0.0 if overlapped else dur
        else:
            exp = min(dur, max(0.0, float(exposed_ms)))
        rec = {
            "step": int(step) if step is not None else _current_step(),
            "ts": float(ts) if ts is not None else time.time(),
            "op": normalize_op(op),
            "dtype": str(dtype or ""),
            "bytes": max(0, int(nbytes)),
            "group_size": max(1, int(group_size)),
            "duration_ms": dur,
            "exposed_ms": exp,
        }
    except Exception as exc:
        get_error_log().warning("record_collective failed", exc)
        return False
    return GLOBAL_COLLECTIVES_QUEUE.put(rec)


# --- profiler trace-event source (preferred when a capture runs) ------------

_trace_sources: List[Callable[[], List[Dict[str, Any]]]] = []


def register_trace_source(fn: Callable[[], List[Dict[str, Any]]]) -> None:
    """Register a callable returning raw trace-event dicts to harvest.
    The sampler drains it each tick; exceptions disable nothing (the
    fallback recorders keep the domain alive)."""
    _trace_sources.append(fn)


def clear_trace_sources() -> None:
    _trace_sources.clear()


def drain_trace_sources() -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    for src in list(_trace_sources):
        try:
            events.extend(src() or [])
        except Exception as exc:
            get_error_log().warning("collective trace source failed", exc)
    return events


def extract_collectives_from_trace_events(
    events: Sequence[Dict[str, Any]],
    default_step: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Map raw XLA trace events to canonical collective records.

    Expects the chrome-trace-ish rows the profiler emits: ``name``,
    ``dur`` (µs), ``ts`` (µs), optional ``args`` with
    ``bytes_accessed``/``shape``/``dtype``/``group_size``/``step``.
    Exposure: a trace row may carry ``args.exposed_us`` (computed by the
    capture backend from concurrent compute spans); otherwise the event
    counts as fully exposed — the conservative reading.

    Pure function (unit-testable without a profiler present).
    """
    out: List[Dict[str, Any]] = []
    for ev in events:
        try:
            op = normalize_op(ev.get("name"))
            if op == OP_OTHER and normalize_op(str(ev.get("name"))) == OP_OTHER:
                # not a collective at all → skip non-matching trace rows
                if not any(
                    str(ev.get("name", "")).lower().startswith(a)
                    for a in _OP_ALIASES
                ):
                    continue
            args = ev.get("args") or {}
            dur_ms = float(ev.get("dur", 0.0)) / 1000.0
            exposed_us = args.get("exposed_us")
            step = args.get("step", default_step)
            rec = {
                "step": int(step) if step is not None else _current_step(),
                "ts": float(ev.get("ts", 0.0)) / 1e6 or time.time(),
                "op": op,
                "dtype": str(args.get("dtype", "") or ""),
                "bytes": max(0, int(args.get("bytes_accessed", 0) or 0)),
                "group_size": max(1, int(args.get("group_size", 1) or 1)),
                "duration_ms": max(0.0, dur_ms),
                "exposed_ms": (
                    min(max(0.0, float(exposed_us) / 1000.0), max(0.0, dur_ms))
                    if exposed_us is not None
                    else max(0.0, dur_ms)
                ),
            }
            out.append(rec)
        except Exception:
            continue  # one malformed row never poisons the batch
    return out


# --- pure-Python fallback recorders ----------------------------------------


def _default_group_size() -> int:
    try:
        import jax

        return int(jax.device_count())
    except Exception:
        return 1


def instrument_collective(
    fn: Callable,
    op: str = OP_ALL_REDUCE,
    state: Any = None,
    group_size: Optional[int] = None,
    overlapped: bool = False,
) -> Callable:
    """Fallback capture for a host-dispatched collective callable.

    Composes with the step-phase machinery: the call is also timed as
    the first-class ``collective`` phase (sdk wrap_collective), so
    COLLECTIVE_STRAGGLER attribution keeps working, and additionally
    emits a collectives-domain record with bytes/dtype estimated from
    the outputs.  A host-blocking dispatch is recorded fully exposed
    unless the caller declares ``overlapped=True`` (e.g. an async
    dispatch known to run under compute).
    """
    from traceml_tpu.sdk.wrappers import wrap_collective

    timed = wrap_collective(fn, state)
    kind = normalize_op(op)

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        if not collectives_enabled():
            return timed(*args, **kwargs)
        t0 = time.perf_counter()
        out = timed(*args, **kwargs)
        dur_ms = (time.perf_counter() - t0) * 1000.0
        try:
            nbytes, dtype = bytes_of(out)
            record_collective(
                kind,
                nbytes=nbytes,
                dtype=dtype,
                group_size=(
                    group_size if group_size is not None else _default_group_size()
                ),
                duration_ms=dur_ms,
                overlapped=overlapped,
            )
        except Exception as exc:  # never raise into user code
            get_error_log().warning("instrument_collective record failed", exc)
        return out

    wrapped._traceml_collective_instrumented = True  # type: ignore[attr-defined]
    return wrapped


# jax.lax entry point → canonical op kind for the eager-call patches
_LAX_COLLECTIVES = {
    "psum": OP_ALL_REDUCE,
    "pmean": OP_ALL_REDUCE,
    "pmax": OP_ALL_REDUCE,
    "pmin": OP_ALL_REDUCE,
    "all_gather": OP_ALL_GATHER,
    "psum_scatter": OP_REDUCE_SCATTER,
    "all_to_all": OP_ALL_TO_ALL,
    "ppermute": OP_P2P,
}

_lax_patched = False


def _is_tracing(args: Any, kwargs: Any) -> bool:
    """True when any argument is a JAX tracer — i.e. we are inside a
    jit/pmap trace, where wall time measures tracing, not communication,
    and one trace serves many steps.  Such calls are skipped."""
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves((args, kwargs)):
            if isinstance(leaf, jax.core.Tracer):
                return True
    except Exception:
        pass
    return False


def patch_lax_collectives() -> bool:
    """Wrap the eager ``jax.lax`` collective entry points so call sites
    need no code change.  Trace-time calls (tracer arguments) pass
    through unrecorded; only host-dispatched eager calls are timed.
    Idempotent; returns whether the patch is installed."""
    global _lax_patched
    if _lax_patched:
        return True
    if not collectives_enabled():
        return False
    try:
        import jax
    except Exception:
        return False
    lax = jax.lax
    for name, kind in _LAX_COLLECTIVES.items():
        orig = getattr(lax, name, None)
        if orig is None or getattr(orig, "_traceml_collective_instrumented", False):
            continue

        def make(orig: Callable, kind: str) -> Callable:
            @functools.wraps(orig)
            def wrapped(*args: Any, **kwargs: Any):
                if not collectives_enabled() or _is_tracing(args, kwargs):
                    return orig(*args, **kwargs)
                t0 = time.perf_counter()
                out = orig(*args, **kwargs)
                dur_ms = (time.perf_counter() - t0) * 1000.0
                try:
                    nbytes, dtype = bytes_of(out)
                    record_collective(
                        kind,
                        nbytes=nbytes,
                        dtype=dtype,
                        group_size=_default_group_size(),
                        duration_ms=dur_ms,
                    )
                except Exception:
                    pass
                return out

            wrapped._traceml_collective_instrumented = True  # type: ignore[attr-defined]
            return wrapped

        setattr(lax, name, make(orig, kind))
    _lax_patched = True
    return True
