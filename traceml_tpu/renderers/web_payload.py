"""JSON-able live payload for the browser dashboard
(reference pattern: renderers/<domain>/dashboard_compute.py).

One pipeline, N surfaces: the payload is derived from the SAME
``LiveComputer`` the CLI renders from (one load→views→diagnose pass per
version change regardless of how many dashboard tabs poll), with the
typed views serialized verbatim via ``as_dict()``.

Since the serving-tier split (docs/developer_guide/serving-tier.md) the
payload is built as PER-DOMAIN FRAGMENTS: each fragment owns a disjoint
set of top-level payload keys (``_FRAGMENT_KEYS``) and recomputes only
when the snapshot-store versions it depends on (``FRAGMENT_DEPS``)
advance.  ``build_web_payload`` composes every fragment back into the
flat dict the dashboard has always consumed — same keys, same order —
while the delta/SSE endpoints ship fragments individually, serialized
once per (fragment, version) by ``renderers/serving.py``.

The old module-global ``_computers`` cache (which closed EVERY cached
computer whenever a different db_path polled — one session per process)
is gone: computers now live inside the serving tier's keyed, LRU-bounded
publisher cache, so N sessions polling concurrently keep N live sqlite
connections instead of thrashing each other's.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Tuple

PAYLOAD_VERSION = 3

#: fragment name → top-level payload keys it owns, in payload key order
#: (``header`` first; the assembler splices ``ts`` between header and
#: the domain fragments to preserve the historical key order)
_FRAGMENT_KEYS: Dict[str, Tuple[str, ...]] = {
    "header": ("version", "session"),
    "step_time": ("step_time",),
    "memory": ("memory",),
    "collectives": ("collectives",),
    "serving": ("serving",),
    "system": ("system",),
    "process": ("process",),
    "stdout": ("stdout",),
    "history": ("history",),
    "diagnosis": ("diagnosis", "findings"),
    "meta": ("ingest", "rank_status", "mesh", "regressions", "window_build"),
}

#: serving order — also the position of each counter in the version token
FRAGMENT_ORDER: Tuple[str, ...] = tuple(_FRAGMENT_KEYS)

#: fragment → snapshot-store domains whose ``data_version`` gates its
#: recompute.  ``diagnosis`` joins every diagnosing domain (the composed
#: findings list can reorder when any of them moves).  ``header`` is
#: constant and ``meta`` is file-backed (ingest_stats/rank_status json),
#: so both are content-compared instead of version-gated.
FRAGMENT_DEPS: Dict[str, Tuple[str, ...]] = {
    "step_time": ("step_time", "model_stats", "topology"),
    "memory": ("step_memory", "topology"),
    "collectives": ("collectives", "step_time", "topology"),
    "serving": ("serving", "topology"),
    "system": ("system", "topology"),
    "process": ("process",),
    "stdout": ("stdout",),
    "history": ("rollup", "step_time"),
    "diagnosis": (
        "step_time", "model_stats", "topology", "step_memory",
        "collectives", "serving", "system", "process",
    ),
}


def _issue_dict(issue: Any) -> Dict[str, Any]:
    from traceml_tpu.diagnostics.common import confidence_label

    out = {
        "kind": issue.kind,
        "severity": issue.severity,
        "summary": issue.summary,
        "action": issue.action,
        "confidence": getattr(issue, "confidence", None),
        "confidence_label": confidence_label(
            getattr(issue, "confidence", None)
        ),
    }
    # topology attribution rides only when present: pre-topology
    # sessions serialize the exact historical shape (back-compat pin)
    attribution = getattr(issue, "attribution", None)
    if attribution:
        out["attribution"] = attribution
    return out


def _view_fragment(payload: Dict[str, Any], key: str) -> Dict[str, Any]:
    view = (payload.get("views") or {}).get(key)
    return {key: view.as_dict() if view is not None else None}


def _serving_fragment(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Unlike the training domains, ``serving`` omits its key entirely
    when the session recorded no serving rows: a training-only session's
    payload (and the final report derived from it) must stay
    byte-identical to the pre-serving-domain shape."""
    view = (payload.get("views") or {}).get("serving")
    if view is None:
        return {}
    return {"serving": view.as_dict()}


def _history_fragment(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Full-run history strip (stitched rollup tiers).  Like
    ``serving``, the key is omitted entirely until the first fold lands
    — a short run's payload keeps the pre-rollup shape byte-identical."""
    history = payload.get("history")
    if not history or not isinstance(history, dict):
        return {}
    if not history.get("step_time"):
        return {}
    return {"history": history}


def _diagnosis_fragment(payload: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {"diagnosis": None, "findings": []}
    if not payload.get("db_exists"):
        return out
    st_result = (payload.get("step_time") or {}).get("diagnosis")
    if st_result is not None:
        out["diagnosis"] = _issue_dict(st_result.diagnosis)
    domain_results = {
        "step_time": st_result,
        "step_memory": payload.get("step_memory_diagnosis"),
        "collectives": (payload.get("collectives") or {}).get("diagnosis"),
        "serving": (payload.get("serving") or {}).get("diagnosis"),
        "system": payload.get("system_diagnosis"),
        "process": payload.get("process_diagnosis"),
    }
    try:
        from traceml_tpu.diagnostics.model_diagnostics import compose

        composed = compose(domain_results)
        out["findings"] = [
            dict(_issue_dict(i), domain=i.evidence.get("domain", "?"))
            for i in composed.issues[:8]
        ]
    except Exception:
        pass
    return out


def _meta_fragment(
    payload: Dict[str, Any], session_dir: Path
) -> Dict[str, Any]:
    """Aggregator self-metrics for the dashboard meta strip: backpressure
    (queue depth/hwm, per-domain sheds), writer latency, and the per-rank
    liveness strip — live, not just in the post-run summary."""
    out: Dict[str, Any] = {}
    if not payload.get("db_exists"):
        return out
    try:
        from traceml_tpu.reporting.loaders import (
            load_ingest_stats,
            load_rank_status,
        )

        stats = load_ingest_stats(session_dir)
        if stats:
            out["ingest"] = {
                k: stats[k]
                for k in (
                    "envelopes_ingested", "rows_dropped", "drop_warnings",
                    "dropped_by_domain", "unknown_domain_drops", "queues",
                    "group_commit", "prune", "corrupt_frame_drops",
                    "replay_duplicates",
                    "pending_frames_hwm", "producers", "transports", "ts",
                )
                if k in stats
            }
        # per-rank liveness strip (ACTIVE/STALE/LOST/FINISHED): the
        # dashboard shows which ranks a live dip is actually averaging
        status = load_rank_status(session_dir)
        if status and isinstance(status.get("ranks"), dict):
            out["rank_status"] = {
                "ts": status.get("ts"),
                "thresholds": status.get("thresholds"),
                "states": {
                    r: (info or {}).get("state")
                    for r, info in status["ranks"].items()
                    if isinstance(info, dict)
                },
            }
    except Exception:
        pass
    # mesh strip: the compact axes/source/host-count block the topology
    # reader attached to the store snapshot — only when a mesh was
    # captured (the meta fragment is content-compared, so a late mesh
    # message republishes it; absent key == pre-topology shape)
    mesh = (payload.get("topology") or {}).get("mesh")
    if mesh:
        out["mesh"] = mesh
    # cross-run regression verdict (analytics/baselines.py): written at
    # finalize as regressions.json; served live so a dashboard left open
    # shows the verdict the moment the run completes.  Absent file ==
    # absent key (pre-baseline sessions keep their exact shape).
    try:
        from traceml_tpu.reporting.loaders import load_regressions

        regressions = load_regressions(session_dir)
        if regressions:
            out["regressions"] = regressions
    except Exception:
        pass
    # incremental window-engine health (round 19): per-domain incr-tick
    # vs full-rebuild counters + invalidation reasons, attached by
    # payload_with_versions when TRACEML_INCR_WINDOW is on.  Absent key
    # when the engine is off or never consulted (pre-r19 shape).
    window_build = payload.get("window_build_stats")
    if window_build:
        out["window_build"] = window_build
    return out


def build_fragment(
    name: str,
    payload: Dict[str, Any],
    *,
    session: str,
    db_path: Path,
) -> Dict[str, Any]:
    """One fragment's top-level payload keys, built from a
    ``LiveComputer.payload()`` result.  Fragments are plain JSON-able
    dicts — the serving tier serializes each exactly once per version."""
    if name == "header":
        return {"version": PAYLOAD_VERSION, "session": session}
    if name in ("step_time", "memory", "collectives", "system", "process"):
        return _view_fragment(payload, name)
    if name == "serving":
        return _serving_fragment(payload)
    if name == "stdout":
        return {
            "stdout": [
                {"stream": s, "line": l}
                for s, l in (payload.get("stdout") or [])
            ]
        }
    if name == "history":
        return _history_fragment(payload)
    if name == "diagnosis":
        return _diagnosis_fragment(payload)
    if name == "meta":
        return _meta_fragment(payload, Path(db_path).parent)
    raise KeyError(name)


def build_web_payload(
    db_path: Path, session: str, window_steps: int = 150
) -> Dict[str, Any]:
    """The flat full payload (legacy full-poll shape) — every fragment
    merged in historical key order, plus a fresh ``ts``.  Reads through
    the serving tier's publisher cache, so dashboard polls share the
    per-(fragment, version) work with the delta/SSE endpoints."""
    from traceml_tpu.renderers.serving import publisher_for

    pub = publisher_for(Path(db_path), session, window_steps=window_steps)
    return pub.full_payload_dict()
