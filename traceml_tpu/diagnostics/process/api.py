"""Process diagnosis entrypoint (reference: diagnostics/process/api.py)."""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from traceml_tpu.diagnostics.common import DiagnosticResult, run_rules
from traceml_tpu.diagnostics.process.rules import (
    DEFAULT_POLICY,
    DEFAULT_RULES,
    ProcessPolicy,
    build_process_context,
)

DOMAIN = "process"


def diagnose(
    proc_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    device_rows: Mapping[tuple, Sequence[Mapping[str, Any]]],
    policy: ProcessPolicy = DEFAULT_POLICY,
) -> DiagnosticResult:
    ctx = build_process_context(proc_rows, device_rows, policy)
    return run_rules(DOMAIN, DEFAULT_RULES, ctx)
