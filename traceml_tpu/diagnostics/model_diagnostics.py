"""Model-diagnostics composer
(reference: src/traceml_ai/diagnostics/model_diagnostics.py:28-466 +
registry.py:63).

Merges the per-domain results (step-time + step-memory are the "model"
domains; system/process are environment) into one card for dashboards
and the summary: the ordered union of issues, a composed headline, and a
per-domain health map.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from traceml_tpu.core.registry import Registry
from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
    sort_issues,
)

# pluggable domain registry (reference: DiagnosticDomainRegistry)
DOMAIN_REGISTRY = Registry("diagnostic-domains")

MODEL_DOMAINS = ("step_time", "step_memory", "collectives", "serving")
ENV_DOMAINS = ("system", "process")


@dataclasses.dataclass
class ComposedDiagnostics:
    headline: DiagnosticIssue
    issues: List[DiagnosticIssue]  # ordered, cross-domain
    domain_health: Dict[str, bool]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "headline": self.headline.to_dict(),
            "issues": [i.to_dict() for i in self.issues],
            "domain_health": dict(self.domain_health),
        }


def compose(
    results: Dict[str, Optional[DiagnosticResult]],
    model_domains_first: bool = True,
) -> ComposedDiagnostics:
    """Merge domain results into one ranked card.

    Model-domain issues (step time / memory — things the user's code
    causes) outrank environment findings of equal severity.
    """
    issues: List[DiagnosticIssue] = []
    health: Dict[str, bool] = {}
    for domain, result in results.items():
        if result is None:
            continue
        health[domain] = result.healthy
        for issue in result.issues:
            if issue.status == "ok":
                continue
            tagged = dataclasses.replace(issue)
            tagged.evidence = dict(issue.evidence)
            tagged.evidence["domain"] = domain
            issues.append(tagged)
    ordered = sort_issues(issues)
    if model_domains_first:
        ordered.sort(
            key=lambda i: 0 if i.evidence.get("domain") in MODEL_DOMAINS else 1
        )
        # sort is stable: severity order is preserved within each group;
        # re-rank so a critical env issue still beats a warning model one
        ordered = sorted(
            ordered,
            key=lambda i: (
                -{"critical": 2, "warning": 1, "info": 0}.get(i.severity, 0),
                0 if i.evidence.get("domain") in MODEL_DOMAINS else 1,
                -(i.score or 0.0),
            ),
        )
    if ordered:
        headline = ordered[0]
    else:
        from traceml_tpu.diagnostics.common import healthy_issue

        headline = healthy_issue(
            "model", "Model and environment look healthy in the analyzed window."
        )
    return ComposedDiagnostics(
        headline=headline, issues=ordered, domain_health=health
    )
