"""``traceml-tpu watch`` — live text view over a session's SQLite DB.

The full Rich dashboard lives in the CLI display driver; watch is the
detached flavor: it polls ``telemetry.sqlite`` read-only and redraws a
compact status (reference: `traceml watch`, launcher/cli.py).

The poll loop holds ONE :class:`LiveSnapshotStore` across ticks, so an
idle second costs a single ``PRAGMA data_version`` read and the
step-time window + diagnosis recompute only when new rows arrived
(dirty-gated on the store's step_time version).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import List, Optional

from traceml_tpu.utils.atomic_io import read_json


class _WatchState:
    """Per-loop snapshot cache: store + the step-time lines rendered at
    the store's current step_time version."""

    def __init__(self, db_path: Path) -> None:
        from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore

        self.store = LiveSnapshotStore(db_path, window_steps=120)
        self._lines: List[str] = []
        self._version: Optional[tuple] = None

    def close(self) -> None:
        self.store.close()

    def step_time_lines(self) -> List[str]:
        from traceml_tpu.diagnostics.step_time.api import diagnose_window
        from traceml_tpu.utils.formatting import fmt_ms

        self.store.refresh()
        # topology version joins the gate: a late mesh_topology message
        # must re-render so the mesh strip + attribution appear; serving
        # joins so an inference session's line tracks its own writes
        version = (
            self.store.versions["step_time"],
            self.store.versions["topology"],
            self.store.versions["serving"],
        )
        if version == self._version:
            return self._lines
        lines: List[str] = []
        mesh = None
        try:
            mesh = self.store.mesh_topology()
        except Exception:
            pass
        if mesh is not None:
            axes = " · ".join(
                f"{a.name}×{a.size}" + (" (dcn)" if a.kind == "dcn" else "")
                for a in mesh.axes
            )
            lines.append(f"mesh: {axes}")
        if self.store.has_step_time_rows():
            w = self.store.build_step_time_window(max_steps=120)
            if w:
                step = w.metric("step_time")
                lines.append(
                    f"steps {w.steps[0]}–{w.steps[-1]} ({w.clock} clock)  "
                    f"median {fmt_ms(step.median_ms)}  worst {fmt_ms(step.worst_ms)} "
                    f"(rank {step.worst_rank})"
                )
                # one window build feeds both the stats line and the
                # diagnosis (the seed built it twice per poll)
                result = diagnose_window(w, mode="live", topology=mesh)
                d = result.diagnosis
                lines.append(
                    f"diagnosis: [{d.severity}] {d.kind} — {d.summary}"
                )
        else:
            lines.append("no step telemetry yet")
        # serving line only for sessions that actually serve: watch on a
        # training-only session renders exactly the pre-serving output
        if self.store.has_serving_rows():
            try:
                sw = self.store.build_serving_window(max_steps=120)
            except Exception:
                sw = None
            if sw is not None:
                t = sw.totals
                lines.append(
                    f"serving: {len(sw.ranks)} replica(s)  "
                    f"{t.get('tokens_per_s', 0.0):.1f} tok/s  "
                    f"ttft p99 {t.get('ttft_p99_ms', 0.0):.0f} ms  "
                    f"queue {int(t.get('queue_depth_last', 0))}"
                )
        self._lines = lines
        self._version = version
        return lines


def _snapshot(session_dir: Path, state: Optional[_WatchState] = None) -> str:
    db = session_dir / "telemetry.sqlite"
    lines = [f"session: {session_dir.name}"]
    manifest = read_json(session_dir / "manifest.json") or {}
    lines.append(
        f"status: {manifest.get('status', '?')}  "
        f"telemetry: {manifest.get('telemetry_status', '?')}"
    )
    if not db.exists():
        lines.append("waiting for telemetry…")
        return "\n".join(lines)
    if state is None:
        state = _WatchState(db)  # one-shot caller: fresh store
        try:
            return "\n".join(lines + state.step_time_lines())
        finally:
            state.close()
    try:
        lines.extend(state.step_time_lines())
    except Exception as exc:
        lines.append(f"(db busy: {exc})")
    return "\n".join(lines)


def run_watch(
    session_dir: Path,
    interval: float = 1.0,
    browser: bool = False,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> int:
    session_dir = Path(session_dir)
    if not session_dir.exists():
        print(f"no session at {session_dir}")
        return 1
    if browser:
        return _run_watch_browser(session_dir, host=host, port=port)
    state: Optional[_WatchState] = None
    try:
        while True:
            db = session_dir / "telemetry.sqlite"
            if state is None and db.exists():
                state = _WatchState(db)
            print("\x1b[2J\x1b[H" + _snapshot(session_dir, state), flush=True)
            manifest = read_json(session_dir / "manifest.json") or {}
            if manifest.get("status") in ("completed", "failed"):
                summary = session_dir / "final_summary.txt"
                if summary.exists():
                    print("\n" + summary.read_text())
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if state is not None:
            state.close()


def _run_watch_browser(
    session_dir: Path,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> int:
    """Serve the browser dashboard over an existing session (live or
    post-hoc): `traceml-tpu watch --browser <session_dir>`.  A pinned
    ``--port`` makes the dashboard addressable as a fleet-router shard
    (docs/developer_guide/federation.md)."""
    import dataclasses

    from traceml_tpu.aggregator.display_drivers.browser import (
        BrowserDisplayDriver,
    )
    from traceml_tpu.runtime.settings import TraceMLSettings

    settings = TraceMLSettings(
        session_id=session_dir.name, logs_dir=session_dir.parent
    )

    @dataclasses.dataclass
    class _Ctx:
        db_path: Path
        settings: TraceMLSettings

    driver = BrowserDisplayDriver(
        host=host or "127.0.0.1", port=port or 0
    )
    driver.start(_Ctx(session_dir / "telemetry.sqlite", settings))
    if driver.port is None:
        print("dashboard failed to start")
        return 1
    from traceml_tpu.aggregator.display_drivers.browser import wait_until_ready

    # probe the driver's OWN bind host (start() already printed the URL)
    if not wait_until_ready(driver.host, driver.port, timeout=10.0):
        print("dashboard bound but never became ready")
        driver.stop()
        return 1
    # a test runner (or shell) that dies without ^C must not leave this
    # server looping forever — round 3 leaked one for 6 hours
    import threading

    stop_evt = threading.Event()
    from traceml_tpu.utils.orphan_watch import arm_parent_death_watch

    arm_parent_death_watch(stop_evt.set)
    try:
        while not stop_evt.wait(1.0):
            pass
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        driver.stop()
