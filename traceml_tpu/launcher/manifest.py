"""Run + code manifests
(reference: src/traceml_ai/launcher/manifest.py:58-228 and the AST code
manifest utils/ast_analysis/ — here a single-pass static scan of the
entry script tuned to JAX/TPU signals).
"""

from __future__ import annotations

import ast
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from traceml_tpu.utils.atomic_io import atomic_write_json, read_json

STATUS_STARTING = "starting"
STATUS_RUNNING = "running"
STATUS_COMPLETED = "completed"
STATUS_FAILED = "failed"
STATUS_DEGRADED = "degraded"


def manifest_path(session_dir: Path) -> Path:
    return Path(session_dir) / "manifest.json"


def write_run_manifest(
    session_dir: Path,
    *,
    session_id: str,
    script: str,
    mode: str,
    world_size: int,
    status: str = STATUS_STARTING,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    data = {
        "schema": 1,
        "session_id": session_id,
        "script": script,
        "mode": mode,
        "world_size": world_size,
        "status": status,
        "telemetry_status": "ok",
        "created_at": time.time(),
        "updated_at": time.time(),
        "artifacts": {
            "final_summary_json": str(Path(session_dir) / "final_summary.json"),
            "final_summary_txt": str(Path(session_dir) / "final_summary.txt"),
            "telemetry_db": str(Path(session_dir) / "telemetry.sqlite"),
        },
    }
    if extra:
        data.update(extra)
    atomic_write_json(manifest_path(session_dir), data)
    return data


def update_run_manifest(session_dir: Path, **fields: Any) -> None:
    data = read_json(manifest_path(session_dir), default={}) or {}
    data.update(fields)
    data["updated_at"] = time.time()
    atomic_write_json(manifest_path(session_dir), data)


# -- code manifest (static analysis) --------------------------------------


class _ScriptVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: set = set()
        self.calls: List[str] = []
        self.attrs: List[str] = []
        # call name → list of per-call {kwarg: literal value} (a script
        # may build several DataLoaders with different configs)
        self.call_kwargs: Dict[str, List[Dict[str, Any]]] = {}

    _KWARG_TARGETS = ("DataLoader", "TrainingArguments", "jit", "pjit")

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports.add(a.name.split(".")[0])

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            self.imports.add(node.module.split(".")[0])
        for a in node.names:
            # imported symbol names carry parallelism signals
            # (Mesh, PartitionSpec, shard_map, …)
            self.attrs.append(a.name)

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if name:
            self.calls.append(name)
            tail = name.split(".")[-1]
            if tail in self._KWARG_TARGETS:
                kws: Dict[str, Any] = {}
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    try:
                        kws[kw.arg] = ast.literal_eval(kw.value)
                    except (ValueError, SyntaxError):
                        kws[kw.arg] = "<dynamic>"
                self.call_kwargs.setdefault(tail, []).append(kws)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        name = _dotted(node)
        if name:
            self.attrs.append(name)
        self.generic_visit(node)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def analyze_script(script: Path) -> Dict[str, Any]:
    """Best-effort static scan: framework, parallelism hints, precision,
    optimizer, input-pipeline hints (reference: ast_analysis/scanner.py:59)."""
    out: Dict[str, Any] = {
        "script": str(script),
        "framework": "unknown",
        "uses": [],
        "parallelism_hints": [],
        "precision_hints": [],
        "optimizer_hints": [],
        "input_hints": [],
    }
    try:
        tree = ast.parse(Path(script).read_text(encoding="utf-8"))
    except Exception as exc:
        out["error"] = str(exc)
        return out
    v = _ScriptVisitor()
    v.visit(tree)
    names = set(v.calls) | set(v.attrs)
    imports = v.imports

    if "jax" in imports or "flax" in imports:
        out["framework"] = "jax"
    elif "torch" in imports:
        out["framework"] = "torch"
    out["uses"] = sorted(
        imports
        & {
            "jax", "flax", "optax", "orbax", "torch", "transformers",
            "numpy", "tensorflow", "grain",
        }
    )

    def any_in(*subs: str) -> bool:
        return any(any(s in n for n in names) for s in subs)

    if any_in("pjit", "shard_map", "NamedSharding", "PartitionSpec", "Mesh"):
        out["parallelism_hints"].append("gspmd")
    if any_in("pmap"):
        out["parallelism_hints"].append("pmap")
    if any_in("distributed.initialize"):
        out["parallelism_hints"].append("multi_host")
    if any_in("DistributedDataParallel"):
        out["parallelism_hints"].append("ddp")
    if any_in("FSDP", "fully_shard"):
        out["parallelism_hints"].append("fsdp")
    if any_in("bfloat16", "bf16"):
        out["precision_hints"].append("bf16")
    if any_in("float16", "fp16", "autocast"):
        out["precision_hints"].append("fp16/amp")
    for opt in ("adamw", "adam", "sgd", "adafactor", "lion", "lamb"):
        if any_in(opt):
            out["optimizer_hints"].append(opt)
    if any_in("DataLoader"):
        out["input_hints"].append("torch_dataloader")
    if any_in("device_put"):
        out["input_hints"].append("explicit_device_put")
    if any_in("jax.checkpoint", "remat"):
        out["uses"].append("remat")

    # config extraction (reference: scanner pulls dataloader args,
    # TrainingArguments precision, grad accumulation, QLoRA markers)
    dls = v.call_kwargs.get("DataLoader", [])
    if dls:
        keep = ("num_workers", "pin_memory", "prefetch_factor",
                "batch_size", "persistent_workers")
        out["dataloader_args"] = [
            {k: dl[k] for k in keep if k in dl} for dl in dls[:8]
        ]
        # torch's DataLoader default is num_workers=0 (single worker in
        # the main process) — exactly the input-bound setup this hint
        # exists to flag, so a missing kwarg counts
        if any(dl.get("num_workers", 0) in (0, None) for dl in dls):
            out["input_hints"].append("single_worker_dataloader")
    ta = {
        k: val
        for call in v.call_kwargs.get("TrainingArguments", [])
        for k, val in call.items()
    }
    if ta:
        out["hf_training_args"] = {
            k: ta[k]
            for k in ("per_device_train_batch_size",
                      "gradient_accumulation_steps", "bf16", "fp16",
                      "gradient_checkpointing", "optim")
            if k in ta
        }
        if ta.get("bf16"):
            out["precision_hints"].append("bf16")
        if ta.get("fp16"):
            out["precision_hints"].append("fp16/amp")
    jit_kw = {
        k: val
        for call in v.call_kwargs.get("jit", []) + v.call_kwargs.get("pjit", [])
        for k, val in call.items()
    }
    if "donate_argnums" in jit_kw:
        out["uses"].append("buffer_donation")
    if imports & {"peft", "bitsandbytes"} or any_in("lora", "Lora", "LoRA"):
        out["uses"].append("lora/qlora")
    # host-sync calls inside the loop are a classic TPU/GPU perf trap
    sync_markers = [
        n for n in ("item", "block_until_ready", "device_get", "tolist")
        if any(name.endswith("." + n) or name == n for name in set(v.calls))
    ]
    if sync_markers:
        out["sync_call_hints"] = sync_markers
    return out


def write_code_manifest(session_dir: Path, script: Path) -> Dict[str, Any]:
    data = analyze_script(script)
    data["generated_at"] = time.time()
    atomic_write_json(Path(session_dir) / "code_manifest.json", data)
    return data
