"""Cross-run regression baseline store
(docs/developer_guide/retention-rollups.md, DIAGNOSIS.md: Cross-run
regression).

Completed sessions become automatic regression detection: at finalize,
each run's fingerprint (run name, mesh axes from the r14 topology
capture, world size) plus summary stats (steady-state step time,
overlap efficiency, memory slope, serving tokens/s) are ingested into
``traceml_baselines.sqlite`` in the LOGS dir (one level above the
session dir, so every run under the same logs root shares it).  New
runs are evaluated against robust bands over the last
``TRACEML_BASELINE_MAX_RUNS`` sessions with the SAME fingerprint —
the cross-run analogue of r14's within-run topology attribution
("12% slower than the last 20 like it, attributed to host 7"): when
the step-time check fires and per-rank means are on record, the delta
per rank goes through ``utils.topology.attribute_ranks``.

Bands are median ± max(k·MAD, relative floor) — MAD so one earlier
outlier run can't widen the band arbitrarily; small-n fallbacks keep
the check usable from the second run (n=1: ±50%, n=2: ±30%).

Evaluation strictly precedes ingestion, so a slow run never pollutes
the band it is judged against.  Everything is fail-open: a missing or
unwritable store returns None and the final summary simply omits its
``regressions`` section (pre-baseline shape).
"""

from __future__ import annotations

import json
import sqlite3
import statistics
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from traceml_tpu.config import flags
from traceml_tpu.utils.error_log import get_error_log

STORE_FILENAME = "traceml_baselines.sqlite"

#: metric key → (direction that is a REGRESSION, relative band floor)
#: direction "high" = larger is worse, "low" = smaller is worse
METRICS: Dict[str, Dict[str, Any]] = {
    "steady_step_ms": {"bad": "high", "rel_floor": 0.15, "unit": "ms"},
    "overlap_efficiency": {"bad": "low", "rel_floor": 0.10, "unit": ""},
    "memory_slope_pct_per_100": {"bad": "high", "rel_floor": 0.25,
                                 "unit": "%/100 steps", "abs_floor": 0.5},
    "tokens_per_s": {"bad": "low", "rel_floor": 0.15, "unit": "tok/s"},
}

_MAD_K = 3.0 * 1.4826  # 3-sigma-equivalent under normality


# -- fingerprint + stats extraction ---------------------------------------


def fingerprint_from_summary(payload: Dict[str, Any]) -> Dict[str, Any]:
    """What makes two runs comparable: same run name, same mesh axes,
    same world size.  The mesh axes string comes from the r14 topology
    capture when present (``meta.topology.mesh.axes``)."""
    meta = payload.get("meta") or {}
    topo = meta.get("topology") or {}
    mesh = topo.get("mesh") or {}
    axes = mesh.get("axes")
    if isinstance(axes, list):
        axes_str = ",".join(
            f"{a.get('name')}:{a.get('size')}@{a.get('kind', 'ici')}"
            for a in axes
            if isinstance(a, dict)
        )
    else:
        axes_str = ""
    return {
        "run_name": meta.get("run_name") or "",
        "mesh_axes": axes_str,
        "world_size": int(topo.get("world_size") or 0),
    }


def fingerprint_key(fp: Dict[str, Any]) -> str:
    return json.dumps(fp, sort_keys=True)


def _steady_step(payload: Dict[str, Any]) -> Dict[str, Any]:
    g = ((payload.get("sections") or {}).get("step_time") or {}).get(
        "global"
    ) or {}
    steady = g.get("steady_state") or {}
    median = steady.get("median_ms")
    per_rank = steady.get("per_rank_median_ms") or {}
    if median is None:
        median = ((g.get("phases") or {}).get("step_time") or {}).get(
            "median_ms"
        )
    return {"median_ms": median, "per_rank_ms": per_rank}


def summary_stats(payload: Dict[str, Any]) -> Dict[str, Any]:
    """The comparable stats of one finished session, pulled from its
    final-summary payload (missing sections yield None entries — a
    training-only run has no tokens/s and that is not a regression)."""
    sections = payload.get("sections") or {}
    step = _steady_step(payload)
    coll_g = (sections.get("collectives") or {}).get("global") or {}
    mem_g = (sections.get("step_memory") or {}).get("global") or {}
    serv_g = (sections.get("serving") or {}).get("global") or {}

    slopes: List[float] = []
    for card in (mem_g.get("per_rank") or {}).values():
        trend = (card or {}).get("trend") or {}
        v = trend.get("slope_pct_per_100")
        if v is not None:
            slopes.append(float(v))
    tokens = serv_g.get("tokens_per_s")
    if tokens is None:
        tokens = (serv_g.get("totals") or {}).get("tokens_per_s")
    return {
        "steady_step_ms": step["median_ms"],
        "per_rank_step_ms": step["per_rank_ms"],
        "overlap_efficiency": coll_g.get("overlap_efficiency"),
        "memory_slope_pct_per_100": (
            statistics.median(slopes) if slopes else None
        ),
        "tokens_per_s": tokens,
    }


# -- the store ------------------------------------------------------------


class BaselineStore:
    """Tiny SQLite store keyed by fingerprint; per-fingerprint history
    trimmed to ``TRACEML_BASELINE_MAX_RUNS`` newest sessions."""

    def __init__(self, path: Path, max_runs: Optional[int] = None) -> None:
        self.path = Path(path)
        self.max_runs = (
            int(max_runs)
            if max_runs is not None
            else max(1, flags.BASELINE_MAX_RUNS.get_int(20))
        )
        self._conn = sqlite3.connect(str(self.path))
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS baseline_runs (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                fingerprint TEXT NOT NULL,
                session_id TEXT NOT NULL,
                recorded_ts REAL,
                stats_json TEXT NOT NULL,
                UNIQUE (fingerprint, session_id)
            )"""
        )
        self._conn.commit()

    def close(self) -> None:
        try:
            self._conn.close()
        except sqlite3.Error:
            pass

    def matching_runs(
        self, fp: Dict[str, Any], exclude_session: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Prior runs with this fingerprint, oldest first, excluding
        the session under evaluation (re-finalize must not self-match)."""
        rows = self._conn.execute(
            "SELECT session_id, recorded_ts, stats_json FROM baseline_runs"
            " WHERE fingerprint=? ORDER BY id",
            (fingerprint_key(fp),),
        ).fetchall()
        out = []
        for session_id, ts, stats_json in rows:
            if exclude_session is not None and session_id == exclude_session:
                continue
            try:
                stats = json.loads(stats_json)
            except ValueError:
                continue
            out.append(
                {"session_id": session_id, "ts": ts, "stats": stats}
            )
        return out

    def record(
        self,
        fp: Dict[str, Any],
        session_id: str,
        stats: Dict[str, Any],
        ts: Optional[float] = None,
    ) -> None:
        """Upsert this session's stats and trim the fingerprint's
        history to ``max_runs`` newest rows."""
        key = fingerprint_key(fp)
        self._conn.execute(
            "INSERT INTO baseline_runs"
            " (fingerprint, session_id, recorded_ts, stats_json)"
            " VALUES (?,?,?,?)"
            " ON CONFLICT(fingerprint, session_id) DO UPDATE SET"
            " recorded_ts=excluded.recorded_ts,"
            " stats_json=excluded.stats_json",
            (key, session_id, ts if ts is not None else time.time(),
             json.dumps(stats)),
        )
        self._conn.execute(
            "DELETE FROM baseline_runs WHERE fingerprint=? AND id NOT IN ("
            " SELECT id FROM baseline_runs WHERE fingerprint=?"
            " ORDER BY id DESC LIMIT ?)",
            (key, key, self.max_runs),
        )
        self._conn.commit()


# -- robust bands + evaluation --------------------------------------------


def robust_band(
    values: List[float], rel_floor: float, abs_floor: float = 0.0
) -> Optional[Dict[str, float]]:
    """Median ± max(k·MAD, floors).  Small-n fallbacks: one prior run
    allows ±50%, two allow ±30% — usable detection from run #2 while a
    deep history tightens the band."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return None
    center = statistics.median(vals)
    scale = max(abs(center), 1e-12)
    if len(vals) == 1:
        half = max(0.5 * scale, abs_floor)
    elif len(vals) == 2:
        half = max(0.3 * scale, abs_floor)
    else:
        mad = statistics.median([abs(v - center) for v in vals])
        half = max(_MAD_K * mad, rel_floor * scale, abs_floor)
    return {"center": center, "low": center - half, "high": center + half,
            "n": len(vals)}


def evaluate(
    stats: Dict[str, Any],
    baseline_runs: List[Dict[str, Any]],
    topology: Any = None,
) -> Dict[str, Any]:
    """Check each metric against its band over the baseline runs.
    Returns the ``regressions`` payload section: overall status, one
    entry per evaluable metric, and PERF_REGRESSION issues (with r14
    attribution over per-rank step deltas when a mesh is known)."""
    checks: List[Dict[str, Any]] = []
    issues: List[Dict[str, Any]] = []
    for metric, spec in METRICS.items():
        current = stats.get(metric)
        history = [r["stats"].get(metric) for r in baseline_runs]
        band = robust_band(
            history, spec["rel_floor"], spec.get("abs_floor", 0.0)
        )
        if current is None or band is None:
            continue
        current = float(current)
        bad = spec["bad"]
        outside_bad = (
            current > band["high"] if bad == "high" else current < band["low"]
        )
        outside_good = (
            current < band["low"] if bad == "high" else current > band["high"]
        )
        delta_pct = (
            (current - band["center"]) / abs(band["center"]) * 100.0
            if band["center"]
            else None
        )
        check = {
            "metric": metric,
            "current": current,
            "baseline_median": band["center"],
            "band": [band["low"], band["high"]],
            "baseline_runs": band["n"],
            "delta_pct": round(delta_pct, 2) if delta_pct is not None else None,
            "status": (
                "regression" if outside_bad
                else "improved" if outside_good
                else "ok"
            ),
        }
        checks.append(check)
        if outside_bad:
            issues.append(
                _regression_issue(metric, spec, check, stats,
                                  baseline_runs, topology)
            )
    status = (
        "regression" if any(c["status"] == "regression" for c in checks)
        else "ok" if checks
        else "no_baseline"
    )
    return {
        "status": status,
        "baseline_runs": len(baseline_runs),
        "checks": checks,
        "issues": issues,
    }


def _regression_issue(
    metric: str,
    spec: Dict[str, Any],
    check: Dict[str, Any],
    stats: Dict[str, Any],
    baseline_runs: List[Dict[str, Any]],
    topology: Any,
) -> Dict[str, Any]:
    delta = check.get("delta_pct")
    worse = (
        f"{abs(delta):.1f}% "
        + ("above" if spec["bad"] == "high" else "below")
        if delta is not None
        else "outside"
    )
    issue: Dict[str, Any] = {
        "kind": "PERF_REGRESSION",
        "severity": "warn",
        "metric": metric,
        "summary": (
            f"{metric} {check['current']:.4g}{spec['unit'] and ' ' + spec['unit']} is "
            f"{worse} the median of the last {check['baseline_runs']} "
            f"matching run(s) ({check['baseline_median']:.4g})"
        ),
        "action": (
            "diff this run against the baseline sessions (traceml compare) "
            "and check the attributed ranks' hosts before trusting new code"
        ),
    }
    # cross-run analogue of the r14 hook: attribute WHICH ranks moved
    if metric == "steady_step_ms" and topology is not None:
        deltas = _per_rank_step_deltas(stats, baseline_runs)
        if deltas:
            try:
                from traceml_tpu.utils.topology import attribute_ranks

                attribution = attribute_ranks(deltas, topology)
                if attribution is not None:
                    issue["attribution"] = attribution.to_dict()
            except Exception:
                pass
    return issue


def _per_rank_step_deltas(
    stats: Dict[str, Any], baseline_runs: List[Dict[str, Any]]
) -> Dict[int, float]:
    """Per-rank current-minus-baseline steady step ms (baseline = the
    per-rank median across matching runs)."""
    current = stats.get("per_rank_step_ms") or {}
    history: Dict[str, List[float]] = {}
    for run in baseline_runs:
        for r, v in (run["stats"].get("per_rank_step_ms") or {}).items():
            if v is not None:
                history.setdefault(str(r), []).append(float(v))
    deltas: Dict[int, float] = {}
    for r, v in current.items():
        base = history.get(str(r))
        if v is None or not base:
            continue
        deltas[int(r)] = float(v) - statistics.median(base)
    return deltas


# -- the finalize entry point ---------------------------------------------


def evaluate_and_record(
    session_dir: Path,
    payload: Dict[str, Any],
    topology: Any = None,
    store_path: Optional[Path] = None,
) -> Optional[Dict[str, Any]]:
    """Evaluate this finished session against its fingerprint's prior
    runs, THEN ingest it (in that order — a regressed run must not
    widen the band that judged it).  Returns the ``regressions``
    section, or None when the store is unusable (caller omits the
    section; fail-open)."""
    session_dir = Path(session_dir)
    path = (
        Path(store_path)
        if store_path is not None
        else session_dir.parent / STORE_FILENAME
    )
    fp = fingerprint_from_summary(payload)
    stats = summary_stats(payload)
    session_id = (payload.get("meta") or {}).get("session_id") or (
        session_dir.name
    )
    if all(
        stats.get(m) is None for m in METRICS
    ):
        return None  # nothing comparable (e.g. an empty/aborted run)
    try:
        store = BaselineStore(path)
    except sqlite3.Error as exc:
        get_error_log().warning("baseline store unavailable", exc)
        return None
    try:
        prior = store.matching_runs(fp, exclude_session=str(session_id))
        result = evaluate(stats, prior, topology=topology)
        result["fingerprint"] = fp
        store.record(fp, str(session_id), stats)
        return result
    except sqlite3.Error as exc:
        get_error_log().warning("baseline evaluate/record failed", exc)
        return None
    finally:
        store.close()
