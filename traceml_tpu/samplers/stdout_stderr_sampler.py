"""stdout/stderr sampler
(reference: src/traceml_ai/samplers/stdout_stderr_sampler.py:25-76).

Drains the StreamCapture buffer into telemetry rows (the aggregator's
live CLI shows rank-0 output) and appends every rank's lines to a local
log file.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional

from traceml_tpu.runtime.stdout_capture import StreamCapture
from traceml_tpu.samplers.base_sampler import BaseSampler

TABLE = "stdout_stderr"


class StdoutStderrSampler(BaseSampler):
    name = "stdout_stderr"

    def __init__(
        self,
        capture: StreamCapture,
        *args: Any,
        log_path: Optional[Path] = None,
        mirror_to_db: bool = True,
        **kw: Any,
    ) -> None:
        super().__init__(*args, **kw)
        self._capture = capture
        self._log_path = Path(log_path) if log_path else None
        self._mirror = mirror_to_db

    def _sample(self) -> None:
        lines = self._capture.drain()
        if not lines:
            return
        ts = time.time()
        if self._log_path is not None:
            self._log_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self._log_path, "a", encoding="utf-8") as fh:
                for stream, line in lines:
                    fh.write(f"[{stream}] {line}\n")
        if self._mirror:
            self.db.add_records(
                TABLE,
                [
                    {"timestamp": ts, "stream": stream, "line": line[:4096]}
                    for stream, line in lines
                ],
            )
