"""Dirty-gated LiveComputer: idle ticks are free, recompute is per-domain.

Contract (docs/developer_guide/live-read-path.md): an idle tick — no
commits since the last one — performs ZERO SQLite row reads (only the
``PRAGMA data_version`` header check) and returns the IDENTICAL cached
payload object; after new rows land, only the domains whose tables
changed are recomputed, and clean domains keep their exact fragment
objects.
"""

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.renderers.compute import LiveComputer
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T


def _ident(rank=0, node=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=100 + rank,
    )


def _step_rows(start, n, base_ms=50.0):
    return [
        {
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": base_ms, "device_ms": base_ms, "count": 1},
                T.COMPUTE_TIME: {
                    "cpu_ms": 1.0, "device_ms": base_ms * 0.9, "count": 1,
                },
            },
        }
        for s in range(start, start + n)
    ]


def _system_rows(ts):
    return {
        "system": [{"timestamp": ts, "cpu_pct": 10.0,
                    "memory_used_bytes": 1, "memory_total_bytes": 2,
                    "memory_pct": 50.0}],
        "system_device": [{"timestamp": ts, "device_id": 0,
                           "device_kind": "tpu", "memory_used_bytes": 5,
                           "memory_peak_bytes": 6, "memory_total_bytes": 10}],
    }


def _seed_db(db):
    w = SQLiteWriter(db)
    w.start()
    for rank in (0, 1):
        w.ingest(build_telemetry_envelope(
            "step_time", {"step_time": _step_rows(1, 20)}, _ident(rank),
        ))
    w.ingest(build_telemetry_envelope("system", _system_rows(1.0), _ident(0)))
    w.ingest(build_telemetry_envelope(
        "process",
        {"process": [{"timestamp": 1.0, "cpu_pct": 5.0, "rss_bytes": 10,
                      "vms_bytes": 20, "num_threads": 3}]},
        _ident(1),
    ))
    w.ingest(build_telemetry_envelope(
        "stdout_stderr",
        {"stdout_stderr": [{"timestamp": 1.0, "stream": "stdout",
                            "line": "hello"}]},
        _ident(0),
    ))
    assert w.force_flush()
    return w


def test_idle_tick_zero_row_reads_and_identical_payload(tmp_path):
    db = tmp_path / "t.sqlite"
    w = _seed_db(db)
    computer = LiveComputer(db)

    p1 = computer.payload()
    assert p1["views"]["step_time"] is not None
    ts1 = p1["ts"]

    statements = []
    computer.store.connection.set_trace_callback(statements.append)
    try:
        p2 = computer.payload()
    finally:
        computer.store.connection.set_trace_callback(None)

    # identical object back, with only the timestamp refreshed in place
    assert p2 is p1
    assert p2["ts"] >= ts1
    # the ONLY SQL the idle tick ran is the data_version header check —
    # zero table reads, zero json decodes
    assert statements, "expected the data_version probe to be traced"
    assert all("data_version" in s for s in statements), statements
    assert not any("SELECT" in s.upper() for s in statements), statements

    w.finalize()
    computer.close()


def test_tick_after_ingest_recomputes_only_dirty_domains(tmp_path):
    db = tmp_path / "t.sqlite"
    w = _seed_db(db)
    computer = LiveComputer(db)
    p1 = computer.payload()

    # new step rows for rank 0 → step_time domain must recompute
    w.ingest(build_telemetry_envelope(
        "step_time", {"step_time": _step_rows(21, 5)}, _ident(0),
    ))
    assert w.force_flush()
    p2 = computer.payload()
    assert p2 is not p1
    assert p2["latest_row_ts"] == 25.0
    assert p2["step_time"] is not p1["step_time"]
    assert p2["views"]["step_time"] is not p1["views"]["step_time"]
    # untouched domains keep their exact cached fragments
    assert p2["system"] is p1["system"]
    assert p2["process"] is p1["process"]
    assert p2["stdout"] is p1["stdout"]
    assert p2["views"]["system"] is p1["views"]["system"]
    assert p2["views"]["process"] is p1["views"]["process"]

    # now only system rows arrive → step_time fragment is reused
    w.ingest(build_telemetry_envelope("system", _system_rows(2.0), _ident(0)))
    assert w.force_flush()
    p3 = computer.payload()
    assert p3 is not p2
    assert p3["system"] is not p2["system"]
    assert p3["views"]["system"] is not p2["views"]["system"]
    assert p3["step_time"] is p2["step_time"]
    assert p3["views"]["step_time"] is p2["views"]["step_time"]

    # and the next idle tick returns p3 itself again
    assert computer.payload() is p3

    w.finalize()
    computer.close()


def test_missing_db_payload_and_late_attach(tmp_path):
    db = tmp_path / "nope.sqlite"
    computer = LiveComputer(db)
    p = computer.payload()
    assert p["db_exists"] is False
    assert p["views"] == {}

    w = _seed_db(db)
    p2 = computer.payload()
    assert p2["db_exists"] is True
    assert "step_time" in p2["views"]
    w.finalize()
    computer.close()
