"""Contract tests pinning the torch_xla fake to the real public API
(FAKES.md rows — VERDICT r4 item 4).  Each test names the API surface
it encodes; if the fake drifts from these shapes, the e2e runs stop
meaning anything about real torch-xla.
"""

import inspect
import sys
import time
from pathlib import Path

import pytest

FAKES = Path(__file__).resolve().parents[1] / "fakes"


@pytest.fixture()
def fake_torch_xla(monkeypatch):
    monkeypatch.syspath_prepend(str(FAKES))
    # fresh import each test: module-global counters
    for name in [m for m in sys.modules if m.startswith("torch_xla")]:
        del sys.modules[name]
    import torch_xla

    yield torch_xla
    from traceml_tpu.instrumentation import torch_xla_support

    torch_xla_support.unpatch_mark_step()
    for name in [m for m in sys.modules if m.startswith("torch_xla")]:
        del sys.modules[name]


def test_mark_step_signature_and_blocking(fake_torch_xla, monkeypatch):
    """B1: xla_model.mark_step(wait=False) — documented signature; the
    barrier's wall time is the pending graph's execution."""
    import torch_xla.core.xla_model as xm

    sig = inspect.signature(xm.mark_step)
    assert list(sig.parameters) == ["wait"]
    assert sig.parameters["wait"].default is False
    monkeypatch.setenv("FAKE_XLA_MARK_STEP_MS", "30")
    t0 = time.perf_counter()
    xm.mark_step()
    assert time.perf_counter() - t0 >= 0.025  # the barrier blocks


def test_sync_is_separate_patch_target(fake_torch_xla, monkeypatch):
    """B2: torch_xla.sync() is the 2.x barrier spelling; traceml must
    patch it separately (real sync does not route through the
    xm.mark_step module attribute)."""
    monkeypatch.setenv("FAKE_XLA_MARK_STEP_MS", "1")
    import torch_xla

    from traceml_tpu.instrumentation.torch_xla_support import (
        patch_mark_step,
        unpatch_mark_step,
    )

    assert callable(torch_xla.sync)
    assert patch_mark_step()
    import torch_xla.core.xla_model as xm

    assert hasattr(xm.mark_step, "_traceml_original")
    assert hasattr(torch_xla.sync, "_traceml_original")
    unpatch_mark_step()
    assert not hasattr(torch_xla.sync, "_traceml_original")
    assert not hasattr(xm.mark_step, "_traceml_original")


def test_memory_info_kb_shape(fake_torch_xla, monkeypatch):
    """M1: XRT-era return shape {"kb_total", "kb_free"} (kb units),
    and the backend's byte conversion."""
    monkeypatch.delenv("FAKE_XLA_MEMORY_SHAPE", raising=False)
    from traceml_tpu.instrumentation.torch_xla_support import XlaMemoryBackend

    import torch_xla.core.xla_model as xm

    info = xm.get_memory_info("xla:0")
    assert set(info) == {"kb_total", "kb_free"}
    rows = XlaMemoryBackend().sample()
    assert rows and rows[0]["limit_bytes"] == info["kb_total"] * 1024
    assert rows[0]["current_bytes"] > 0


def test_memory_info_bytes_shape(fake_torch_xla, monkeypatch):
    """M2: PJRT-era return shape {"bytes_used", "bytes_limit",
    "peak_bytes"} — the backend must read it natively."""
    monkeypatch.setenv("FAKE_XLA_MEMORY_SHAPE", "bytes")
    from traceml_tpu.instrumentation.torch_xla_support import XlaMemoryBackend

    rows = XlaMemoryBackend().sample()
    assert rows
    assert rows[0]["current_bytes"] > 0
    assert rows[0]["limit_bytes"] and rows[0]["limit_bytes"] > rows[0][
        "current_bytes"
    ]
    assert rows[0]["peak_bytes"] >= rows[0]["current_bytes"]


def test_device_enumeration_signatures(fake_torch_xla):
    """D1/D2: get_xla_supported_devices(devkind, max_devices) and
    xla_device(n, devkind) — documented signatures."""
    import torch_xla.core.xla_model as xm

    sig = inspect.signature(xm.get_xla_supported_devices)
    assert list(sig.parameters) == ["devkind", "max_devices"]
    sig = inspect.signature(xm.xla_device)
    assert list(sig.parameters) == ["n", "devkind"]
    devs = xm.get_xla_supported_devices()
    assert devs and all(str(d).startswith("xla") for d in devs)


def test_identity_both_eras(fake_torch_xla, monkeypatch):
    """I1/I2: legacy xm.get_ordinal()/xrt_world_size() and the
    PJRT-era torch_xla.runtime replacements agree."""
    monkeypatch.setenv("RANK", "3")
    monkeypatch.setenv("WORLD_SIZE", "8")
    import torch_xla.core.xla_model as xm
    import torch_xla.runtime as xr

    assert xm.get_ordinal() == 3 and xr.global_ordinal() == 3
    assert xm.xrt_world_size() == 8 and xr.world_size() == 8


def test_barrier_delegation_counts_one_collective_sample(fake_torch_xla, monkeypatch):
    """The two barrier spellings delegate to each other (direction
    depends on torch_xla version) — one user barrier must sink exactly
    ONE collective sample, not two (review r5: the fake's sync() calls
    xm.mark_step, which reproduced the double count)."""
    monkeypatch.setenv("FAKE_XLA_MARK_STEP_MS", "1")
    import torch_xla

    from traceml_tpu.instrumentation.torch_xla_support import (
        patch_mark_step,
        unpatch_mark_step,
    )
    from traceml_tpu.sdk.state import get_state
    from traceml_tpu.utils import timing as T

    assert patch_mark_step()
    st = get_state()
    st.tls.in_step = True
    try:
        # count COLLECTIVE_TIME events reaching the buffer
        events = []
        orig_add = st.buffer.add
        st.buffer.add = lambda ev: (events.append(ev), orig_add(ev))[1]
        try:
            torch_xla.sync()  # delegates to xm.mark_step internally
        finally:
            st.buffer.add = orig_add
        collective = [e for e in events if e.name == T.COLLECTIVE_TIME]
        assert len(collective) == 1, [e.name for e in events]
    finally:
        st.tls.in_step = False
        unpatch_mark_step()
