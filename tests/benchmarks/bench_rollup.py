"""Tiered-retention rollups: fold-at-prune cost, bounded DB, stitched read.

Three claims, golden-compared before any timing is reported:

1. **Prune-phase ingest p99 stays inside the r09 envelope.**  The exact
   256-rank prune-heavy steady state ``bench_ingest.py`` timed for round
   9 (pre-filled to retention, every new row is overflow, every batch
   prunes) is re-driven through the watermark writer with rollups ON —
   every prune now folds its doomed id-range into the 10s/1m tiers
   inside the same transaction.  The recorded r09 baseline for this
   workload is ``wm_batch_p99_ms = 10.91`` (BENCH_LOCAL_r09.json); the
   CI gate is 2x that envelope, so the fold may cost at most as much
   again as the write+prune it rides on.

2. **A (compressed) week-long run keeps the DB bounded.**  2 ranks x
   120960 steps at a 5 s cadence span exactly 7 days of run time.  With
   rollups on and a live-window retention of 600 rows/rank the final DB
   must be a fraction of the unbounded counterfactual (same stream, no
   prune, no rollups) — yet the stitched read still covers the whole
   week.

3. **The stitched full-run read is bounded.**  One
   ``load_stitched_series`` call answers the whole week under a fixed
   time budget, because it touches `retention` raw rows + tier buckets,
   never the full history.

Goldens: ``fold_buckets`` vs the scalar reference must be BIT-exact on
ragged arrivals, and the stitched series must match an unbounded
reference fold over the full in-memory log (counts/min/max/step bounds
exact, sums to 1e-9 relative) with every ingested row accounted for.

Emits bench_common JSON lines (collected into BENCH_LOCAL_r18.json).
"""

import json
import math
import os
import sqlite3
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
# standalone `python tests/benchmarks/bench_rollup.py` support
sys.path.insert(1, str(Path(__file__).parent.parent.parent))
import bench_common  # noqa: E402
import bench_ingest  # noqa: E402  (the r09 harness this bench re-drives)

from traceml_tpu.aggregator.rollup import (  # noqa: E402
    fold_buckets,
    fold_buckets_reference,
)
from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter  # noqa: E402
from traceml_tpu.reporting import tiers  # noqa: E402
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_telemetry_envelope,
)

pytestmark = pytest.mark.slow

BENCH = "rollup"

# the r09 256-rank prune-phase envelope this round must stay inside
# (BENCH_LOCAL_r09.json wm_batch_p99_ms at ranks=256); CI gates at 2x —
# the in-transaction fold may at most double the batch tail
R09_P99_ENVELOPE_MS = 10.9093
P99_GATE_X = 2.0

# week-long arm: 2 ranks x 120960 steps x 5 s = exactly 7 days of run
WEEK_RANKS = 2
WEEK_STEPS = 120960
WEEK_DT_S = 5.0
WEEK_SPAN_S = WEEK_STEPS * WEEK_DT_S  # 604800
WEEK_WINDOW_ROWS = 400  # retention = 600 rows/rank (1.5x)
STITCH_READ_BUDGET_MS = 2000.0  # single-core shared-host budget
DB_BYTES_RATIO_MAX = 0.5  # bounded DB must be <= half the unbounded one


def _golden_fold_bit_exact():
    """fold_buckets == scalar reference, bit-exact, on ragged arrivals —
    run before any arm reports a number."""
    import random

    rng = random.Random(20260808)
    ts, steps, vals = [], [], []
    for step in range(400):
        ts.append(step * 1.7 + rng.uniform(-0.8, 0.8))
        steps.append(step)
        vals.append(100.0 + rng.gauss(0.0, 9.0))
    rng.shuffle(list(zip(ts, steps, vals)))  # arrival order is ragged
    for width in (10.0, 60.0):
        assert fold_buckets(ts, steps, vals, width) == \
            fold_buckets_reference(ts, steps, vals, width), (
                f"vectorized fold diverges from scalar reference at {width}s"
            )


# -- arm 1: prune-phase p99 within the r09 envelope -----------------------


def _run_p99_arm(tmp):
    """Re-drive the r09 256-rank prune-heavy case (same prefill, same
    batches, same slack) through the watermark writer — which now folds
    every doomed id-range before deleting it."""
    ranks = 256
    window_rows = bench_ingest._WINDOW_ROWS[ranks]
    retention = int(window_rows * 1.5)
    rounds = bench_ingest._rounds(ranks)
    start_step = retention + 1
    prune_slack = max(4, rounds * bench_ingest.ROWS_PER_ENV // 2)

    base_db = Path(tmp) / "p99_base.sqlite"
    bench_ingest._prefill(base_db, ranks, retention)

    import shutil

    # min-of-N per statistic: the driven work is deterministic, so
    # shared-host noise only ever ADDS time — min is the faithful
    # estimator (timeit's rule).  The tail gate takes the min of the
    # per-repeat p99s (3 repeats: a single noisy scheduler slice lands
    # in one repeat's tail, not all three).
    wm_s = wm_fin_s = wm_p99 = wm_max = None
    wm_db = Path(tmp) / "p99_wm.sqlite"
    for _ in range(3):
        shutil.copy(base_db, wm_db)
        s, fin, lat = bench_ingest._drive(
            bench_ingest._WatermarkDrive(wm_db, window_rows, prune_slack),
            ranks, rounds, start_step,
        )
        wm_s = s if wm_s is None else min(wm_s, s)
        wm_fin_s = fin if wm_fin_s is None else min(wm_fin_s, fin)
        p99 = bench_ingest._p99(lat)
        wm_p99 = p99 if wm_p99 is None else min(wm_p99, p99)
        wm_max = max(lat) if wm_max is None else min(wm_max, max(lat))

    # golden before reporting: every row ever ingested is raw or rolled
    # up — the stitched series accounts for all retention+rounds steps
    # per rank, with exact step bounds
    total_steps = retention + rounds * bench_ingest.ROWS_PER_ENV
    conn = sqlite3.connect(f"file:{wm_db}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    try:
        assert tiers.has_rollups(conn), "no rollup tiers after pruned drive"
        series = tiers.load_stitched_series(conn, "step_time_samples",
                                            "step_ms")
        assert len(series) == ranks, f"stitched ranks {len(series)}"
        for rank_key, points in series.items():
            n = sum(p["n"] for p in points)
            assert n == total_steps, (
                f"rank {rank_key}: {n} stitched rows != {total_steps} ingested"
            )
            assert points[0]["step_min"] == 1
            assert points[-1]["step_max"] == total_steps
        raw = conn.execute(
            "SELECT COUNT(*) FROM step_time_samples"
        ).fetchone()[0]
        assert raw == ranks * retention, raw
    finally:
        conn.close()

    extra = {
        "ranks": ranks, "rounds": rounds,
        "rows_per_env": bench_ingest.ROWS_PER_ENV,
        "batch_envelopes": bench_ingest.BATCH_ENVELOPES,
        "retention_rows": retention, "prefill_rows": ranks * retention,
        "prune_slack": prune_slack, "rollups": 1,
    }
    bench_common.emit(BENCH, "wm_rollup_envelopes_per_s",
                      ranks * rounds / wm_s, "env/s", **extra)
    bench_common.emit(BENCH, "wm_rollup_batch_p99_ms", wm_p99, "ms",
                      r09_p99_envelope_ms=R09_P99_ENVELOPE_MS,
                      gate_x=P99_GATE_X, **extra)
    bench_common.emit(BENCH, "wm_rollup_batch_max_ms", wm_max, "ms",
                      **extra)
    bench_common.emit(BENCH, "wm_rollup_finalize_ms", wm_fin_s * 1000.0,
                      "ms", **extra)
    return wm_p99


# -- arms 2+3: week-long bounded DB + stitched full-run read --------------


def _week_value(rank, step):
    # deterministic, non-constant: folds see real spread per bucket
    return 100.0 + (step % 97) * 0.25 + rank * 3.0


def _week_env(rank, step):
    ident = SenderIdentity(
        session_id="bench", global_rank=rank, local_rank=rank,
        world_size=WEEK_RANKS, node_rank=0, hostname="h0", pid=100 + rank,
    )
    rows = [{
        "step": step, "timestamp": step * WEEK_DT_S, "clock": "device",
        "events": {"_traceml_internal:step_time":
                   {"cpu_ms": _week_value(rank, step) - 1.0,
                    "device_ms": _week_value(rank, step), "count": 1}},
    }]
    return build_telemetry_envelope("step_time", {"step_time": rows}, ident)


def _week_batches():
    batch = []
    for step in range(1, WEEK_STEPS + 1):
        for rank in range(WEEK_RANKS):
            batch.append(_week_env(rank, step))
            if len(batch) == bench_ingest.BATCH_ENVELOPES:
                yield batch
                batch = []
    if batch:
        yield batch


def _drive_week(db_path, window_rows, prune_slack):
    w = SQLiteWriter(db_path, summary_window_rows=window_rows)
    if prune_slack is not None:
        w._prune_slack = prune_slack
    conn = w._connect()
    t0 = time.perf_counter()
    for batch in _week_batches():
        w._write_batch(conn, batch)
    sustained = time.perf_counter() - t0
    w._prune_all(conn)
    conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
    conn.commit()
    conn.close()
    return sustained


def _db_bytes(db_path):
    total = os.path.getsize(db_path)
    for suffix in ("-wal", "-shm"):
        p = str(db_path) + suffix
        if os.path.exists(p):
            total += os.path.getsize(p)
    return total


def _golden_stitched_vs_unbounded(conn):
    """Every stitched point must match the unbounded reference fold of
    the full in-memory log at the point's own resolution: n/min/max and
    step bounds exact, sums to 1e-9 relative; total n == every row
    ingested; coverage spans the whole week."""
    series = tiers.load_stitched_series(conn, "step_time_samples", "step_ms")
    assert len(series) == WEEK_RANKS, sorted(series)
    for rank in range(WEEK_RANKS):
        log_ts = [s * WEEK_DT_S for s in range(1, WEEK_STEPS + 1)]
        log_steps = list(range(1, WEEK_STEPS + 1))
        log_vals = [_week_value(rank, s) for s in range(1, WEEK_STEPS + 1)]
        ref = {}
        for width in (10.0, 60.0):
            for b in fold_buckets_reference(log_ts, log_steps, log_vals,
                                            width):
                ref[(width, b[0])] = b
        points = series[str(rank)]
        assert sum(p["n"] for p in points) == WEEK_STEPS, (
            f"rank {rank}: stitched rows != ingested rows"
        )
        for p in points:
            width = 60.0 if p["res"] == "1m" else 10.0
            b = ref.get((width, p["t"]))
            assert b is not None, f"stitched bucket {p['t']} not in reference"
            assert (p["n"], p["min"], p["max"]) == (b[1], b[3], b[4]), p
            assert (p["step_min"], p["step_max"]) == (b[6], b[7]), p
            assert math.isclose(p["sum"], b[2], rel_tol=1e-9), p
        first, last = points[0], points[-1]
        covered = (last["t"] + (60.0 if last["res"] == "1m" else 10.0)
                   - first["t"])
        assert covered >= 0.99 * WEEK_SPAN_S, (
            f"rank {rank}: stitched coverage {covered}s < week {WEEK_SPAN_S}s"
        )


def _run_week_arm(tmp):
    bounded_db = Path(tmp) / "week_bounded.sqlite"
    unbounded_db = Path(tmp) / "week_unbounded.sqlite"

    # bounded: live-window retention + rollups (default-on)
    _drive_week(bounded_db, WEEK_WINDOW_ROWS, prune_slack=64)

    # unbounded counterfactual: same stream, retention never triggers,
    # rollups off — the pure raw history a no-decay design would keep
    prev = os.environ.get("TRACEML_ROLLUP")
    os.environ["TRACEML_ROLLUP"] = "0"
    try:
        _drive_week(unbounded_db, WEEK_STEPS, prune_slack=None)
    finally:
        if prev is None:
            os.environ.pop("TRACEML_ROLLUP", None)
        else:
            os.environ["TRACEML_ROLLUP"] = prev

    conn = sqlite3.connect(f"file:{bounded_db}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    try:
        # goldens before any timing: bit-exact stitched reconstruction
        _golden_stitched_vs_unbounded(conn)

        raw_rows = conn.execute(
            "SELECT COUNT(*) FROM step_time_samples"
        ).fetchone()[0]
        tier_rows = sum(
            conn.execute(f"SELECT COUNT(*) FROM {t}").fetchone()[0]
            for t in ("rollup_samples_10s", "rollup_samples_1m")
        )

        # arm 3: the stitched full-run read, timed cold-cache per repeat
        read_s = None
        points = 0
        for _ in range(3):
            t0 = time.perf_counter()
            series = tiers.load_stitched_series(
                conn, "step_time_samples", "step_ms"
            )
            dt = time.perf_counter() - t0
            if read_s is None or dt < read_s:
                read_s = dt
                points = sum(len(v) for v in series.values())
    finally:
        conn.close()

    bounded_bytes = _db_bytes(bounded_db)
    unbounded_bytes = _db_bytes(unbounded_db)
    unbounded_rows = WEEK_RANKS * WEEK_STEPS

    extra = {"ranks": WEEK_RANKS, "steps": WEEK_STEPS, "dt_s": WEEK_DT_S,
             "span_s": WEEK_SPAN_S, "retention_rows": WEEK_WINDOW_ROWS * 3 // 2}
    bench_common.emit(BENCH, "week_db_bytes_bounded", bounded_bytes, "bytes",
                      raw_rows=raw_rows, tier_rows=tier_rows, **extra)
    bench_common.emit(BENCH, "week_db_bytes_unbounded", unbounded_bytes,
                      "bytes", raw_rows=unbounded_rows, **extra)
    bench_common.emit(BENCH, "week_db_bytes_ratio",
                      bounded_bytes / unbounded_bytes, "x",
                      gate_max=DB_BYTES_RATIO_MAX, **extra)
    bench_common.emit(BENCH, "week_stitched_read_ms", read_s * 1000.0, "ms",
                      points=points, budget_ms=STITCH_READ_BUDGET_MS, **extra)
    return bounded_bytes / unbounded_bytes, read_s * 1000.0


# -- pytest lane ----------------------------------------------------------


def test_rollup_prune_phase_p99_within_envelope(tmp_path):
    _golden_fold_bit_exact()
    wm_p99 = _run_p99_arm(tmp_path)
    assert wm_p99 <= P99_GATE_X * R09_P99_ENVELOPE_MS, (
        f"prune-phase p99 {wm_p99:.2f}ms exceeds "
        f"{P99_GATE_X}x r09 envelope {R09_P99_ENVELOPE_MS}ms"
    )


def test_rollup_week_long_db_bounded_and_stitched_read(tmp_path):
    _golden_fold_bit_exact()
    ratio, read_ms = _run_week_arm(tmp_path)
    assert ratio <= DB_BYTES_RATIO_MAX, (
        f"bounded DB is {ratio:.2f}x the unbounded one (gate "
        f"{DB_BYTES_RATIO_MAX}x)"
    )
    assert read_ms <= STITCH_READ_BUDGET_MS, (
        f"stitched full-run read {read_ms:.1f}ms over budget "
        f"{STITCH_READ_BUDGET_MS}ms"
    )


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        _golden_fold_bit_exact()
        p99 = _run_p99_arm(tmp)
        ratio, read_ms = _run_week_arm(tmp)
        print(
            f"# p99 {p99:.2f}ms (envelope {R09_P99_ENVELOPE_MS}ms), "
            f"db ratio {ratio:.3f}x, stitched read {read_ms:.1f}ms",
            file=sys.stderr,
        )
