"""Zero-copy producer path (r10): columnar accumulators, single-encode
publish, v2 backup frames, idle gate, producer self-observability.

The contract under test throughout: the fast path must be **golden
identical** to the pre-accumulator path — same wire envelopes (modulo
timestamp), same backup rows — under bursts, eviction, and resets.
"""

import struct
from pathlib import Path

import pytest

from traceml_tpu.database.database import Database
from traceml_tpu.database.database_sender import DBIncrementalSender
from traceml_tpu.database.database_writer import (
    ENVELOPE_FILE,
    V2_MAGIC,
    DatabaseWriter,
    iter_backup_file,
    iter_backup_tables,
)
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    build_columnar_envelope,
    columns_to_rows,
    rows_to_columns,
)
from traceml_tpu.utils import msgpack_codec

_LEN = struct.Struct(">I")


def _strip_ts(wire):
    wire = dict(wire)
    meta = dict(wire["meta"])
    meta.pop("timestamp", None)
    wire["meta"] = meta
    return wire


def _seed_wire(sampler, tables):
    """What the pre-r10 sender shipped for the same batch of rows."""
    return _strip_ts(build_columnar_envelope(sampler, tables).to_wire())


# -- columnar accumulator golden equivalence ----------------------------


def test_fast_path_matches_seed_wire():
    db = Database(max_rows_per_table=100)
    s = DBIncrementalSender("samp", db)
    rows = [{"a": i, "b": i * 2} for i in range(5)] + [{"a": 99, "c": "x"}]
    for r in rows:
        db.add_record("t", r)
    assert s.dirty()
    assert _strip_ts(s.collect_payload()) == _seed_wire("samp", {"t": rows})
    assert not s.dirty()
    assert s.collect_payload() is None  # idle: nothing new


def test_fast_path_nested_soa_columns():
    # dict-valued cells with uniform keys hit the nested-SoA encoding on
    # both paths — the accumulated columns must encode identically
    db = Database(max_rows_per_table=100)
    s = DBIncrementalSender("samp", db)
    rows = [
        {"step": i, "events": {"fwd": {"ms": 1.0 * i}, "bwd": {"ms": 2.0 * i}}}
        for i in range(4)
    ]
    db.add_records("t", rows)
    assert _strip_ts(s.collect_payload()) == _seed_wire("samp", {"t": rows})


@pytest.mark.parametrize(
    "windows",
    [
        # pure-tail windows smaller than the drain chunk, repeated so
        # pend_shape persistence across collection resets is exercised
        pytest.param(
            [[{"a": i, "b": {"x": i, "y": i * 2}} for i in range(5)]] * 4,
            id="sub-chunk-windows",
        ),
        # windows straddling multiple chunk boundaries
        pytest.param(
            [[{"a": i, "b": {"x": i, "y": i * 2}} for i in range(35)]] * 2,
            id="multi-chunk-windows",
        ),
        # shape drift while rows sit in the tail buffer
        pytest.param(
            [[{"a": 1, "b": 2}] * 10 + [{"a": 1}] * 3 + [{"a": 1, "b": 2}] * 7],
            id="drift-mid-tail",
        ),
        # nested-SoA degradation mid-window (key-set change, then scalar)
        pytest.param(
            [[{"a": {"x": 1, "y": 2}}] * 20 + [{"a": {"x": 1}}] * 5 + [{"a": 3}] * 4],
            id="nested-degradation",
        ),
        # same key set, different insertion order → general path
        pytest.param(
            [[{"a": 1, "b": 2}] * 5 + [{"b": 2, "a": 1}] * 5],
            id="reordered-keys",
        ),
        # empty dict rows (no columns, count only), then keyed rows
        pytest.param([[{}] * 3 + [{"a": 1}] * 3], id="empty-then-keyed"),
        # chunk-aligned window, then a one-row window straight into the
        # tail of a freshly reset (but shape-retaining) accumulator
        pytest.param(
            [
                [{"a": i, "n": {"p": 1, "q": 2}} for i in range(32)],
                [{"a": 9, "n": {"p": 3, "q": 4}}],
            ],
            id="chunk-aligned-then-single",
        ),
    ],
)
def test_chunked_tail_windows_match_seed_wire(windows):
    # the tail buffer + chunked transpose must stay golden-identical to
    # the batch path for every window shape, including partial chunks
    db = Database(max_rows_per_table=100)
    s = DBIncrementalSender("samp", db)
    for rows in windows:
        db.add_records("t", rows)
        assert _strip_ts(s.collect_payload()) == _seed_wire(
            "samp", {"t": rows}
        )


def test_overflow_falls_back_to_row_deque_golden():
    # burst past retention between collects: the accumulator can no
    # longer represent the window; the fallback must ship exactly the
    # surviving rows, like the pre-r10 path
    db = Database(max_rows_per_table=10)
    s = DBIncrementalSender("samp", db)
    for i in range(25):
        db.add_record("t", {"i": i})
    survivors = [{"i": i} for i in range(15, 25)]
    assert _strip_ts(s.collect_payload()) == _seed_wire("samp", {"t": survivors})
    # accumulator recovers after the fallback collection
    db.add_record("t", {"i": 100})
    assert _strip_ts(s.collect_payload()) == _seed_wire("samp", {"t": [{"i": 100}]})


def test_reset_reships_via_fallback():
    db = Database(max_rows_per_table=100)
    s = DBIncrementalSender("samp", db)
    rows = [{"i": i} for i in range(6)]
    db.add_records("t", rows)
    s.collect_payload()
    s.reset()  # cursor no longer matches the accumulator's → fallback
    assert s.dirty()
    assert _strip_ts(s.collect_payload()) == _seed_wire("samp", {"t": rows})


def test_interleaved_tables_and_incremental_batches():
    db = Database(max_rows_per_table=100)
    s = DBIncrementalSender("samp", db)
    db.add_record("a", {"x": 1})
    db.add_record("b", {"y": 1})
    p = _strip_ts(s.collect_payload())
    assert p == _seed_wire("samp", {"a": [{"x": 1}], "b": [{"y": 1}]})
    db.add_record("a", {"x": 2, "z": 3})
    p = _strip_ts(s.collect_payload())
    assert p == _seed_wire("samp", {"a": [{"x": 2, "z": 3}]})


def test_dirty_is_cheap_and_exact():
    db = Database()
    s = DBIncrementalSender("samp", db)
    assert not s.dirty()
    db.add_record("t", {"i": 0})
    assert s.dirty()
    s.collect_payload()
    assert not s.dirty()


# -- single-encode batch splice -----------------------------------------


def test_encode_batch_splice_matches_whole_list_encode():
    env = build_columnar_envelope(
        "samp", {"t": [{"i": i, "v": "x" * i} for i in range(20)]}
    ).to_wire()
    enc = msgpack_codec.preencode(env)
    plain = {"_traceml_control": "rank_finished", "meta": {"rank": 0}}
    assert msgpack_codec.encode_batch([enc, plain]) == msgpack_codec.encode(
        [env, plain]
    )
    # and the standalone body matches encode() of the object
    assert enc.body() == msgpack_codec.encode(env)


def test_encode_batch_large_array_headers():
    objs = [{"i": i} for i in range(300)]  # > fixarray and > 0xFF
    encs = [msgpack_codec.preencode(o) for o in objs]
    assert msgpack_codec.encode_batch(encs) == msgpack_codec.encode(objs)


# -- backup format v2 ----------------------------------------------------


def _mk_envelope(rows, sampler="samp", table="t"):
    env = build_columnar_envelope(sampler, {table: rows}).to_wire()
    return msgpack_codec.preencode(env)


def _wire_rows(rows):
    """Rows as a columnar consumer materializes them (absent → None)."""
    return columns_to_rows(rows_to_columns(rows))


def test_v2_backup_roundtrip(tmp_path):
    db = Database()
    w = DatabaseWriter("samp", db, tmp_path, flush_every=1)
    rows = [{"a": i} for i in range(4)] + [{"a": 9, "b": "x"}]
    w.append_envelope(_mk_envelope(rows))
    assert w.envelope_mode and w.has_pending()
    assert w.flush(force=True) == 1
    assert not w.has_pending()
    f = tmp_path / "samp" / ENVELOPE_FILE
    got = list(iter_backup_tables(f))
    assert [t for t, _ in got] == ["t"] * 5
    assert [r for _, r in got] == _wire_rows(rows)
    assert list(iter_backup_file(f)) == _wire_rows(rows)


def test_v2_backup_multiple_tables_per_frame(tmp_path):
    db = Database()
    w = DatabaseWriter("samp", db, tmp_path, flush_every=1)
    env = build_columnar_envelope(
        "samp", {"a": [{"x": 1}], "b": [{"y": 2}, {"y": 3}]}
    ).to_wire()
    w.append_envelope(msgpack_codec.preencode(env))
    w.flush(force=True)
    got = list(iter_backup_tables(tmp_path / "samp" / ENVELOPE_FILE))
    assert got == [("a", {"x": 1}), ("b", {"y": 2}), ("b", {"y": 3})]


def test_v1_backup_still_readable(tmp_path):
    # legacy writer (never fed envelopes) keeps the per-row format
    db = Database()
    w = DatabaseWriter("s", db, tmp_path, flush_every=1)
    db.add_records("t", [{"i": 0}, {"i": 1}])
    assert not w.envelope_mode
    assert w.flush(force=True) == 2
    f = tmp_path / "s" / "t.msgpack"
    assert list(iter_backup_file(f)) == [{"i": 0}, {"i": 1}]
    assert list(iter_backup_tables(f)) == [(None, {"i": 0}), (None, {"i": 1})]


def test_mixed_v1_v2_frames_one_file(tmp_path):
    f = tmp_path / "mixed.msgpack"
    buf = bytearray()
    for r in ({"i": 0}, {"i": 1}):
        frame = msgpack_codec.encode(r)
        buf += _LEN.pack(len(frame)) + frame
    enc = _mk_envelope([{"a": 1}, {"a": 2}])
    buf += V2_MAGIC + _LEN.pack(len(enc.body())) + enc.body()
    frame = msgpack_codec.encode({"i": 2})
    buf += _LEN.pack(len(frame)) + frame
    f.write_bytes(bytes(buf))
    assert list(iter_backup_tables(f)) == [
        (None, {"i": 0}),
        (None, {"i": 1}),
        ("t", {"a": 1}),
        ("t", {"a": 2}),
        (None, {"i": 2}),
    ]


@pytest.mark.parametrize("cut", ["magic", "length", "body"])
def test_v2_torn_tail_stops_cleanly(tmp_path, cut):
    f = tmp_path / "envelopes.msgpack"
    enc = _mk_envelope([{"a": 1}])
    good = V2_MAGIC + _LEN.pack(len(enc.body())) + enc.body()
    torn = {
        "magic": V2_MAGIC[:2],
        "length": V2_MAGIC + _LEN.pack(len(enc.body()))[:3],
        "body": V2_MAGIC + _LEN.pack(len(enc.body())) + enc.body()[:5],
    }[cut]
    f.write_bytes(good + torn)
    assert list(iter_backup_tables(f)) == [("t", {"a": 1})]


def test_v1_torn_tail_stops_cleanly(tmp_path):
    f = tmp_path / "t.msgpack"
    frame = msgpack_codec.encode({"i": 0})
    f.write_bytes(_LEN.pack(len(frame)) + frame + _LEN.pack(99) + b"\x01par")
    assert list(iter_backup_file(f)) == [{"i": 0}]


def test_v2_magic_stops_v1_corrupt_length_bound(tmp_path):
    # the magic deliberately parses as a >64MiB length for old readers;
    # the new reader's own corrupt-length bound must still hold for
    # genuinely corrupt v2 lengths
    f = tmp_path / "envelopes.msgpack"
    f.write_bytes(V2_MAGIC + _LEN.pack(200 * 1024 * 1024) + b"junk")
    assert list(iter_backup_tables(f)) == []


def test_writer_hwm_flushes_midburst(tmp_path):
    db = Database()
    w = DatabaseWriter("samp", db, tmp_path, flush_every=10**9)
    big = [{"i": i, "pad": "x" * 1000} for i in range(700)]  # ~0.7MB encoded
    w.append_envelope(_mk_envelope(big))
    # the 512KiB high-water mark wrote the buffer despite the throttle
    assert not w.has_pending()
    assert (tmp_path / "samp" / ENVELOPE_FILE).exists()


def test_writer_failed_write_keeps_buffer(tmp_path):
    db = Database()
    blocked = tmp_path / "nope"
    blocked.write_text("file, not a dir")  # mkdir(parents) will fail
    w = DatabaseWriter("samp", db, blocked, flush_every=1)
    w.append_envelope(_mk_envelope([{"i": 1}]))
    assert w.flush(force=True) == 0
    assert w.has_pending()  # frames retained for the next attempt
