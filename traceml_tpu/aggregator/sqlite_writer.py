"""Async SQLite writer
(reference: src/traceml_ai/aggregator/sqlite_writer.py:112-647).

One dedicated writer thread owns the connection (sqlite is
single-writer anyway).  The high-rank write path is built from three
pieces that keep every stage of drain → project → commit → prune
bounded (no stage ever stalls the pipe):

* **Prioritized backpressure** — the ingest queue is split by domain
  priority: step_time / step_memory (the rows diagnosis depends on)
  get their own large queue, system / process / stdout share a smaller
  one.  Overload sheds the low-value domains first instead of whatever
  arrives last, with per-domain shed counters, queue high-water marks,
  and a rate-limited producer-visible warning on drop.

* **Group-commit scheduling** — drained envelopes coalesce into one
  transaction per size-or-interval threshold (``_GROUP_COMMIT_ENVS`` /
  ``_GROUP_COMMIT_INTERVAL_S``), with ``writer_for``/``insert_sql``
  lookups cached per sampler/table instead of re-resolved per envelope.
  Flush barriers stay read-your-writes correct: a barrier forces the
  pending group to commit before its event fires.

* **O(new) watermark retention** — the writer tracks per
  ``(table, session_id, global_rank)`` row counts from its own inserts;
  when a partition overflows ``retention + slack`` it is queued for
  pruning, and each commit cycle prunes a bounded slice of partitions
  via an indexed range delete: the watermark id comes from
  ``ORDER BY id DESC LIMIT 1 OFFSET retention`` on that partition only,
  then ``DELETE … WHERE id <= watermark``.  No commit ever absorbs a
  full-table ``ROW_NUMBER()`` scan (the seed design stalled for
  hundreds of ms at 256+ ranks).  Every prune is journaled to the
  ``retention_watermarks`` table so the live snapshot store can evict
  exactly the deleted rows per rank (per-partition deletes do not move
  the global ``MIN(id)`` the old trim detection watched).

``finalize()`` = drain → prune every overflowing partition to exactly
``retention`` rows (same survivors the seed's windowed prune kept) →
``wal_checkpoint(TRUNCATE)`` → close.
"""

from __future__ import annotations

import queue
import sqlite3
import threading
import time
from collections import deque
from pathlib import Path
from typing import Deque, Dict, List, Optional, Tuple

from traceml_tpu.aggregator import rollup
from traceml_tpu.aggregator.sqlite_writers import ALL_WRITERS, writer_for
from traceml_tpu.telemetry.envelope import TelemetryEnvelope
from traceml_tpu.utils.error_log import get_error_log

# queue capacities per priority class (high + low ≈ the seed's single
# 50k queue, but a low-domain flood can no longer evict step telemetry)
_QUEUE_HIGH_MAX = 40_000
_QUEUE_LOW_MAX = 10_000

# samplers whose rows drive diagnosis — everything else (system, process,
# stdout_stderr, unknown samplers) sheds first under overload.  Control
# messages never reach this queue: the aggregator handles them inline,
# ahead of any telemetry backpressure.
HIGH_PRIORITY_SAMPLERS = frozenset(
    {"step_time", "step_memory", "collectives", "serving"}
)
PRIORITY_NAMES = ("high", "low")

# group-commit thresholds: commit when this many envelopes are pending,
# or when the oldest pending envelope has waited this long
_GROUP_COMMIT_ENVS = 512
_GROUP_COMMIT_INTERVAL_S = 0.2

# bounded prune slice per commit cycle (partitions per slice); the
# backlog queue carries the rest to the next cycle
_PRUNE_PARTITIONS_PER_SLICE = 8

# journal self-trim: cap the watermark journal's size (deleting old
# journal rows is invisible to store cursors, which only move forward)
_JOURNAL_MAX_ROWS = 4096

_DROP_WARN_INTERVAL_S = 5.0

WATERMARK_TABLE = "retention_watermarks"

# durable-replay dedup: per (session, rank, lane) max committed seq.
# Lane-scoped because FIFO commit order is only guaranteed WITHIN a
# priority queue — a low-lane envelope with a smaller seq legitimately
# commits after a high-lane envelope with a larger one, and a single
# (session, rank) max would swallow it as a duplicate.
RANK_SEQ_TABLE = "rank_seq"

_MISSING = object()


def _p99(values: Deque[float]) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


def ingest_priority(sampler: str) -> int:
    return 0 if sampler in HIGH_PRIORITY_SAMPLERS else 1


class _FlushBarrier:
    __slots__ = ("event",)

    def __init__(self) -> None:
        self.event = threading.Event()


class SQLiteWriter:
    def __init__(
        self,
        db_path: Path,
        summary_window_rows: int = 10_000,
        retention_factor: float = 1.5,
        queue_max_high: int = _QUEUE_HIGH_MAX,
        queue_max_low: int = _QUEUE_LOW_MAX,
        group_commit_envelopes: int = _GROUP_COMMIT_ENVS,
        group_commit_interval_s: float = _GROUP_COMMIT_INTERVAL_S,
        prune_partitions_per_slice: int = _PRUNE_PARTITIONS_PER_SLICE,
    ) -> None:
        self.db_path = Path(db_path)
        self._retention_rows = int(summary_window_rows * retention_factor)
        # hysteresis: a partition is pruned online once it overflows
        # retention by this slack (so steady trickle doesn't prune one
        # row per batch, and disk stays bounded at ~2x the cap during a
        # long run); finalize() still trims every partition to exactly
        # retention (the seed-prune-equivalent final state), which is
        # where short runs — and the golden tests — see their only prune
        self._prune_slack = max(256, self._retention_rows)
        self._group_envs = int(group_commit_envelopes)
        self._group_interval = float(group_commit_interval_s)
        self._prune_slice_max = int(prune_partitions_per_slice)

        self._queues: Tuple["queue.Queue[object]", ...] = (
            queue.Queue(maxsize=queue_max_high),
            queue.Queue(maxsize=queue_max_low),
        )
        self._work = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._finalized = threading.Event()

        # public counters (back-compat: ingest_stats.json / tests)
        self.enqueued = 0
        self.dropped = 0
        self.written = 0
        self._batches = 0
        # envelopes permanently resolved: group-committed (including
        # dedup'd replays / unknown domains — they will never be
        # retried) plus queue-full drops.  The aggregator gates shm
        # ring-tail commits on this watermark: a ring frame's space is
        # reclaimable only once every envelope drained before it can no
        # longer be lost by a crash.
        self._settled = 0

        self._stats_lock = threading.Lock()
        self._enq_by_domain: Dict[str, int] = {}
        self._drop_by_domain: Dict[str, int] = {}
        self._queue_hwm = [0, 0]
        self._last_drop_warn = 0.0
        self._drops_since_warn = 0
        self.drop_warnings = 0

        # envelopes whose sampler has no registered projection writer —
        # counted and surfaced instead of silently skipped (a version-skewed
        # producer shipping a new domain must be visible in ingest stats)
        self._unknown_by_domain: Dict[str, int] = {}
        self._last_unknown_warn = 0.0
        self._unknown_since_warn = 0

        # retention bookkeeping (writer thread only)
        self._part_counts: Dict[Tuple[str, str, int], int] = {}
        self._prune_due: Deque[Tuple[str, str, int]] = deque()
        self._prune_due_set: set = set()
        self._retention_tables = frozenset(
            t for w in ALL_WRITERS for t in getattr(w, "RETENTION_TABLES", ())
        )
        self._journal_rows = 0

        # lookup caches (satellite: never re-resolve per envelope)
        self._writer_cache: Dict[str, object] = {}
        self._sql_cache: Dict[str, str] = {}

        # latency telemetry (writer thread appends; stats() reads)
        self._commit_lat_ms: Deque[float] = deque(maxlen=512)
        self._prune_lat_ms: Deque[float] = deque(maxlen=512)
        self._commit_max_ms = 0.0
        self._prune_max_ms = 0.0
        self.prunes = 0
        self.rows_pruned = 0

        # replay dedup state (writer thread only): seeded from the
        # rank_seq table on (re)open so a restarted aggregator keeps
        # rejecting envelopes its previous incarnation already committed
        self._seq_max: Dict[Tuple[str, int, str], int] = {}
        self.replay_duplicates = 0

        # tiered rollup decay: folds each prune's doomed id-range into
        # rollup_samples_10s/_1m inside the same transaction as the
        # delete (None when TRACEML_ROLLUP=0 — prunes discard history)
        self._rollup = rollup.build_engine()

    # -- producer side (aggregator loop) --------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self.db_path.parent.mkdir(parents=True, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="traceml-sqlite-writer", daemon=True
        )
        self._thread.start()

    def ingest(self, env: TelemetryEnvelope) -> bool:
        pri = ingest_priority(env.sampler)
        q = self._queues[pri]
        try:
            q.put_nowait(env)
        except queue.Full:
            self._record_drop(env.sampler, pri)
            return False
        self.enqueued += 1
        with self._stats_lock:
            self._enq_by_domain[env.sampler] = (
                self._enq_by_domain.get(env.sampler, 0) + 1
            )
            depth = q.qsize()
            if depth > self._queue_hwm[pri]:
                self._queue_hwm[pri] = depth
        self._work.set()
        return True

    def _record_drop(self, sampler: str, pri: int) -> None:
        """Count the shed envelope per domain and warn (rate-limited) —
        a silent counter bump only discovered in ingest_stats.json after
        the run is not a producer-visible signal."""
        warn_count = 0
        with self._stats_lock:
            self.dropped += 1
            self._settled += 1  # shed = resolved: it will never be written
            self._drop_by_domain[sampler] = (
                self._drop_by_domain.get(sampler, 0) + 1
            )
            self._drops_since_warn += 1
            now = time.monotonic()
            if now - self._last_drop_warn >= _DROP_WARN_INTERVAL_S:
                self._last_drop_warn = now
                warn_count = self._drops_since_warn
                self._drops_since_warn = 0
                totals = dict(self._drop_by_domain)
        if warn_count:
            self.drop_warnings += 1
            get_error_log().warning(
                f"ingest queue ({PRIORITY_NAMES[pri]}) full: shed "
                f"{warn_count} envelope(s) in the last "
                f"{_DROP_WARN_INTERVAL_S:.0f}s (latest sampler="
                f"{sampler}); dropped by domain so far: {totals}"
            )

    def settled_envelopes(self) -> int:
        """Cumulative envelopes permanently resolved (committed batches
        + queue-full drops).  Monotonic; safe to read from any thread."""
        with self._stats_lock:
            return self._settled

    def _record_unknown_domain(self, sampler: str) -> None:
        """An envelope named a table with no registered writer.  Neither
        raise nor vanish: count it per domain for ingest_stats.json and
        warn rate-limited (the producer may be a newer version shipping a
        domain this aggregator doesn't know)."""
        warn_count = 0
        with self._stats_lock:
            self._unknown_by_domain[sampler] = (
                self._unknown_by_domain.get(sampler, 0) + 1
            )
            self._unknown_since_warn += 1
            now = time.monotonic()
            if now - self._last_unknown_warn >= _DROP_WARN_INTERVAL_S:
                self._last_unknown_warn = now
                warn_count = self._unknown_since_warn
                self._unknown_since_warn = 0
                totals = dict(self._unknown_by_domain)
        if warn_count:
            get_error_log().warning(
                f"no projection writer for telemetry domain {sampler!r}: "
                f"dropped {warn_count} envelope(s) in the last "
                f"{_DROP_WARN_INTERVAL_S:.0f}s; unknown-domain drops so "
                f"far: {totals}"
            )

    def force_flush(self, timeout: float = 10.0) -> bool:
        """Barrier: returns once everything enqueued so far is committed
        (reference: sqlite_writer.py:168).  One barrier per priority
        queue — each guarantees the items ahead of it in its own queue;
        waiting on both covers everything enqueued before this call."""
        if self._thread is None or self._finalized.is_set():
            return False
        deadline = time.monotonic() + timeout
        barriers: List[_FlushBarrier] = []
        ok = True
        for q in self._queues:
            b = _FlushBarrier()
            try:
                q.put(b, timeout=max(0.0, deadline - time.monotonic()))
                barriers.append(b)
            except queue.Full:
                ok = False
        self._work.set()
        for b in barriers:
            ok &= b.event.wait(max(0.01, deadline - time.monotonic()))
        return ok

    def finalize(self, timeout: float = 30.0) -> bool:
        """Drain, prune, checkpoint, close (reference: 206-272, 554-622)."""
        if self._thread is None:
            return True
        ok = self.force_flush(timeout)
        self._stop_evt.set()
        self._work.set()
        self._thread.join(timeout=timeout)
        alive = self._thread.is_alive()
        self._thread = None
        return ok and not alive

    # -- observability ---------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Backpressure / group-commit / retention self-metrics for
        ingest_stats.json and the live UI meta."""
        with self._stats_lock:
            enq = dict(self._enq_by_domain)
            drop = dict(self._drop_by_domain)
            unknown = dict(self._unknown_by_domain)
            hwm = list(self._queue_hwm)
            dropped = self.dropped
        queues = {}
        for pri, name in enumerate(PRIORITY_NAMES):
            q = self._queues[pri]
            queues[name] = {
                "depth": q.qsize(),
                "hwm": hwm[pri],
                "capacity": q.maxsize,
            }
        return {
            "enqueued": self.enqueued,
            "dropped": dropped,
            "written": self.written,
            "enqueued_by_domain": enq,
            "dropped_by_domain": drop,
            "unknown_domain_drops": unknown,
            "drop_warnings": self.drop_warnings,
            "replay_duplicates": self.replay_duplicates,
            "queues": queues,
            "group_commit": {
                "commits": self._batches,
                "commit_p99_ms": _p99(self._commit_lat_ms),
                "commit_max_ms": round(self._commit_max_ms, 3),
            },
            "prune": {
                "prunes": self.prunes,
                "rows_pruned": self.rows_pruned,
                "partitions_tracked": len(self._part_counts),
                "partitions_due": len(self._prune_due),
                "prune_p99_ms": _p99(self._prune_lat_ms),
                "prune_max_ms": round(self._prune_max_ms, 3),
                "retention_rows": self._retention_rows,
            },
            "rollup": (
                self._rollup.stats()
                if self._rollup is not None
                else {"enabled": False}
            ),
        }

    # -- writer thread ---------------------------------------------------
    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(str(self.db_path))
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        # 64 MiB page cache: at 1k+ ranks the live window alone is
        # ranks x retention rows (~hundreds of MB of B-tree pages), and
        # the default ~2 MiB cache thrashes on the rank-interleaved
        # index inserts and partition-scoped prune scans
        conn.execute("PRAGMA cache_size=-65536")
        # rank-interleaved commits rewrite the same hot index pages over
        # and over; the default 1000-page autocheckpoint re-copies them
        # into the main DB every ~4 MiB of WAL.  A 10x window dedups
        # those copies and keeps checkpoint stalls off the commit path
        # (finalize still runs wal_checkpoint(TRUNCATE))
        conn.execute("PRAGMA wal_autocheckpoint=10000")
        for w in ALL_WRITERS:
            w.init_schema(conn)
        conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {WATERMARK_TABLE} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                table_name TEXT,
                session_id TEXT,
                global_rank INTEGER,
                watermark_id INTEGER,
                deleted_rows INTEGER,
                ts REAL
            )"""
        )
        conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {RANK_SEQ_TABLE} (
                session_id TEXT,
                global_rank INTEGER,
                lane TEXT,
                max_seq INTEGER,
                PRIMARY KEY (session_id, global_rank, lane)
            )"""
        )
        for table in self._retention_tables:
            # the watermark SELECT and the range DELETE both need a
            # (session_id, global_rank) prefix to stay partition-scoped
            # (rowid is the implicit tiebreaker, so ORDER BY id comes
            # free).  Most tables already carry one for the read path —
            # duplicating it would tax EVERY insert with a second
            # B-tree (measured ~25% throughput loss), so only tables
            # without one (stdout, model_stats) get a new index.
            if not self._has_partition_index(conn, table):
                conn.execute(
                    f"CREATE INDEX IF NOT EXISTS idx_{table}_retention"
                    f" ON {table} (session_id, global_rank)"
                )
        if self._rollup is not None:
            self._rollup.init_schema(conn)
        conn.commit()
        self._seed_partition_counts(conn)
        self._seed_seq_max(conn)
        return conn

    @staticmethod
    def _has_partition_index(conn: sqlite3.Connection, table: str) -> bool:
        for idx in conn.execute(f"PRAGMA index_list({table})").fetchall():
            cols = [
                r[2]
                for r in conn.execute(f"PRAGMA index_info({idx[1]})")
            ]
            if cols[:2] == ["session_id", "global_rank"]:
                return True
        return False

    def _seed_partition_counts(self, conn: sqlite3.Connection) -> None:
        """Resumed/pre-existing DB: learn current per-partition row
        counts once so retention stays O(new) from the first batch."""
        for table in self._retention_tables:
            try:
                rows = conn.execute(
                    f"SELECT session_id, global_rank, COUNT(*) FROM {table}"
                    " GROUP BY session_id, global_rank"
                ).fetchall()
            except sqlite3.Error:
                continue
            for session_id, rank, n in rows:
                key = (table, str(session_id), int(rank))
                self._part_counts[key] = int(n)
                self._note_overflow(key, int(n))

    def _seed_seq_max(self, conn: sqlite3.Connection) -> None:
        """Crash-resume: reload committed per-lane seq maxima so a
        restarted aggregator dedups the ranks' reconnect replay against
        everything its previous incarnation durably wrote."""
        try:
            rows = conn.execute(
                f"SELECT session_id, global_rank, lane, max_seq"
                f" FROM {RANK_SEQ_TABLE}"
            ).fetchall()
        except sqlite3.Error:
            return
        for session_id, rank, lane, mx in rows:
            self._seq_max[(str(session_id), int(rank), str(lane))] = int(mx)

    def _note_overflow(self, key: Tuple[str, str, int], count: int) -> None:
        if (
            count >= self._retention_rows + self._prune_slack
            and key not in self._prune_due_set
        ):
            self._prune_due_set.add(key)
            self._prune_due.append(key)

    def _queues_empty(self) -> bool:
        return all(q.empty() for q in self._queues)

    def _run(self) -> None:
        try:
            conn = self._connect()
        except Exception as exc:
            get_error_log().error("sqlite writer failed to open db", exc)
            self._finalized.set()
            return
        pending: List[TelemetryEnvelope] = []
        barriers: List[_FlushBarrier] = []
        pending_since: Optional[float] = None
        try:
            while True:
                if pending_since is not None:
                    timeout = min(
                        0.25,
                        max(
                            0.005,
                            self._group_interval
                            - (time.monotonic() - pending_since),
                        ),
                    )
                else:
                    timeout = 0.25
                if self._work.wait(timeout):
                    self._work.clear()
                # pop everything currently queued, high priority first
                for q in self._queues:
                    while True:
                        try:
                            item = q.get_nowait()
                        except queue.Empty:
                            break
                        if item is None:
                            continue
                        if isinstance(item, _FlushBarrier):
                            barriers.append(item)
                        else:
                            pending.append(item)
                now = time.monotonic()
                if pending and pending_since is None:
                    pending_since = now
                # group-commit gate: barriers and shutdown flush
                # immediately; otherwise wait for size or interval
                flush_now = (
                    bool(barriers)
                    or self._stop_evt.is_set()
                    or len(pending) >= self._group_envs
                    or (
                        pending_since is not None
                        and now - pending_since >= self._group_interval
                    )
                )
                if pending and flush_now:
                    # _write_batch folds the retention prune slice into
                    # the same transaction
                    self._write_batch(conn, pending)
                    pending = []
                    pending_since = None
                if barriers and not pending:
                    for b in barriers:
                        b.event.set()
                    barriers = []
                if (
                    self._stop_evt.is_set()
                    and not pending
                    and self._queues_empty()
                ):
                    break
            self._prune_all(conn)
            try:
                conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                conn.commit()
            except sqlite3.Error:
                pass
        except Exception as exc:  # pragma: no cover
            get_error_log().error("sqlite writer thread crashed", exc)
        finally:
            try:
                conn.close()
            except Exception:
                pass
            self._finalized.set()

    def _write_batch(
        self, conn: sqlite3.Connection, batch: List[TelemetryEnvelope]
    ) -> None:
        # Build parameter tuples for the WHOLE batch first, grouped by
        # insert statement, so each (table, batch) costs exactly one
        # executemany inside one transaction — never per-row, and never
        # per-envelope when many ranks ship the same table.
        grouped: Dict[str, List[tuple]] = {}
        touched: Dict[Tuple[str, str, int], int] = {}
        seq_touched: Dict[Tuple[str, int, str], int] = {}
        for env in batch:
            seq = env.seq
            if seq is not None:
                # dedup replayed envelopes: the spool re-delivers
                # anything sent-but-unacked around a link failure, so a
                # seq at or below the lane's committed max is a replay
                # of a row already in the DB.  seq_touched covers dups
                # landing inside this same batch (original + replay
                # drained together).
                skey = (
                    str(env.meta.get("session_id", "unknown")),
                    env.global_rank,
                    PRIORITY_NAMES[ingest_priority(env.sampler)],
                )
                cur_max = seq_touched.get(skey, self._seq_max.get(skey, -1))
                if seq <= cur_max:
                    self.replay_duplicates += 1
                    continue
                seq_touched[skey] = seq
            writer = self._writer_cache.get(env.sampler, _MISSING)
            if writer is _MISSING:
                writer = writer_for(env.sampler)
                self._writer_cache[env.sampler] = writer
            if writer is None:
                self._record_unknown_domain(env.sampler)
                continue
            try:
                table_rows = writer.build_rows(env)
            except Exception as exc:
                get_error_log().warning(
                    f"projection build failed for {env.sampler}", exc
                )
                continue
            for table, rows in table_rows.items():
                if not rows:
                    continue
                sql = self._sql_cache.get(table)
                if sql is None:
                    sql = self._sql_cache[table] = writer.insert_sql(table)
                grouped.setdefault(sql, []).extend(rows)
                if table in self._retention_tables:
                    # every row of an envelope shares one identity tuple
                    # (session_id, global_rank lead each row), so the
                    # partition count costs O(1) per (envelope, table)
                    key = (table, rows[0][0], rows[0][1])
                    touched[key] = touched.get(key, 0) + len(rows)
        t0 = time.perf_counter()
        try:
            conn.execute("BEGIN")
            for sql, rows in grouped.items():
                conn.executemany(sql, rows)
                self.written += len(rows)
            if seq_touched:
                # the new maxima commit atomically with the rows they
                # cover: a crash between the two can never produce an
                # aggregator that drops a replay it didn't persist
                conn.executemany(
                    f"INSERT OR REPLACE INTO {RANK_SEQ_TABLE}"
                    " (session_id, global_rank, lane, max_seq)"
                    " VALUES (?,?,?,?)",
                    [(k[0], k[1], k[2], mx) for k, mx in seq_touched.items()],
                )
            for key, n in touched.items():
                count = self._part_counts.get(key, 0) + n
                self._part_counts[key] = count
                self._note_overflow(key, count)
            # retention deletes ride the batch transaction: one commit
            # per cycle instead of two, and the journal row lands
            # atomically with the inserts that triggered it
            self._prune_slice(conn, commit=False)
            conn.commit()
            # in-memory maxima advance only after the commit lands —
            # on rollback the rows are gone, so their replay must pass
            self._seq_max.update(seq_touched)
        except sqlite3.Error as exc:
            get_error_log().warning("sqlite batch write failed", exc)
            try:
                conn.rollback()
            except sqlite3.Error:
                pass
            return
        finally:
            self._batches += 1
            # the whole batch is resolved — committed, dedup'd, unknown,
            # or (on the rollback path above) permanently lost; none of
            # it will ever be retried, so the watermark may advance
            with self._stats_lock:
                self._settled += len(batch)
        lat = (time.perf_counter() - t0) * 1000.0
        self._commit_lat_ms.append(lat)
        if lat > self._commit_max_ms:
            self._commit_max_ms = lat

    # -- retention (O(new) watermark deletes) ----------------------------
    def _prune_slice(
        self, conn: sqlite3.Connection, commit: bool = True
    ) -> int:
        """Prune a bounded number of due partitions — amortized so no
        commit cycle ever absorbs a full-scan spike.  With
        ``commit=False`` the deletes join the caller's open
        transaction (the batch-write path)."""
        if not self._prune_due:
            return 0
        pruned = 0
        budget = min(self._prune_slice_max, len(self._prune_due))
        for _ in range(budget):
            key = self._prune_due.popleft()
            self._prune_due_set.discard(key)
            pruned += self._prune_partition(conn, key)
        if commit:
            try:
                conn.commit()
            except sqlite3.Error as exc:
                get_error_log().warning("prune commit failed", exc)
        return pruned

    def _prune_all(self, conn: sqlite3.Connection) -> None:
        """Finalize path: trim EVERY partition holding more than
        ``retention`` rows (not just those past the hysteresis slack) so
        the final DB matches the seed windowed prune row-for-row."""
        self._prune_due.clear()
        self._prune_due_set.clear()
        for key, count in list(self._part_counts.items()):
            if count > self._retention_rows:
                self._prune_partition(conn, key)
        try:
            conn.commit()
        except sqlite3.Error as exc:
            get_error_log().warning("final prune commit failed", exc)

    def _prune_partition(
        self, conn: sqlite3.Connection, key: Tuple[str, str, int]
    ) -> int:
        """Delete one partition's overflow via an indexed range delete.

        The watermark — the id of the (retention+1)-th newest row — is
        found by an index-only walk over this partition (O(retention)),
        and the DELETE removes only ids at or below it (O(deleted)).
        The journal row commits atomically with the delete so readers
        observe the trim exactly (reporting/snapshot_store.py).
        """
        table, session_id, rank = key
        t0 = time.perf_counter()
        try:
            row = conn.execute(
                f"SELECT id FROM {table} WHERE session_id=? AND"
                " global_rank=? ORDER BY id DESC LIMIT 1 OFFSET ?",
                (session_id, rank, self._retention_rows),
            ).fetchone()
            if row is None:
                # fewer rows than retention: the count was stale (e.g.
                # seeded upper bound) — clamp it so we don't re-queue
                self._part_counts[key] = self._retention_rows
                return 0
            watermark = int(row[0])
            # fold the doomed id-range into the rollup tiers BEFORE the
            # delete, inside this same transaction: commit lands
            # fold+delete+journal together, rollback restores all-raw —
            # a crash can never leave rows neither raw nor rolled up.
            # A fold failure degrades to plain (history-discarding)
            # retention rather than blocking the prune: partial tier
            # upserts still cover only doomed rows, so deleting keeps
            # the invariant while double-fold on retry would not.
            if self._rollup is not None and table in self._rollup.sources:
                try:
                    self._rollup.fold_doomed(
                        conn, table, session_id, rank, watermark
                    )
                except Exception as exc:
                    get_error_log().warning(
                        f"rollup fold failed for {table}", exc
                    )
            cur = conn.execute(
                f"DELETE FROM {table} WHERE session_id=? AND global_rank=?"
                " AND id <= ?",
                (session_id, rank, watermark),
            )
            deleted = cur.rowcount if cur.rowcount is not None else 0
            conn.execute(
                f"INSERT INTO {WATERMARK_TABLE} (table_name, session_id,"
                " global_rank, watermark_id, deleted_rows, ts)"
                " VALUES (?,?,?,?,?,?)",
                (table, session_id, rank, watermark, deleted, time.time()),
            )
        except sqlite3.Error as exc:
            get_error_log().warning(f"prune failed for {table}", exc)
            return 0
        self._part_counts[key] = self._retention_rows
        self.prunes += 1
        self.rows_pruned += deleted
        lat = (time.perf_counter() - t0) * 1000.0
        self._prune_lat_ms.append(lat)
        if lat > self._prune_max_ms:
            self._prune_max_ms = lat
        self._journal_rows += 1
        if self._journal_rows >= _JOURNAL_MAX_ROWS:
            self._trim_journal(conn)
        return deleted

    def _trim_journal(self, conn: sqlite3.Connection) -> None:
        """Keep the watermark journal bounded.  Store cursors only move
        forward, so deleting old journal rows is invisible to any live
        reader; a reader attaching later never held the trimmed data
        rows in the first place."""
        try:
            row = conn.execute(
                f"SELECT MAX(id) FROM {WATERMARK_TABLE}"
            ).fetchone()
            if row and row[0]:
                conn.execute(
                    f"DELETE FROM {WATERMARK_TABLE} WHERE id <= ?",
                    (int(row[0]) - _JOURNAL_MAX_ROWS // 2,),
                )
            self._journal_rows = 0
        except sqlite3.Error as exc:
            get_error_log().warning("journal trim failed", exc)
