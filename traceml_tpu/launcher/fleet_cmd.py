"""``traceml-tpu fleet-router`` — supervise the fleet-router process
(docs/developer_guide/federation.md).

The router runs as its own child (``python -m traceml_tpu.federation``)
under the same supervision contract as the aggregator: env-serialized
config, a ready file advertising the bound port, a stderr ring for
crash logs, and bounded crash-resume pinned to the original port so
every viewer's reconnect lands — the router is stateless, so a restart
loses nothing but a warm edge cache.
"""

from __future__ import annotations

import sys
import tempfile
import threading
from pathlib import Path
from typing import Dict, Optional

from traceml_tpu.config import flags
from traceml_tpu.launcher.process import (
    SupervisedChild,
    python_argv,
    spawn_supervised,
    terminate,
    wait_for_ready_file,
)

READY_FILE = "fleet_router_ready.json"
DEFAULT_MAX_RESTARTS = 3


def _router_env(
    shards: str,
    host: str,
    port: int,
    cache_ttl: Optional[float],
    probe_s: Optional[float],
    state_dir: Path,
) -> Dict[str, str]:
    env = {
        flags.FLEET_SHARDS.name: shards,
        flags.FLEET_HOST.name: host,
        flags.FLEET_PORT.name: str(port),
        flags.FLEET_STATE_DIR.name: str(state_dir),
    }
    if cache_ttl is not None:
        env[flags.FLEET_CACHE_TTL.name] = str(cache_ttl)
    if probe_s is not None:
        env[flags.FLEET_PROBE_S.name] = str(probe_s)
    return env


def _spawn_router(
    env: Dict[str, str], state_dir: Path
) -> Optional[SupervisedChild]:
    ready_path = state_dir / READY_FILE
    try:
        ready_path.unlink()  # a stale file advertises a dead pid
    except OSError:
        pass
    child = spawn_supervised(
        python_argv("traceml_tpu.federation"),
        label="fleet-router",
        env=env,
    )
    ready = wait_for_ready_file(ready_path, timeout=20.0)
    if ready is None or child.poll() is not None:
        terminate(child.proc, grace_sec=2)
        return None
    return child


def run_fleet_router(
    shards: Optional[str] = None,
    host: Optional[str] = None,
    port: Optional[int] = None,
    cache_ttl: Optional[float] = None,
    probe_s: Optional[float] = None,
    state_dir: Optional[Path] = None,
    max_restarts: Optional[int] = None,
) -> int:
    """Run the supervised router in the foreground until ^C."""
    shards = shards or flags.FLEET_SHARDS.get_str()
    if not shards:
        print(
            "traceml-tpu fleet-router: no shards — pass --shards "
            "host:port,host:port (or a shards.json path), or set "
            f"{flags.FLEET_SHARDS.name}",
            file=sys.stderr,
        )
        return 2
    host = host or flags.FLEET_HOST.get_str() or "127.0.0.1"
    port = flags.FLEET_PORT.get_int(0) if port is None else int(port)
    if max_restarts is None:
        max_restarts = flags.AGG_MAX_RESTARTS.get_int(DEFAULT_MAX_RESTARTS)
    if state_dir is None:
        state_dir = Path(tempfile.mkdtemp(prefix="traceml-fleet-"))
    state_dir = Path(state_dir)
    state_dir.mkdir(parents=True, exist_ok=True)

    env = _router_env(shards, host, port, cache_ttl, probe_s, state_dir)
    child = _spawn_router(env, state_dir)
    if child is None:
        print(
            "traceml-tpu fleet-router: router failed to start "
            f"(see {state_dir})",
            file=sys.stderr,
        )
        return 1
    ready = wait_for_ready_file(state_dir / READY_FILE, timeout=1.0) or {}
    bound_port = int(ready.get("port") or 0)
    print(
        f"[TraceML] fleet router up: http://{host}:{bound_port}/fleet "
        f"(ready file: {state_dir / READY_FILE})"
    )

    stop_evt = threading.Event()
    from traceml_tpu.utils.orphan_watch import arm_parent_death_watch

    arm_parent_death_watch(stop_evt.set)
    restarts = 0
    try:
        while not stop_evt.wait(0.25):
            if child.poll() is None:
                continue
            child.write_crash_log(state_dir)
            if restarts >= max_restarts:
                print(
                    "traceml-tpu fleet-router: router died "
                    f"({child.describe_exit()}) after {restarts} "
                    "restart(s) — giving up",
                    file=sys.stderr,
                )
                return 1
            restarts += 1
            print(
                f"[TraceML] fleet router died ({child.describe_exit()}); "
                f"restart {restarts}/{max_restarts} on port {bound_port}",
                file=sys.stderr,
            )
            # pin the original port: bookmarked pages and dashboards
            # keep their URL across the respawn
            env[flags.FLEET_PORT.name] = str(bound_port)
            child = _spawn_router(env, state_dir)
            if child is None:
                print(
                    "traceml-tpu fleet-router: restart failed",
                    file=sys.stderr,
                )
                return 1
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        if child is not None:
            terminate(child.proc, grace_sec=5)
