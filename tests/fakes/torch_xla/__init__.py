"""Test-only fake of the ``torch_xla`` import surface (VERDICT r2
item 3): just enough shape for traceml_tpu's torch-xla support path —
``patch_mark_step`` + ``XlaMemoryBackend`` — to execute end-to-end in an
image without real torch-xla.  Semantics mimicked:

* ``core.xla_model.mark_step()`` blocks for the simulated lazy-graph
  execution time (env ``FAKE_XLA_MARK_STEP_MS``, default 50) — under
  real torch-xla the pending graph executes AT the barrier, so wall
  time there is device execution + collective wait;
* ``core.xla_model.get_memory_info(dev)`` returns the kb_total/kb_free
  dict shape, with kb_free shrinking per call so usage is visible;
* ``core.xla_model.get_xla_supported_devices()`` → one fake device.
* ``torch_xla.sync()`` — the newer-API alias for the same barrier.

Importable by putting ``tests/fakes`` on PYTHONPATH (the e2e launcher
test does this for its child processes).
"""

from torch_xla.core import xla_model as _xm

__version__ = "0.0-fake"


def sync():
    """Newer torch-xla API name for the step barrier."""
    return _xm.mark_step()
