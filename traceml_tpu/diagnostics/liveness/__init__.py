from traceml_tpu.diagnostics.liveness.api import (
    DOMAIN,
    diagnose_rank_status,
)

__all__ = ["DOMAIN", "diagnose_rank_status"]
