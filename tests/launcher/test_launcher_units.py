import json

from traceml_tpu.launcher.manifest import (
    analyze_script,
    update_run_manifest,
    write_run_manifest,
)
from traceml_tpu.config.yaml_loader import load_yaml_config
from traceml_tpu.launcher.commands import resolve_settings
from traceml_tpu.reporting.compare.command import build_compare_payload


def test_run_manifest_lifecycle(tmp_path):
    write_run_manifest(
        tmp_path, session_id="s", script="t.py", mode="summary", world_size=4
    )
    data = json.loads((tmp_path / "manifest.json").read_text())
    assert data["status"] == "starting"
    assert data["world_size"] == 4
    update_run_manifest(tmp_path, status="running")
    data = json.loads((tmp_path / "manifest.json").read_text())
    assert data["status"] == "running"
    assert data["session_id"] == "s"


def test_code_manifest_jax_hints(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import jax\nimport optax\n"
        "from jax.sharding import Mesh, PartitionSpec\n"
        "import jax.numpy as jnp\n"
        "opt = optax.adamw(1e-3)\n"
        "x = jax.device_put(jnp.ones(3).astype(jnp.bfloat16))\n"
    )
    info = analyze_script(script)
    assert info["framework"] == "jax"
    assert "gspmd" in info["parallelism_hints"]
    assert "adamw" in info["optimizer_hints"]
    assert "bf16" in info["precision_hints"]
    assert "explicit_device_put" in info["input_hints"]


def test_code_manifest_bad_script(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("def broken(:\n")
    info = analyze_script(script)
    assert "error" in info


def test_yaml_loader(tmp_path, monkeypatch):
    (tmp_path / "traceml.yaml").write_text(
        "mode: summary\nsampler_interval_sec: 0.5\ntrace_max_steps: 42\n"
        "unknown_key: zap\ndisk_backup: 'true'\n"
    )
    monkeypatch.chdir(tmp_path)
    cfg = load_yaml_config()
    assert cfg["mode"] == "summary"
    assert cfg["sampler_interval_sec"] == 0.5
    assert cfg["trace_max_steps"] == 42
    assert cfg["disk_backup"] is True
    assert "unknown_key" not in cfg


def test_resolve_settings_precedence(tmp_path, monkeypatch):
    (tmp_path / "traceml.yaml").write_text("mode: summary\nsampler_interval_sec: 0.7\n")
    monkeypatch.chdir(tmp_path)
    # CLI beats yaml
    s = resolve_settings({"mode": "cli", "nprocs": 2, "nnodes": 1,
                          "logs_dir": str(tmp_path)})
    assert s.mode == "cli"
    assert s.sampler_interval_sec == 0.7  # yaml survives for unset CLI
    assert s.expected_world_size == 2
    # multi-node default flips to summary (explicit port required)
    s = resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path),
                          "aggregator_port": 7777})
    assert s.mode == "summary"
    assert s.aggregator.bind_host == "0.0.0.0"


def _summary(step_ms, input_share, peak, kind="COMPUTE_BOUND", session="a"):
    return {
        "meta": {"session_id": session},
        "primary_diagnosis": {
            "kind": kind,
            "severity": "info" if kind in ("COMPUTE_BOUND",
                                           "NO_CLEAR_PERFORMANCE_BOTTLENECK")
            else "critical",
        },
        "sections": {
            "step_time": {
                "global": {
                    "phases": {
                        "step_time": {"median_ms": step_ms},
                        "input": {"median_ms": step_ms * input_share,
                                  "share_of_step": input_share},
                        "compute": {"median_ms": step_ms * (1 - input_share),
                                    "share_of_step": 1 - input_share},
                    }
                }
            },
            "step_memory": {
                "global": {"per_rank": {"0": {"step_peak_bytes": peak}}}
            },
        },
    }


def test_compare_regression_detected():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(115.0, 0.05, 8 << 30, session="b")
    payload = build_compare_payload(base, cand)
    assert payload["verdict"] == "REGRESSION"
    assert any(f["kind"] == "STEP_TIME_REGRESSION" for f in payload["findings"])


def test_compare_improvement_and_equivalent():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(90.0, 0.05, 8 << 30, session="b")
    assert build_compare_payload(base, cand)["verdict"] == "IMPROVEMENT"
    cand2 = _summary(101.0, 0.05, 8 << 30, session="c")  # 1% — noise
    assert build_compare_payload(base, cand2)["verdict"] == "EQUIVALENT"


def test_compare_diagnosis_change_and_memory():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(100.0, 0.40, 10 << 30, kind="INPUT_BOUND", session="b")
    payload = build_compare_payload(base, cand)
    kinds = {f["kind"] for f in payload["findings"]}
    assert "DIAGNOSIS_CHANGED" in kinds
    assert "PHASE_SHIFT" in kinds
    assert "MEMORY_REGRESSION" in kinds
    assert payload["verdict"] == "REGRESSION"


def test_resolve_settings_env_bool_strings(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("TRACEML_CAPTURE_STDERR", "0")
    monkeypatch.setenv("TRACEML_DISK_BACKUP", "false")
    s = resolve_settings({"nprocs": 1, "nnodes": 1, "logs_dir": str(tmp_path)})
    assert s.capture_stderr is False
    assert s.disk_backup is False


def test_resolve_settings_multinode_requires_port(tmp_path, monkeypatch):
    import pytest as _pytest

    monkeypatch.chdir(tmp_path)
    with _pytest.raises(ValueError):
        resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path)})
    s = resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path),
                          "aggregator_port": 9999})
    assert s.aggregator.port == 9999


def test_compare_diagnosis_change_to_healthy_is_not_regression():
    base = _summary(100.0, 0.40, 8 << 30, kind="INPUT_BOUND")
    cand = _summary(90.0, 0.05, 8 << 30, kind="COMPUTE_BOUND", session="b")
    cand["primary_diagnosis"]["severity"] = "info"
    payload = build_compare_payload(base, cand)
    assert payload["verdict"] == "IMPROVEMENT"


def test_code_manifest_deep_extraction(tmp_path):
    script = tmp_path / "deep.py"
    script.write_text(
        "import torch\n"
        "from torch.utils.data import DataLoader\n"
        "from transformers import TrainingArguments\n"
        "import peft\n"
        "loader = DataLoader(ds, batch_size=32, num_workers=0, pin_memory=True)\n"
        "args = TrainingArguments(output_dir='x', bf16=True,\n"
        "                         gradient_accumulation_steps=4,\n"
        "                         per_device_train_batch_size=8)\n"
        "loss.item()\n"
    )
    info = analyze_script(script)
    assert info["dataloader_args"][0]["num_workers"] == 0
    assert info["dataloader_args"][0]["pin_memory"] is True
    assert "single_worker_dataloader" in info["input_hints"]
    assert info["hf_training_args"]["gradient_accumulation_steps"] == 4
    assert "bf16" in info["precision_hints"]
    assert "lora/qlora" in info["uses"]
    assert "item" in info["sync_call_hints"]


def test_code_manifest_jax_donation(tmp_path):
    script = tmp_path / "j.py"
    script.write_text(
        "import jax\n"
        "step = jax.jit(f, donate_argnums=(0,))\n"
        "jax.block_until_ready(x)\n"
    )
    info = analyze_script(script)
    assert "buffer_donation" in info["uses"]
    assert "block_until_ready" in info["sync_call_hints"]


def test_code_manifest_multiple_dataloaders_not_merged(tmp_path):
    script = tmp_path / "two.py"
    script.write_text(
        "import torch\nfrom torch.utils.data import DataLoader\n"
        "train = DataLoader(a, num_workers=8)\n"
        "val = DataLoader(b)\n"  # torch default: 0 workers
    )
    info = analyze_script(script)
    assert len(info["dataloader_args"]) == 2
    # the val loader (default num_workers=0) still flags single-worker
    assert "single_worker_dataloader" in info["input_hints"]


# -- per-site AST classification (VERDICT r3 item 8; reference
#    ast_analysis/visitor.py:498-565) ---------------------------------------

_LOOP_SCRIPT = """
import torch
from torch.utils.data import DataLoader, DistributedSampler

sampler = DistributedSampler(ds)
loader = DataLoader(ds, sampler=sampler)
model.to("cuda", non_blocking=True)
for batch in loader:
    optimizer.zero_grad()
    loss = model(batch.to("cuda"))
    loss.backward()
    optimizer.step()
    print(loss.item())
    if step % 100 == 0:
        torch.save(model.state_dict(), "ckpt.pt")
x = tensor.item()  # outside any loop: must not count as in_loop
"""


def test_sync_sites_classified_per_site_with_loop_context(tmp_path):
    script = tmp_path / "loopy.py"
    script.write_text(_LOOP_SCRIPT)
    info = analyze_script(script)
    sites = info["sync_sites"]
    assert sites["item"]["count"] == 2
    assert sites["item"]["in_loop"] == 1  # the print(loss.item()) one
    assert len(sites["item"]["lines"]) == 2
    assert "host_sync_in_loop" in info["input_hints"]


def test_h2d_idioms_and_loop_flags(tmp_path):
    script = tmp_path / "loopy.py"
    script.write_text(_LOOP_SCRIPT)
    info = analyze_script(script)
    h2d = info["h2d"]
    assert h2d["to_device"] and h2d["non_blocking"]
    assert h2d["h2d_in_loop"] == 1  # batch.to inside the loop
    assert "blocking_h2d" not in info["input_hints"]
    flags = info["loop_flags"]
    assert flags["checkpoint_in_loop"] and flags["print_in_loop"]
    # bare print() is NOT logger traffic (advisor r4)
    assert "logging_in_loop" not in flags


def test_scheduler_step_loop_is_not_training(tmp_path):
    """A loop whose only marker is .step() (scheduler/env/tqdm) must not
    be classified as a training loop (advisor r4: false in-loop sync
    hints feed the INPUT_BOUND guidance surface)."""
    script = tmp_path / "sched.py"
    script.write_text(
        "for epoch in range(10):\n"
        "    scheduler.step()\n"
        "    metrics.append(loss.item())\n"
        "    print(epoch)\n"
    )
    info = analyze_script(script)
    assert info["sync_sites"]["item"]["in_loop"] == 0
    assert "host_sync_in_loop" not in info.get("input_hints", [])


def test_distributed_sampler_without_set_epoch_flagged(tmp_path):
    script = tmp_path / "loopy.py"
    script.write_text(_LOOP_SCRIPT)
    info = analyze_script(script)
    assert "distributed_sampler" in info["input_hints"]
    assert "distributed_sampler_no_set_epoch" in info["input_hints"]
    fixed = tmp_path / "fixed.py"
    fixed.write_text(_LOOP_SCRIPT + "\nsampler.set_epoch(0)\n")
    info2 = analyze_script(fixed)
    assert "distributed_sampler_no_set_epoch" not in info2["input_hints"]


def test_jax_sync_and_device_put_sites(tmp_path):
    script = tmp_path / "jaxy.py"
    script.write_text(
        "import jax\n"
        "import traceml_tpu\n"
        "for x in loader:\n"
        "    with traceml_tpu.trace_step():\n"
        "        x = jax.device_put(x)\n"
        "        loss = step(x)\n"
        "        jax.block_until_ready(loss)\n"
    )
    info = analyze_script(script)
    assert info["sync_sites"]["block_until_ready"]["in_loop"] == 1
    assert info["h2d"]["device_put_count"] == 1
    assert info["h2d"]["h2d_in_loop"] == 1


def test_non_training_loop_not_counted(tmp_path):
    script = tmp_path / "plain.py"
    script.write_text(
        "for f in files:\n"
        "    data.append(f.item())\n"  # a loop, but not a TRAINING loop
    )
    info = analyze_script(script)
    assert info["sync_sites"]["item"]["in_loop"] == 0
    assert "host_sync_in_loop" not in info.get("input_hints", [])


def test_bare_step_call_still_marks_training_loop(tmp_path):
    """`step = jax.jit(make_train_step(...))` then `step(state, batch)`
    is the canonical jax idiom — the BARE NAME form must still mark the
    loop as training even though attribute `.step()` no longer does
    (review r5)."""
    script = tmp_path / "jax_step.py"
    script.write_text(
        "import jax\n"
        "step = jax.jit(train_step)\n"
        "for batch in ds:\n"
        "    state, m = step(state, batch)\n"
        "    losses.append(m['loss'].item())\n"
    )
    info = analyze_script(script)
    assert info["sync_sites"]["item"]["in_loop"] == 1
    assert "host_sync_in_loop" in info["input_hints"]
    # chained receiver (`m['loss'].item()`) must surface in BOTH
    # sync_sites and sync_call_hints — internally consistent manifest
    assert "item" in info["sync_call_hints"]


def test_optimizer_step_closure_marks_training_loop(tmp_path):
    """`optimizer.step(closure)` (LBFGS: backward lives inside the
    closure, defined outside the loop) must still mark the loop as
    training via the optimizer-named receiver (review r5)."""
    script = tmp_path / "lbfgs.py"
    script.write_text(
        "def closure():\n"
        "    loss = model(x)\n"
        "    loss.backward()\n"
        "    return loss\n"
        "for epoch in range(10):\n"
        "    optimizer.step(closure)\n"
        "    losses.append(loss.item())\n"
    )
    info = analyze_script(script)
    assert info["sync_sites"]["item"]["in_loop"] == 1
    assert "host_sync_in_loop" in info["input_hints"]


def test_subscripted_optimizer_and_chained_cpu_sync(tmp_path):
    """`optimizers[0].step()` (GAN/Lightning multi-optimizer) still
    marks the loop as training, and a chained `.cpu()` sync surfaces in
    BOTH sync_sites and sync_call_hints (review r5)."""
    script = tmp_path / "gan.py"
    script.write_text(
        "for batch in ds:\n"
        "    optimizers[0].step(closure)\n"
        "    stats.append(model(batch).cpu())\n"
    )
    info = analyze_script(script)
    assert info["sync_sites"]["cpu"]["in_loop"] == 1
    assert "cpu" in info["sync_call_hints"]
    assert "host_sync_in_loop" in info["input_hints"]


def test_maybe_pin_cpu_gating(monkeypatch):
    """Pinning activates only when opted in AND cores >= local world."""
    from traceml_tpu.runtime.executor import _maybe_pin_cpu

    monkeypatch.delenv("TRACEML_PIN_RANK_CPUS", raising=False)
    assert _maybe_pin_cpu() is False  # not opted in

    import os

    before = os.sched_getaffinity(0)
    try:
        monkeypatch.setenv("TRACEML_PIN_RANK_CPUS", "1")
        monkeypatch.setenv("LOCAL_RANK", "0")
        # more ranks than any host has cores → must refuse to pin
        monkeypatch.setenv("LOCAL_WORLD_SIZE", str(len(before) + 1))
        assert _maybe_pin_cpu() is False
        assert os.sched_getaffinity(0) == before

        monkeypatch.setenv("LOCAL_WORLD_SIZE", "1")
        assert _maybe_pin_cpu() is True  # 1 rank always fits
        assert os.sched_getaffinity(0) == before  # all cores → unchanged
    finally:
        os.sched_setaffinity(0, before)


def test_set_epoch_in_other_module_not_flagged(tmp_path):
    """DistributedSampler in data.py + set_epoch in train.py (the entry,
    scanned first) must NOT fabricate the missing-set_epoch hint —
    extraction is per-file over a BFS, so the fold is unconditional."""
    from traceml_tpu.launcher.ast_scan import analyze_project

    (tmp_path / "data.py").write_text(
        "from torch.utils.data import DistributedSampler\n"
        "def make(ds):\n"
        "    return DistributedSampler(ds)\n"
    )
    (tmp_path / "train.py").write_text(
        "import data\n"
        "sampler = data.make(ds)\n"
        "for epoch in range(3):\n"
        "    sampler.set_epoch(epoch)\n"
    )
    info = analyze_project(tmp_path / "train.py")
    assert "distributed_sampler" in info["input_hints"]
    assert "distributed_sampler_no_set_epoch" not in info["input_hints"]
    assert not any(k.startswith("_") for k in info)  # no state leak


def test_blocking_h2d_hint_retracted_by_later_file(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_project

    (tmp_path / "train.py").write_text(
        "import data\nmodel.to('cuda')\n"
    )
    (tmp_path / "data.py").write_text(
        "batch.to('cuda', non_blocking=True)\n"
    )
    info = analyze_project(tmp_path / "train.py")
    assert info["h2d"]["non_blocking"] is True
    assert "blocking_h2d" not in info["input_hints"]
