"""Manual-mode wrappers (reference coverage model:
tests/sdk/test_init_and_wrappers.py — duplicate guards, TLS gating)."""

import pytest

from traceml_tpu.sdk import state as state_mod
from traceml_tpu.sdk.instrumentation import trace_step
from traceml_tpu.sdk.wrappers import (
    wrap_backward,
    wrap_forward,
    wrap_h2d,
    wrap_optimizer,
)
from traceml_tpu.utils.step_memory import FakeMemoryBackend, StepMemoryTracker
from traceml_tpu.utils.timing import (
    BACKWARD_TIME,
    FORWARD_TIME,
    GLOBAL_STEP_QUEUE,
    H2D_TIME,
    OPTIMIZER_STEP,
    drain_step_memory_rows,
)


@pytest.fixture(autouse=True)
def fresh_state():
    st = state_mod.reset_state_for_tests()
    st.mem_tracker = StepMemoryTracker(FakeMemoryBackend([[]]))
    GLOBAL_STEP_QUEUE.drain()
    drain_step_memory_rows()
    yield st
    GLOBAL_STEP_QUEUE.drain()


def _names():
    return [e.name for b in GLOBAL_STEP_QUEUE.drain() for e in b.events]


def test_wrap_forward_and_backward_time_phases(fresh_state):
    fwd = wrap_forward(lambda x: x * 2)
    bwd = wrap_backward(lambda g: g + 1)
    with trace_step():
        assert fwd(3) == 6
        assert bwd(1) == 2
    names = _names()
    assert FORWARD_TIME in names
    assert BACKWARD_TIME in names


def test_nested_wrapped_forward_times_once(fresh_state):
    inner = wrap_forward(lambda x: x + 1)
    outer = wrap_forward(lambda x: inner(x) * 2)
    with trace_step():
        assert outer(1) == 4
    names = _names()
    assert names.count(FORWARD_TIME) == 1  # depth guard


def test_wrap_optimizer_inplace_and_guarded(fresh_state):
    class Opt:
        def __init__(self):
            self.calls = 0

        def step(self):
            self.calls += 1

    opt = Opt()
    out = wrap_optimizer(opt)
    assert out is opt
    wrap_optimizer(opt)  # duplicate guard: no double wrap
    with trace_step():
        opt.step()
    opt.step()  # outside a step: passes through untimed
    assert opt.calls == 2
    names = _names()
    assert names.count(OPTIMIZER_STEP) == 1


def test_wrap_h2d_moves_and_times(fresh_state):
    import numpy as np

    with trace_step():
        arr = wrap_h2d(np.ones((8, 8), np.float32))
    assert float(arr.sum()) == 64.0
    names = _names()
    assert H2D_TIME in names


def test_wrappers_propagate_user_errors(fresh_state):
    f = wrap_forward(lambda x: 1 / 0)
    with trace_step():
        with pytest.raises(ZeroDivisionError):
            f(1)
    assert not fresh_state.tls.in_step is None  # gates released
    assert fresh_state.tls.forward_depth == 0
