from traceml_tpu.telemetry import (
    SCHEMA_V2,
    SenderIdentity,
    build_columnar_envelope,
    build_rank_finished,
    build_telemetry_envelope,
    columns_to_rows,
    control_kind,
    is_control_message,
    normalize_telemetry_envelope,
    rows_to_columns,
)
from traceml_tpu.utils import msgpack_codec


def _identity(rank=3):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank % 4,
        world_size=8,
        local_world_size=4,
        node_rank=rank // 4,
        hostname="host-a",
        pid=1234,
        platform="tpu",
        device_kind="TPU v5p",
    )


def test_build_and_normalize_canonical():
    env = build_telemetry_envelope(
        "step_time", {"steps": [{"step": 1}]}, identity=_identity()
    )
    wire = env.to_wire()
    norm = normalize_telemetry_envelope(wire)
    assert norm is not None
    assert norm.sampler == "step_time"
    assert norm.global_rank == 3
    assert norm.meta["node_rank"] == 0
    assert norm.meta["world_size"] == 8
    assert norm.tables == {"steps": [{"step": 1}]}
    assert norm.meta["rank"] == norm.meta["global_rank"]


def test_normalize_legacy_flat_shape():
    legacy = {"sampler": "system", "rank": 2, "tables": {"t": [{"a": 1}]}}
    norm = normalize_telemetry_envelope(legacy)
    assert norm is not None
    assert norm.sampler == "system"
    assert norm.global_rank == 2
    assert norm.tables == {"t": [{"a": 1}]}


def test_normalize_rejects_garbage():
    assert normalize_telemetry_envelope(None) is None
    assert normalize_telemetry_envelope([1, 2]) is None
    assert normalize_telemetry_envelope({"meta": {}, "body": {}}) is None
    assert normalize_telemetry_envelope({"nope": 1}) is None


def test_v1_wire_roundtrip_bit_identical():
    rows = [{"step": s, "timestamp": float(s), "clock": "device"} for s in range(8)]
    env = build_telemetry_envelope("step_time", {"step_time": rows}, _identity())
    wire = msgpack_codec.decode(msgpack_codec.encode(env.to_wire()))
    norm = normalize_telemetry_envelope(wire)
    assert norm.tables["step_time"] == rows
    assert norm.schema == 1


def test_columnar_envelope_wire_shape():
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    env = build_columnar_envelope("system", {"t": rows}, _identity())
    wire = env.to_wire()
    assert wire["meta"]["schema"] == SCHEMA_V2
    table = wire["body"]["tables"]["t"]
    assert table["cols"] == ["a", "b"]
    assert table["vals"] == [[1, 2], ["x", "y"]]
    assert table["n"] == 2


def test_columnar_roundtrip_and_lazy_materialization():
    rows = [
        {"step": s, "timestamp": float(s),
         "events": {"phase_a": {"cpu_ms": 1.0 * s, "count": 1},
                    "phase_b": {"cpu_ms": 2.0 * s, "count": 1}}}
        for s in range(16)
    ]
    env = build_columnar_envelope("step_time", {"step_time": rows}, _identity())
    wire = msgpack_codec.decode(msgpack_codec.encode(env.to_wire()))
    norm = normalize_telemetry_envelope(wire)
    assert norm is not None
    assert norm.schema == SCHEMA_V2
    # columnar access without materializing rows
    view = norm.column_view("step_time")
    assert len(view) == 16
    assert view.ints("step") == list(range(16))
    assert view.col("events")[3] == rows[3]["events"]
    assert view.col("missing") == [None] * 16
    # lazy row materialization matches the original batch exactly
    assert norm.tables["step_time"] == rows


def test_columnar_missing_keys_none_filled():
    rows = [{"a": 1}, {"a": 2, "b": 9}]
    ct = rows_to_columns(rows)
    assert ct["cols"] == ["a", "b"]
    assert ct["vals"] == [[1, 2], [None, 9]]
    assert columns_to_rows(ct) == [{"a": 1, "b": None}, {"a": 2, "b": 9}]


def test_nested_dict_columns_transposed_only_when_uniform():
    uniform = [{"m": {"x": i, "y": i}} for i in range(3)]
    ragged = [{"m": {"x": 1}}, {"m": {"z": 2}}]
    ct_u = rows_to_columns(uniform)
    ct_r = rows_to_columns(ragged)
    assert isinstance(ct_u["vals"][0], dict)  # nested SoA marker
    assert isinstance(ct_r["vals"][0], list)  # ragged keys stay row-form
    assert columns_to_rows(ct_u) == uniform
    assert columns_to_rows(ct_r) == ragged


def test_mixed_table_encodings_in_one_envelope():
    wire = {
        "meta": {"schema": 2, "sampler": "s", "rank": 1},
        "body": {"tables": {
            "rowy": [{"i": 1}],
            "colly": {"cols": ["i"], "vals": [[2, 3]], "n": 2},
        }},
    }
    norm = normalize_telemetry_envelope(wire)
    assert norm.tables["rowy"] == [{"i": 1}]
    assert norm.tables["colly"] == [{"i": 2}, {"i": 3}]
    assert sorted(norm.table_names()) == ["colly", "rowy"]


def test_malformed_columnar_table_dropped():
    wire = {
        "meta": {"sampler": "s", "rank": 0},
        "body": {"tables": {
            "bad_len": {"cols": ["a", "b"], "vals": [[1]]},          # cols≠vals
            "bad_col": {"cols": ["a"], "vals": [[1], [2]]},          # cols≠vals
            "ragged": {"cols": ["a", "b"], "vals": [[1], [2, 3]]},   # lengths differ
            "good": {"cols": ["a"], "vals": [[7]], "n": 1},
        }},
    }
    norm = normalize_telemetry_envelope(wire)
    assert norm.tables == {"good": [{"a": 7}]}


def test_legacy_flat_shape_with_columnar_table():
    legacy = {
        "sampler": "system",
        "rank": 4,
        "tables": {"t": {"cols": ["a"], "vals": [[1, 2]], "n": 2}},
    }
    norm = normalize_telemetry_envelope(legacy)
    assert norm.global_rank == 4
    assert norm.tables["t"] == [{"a": 1}, {"a": 2}]


def test_build_envelope_copy_false_shares_lists():
    rows = [{"i": 0}]
    tables = {"t": rows}
    env_copy = build_telemetry_envelope("s", tables, _identity())
    env_share = build_telemetry_envelope("s", tables, _identity(), copy=False)
    rows.append({"i": 1})
    assert env_copy.tables["t"] == [{"i": 0}]       # defensive copy
    assert env_share.tables["t"] is rows            # trusted internal path


def test_control_messages():
    msg = build_rank_finished(_identity().to_meta())
    assert is_control_message(msg)
    assert control_kind(msg) == "rank_finished"
    assert not is_control_message({"meta": {}})
    assert control_kind({}) is None
    # control messages are not telemetry
    assert normalize_telemetry_envelope(msg) is None
