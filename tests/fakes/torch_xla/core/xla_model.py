"""Fake ``torch_xla.core.xla_model`` — see package docstring."""

import os
import time

_KB_TOTAL = 8 * 1024 * 1024  # 8 GiB "HBM"
_used_kb = 256 * 1024  # grows per get_memory_info call
_mark_steps = 0


def mark_step(wait: bool = False):
    """The lazy-execution barrier: the pending graph 'executes' here."""
    global _mark_steps
    _mark_steps += 1
    time.sleep(float(os.environ.get("FAKE_XLA_MARK_STEP_MS", "50")) / 1000.0)


def get_xla_supported_devices(devkind=None, max_devices=None):
    return ["xla:0"]


def xla_device(n=None, devkind=None):
    return "xla:0"


def get_memory_info(dev):
    """Two real return shapes, selected by FAKE_XLA_MEMORY_SHAPE:

    * ``kb`` (default) — the XRT-era/documented shape:
      ``{"kb_total", "kb_free"}`` (torch_xla API docs,
      xla_model.get_memory_info);
    * ``bytes`` — the PJRT-era shape observed from torch_xla 2.x:
      ``{"bytes_used", "bytes_limit", "peak_bytes"}``.

    traceml's XlaMemoryBackend must read BOTH (FAKES.md rows M1-M2).
    """
    global _used_kb
    _used_kb += 1024  # +1 MiB per sample: growth is observable
    if os.environ.get("FAKE_XLA_MEMORY_SHAPE", "kb") == "bytes":
        return {
            "bytes_used": _used_kb * 1024,
            "bytes_limit": _KB_TOTAL * 1024,
            "peak_bytes": _used_kb * 1024,
        }
    return {"kb_total": _KB_TOTAL, "kb_free": _KB_TOTAL - _used_kb}


def get_ordinal():
    return int(os.environ.get("RANK", 0))


def xrt_world_size():
    return int(os.environ.get("WORLD_SIZE", 1))
