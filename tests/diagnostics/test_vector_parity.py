"""Scalar-vs-vectorized diagnosis parity: bit-identical, always.

Contract (docs/developer_guide/diagnosis-engine.md): the vectorized
gate layer (``diagnostics/<pack>/vector.py``) computes every rule input
as a numpy reduction over the window's cubes / rank-slot arrays, and the
emitted issue lists must be **byte-identical** to the scalar reference
arm — the same equivalence the columnar window engine pins via
``ColumnarFallback``.  Every fixture below is swept through BOTH arms of
``TRACEML_VECTOR_DIAGNOSIS`` and compared as
``json.dumps(result.to_dict(), sort_keys=True)`` bytes.

The sweep covers the four window packs (step_time, step_memory,
collectives, serving), deterministic rule-firing fixtures AND seeded
randomized windows, with and without a mesh topology (attribution +
the grouping memo ride the same kill switch).
"""

import json
import random

from traceml_tpu.diagnostics.collectives.api import diagnose_collectives_window
from traceml_tpu.diagnostics.serving.api import diagnose_serving_window
from traceml_tpu.diagnostics.step_memory.api import (
    diagnose_rank_rows as diagnose_memory,
)
from traceml_tpu.diagnostics.step_time.api import diagnose_window
from traceml_tpu.diagnostics.step_time import vector as st_vector
from traceml_tpu.samplers.serving_sampler import pack_floats
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.columnar import (
    StepTimeColumns,
    build_collectives_window_rows,
    build_columnar_step_time_window,
    build_serving_window_rows,
    note_vector_fallback,
    vector_diagnosis_enabled,
    vector_fallback_counts,
)
from traceml_tpu.utils.topology import (
    MeshTopology,
    _coords_for_rank,
    parse_mesh_spec,
)


# -- arm sweep helper ----------------------------------------------------


def _dump(result) -> bytes:
    return json.dumps(result.to_dict(), sort_keys=True).encode()


def _assert_arms_identical(monkeypatch, fn):
    """Run ``fn`` under the vectorized and scalar arms; the serialized
    issue lists must be byte-identical."""
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
    assert vector_diagnosis_enabled()
    on = _dump(fn())
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
    assert not vector_diagnosis_enabled()
    off = _dump(fn())
    assert on == off
    return on


def _mesh(spec, world):
    axes = parse_mesh_spec(spec)
    assert axes, spec
    sizes = [a.size for a in axes]
    return MeshTopology(
        axes=axes,
        rank_coords={
            r: tuple(_coords_for_rank(r, sizes)) for r in range(world)
        },
        rank_hosts={r: r // 4 for r in range(world)},
        rank_hostnames={},
        source="env",
    )


# -- step_time -----------------------------------------------------------


def _st_row(step, step_ms, input_ms=0.0, h2d_ms=0.0, compute_ms=0.0,
            backward_ms=0.0, compile_ms=0.0):
    events = {
        T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
    }
    if input_ms:
        events[T.DATALOADER_NEXT] = {
            "cpu_ms": input_ms, "device_ms": None, "count": 1,
        }
    if h2d_ms:
        events[T.H2D_TIME] = {
            "cpu_ms": 0.2, "device_ms": h2d_ms, "count": 1,
        }
    if compute_ms:
        events[T.COMPUTE_TIME] = {
            "cpu_ms": 0.5, "device_ms": compute_ms, "count": 1,
        }
    if backward_ms:
        events[T.BACKWARD_TIME] = {
            "cpu_ms": backward_ms, "device_ms": backward_ms, "count": 1,
        }
    if compile_ms:
        events[T.COMPILE_TIME] = {
            "cpu_ms": compile_ms, "device_ms": None, "count": 1,
        }
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "clock": "device",
        "late_markers": 0,
        "events": events,
    }


def _st_window(rank_rows, max_steps=200):
    cols = {}
    for rank, rows in rank_rows.items():
        c = StepTimeColumns(512)
        for row in rows:
            c.append(row)
        cols[rank] = c
    w = build_columnar_step_time_window(cols, max_steps)
    assert w is not None and getattr(w, "col", None) is not None
    return w


def _st_fixtures():
    def steady(n, step_ms, **kw):
        return [_st_row(s, step_ms, **kw) for s in range(1, n + 1)]

    # healthy / compute bound
    yield {r: steady(60, 100.0, input_ms=3.0, compute_ms=92.0)
           for r in range(4)}
    # input bound, critical
    yield {r: steady(60, 100.0, input_ms=45.0, compute_ms=50.0)
           for r in range(4)}
    # input straggler on one rank
    rows = {r: steady(60, 100.0, input_ms=4.0, compute_ms=90.0)
            for r in range(7)}
    rows[7] = steady(60, 280.0, input_ms=184.0, compute_ms=90.0)
    yield rows
    # clean straggler: rank 0 slow in residual, others inflated by sync
    rows = {}
    for r in range(8):
        if r == 0:
            rows[r] = steady(60, 200.0, input_ms=4.0, backward_ms=60.0)
        else:
            rows[r] = steady(60, 200.0, input_ms=4.0, backward_ms=156.0)
    yield rows
    # compile storm + residual heavy
    rows = {0: [], 1: []}
    for s in range(1, 61):
        compile_ms = 400.0 if s % 3 == 0 else 0.0
        for r in (0, 1):
            rows[r].append(_st_row(
                s, 100.0 + compile_ms, compute_ms=55.0,
                compile_ms=compile_ms,
            ))
    yield rows
    # randomized ragged multi-rank windows
    for seed in range(6):
        rng = random.Random(seed)
        yield {
            r: [
                _st_row(
                    s,
                    rng.uniform(80.0, 160.0),
                    input_ms=rng.uniform(0.0, 30.0),
                    h2d_ms=rng.uniform(0.0, 8.0),
                    compute_ms=rng.uniform(20.0, 90.0),
                    backward_ms=rng.uniform(0.0, 40.0),
                )
                for s in range(rng.randint(1, 5), 64)
            ]
            for r in range(rng.randint(2, 8))
        }


def test_step_time_parity_all_fixtures(monkeypatch):
    for i, rank_rows in enumerate(_st_fixtures()):
        w = _st_window(rank_rows)
        topo = _mesh("dp:8", max(8, len(rank_rows)))
        _assert_arms_identical(
            monkeypatch, lambda: diagnose_window(w, mode="live")
        )
        _assert_arms_identical(
            monkeypatch,
            lambda: diagnose_window(w, mode="live", topology=topo),
        ), i


def test_step_time_vector_gate_respects_kill_switch(monkeypatch):
    w = _st_window(
        {r: [_st_row(s, 100.0, compute_ms=90.0) for s in range(1, 40)]
         for r in range(2)}
    )
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
    assert st_vector.gate(w) is None
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
    assert st_vector.gate(w) is w.col
    # scalar windows have no cube — the gate stays closed either way
    assert st_vector.gate(object()) is None


# -- step_memory ---------------------------------------------------------

GiB = 1024 ** 3


def _mem_row(step, cur, limit=16 * GiB, dev=0):
    return {
        "step": step,
        "device_id": dev,
        "current_bytes": cur,
        "step_peak_bytes": cur,
        "limit_bytes": limit,
    }


def _mem_fixtures():
    yield {0: [_mem_row(s, 4 * GiB) for s in range(100)]}
    yield {0: [_mem_row(s, int(15.8 * GiB)) for s in range(100)]}
    # imbalance with pressure (fires; worst rank + skew via argmax)
    yield {
        0: [_mem_row(s, 9 * GiB) for s in range(50)],
        1: [_mem_row(s, 14 * GiB) for s in range(50)],
        2: [_mem_row(s, 9 * GiB) for s in range(50)],
    }
    for seed in range(4):
        rng = random.Random(100 + seed)
        yield {
            r: [
                _mem_row(s, rng.randint(1 * GiB, 15 * GiB))
                for s in range(60)
            ]
            for r in range(rng.randint(2, 6))
        }


def test_memory_parity_all_fixtures(monkeypatch):
    for rank_rows in _mem_fixtures():
        topo = _mesh("dp:8", 8)
        _assert_arms_identical(
            monkeypatch, lambda: diagnose_memory(rank_rows)
        )
        _assert_arms_identical(
            monkeypatch, lambda: diagnose_memory(rank_rows, topology=topo)
        )


# -- collectives ---------------------------------------------------------


def _coll_row(step, op="all_reduce", dtype="float32", nbytes=1 << 20,
              dur=4.0, exposed=None, group=8):
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "op": op,
        "dtype": dtype,
        "count": 1,
        "bytes": nbytes,
        "group_size": group,
        "duration_ms": dur,
        "exposed_ms": dur if exposed is None else exposed,
    }


def _coll_fixtures():
    # poor overlap: everything exposed
    yield {r: [_coll_row(s, dur=8.0) for s in range(1, 61)]
           for r in range(4)}
    # good overlap on most ranks, one laggard
    rows = {r: [_coll_row(s, dur=8.0, exposed=0.5) for s in range(1, 61)]
            for r in range(4)}
    rows[4] = [_coll_row(s, dur=8.0, exposed=7.5) for s in range(1, 61)]
    yield rows
    # fp32 allreduce heavy (quantizable)
    yield {
        0: [_coll_row(s, nbytes=1 << 24, dur=6.0, exposed=1.0)
            for s in range(1, 61)]
    }
    # randomized ragged participation
    for seed in range(5):
        rng = random.Random(200 + seed)
        out = {}
        for r in range(rng.randint(1, 6)):
            rows = []
            for s in range(1, 50):
                for op in ("all_reduce", "all_gather", "reduce_scatter"):
                    if rng.random() < 0.3:
                        continue
                    dur = rng.uniform(0.0, 8.0)
                    rows.append(_coll_row(
                        s, op=op,
                        dtype=rng.choice(("float32", "bfloat16")),
                        nbytes=rng.randint(0, 1 << 22),
                        dur=dur, exposed=dur * rng.random(),
                    ))
            out[r] = rows
        yield out


def test_collectives_parity_all_fixtures(monkeypatch):
    for rank_rows in _coll_fixtures():
        w = build_collectives_window_rows(rank_rows, max_steps=60)
        topo = _mesh("dp:8", 8)
        for st_ms in (None, 100.0):
            _assert_arms_identical(
                monkeypatch,
                lambda: diagnose_collectives_window(
                    w, mode="live", step_time_ms=st_ms, topology=topo,
                ),
            )


# -- serving -------------------------------------------------------------


def _srv_row(step, done=2, qd=0, dtok=32, tps=100.0, kvh=None):
    ttft = [30.0] * done
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "requests_enqueued": done,
        "requests_completed": done,
        "requests_active": 1,
        "queue_depth": qd,
        "decode_tokens": dtok,
        "prefill_ms": 20.0,
        "decode_ms": 40.0,
        "tokens_per_s": tps,
        "batch_occupancy": 0.4,
        "kv_bytes": -1,
        "kv_limit_bytes": -1,
        "kv_headroom": -1.0 if kvh is None else kvh,
        "ttft_ms_list": pack_floats(ttft),
        "e2e_ms_list": pack_floats([60.0] * done),
        "tokens_list": ",".join("16" for _ in range(done)),
    }


def _srv_fixtures():
    # queue saturated: backlog across every slot
    yield {0: [_srv_row(s, qd=6) for s in range(1, 41)]}
    # replica skew: one slow replica among four
    rows = {r: [_srv_row(s, tps=400.0) for s in range(1, 41)]
            for r in range(3)}
    rows[3] = [_srv_row(s, tps=120.0) for s in range(1, 41)]
    yield rows
    # kv pressure
    yield {0: [_srv_row(s, kvh=0.04) for s in range(1, 41)]}
    # randomized
    for seed in range(4):
        rng = random.Random(300 + seed)
        yield {
            r: [
                _srv_row(
                    s,
                    done=rng.randint(0, 5),
                    qd=rng.randint(0, 6),
                    dtok=rng.randint(0, 200),
                    tps=rng.uniform(0.0, 500.0),
                    kvh=rng.uniform(0.0, 0.9)
                    if rng.random() < 0.5 else None,
                )
                for s in range(1, 40)
            ]
            for r in range(rng.randint(1, 5))
        }


def test_serving_parity_all_fixtures(monkeypatch):
    for rank_rows in _srv_fixtures():
        w = build_serving_window_rows(rank_rows, max_steps=40)
        topo = _mesh("dp:8", 8)
        _assert_arms_identical(
            monkeypatch, lambda: diagnose_serving_window(w, mode="live")
        )
        _assert_arms_identical(
            monkeypatch,
            lambda: diagnose_serving_window(w, mode="live", topology=topo),
        )


# -- view-layer parity ---------------------------------------------------


def test_view_tables_parity(monkeypatch):
    """The vectorized per-rank view tables (collectives efficiency map,
    serving replica list) must serialize identically to their scalar
    twins — same as_dict(), both arms."""
    from traceml_tpu.renderers import views as V

    for rank_rows in _coll_fixtures():
        w = build_collectives_window_rows(rank_rows, max_steps=60)
        monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
        on = json.dumps(
            V.build_collectives_view(w, step_time_ms=100.0).as_dict(),
            sort_keys=True,
        )
        monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
        off = json.dumps(
            V.build_collectives_view(w, step_time_ms=100.0).as_dict(),
            sort_keys=True,
        )
        assert on == off
    for rank_rows in _srv_fixtures():
        w = build_serving_window_rows(rank_rows, max_steps=40)
        if w is None:
            continue
        monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
        on = json.dumps(
            V.build_serving_view(w).as_dict(), sort_keys=True
        )
        monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
        off = json.dumps(
            V.build_serving_view(w).as_dict(), sort_keys=True
        )
        assert on == off


# -- fallback accounting -------------------------------------------------


def test_vector_fallback_warns_once_then_counts(caplog):
    import logging

    domain = "parity_test_domain"
    assert domain not in vector_fallback_counts()
    with caplog.at_level(logging.WARNING, logger="traceml_tpu.utils.columnar"):
        note_vector_fallback(domain)
        note_vector_fallback(domain)
        note_vector_fallback(domain)
    warnings = [r for r in caplog.records if domain in r.getMessage()]
    assert len(warnings) == 1  # first fallback logs, the rest count
    assert vector_fallback_counts()[domain] == 3
