"""ICI-path stat aggregation — the on-device collective backend
(SURVEY.md §2.5: "per-chip stat vectors all-gathered with
jax.lax.all_gather over ICI so rank-skew diagnostics can be computed
on-device without a TCP round trip").

Each participant contributes one fixed-layout ``StatVector`` (step
duration, phase sums, memory) per aggregation; a single jitted
``shard_map`` all-gather moves every chip's vector over ICI and hands
rank 0's host the full ``(n_devices, n_fields)`` matrix in one transfer.
This is the latency-critical path for live cross-rank skew diagnosis on
a pod: one small collective instead of world_size TCP messages over DCN.

Works identically on the CI mesh (8 virtual CPU devices) and a real
slice; multi-host, every process sees the global result (all_gather is
global over the mesh axis).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

# fixed field layout (order matters — it IS the wire format on ICI)
STAT_FIELDS = (
    "step",
    "step_ms",
    "input_ms",
    "h2d_ms",
    "compute_ms",
    "optimizer_ms",
    "compile_ms",
    "collective_ms",
    "checkpoint_ms",
    "residual_ms",
    "memory_current_bytes",
    "memory_peak_bytes",
)
N_FIELDS = len(STAT_FIELDS)


@dataclasses.dataclass
class StatVector:
    values: Dict[str, float]

    def to_array(self) -> np.ndarray:
        return np.asarray(
            [float(self.values.get(f, 0.0)) for f in STAT_FIELDS],
            dtype=np.float32,
        )

    @classmethod
    def from_array(cls, arr: Sequence[float]) -> "StatVector":
        return cls({f: float(v) for f, v in zip(STAT_FIELDS, arr)})


class IciStatAggregator:
    """All-gather per-device stat vectors over a mesh axis."""

    def __init__(self, mesh=None, axis: Optional[str] = None) -> None:
        import jax

        if mesh is None:
            from traceml_tpu.parallel.mesh import make_mesh

            mesh = make_mesh({"fsdp": len(jax.devices())})
        self.mesh = mesh
        # default: gather over ALL mesh axes (every chip contributes)
        self.axes = (axis,) if axis else tuple(mesh.axis_names)
        self._gather = self._build()

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from traceml_tpu.utils.jax_compat import shard_map

        axes = self.axes

        def gather(local: jnp.ndarray) -> jnp.ndarray:
            # local: (1, N_FIELDS) shard per device → (n_devices, N_FIELDS).
            # Gather over the LAST axis first: each all_gather makes the
            # gathered axis major in dim 0, so reversing the chain leaves
            # the result in mesh-linear (first-axis-major) order — row i
            # IS participant i of the P(axes) input placement.  Rank
            # attribution downstream depends on this.
            out = local
            for ax in reversed(axes):
                out = jax.lax.all_gather(out, ax, axis=0, tiled=True)
            return out

        # check_vma off: the output IS replicated over the gathered axes
        # (all_gather makes it so), but static replication inference
        # can't always prove it across multiple chained axes.
        return jax.jit(
            shard_map(
                gather,
                mesh=self.mesh,
                in_specs=P(axes),
                out_specs=P(),
                check_vma=False,
            )
        )

    @property
    def n_participants(self) -> int:
        n = 1
        for ax in self.axes:
            n *= self.mesh.shape[ax]
        return n

    def aggregate(self, stats: StatVector) -> np.ndarray:
        """Contribute this process's vector; returns the gathered
        ``(n_participants, N_FIELDS)`` matrix (host numpy).

        Single-controller usage (one process drives the whole mesh, as
        in tests and single-host jobs): the same vector is contributed
        for every local device shard.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = self.n_participants
        local = jnp.broadcast_to(
            jnp.asarray(stats.to_array())[None, :], (n, N_FIELDS)
        )
        sharding = NamedSharding(self.mesh, P(self.axes))
        local = jax.device_put(local, sharding)
        with self.mesh:
            out = self._gather(local)
        return np.asarray(jax.device_get(out))

    def aggregate_many(self, stats: Sequence[StatVector]) -> np.ndarray:
        """Single-controller variant: place DISTINCT per-device vectors
        (len must equal n_participants) and gather.  Tests and
        single-host jobs use this to exercise the real collective with
        heterogeneous per-chip stats."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = self.n_participants
        if len(stats) != n:
            raise ValueError(f"need {n} vectors, got {len(stats)}")
        local = jnp.asarray(
            np.stack([s.to_array() for s in stats]), dtype=jnp.float32
        )
        sharding = NamedSharding(self.mesh, P(self.axes))
        local = jax.device_put(local, sharding)
        with self.mesh:
            out = self._gather(local)
        return np.asarray(jax.device_get(out))

    def rank_skew(self, gathered: np.ndarray, field: str) -> Dict[str, float]:
        """Cross-chip skew for one field: (worst − median) / median."""
        idx = STAT_FIELDS.index(field)
        col = np.asarray(gathered)[:, idx]
        med = float(np.median(col))
        worst = int(np.argmax(col))
        skew = (float(col[worst]) - med) / med if med > 0 else 0.0
        return {
            "median": med,
            "worst": float(col[worst]),
            "worst_rank": worst,
            "skew_pct": skew,
        }


def gathered_to_stat_vectors(gathered: np.ndarray) -> List[StatVector]:
    return [StatVector.from_array(row) for row in np.asarray(gathered)]
