"""Rank liveness tracking (docs/developer_guide/fault-tolerance.md).

Every rank ships a ``rank_heartbeat`` control message every
``TRACEML_HEARTBEAT_INTERVAL_SEC`` (default 3s), even across idle
ticks; the aggregator feeds every envelope AND control message into
this tracker.  A rank's state is derived from its last-seen age:

    ACTIVE  — heard from within ``stale_after`` seconds
    STALE   — silent past ``stale_after`` (missed ~3 heartbeats)
    LOST    — silent past ``lost_after`` (hard verdict: the process is
              gone, preempted, or partitioned)
    FINISHED — sent its ``rank_finished`` marker (terminal; a finished
              rank is never STALE/LOST no matter how long it is silent)

The tracker also remembers the last time each rank showed *step
progress* (a ``step_time`` envelope): the diagnostics layer uses the
gap between last-progress and last-seen to split "died mid-stride"
(LIKELY_PREEMPTED — progress right up to the silence) from a rank that
idled before vanishing.

The aggregator persists :meth:`snapshot` to ``rank_status.json`` on
the ingest-stats cadence and once more at settle-end.  Readers consume
the states **as written** — at report time every rank is silent, so
re-deriving from wall-clock would mark the whole world LOST.  A
restarted aggregator re-seeds from the same file via :meth:`seed` so a
rank that finished before the crash stays FINISHED.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Mapping, Optional

from traceml_tpu.config import flags

ENV_STALE_SEC = flags.LIVENESS_STALE_SEC.name
ENV_LOST_SEC = flags.LIVENESS_LOST_SEC.name

DEFAULT_STALE_SEC = 10.0  # ~3 missed heartbeats at the 3s default
DEFAULT_LOST_SEC = 30.0

STATE_ACTIVE = "active"
STATE_STALE = "stale"
STATE_LOST = "lost"
STATE_FINISHED = "finished"


class RankLivenessTracker:
    """Last-seen bookkeeping + state derivation.  Not thread-safe by
    itself: the aggregator calls it from the ticket-ordered ingest
    section only (one thread at a time by construction)."""

    def __init__(
        self,
        stale_after: Optional[float] = None,
        lost_after: Optional[float] = None,
    ) -> None:
        self.stale_after = (
            stale_after
            if stale_after is not None
            else flags.LIVENESS_STALE_SEC.get_float(DEFAULT_STALE_SEC)
        )
        self.lost_after = max(
            self.stale_after,
            lost_after
            if lost_after is not None
            else flags.LIVENESS_LOST_SEC.get_float(DEFAULT_LOST_SEC),
        )
        self._first_seen: Dict[int, float] = {}
        self._last_seen: Dict[int, float] = {}
        self._last_progress: Dict[int, float] = {}
        self._finished: Dict[int, float] = {}

    # -- feed ----------------------------------------------------------
    def observe(
        self,
        rank: int,
        ts: Optional[float] = None,
        progress: bool = False,
    ) -> None:
        now = time.time() if ts is None else float(ts)
        self._first_seen.setdefault(rank, now)
        if now > self._last_seen.get(rank, 0.0):
            self._last_seen[rank] = now
        if progress and now > self._last_progress.get(rank, 0.0):
            self._last_progress[rank] = now

    def mark_finished(self, rank: int, ts: Optional[float] = None) -> None:
        now = time.time() if ts is None else float(ts)
        self.observe(rank, now)
        self._finished.setdefault(rank, now)

    # -- derive --------------------------------------------------------
    def state_of(self, rank: int, now: Optional[float] = None) -> str:
        if rank in self._finished:
            return STATE_FINISHED
        now = time.time() if now is None else now
        age = now - self._last_seen.get(rank, now)
        if age >= self.lost_after:
            return STATE_LOST
        if age >= self.stale_after:
            return STATE_STALE
        return STATE_ACTIVE

    def ranks(self) -> list:
        return sorted(self._last_seen)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Persistable per-rank view for ``rank_status.json``."""
        now = time.time() if now is None else now
        ranks: Dict[str, Any] = {}
        for rank in self.ranks():
            ranks[str(rank)] = {
                "state": self.state_of(rank, now),
                "first_seen": self._first_seen.get(rank),
                "last_seen": self._last_seen.get(rank),
                "last_progress": self._last_progress.get(rank),
                "finished": rank in self._finished,
            }
        return {
            "ts": now,
            "thresholds": {
                "stale_after_sec": self.stale_after,
                "lost_after_sec": self.lost_after,
            },
            "ranks": ranks,
        }

    # -- crash-resume --------------------------------------------------
    def seed(self, snapshot: Mapping[str, Any]) -> None:
        """Re-load a prior aggregator incarnation's snapshot so restart
        keeps finished ranks FINISHED and last-seen history intact."""
        ranks = snapshot.get("ranks")
        if not isinstance(ranks, Mapping):
            return
        for rank_s, info in ranks.items():
            try:
                rank = int(rank_s)
            except (TypeError, ValueError):
                continue
            if not isinstance(info, Mapping):
                continue
            last_seen = info.get("last_seen")
            if isinstance(last_seen, (int, float)):
                self.observe(rank, float(last_seen))
            last_progress = info.get("last_progress")
            if isinstance(last_progress, (int, float)):
                self.observe(rank, float(last_progress), progress=True)
            if info.get("finished"):
                ls = last_seen if isinstance(last_seen, (int, float)) else None
                self.mark_finished(rank, ls)
