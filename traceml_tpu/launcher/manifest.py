"""Run + code manifests
(reference: src/traceml_ai/launcher/manifest.py:58-228; the AST code
scan lives in launcher/ast_scan.py — project-level traversal over local
imports, reference utils/ast_analysis/).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.launcher.ast_scan import (  # noqa: F401  (compat re-export)
    analyze_project,
    analyze_script,
)
from traceml_tpu.utils.atomic_io import atomic_write_json, read_json

STATUS_STARTING = "starting"
STATUS_RUNNING = "running"
STATUS_COMPLETED = "completed"
STATUS_FAILED = "failed"
STATUS_DEGRADED = "degraded"


def manifest_path(session_dir: Path) -> Path:
    return Path(session_dir) / "manifest.json"


def write_run_manifest(
    session_dir: Path,
    *,
    session_id: str,
    script: str,
    mode: str,
    world_size: int,
    status: str = STATUS_STARTING,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    data = {
        "schema": 1,
        "session_id": session_id,
        "script": script,
        "mode": mode,
        "world_size": world_size,
        "status": status,
        "telemetry_status": "ok",
        "created_at": time.time(),
        "updated_at": time.time(),
        "artifacts": {
            "final_summary_json": str(Path(session_dir) / "final_summary.json"),
            "final_summary_txt": str(Path(session_dir) / "final_summary.txt"),
            "telemetry_db": str(Path(session_dir) / "telemetry.sqlite"),
        },
    }
    if extra:
        data.update(extra)
    atomic_write_json(manifest_path(session_dir), data)
    return data


def update_run_manifest(session_dir: Path, **fields: Any) -> None:
    data = read_json(manifest_path(session_dir), default={}) or {}
    data.update(fields)
    data["updated_at"] = time.time()
    atomic_write_json(manifest_path(session_dir), data)


def write_code_manifest(session_dir: Path, script: Path) -> Dict[str, Any]:
    data = analyze_project(script)
    data["generated_at"] = time.time()
    atomic_write_json(Path(session_dir) / "code_manifest.json", data)
    return data
