"""System section + chip-utilization gauge (reference role:
nicegui_sections/system_section.py — CPU time-series card + a
utilization gauge driven by the SAME system payload).

The gauge is an SVG progress ring over the best available busy signal:
libtpu duty-cycle when chips report it, else the step-time view's
median occupancy (device-busy share of wall) — labeled with which
source is showing, so a tunneled chip that can't answer duty-cycle
still gets an honest dial.  The CPU history chart carries a crosshair
tooltip like the step chart.
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">System</h2><span class="sp"></span>
  <span id="sys-badge"></span></div>
<svg id="sys-cpu" class="spark" viewBox="0 0 600 64" preserveAspectRatio="none"></svg>
<div class="muted" id="sys-cpu-cap" style="margin-bottom:.4rem"></div>
<div id="system"></div>
"""

_GAUGE_HTML = """
<div class="chead"><h2 class="ctitle">Chip busy</h2><span class="sp"></span>
  <span class="cmeta" id="gauge-src"></span></div>
<div style="display:flex;justify-content:center;padding:.4rem 0">
<svg id="gauge" width="170" height="150" viewBox="0 0 170 150">
  <path d="M 25 125 A 70 70 0 1 1 145 125" fill="none"
    stroke="rgba(233,236,245,0.08)" stroke-width="13" stroke-linecap="round"/>
  <path id="gauge-arc" d="M 25 125 A 70 70 0 1 1 145 125" fill="none"
    stroke="var(--accent)" stroke-width="13" stroke-linecap="round"
    stroke-dasharray="0 1000" style="transition:stroke-dasharray .6s"/>
  <text id="gauge-val" x="85" y="92" text-anchor="middle"
    font-family="var(--mono)" font-size="30" font-weight="600"
    fill="var(--ink)">—</text>
</svg></div>
<div class="muted" id="gauge-note" style="text-align:center"></div>
"""

_JS = r"""
let sysLast=null;
function render_system(d){
  const s=d.system;badge("sys-badge",d.ts,s&&s.latest_ts);
  const el=document.getElementById("system");
  sysLast=s;
  if(!s||!s.nodes||!s.nodes.length){
    el.innerHTML='<span class="muted">no system telemetry</span>';
    document.getElementById("sys-cpu").innerHTML="";
    document.getElementById("sys-cpu-cap").textContent="";
    render_gauge(d);return}
  // cpu history chart (one line per node)
  const svg=document.getElementById("sys-cpu");
  let paths="";
  s.nodes.forEach((n,ni)=>{const h=n.cpu_history||[];if(h.length<2)return;
    paths+=`<polyline fill="none" stroke="${rankColor(ni)}" stroke-width="1.5"
      points="${sparkPath(h,600,64,100)}"/>`});
  svg.innerHTML=paths;
  document.getElementById("sys-cpu-cap").textContent=
    paths?"host cpu % (window tail, one line per node)":"";
  hookTip("sys-cpu",frac=>{
    if(!sysLast||!sysLast.nodes)return null;
    let h="";
    for(const n of sysLast.nodes){const hist=n.cpu_history||[];
      if(hist.length<2)continue;
      const i=Math.min(hist.length-1,Math.floor(frac*hist.length));
      h+=`${h?"<br>":""}${esc(n.hostname)}: ${hist[i].toFixed(0)}%`}
    return h||null});
  let rows=`<table><tr><th>node</th><th class="num">cpu</th>
    <th class="num">host mem</th><th class="num">load</th><th></th></tr>`;
  for(const n of s.nodes){
    rows+=`<tr><td>${esc(n.hostname)} (#${esc(n.node_rank)})</td>
      <td class="num">${n.cpu_pct==null?"n/a":n.cpu_pct.toFixed(0)+"%"}</td>
      <td class="num">${fmtB(n.memory_used_bytes)} / ${fmtB(n.memory_total_bytes)}</td>
      <td class="num">${n.load_1m==null?"—":n.load_1m.toFixed(1)}</td>
      <td>${n.stale?'<span class="badge stale">stale</span>':""}</td></tr>`}
  const devs=[];for(const n of s.nodes)for(const dv of n.devices||[])devs.push([n,dv]);
  if(devs.length){
    rows+=`</table><table><tr><th>node</th><th class="num">dev</th><th>kind</th>
      <th class="num">mem</th><th class="num">util</th><th class="num">temp</th>
      <th class="num">power</th></tr>`;
    for(const[n,dv]of devs){
      rows+=`<tr><td>${esc(n.hostname)}</td><td class="num">${esc(dv.device_id)}</td>
        <td>${esc(dv.device_kind)}</td>
        <td class="num">${dv.memory_used_bytes==null?"—":fmtB(dv.memory_used_bytes)+" / "+fmtB(dv.memory_total_bytes)}</td>
        <td class="num">${dv.utilization_pct==null?"—":dv.utilization_pct.toFixed(0)+"%"}</td>
        <td class="num">${dv.temperature_c==null?"—":dv.temperature_c.toFixed(0)+"°C"}</td>
        <td class="num">${dv.power_w==null?"—":dv.power_w.toFixed(0)+"W"}</td></tr>`}}
  el.innerHTML=rows+"</table>";
  render_gauge(d)}
function render_gauge(d){
  // best busy signal: libtpu duty-cycle (device rows) > step occupancy
  let val=null,src="";
  const s=d.system;
  if(s&&s.nodes){const utils=[];
    for(const n of s.nodes)for(const dv of n.devices||[])
      if(dv.utilization_pct!=null)utils.push(dv.utilization_pct);
    if(utils.length){
      val=utils.reduce((a,b)=>a+b,0)/utils.length;src="libtpu duty cycle"}}
  const st=d.step_time;
  if(val==null&&st&&st.median_occupancy!=null){
    val=st.median_occupancy*100;src="step occupancy"}
  const arc=document.getElementById("gauge-arc");
  const txt=document.getElementById("gauge-val");
  // arc length of the 290° ring at r=70 ≈ 354px
  const LEN=354;
  if(val==null){arc.setAttribute("stroke-dasharray","0 1000");
    txt.textContent="—";
    document.getElementById("gauge-src").textContent="";
    document.getElementById("gauge-note").textContent="no busy signal yet";
    return}
  const v=Math.max(0,Math.min(100,val));
  arc.setAttribute("stroke-dasharray",`${(v/100*LEN).toFixed(1)} 1000`);
  arc.setAttribute("stroke",v>=85?"var(--good)":v>=50?"var(--accent)":"var(--warn)");
  txt.textContent=v.toFixed(0)+"%";
  document.getElementById("gauge-src").textContent=src;
  document.getElementById("gauge-note").textContent=
    src==="step occupancy"?"device-busy share of wall (step window)":
    "mean across reporting chips"}
"""

SECTION = Section(
    id="system",
    title="System",
    html=_HTML,
    js=_JS,
    contract=(
        "ts",
        "system.latest_ts",
        "system.nodes.hostname",
        "system.nodes.node_rank",
        "system.nodes.cpu_pct",
        "system.nodes.cpu_history",
        "system.nodes.memory_used_bytes",
        "system.nodes.memory_total_bytes",
        "system.nodes.load_1m",
        "system.nodes.stale",
        "system.nodes.devices.device_id",
        "system.nodes.devices.device_kind",
        "system.nodes.devices.memory_used_bytes",
        "system.nodes.devices.memory_total_bytes",
        "system.nodes.devices.utilization_pct",
        "system.nodes.devices.temperature_c",
        "system.nodes.devices.power_w",
        "step_time.median_occupancy",
    ),
)

GAUGE_SECTION = Section(
    id="gauge",
    title="Chip busy",
    html=_GAUGE_HTML,
    js="",  # driven by render_system (one subscriber per payload, like the ref)
    contract=(
        "system.nodes.devices.utilization_pct",
        "step_time.median_occupancy",
    ),
)
