"""Optional per-rank on-disk telemetry backup
(reference: src/traceml_ai/database/database_writer.py:28-137).

Append-only, length-prefixed codec frames per table under
``<logs>/<session>/rank_N/data/<sampler>/<table>.msgpack``.  Used for
post-mortem `inspect` when the aggregator was unreachable.  Flushes are
throttled; failures are logged and swallowed.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Dict, Optional

from traceml_tpu.database.database import Database
from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

_LEN = struct.Struct(">I")


class DatabaseWriter:
    def __init__(
        self,
        sampler_name: str,
        db: Database,
        out_dir: Optional[Path],
        flush_every: int = 20,
    ) -> None:
        self._sampler = sampler_name
        self._db = db
        self._dir = Path(out_dir) / sampler_name if out_dir else None
        self._cursors: Dict[str, int] = {}
        self._flush_every = max(1, flush_every)
        self._calls = 0

    def flush(self, force: bool = False) -> int:
        """Write new rows to disk; returns rows written."""
        if self._dir is None:
            return 0
        self._calls += 1
        if not force and self._calls % self._flush_every:
            return 0
        written = 0
        try:
            self._dir.mkdir(parents=True, exist_ok=True)
            for table in self._db.table_names():
                cursor = self._cursors.get(table, 0)
                rows, new_cursor = self._db.collect_since(table, cursor)
                if not rows:
                    self._cursors[table] = new_cursor
                    continue
                # One buffer, one write: a crash can only tear the final
                # frame, and the cursor advances only after a successful
                # write so no rows are silently dropped on OSError.
                buf = bytearray()
                for row in rows:
                    frame = msgpack_codec.encode(row)
                    buf += _LEN.pack(len(frame))
                    buf += frame
                path = self._dir / f"{table}.msgpack"
                with open(path, "ab") as fh:
                    fh.write(buf)
                self._cursors[table] = new_cursor
                written += len(rows)
        except Exception as exc:
            get_error_log().warning(
                f"disk backup flush failed for sampler={self._sampler}", exc
            )
        return written


def iter_backup_file(path: Path):
    """Decode an append-only backup file → yields rows (used by `inspect`).

    A torn/corrupt tail frame (crash mid-write) terminates iteration
    instead of raising — post-mortem inspection must work on exactly the
    runs that crashed.
    """
    with open(path, "rb") as fh:
        while True:
            hdr = fh.read(_LEN.size)
            if len(hdr) < _LEN.size:
                return
            (n,) = _LEN.unpack(hdr)
            if n > 64 * 1024 * 1024:  # corrupt length → stop
                return
            body = fh.read(n)
            if len(body) < n:
                return
            try:
                yield msgpack_codec.decode(body)
            except msgpack_codec.CodecError:
                return
