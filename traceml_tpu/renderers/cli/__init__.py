"""Per-domain Rich renderers for the live CLI
(reference pattern: renderers/<domain>/renderer.py + cli_compute.py —
here each domain module renders the typed view from renderers/views.py;
no metric math happens at render time)."""

from traceml_tpu.renderers.cli.dashboard import dashboard  # noqa: F401
from traceml_tpu.renderers.cli.diagnostics import diagnostics_panel  # noqa: F401
from traceml_tpu.renderers.cli.memory import step_memory_panel  # noqa: F401
from traceml_tpu.renderers.cli.output import stdout_panel  # noqa: F401
from traceml_tpu.renderers.cli.process import process_panel  # noqa: F401
from traceml_tpu.renderers.cli.step_time import step_time_panel  # noqa: F401
from traceml_tpu.renderers.cli.system import cluster_panel, system_panel  # noqa: F401
