"""Process CLI panel
(reference: renderers/process/renderer.py — per-rank process table with
busiest-rank highlight and per-row staleness)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from traceml_tpu.renderers.views import ProcessView
from traceml_tpu.utils.formatting import fmt_bytes


def process_panel(payload: Dict[str, Any]) -> Panel:
    view: Optional[ProcessView] = (payload.get("views") or {}).get("process")
    if view is None:
        return Panel(Text("no process telemetry", style="dim"), title="processes")
    table = Table(expand=True, box=None)
    table.add_column("rank", justify="right")
    table.add_column("host")
    table.add_column("pid", justify="right")
    table.add_column("cpu", justify="right")
    table.add_column("rss", justify="right")
    table.add_column("threads", justify="right")
    table.add_column("", justify="right")
    for s in view.ranks:
        cpu_style = "bold yellow" if s.rank == view.busiest_rank else ""
        table.add_row(
            str(s.rank),
            s.hostname,
            str(s.pid or "—"),
            Text(
                f"{s.cpu_pct:.0f}%" if s.cpu_pct is not None else "n/a",
                style=cpu_style,
            ),
            fmt_bytes(s.rss_bytes),
            str(s.num_threads or "—"),
            Text("stale", style="yellow") if s.stale else "",
        )
    return Panel(
        table,
        title="processes",
        subtitle=f"total rss {fmt_bytes(view.total_rss_bytes)}",
    )
