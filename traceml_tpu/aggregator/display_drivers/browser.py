"""Dependency-free browser dashboard
(reference role: the NiceGUI dashboard, display_drivers/nicegui.py:503 +
nicegui_sections/ — rebuilt on the stdlib since this image ships no web
framework; a single HTML page polls ``/api/live`` and renders per-domain
sections with vanilla JS + inline SVG).

Serves:

* ``GET /``          — the dashboard page (self-contained HTML/JS/CSS)
* ``GET /api/live``  — live JSON payload (renderers/web_payload.py, v2:
  the typed views from renderers/views.py serialized verbatim)
* ``GET /api/summary`` — final_summary.json once it exists
* ``GET /healthz``   — readiness probe ({"ok": true, session, ts}) —
  ``wait_until_ready()`` polls it so watchers/tests never race startup

Sections (each with its own staleness badge, computed against the
server's payload timestamp so client clock skew is irrelevant):
final summary (appears when the run finalizes) · findings · step time
(phase-stack chart + phase table + per-rank sparklines) · device memory
(per-rank pressure bars + history) · cluster rollup + per-rank heatmap
(multi-rank) · system nodes · processes · rank-0 output.

Security: every interpolated value that originates in telemetry
(hostnames, diagnosis text, phase/rank keys) goes through ``esc()`` —
the ingest port is unauthenticated, so the page treats all payload
strings as hostile.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional

from traceml_tpu.aggregator.display_drivers.base import BaseDisplayDriver
from traceml_tpu.utils.atomic_io import read_json
from traceml_tpu.utils.error_log import get_error_log

_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>TraceML-TPU live</title>
<style>
body{font-family:system-ui,sans-serif;margin:1.5rem auto;max-width:1100px;
     background:#12121a;color:#e8e8f0;padding:0 1rem}
h1{font-size:1.2rem} .muted{color:#9a9ab0;font-size:.85rem}
.card{background:#1c1c28;border-radius:10px;padding:1rem;margin:.8rem 0}
.card h2{font-size:.95rem;margin:0 0 .5rem 0;display:flex;
         justify-content:space-between;align-items:center}
.sev-info{border-left:5px solid #2d7dd2}
.sev-warning{border-left:5px solid #e67e22}
.sev-critical{border-left:5px solid #c0392b}
table{border-collapse:collapse;width:100%;font-size:.88rem}
th,td{text-align:left;padding:.3rem .55rem;border-bottom:1px solid #2c2c3c}
td.num,th.num{text-align:right}
.bar{height:14px;display:inline-block;vertical-align:middle;border-radius:2px}
.meter{background:#2c2c3c;border-radius:3px;width:120px;height:12px;
       display:inline-block;vertical-align:middle;overflow:hidden}
.meter>i{display:block;height:100%;background:#2d7dd2}
.meter>i.warn{background:#e67e22}.meter>i.crit{background:#c0392b}
pre{white-space:pre-wrap;font-size:.8rem;color:#b8e0b8;margin:0}
.err{color:#f0a0a0}
.badge{font-size:.72rem;border-radius:4px;padding:.1rem .4rem;background:#2c2c3c}
.badge.stale{background:#6b4e16;color:#ffd27f}
svg.chart{width:100%;height:110px;background:#15151f;border-radius:6px}
svg.spark{width:100%;height:60px;background:#15151f;border-radius:6px}
.legend span{margin-right:.8rem;font-size:.78rem}
.legend i{display:inline-block;width:10px;height:10px;border-radius:2px;
          margin-right:.3rem;vertical-align:middle}
.finding{margin:.3rem 0;padding:.45rem .6rem;border-radius:6px;background:#23232f}
</style></head><body>
<h1>TraceML-TPU — live dashboard</h1>
<div class="muted" id="meta">connecting…</div>
<div class="card" id="summary" style="display:none"></div>
<div id="findings"></div>
<div class="card"><h2>Step time <span id="st-badge"></span></h2>
  <div id="st-cov" class="muted"></div>
  <div class="legend" id="st-legend"></div>
  <svg id="st-stack" class="chart" viewBox="0 0 600 110" preserveAspectRatio="none"></svg>
  <div id="st-table"></div>
  <svg id="st-spark" class="spark" viewBox="0 0 600 60" preserveAspectRatio="none"></svg>
  <div class="muted">per-rank step time (window tail)</div></div>
<div class="card"><h2>Device memory <span id="mem-badge"></span></h2>
  <div id="memory"></div></div>
<div class="card" id="cluster-card" style="display:none">
  <h2>Cluster <span id="cluster-sub" class="muted"></span></h2>
  <div id="cluster"></div></div>
<div class="card" id="heatmap-card" style="display:none">
  <h2>Per-rank heatmap <span class="muted">relative to cross-rank median</span></h2>
  <div id="heatmap"></div></div>
<div class="card"><h2>System <span id="sys-badge"></span></h2>
  <div id="system"></div></div>
<div class="card"><h2>Processes <span id="proc-badge"></span></h2>
  <div id="process"></div></div>
<div class="card"><h2>Rank 0 output</h2><pre id="stdout"></pre></div>
<script>
const COLORS={input:"#e74c3c",h2d:"#e67e22",forward:"#2d7dd2",
backward:"#2255a4",optimizer:"#7d3dd2",compute:"#2d7dd2",
compile:"#f1c40f",collective:"#16a085",checkpoint:"#8e5a2b",
residual:"#95a5a6"};
// telemetry strings (hostnames, diagnosis text, phase/rank keys) arrive
// from an unauthenticated ingest port — escape EVERY interpolation.
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmtB=n=>{if(n==null||isNaN(n))return"n/a";
  const u=["B","KiB","MiB","GiB","TiB"];let i=0;
  while(n>=1024&&i<u.length-1){n/=1024;i++}return n.toFixed(i?2:0)+" "+u[i]};
const fmtMs=v=>v==null?"n/a":(v<1?(v*1000).toFixed(0)+" µs":
  v<1000?v.toFixed(1)+" ms":(v/1000).toFixed(2)+" s");
const pct=v=>v==null?"—":(v*100).toFixed(1)+"%";
function badge(el,serverTs,latestTs){
  const e=document.getElementById(el);if(!e)return;
  if(latestTs==null){e.innerHTML='<span class="badge">no data</span>';return}
  const age=serverTs-latestTs;
  e.innerHTML=age>5?`<span class="badge stale">${age.toFixed(0)}s stale</span>`
                   :'<span class="badge">live</span>'}
function meter(frac,warn,crit){
  if(frac==null)return"—";
  const cls=frac>=crit?"crit":frac>=warn?"warn":"";
  const w=Math.min(100,frac*100).toFixed(0);
  return`<span class="meter"><i class="${cls}" style="width:${w}%"></i></span>
    <span class="muted">${(frac*100).toFixed(0)}%</span>`}

function renderFindings(d){
  const el=document.getElementById("findings");
  const fs=d.findings||[];
  if(!fs.length){el.innerHTML="";return}
  el.innerHTML=fs.map(f=>`<div class="finding card sev-${esc(f.severity)}">
    <b>${esc(f.domain)}/${esc(f.kind)}</b>
    <span class="muted">[${esc(f.severity)}]</span><br>${esc(f.summary)}
    ${f.action?`<br><span class="muted">→ ${esc(f.action)}</span>`:""}</div>`).join("")}

function renderStepTime(d){
  const st=d.step_time;badge("st-badge",d.ts,st&&st.latest_ts);
  if(!st)return;
  const cov=st.coverage||{};
  const eff=st.efficiency;
  document.getElementById("st-cov").textContent=
    `${st.n_steps} steps · ${st.clock} clock · `+
    `${cov.ranks_present}/${cov.world_size} ranks`+
    (st.median_occupancy!=null?` · chip busy ${(st.median_occupancy*100).toFixed(0)}%`:"")+
    (eff?` · ${eff.achieved_tflops_median.toFixed(1)} TFLOP/s`+
      (eff.mfu_median!=null?` (MFU ${(eff.mfu_median*100).toFixed(0)}%)`:""):"")+
    (cov.incomplete?" · INCOMPLETE":"");
  // stacked per-step phase chart (cross-rank medians)
  const stack=st.phase_stack||{};const keys=Object.keys(stack);
  const n=keys.length?stack[keys[0]].length:0;
  let maxTot=1;const totals=[];
  for(let i=0;i<n;i++){let t=0;for(const k of keys)t+=stack[k][i]||0;
    totals.push(t);maxTot=Math.max(maxTot,t)}
  let bars="";const bw=600/Math.max(1,n);
  for(let i=0;i<n;i++){let y=108;
    for(const k of keys){const h=(stack[k][i]||0)/maxTot*104;y-=h;
      bars+=`<rect x="${(i*bw).toFixed(1)}" y="${y.toFixed(1)}"
        width="${Math.max(0.5,bw-0.6).toFixed(1)}" height="${h.toFixed(1)}"
        fill="${COLORS[k]||"#888"}"><title>step ${esc((st.steps||[])[i])} ${esc(k)} ${fmtMs(stack[k][i])}</title></rect>`}}
  document.getElementById("st-stack").innerHTML=bars;
  document.getElementById("st-legend").innerHTML=keys.map(k=>
    `<span><i style="background:${COLORS[k]||"#888"}"></i>${esc(k)}</span>`).join("");
  // phase table
  let rows=`<table><tr><th>phase</th><th class="num">median</th>
    <th class="num">share</th><th class="num">worst rank</th>
    <th class="num">skew</th></tr>`;
  for(const p of st.phases||[]){
    rows+=`<tr><td>${esc(p.key)}</td><td class="num">${fmtMs(p.median_ms)}</td>
      <td class="num">${pct(p.share)}</td><td class="num">${esc(p.worst_rank)}</td>
      <td class="num">${pct(p.skew_pct)}</td></tr>`}
  document.getElementById("st-table").innerHTML=rows+"</table>";
  // per-rank sparkline
  const svg=document.getElementById("st-spark");
  const series=st.step_series||{};const ranks=Object.keys(series);
  let max=1;for(const r of ranks)for(const v of series[r])max=Math.max(max,v);
  let paths="";
  ranks.forEach((r,ri)=>{const s=series[r];if(!s.length)return;
    const pts=s.map((v,i)=>`${(i/(s.length-1||1))*600},${58-(v/max)*52}`).join(" ");
    paths+=`<polyline fill="none" stroke="hsl(${(ri*67)%360},70%,60%)"
      stroke-width="1.5" points="${pts}"><title>rank ${esc(r)}</title></polyline>`});
  svg.innerHTML=paths}

function renderMemory(d){
  const m=d.memory;badge("mem-badge",d.ts,m&&m.latest_ts);
  const el=document.getElementById("memory");
  if(!m||!m.ranks||!m.ranks.length){el.innerHTML='<span class="muted">no memory telemetry</span>';return}
  let rows=`<table><tr><th class="num">rank</th><th>device</th>
    <th class="num">current</th><th class="num">step peak</th>
    <th class="num">limit</th><th>pressure</th><th class="num">growth</th><th>history</th></tr>`;
  for(const s of m.ranks){
    const hist=s.history||[];const hmax=Math.max(1,...hist);
    const pts=hist.map((v,i)=>`${(i/(hist.length-1||1))*100},${18-(v/hmax)*16}`).join(" ");
    const spark=hist.length>1?`<svg width="100" height="18" viewBox="0 0 100 18">
      <polyline fill="none" stroke="#2d7dd2" stroke-width="1" points="${pts}"/></svg>`:"—";
    const g=s.growth_bytes;
    rows+=`<tr><td class="num">${esc(s.rank)}</td><td>${esc(s.device_kind)}</td>
      <td class="num">${fmtB(s.current_bytes)}</td>
      <td class="num">${fmtB(s.step_peak_bytes)}</td>
      <td class="num">${fmtB(s.limit_bytes)}</td>
      <td>${meter(s.pressure,0.92,0.97)}</td>
      <td class="num">${g?(g>0?"+":"-")+fmtB(Math.abs(g)):"—"}</td>
      <td>${spark}</td></tr>`}
  el.innerHTML=rows+"</table>"}

function renderSystem(d){
  const s=d.system;badge("sys-badge",d.ts,s&&s.latest_ts);
  const el=document.getElementById("system");
  const card=document.getElementById("cluster-card");
  if(!s||!s.nodes||!s.nodes.length){el.innerHTML='<span class="muted">no system telemetry</span>';
    card.style.display="none";return}
  let rows=`<table><tr><th>node</th><th class="num">cpu</th>
    <th class="num">host mem</th><th class="num">load</th><th></th></tr>`;
  for(const n of s.nodes){
    rows+=`<tr><td>${esc(n.hostname)} (#${esc(n.node_rank)})</td>
      <td class="num">${n.cpu_pct==null?"n/a":n.cpu_pct.toFixed(0)+"%"}</td>
      <td class="num">${fmtB(n.memory_used_bytes)} / ${fmtB(n.memory_total_bytes)}</td>
      <td class="num">${n.load_1m==null?"—":n.load_1m.toFixed(1)}</td>
      <td>${n.stale?'<span class="badge stale">stale</span>':""}</td></tr>`}
  const devs=[];for(const n of s.nodes)for(const dv of n.devices||[])devs.push([n,dv]);
  if(devs.length){
    rows+=`</table><table><tr><th>node</th><th class="num">dev</th><th>kind</th>
      <th class="num">mem</th><th class="num">util</th><th class="num">temp</th>
      <th class="num">power</th></tr>`;
    for(const[n,dv]of devs){
      rows+=`<tr><td>${esc(n.hostname)}</td><td class="num">${esc(dv.device_id)}</td>
        <td>${esc(dv.device_kind)}</td>
        <td class="num">${dv.memory_used_bytes==null?"—":fmtB(dv.memory_used_bytes)+" / "+fmtB(dv.memory_total_bytes)}</td>
        <td class="num">${dv.utilization_pct==null?"—":dv.utilization_pct.toFixed(0)+"%"}</td>
        <td class="num">${dv.temperature_c==null?"—":dv.temperature_c.toFixed(0)+"°C"}</td>
        <td class="num">${dv.power_w==null?"—":dv.power_w.toFixed(0)+"W"}</td></tr>`}}
  el.innerHTML=rows+"</table>";
  // cluster rollups (multi-node only)
  if(s.is_cluster&&(s.rollups||[]).length){
    card.style.display="";
    document.getElementById("cluster-sub").textContent=
      `${s.nodes.length}/${s.expected_nodes} nodes`+
      (s.missing_nodes?` · ${s.missing_nodes} MISSING`:"");
    let cr=`<table><tr><th>metric</th><th class="num">min</th>
      <th class="num">median</th><th class="num">max</th><th>max node</th></tr>`;
    for(const r of s.rollups){
      cr+=`<tr><td>${esc(r.metric)}</td><td class="num">${r.min_value.toFixed(1)}</td>
        <td class="num">${r.median_value.toFixed(1)}</td>
        <td class="num">${r.max_value.toFixed(1)}</td><td>${esc(r.max_node)}</td></tr>`}
    document.getElementById("cluster").innerHTML=cr+"</table>"
  }else card.style.display="none"}

function heatColor(ratio){
  // 1.0 = at the cross-rank median (cool); hue walks blue→red as a
  // rank runs hotter than its peers; capped at 2× for the scale
  if(ratio==null||isNaN(ratio))return"#2c2c3c";
  const x=Math.max(0,Math.min(1,(ratio-0.85)/1.15));
  return`hsl(${(220-220*x).toFixed(0)},65%,${(28+x*14).toFixed(0)}%)`}
function renderHeatmap(d){
  const card=document.getElementById("heatmap-card");
  const el=document.getElementById("heatmap");
  const ranks={};
  const st=d.step_time;
  if(st&&st.step_series)for(const r in st.step_series){
    const s=st.step_series[r];if(!s.length)continue;
    const tail=s.slice(-8);
    (ranks[r]=ranks[r]||{}).step_ms=tail.reduce((a,b)=>a+b,0)/tail.length}
  if(d.memory&&d.memory.ranks)for(const m of d.memory.ranks)
    (ranks[m.rank]=ranks[m.rank]||{}).mem_pressure=m.pressure;
  if(d.process&&d.process.ranks)for(const p of d.process.ranks){
    (ranks[p.rank]=ranks[p.rank]||{}).cpu_pct=p.cpu_pct;
    ranks[p.rank].rss=p.rss_bytes}
  const ids=Object.keys(ranks).sort((a,b)=>a-b);
  if(ids.length<2){card.style.display="none";return}
  card.style.display="";
  const METRICS=["step_ms","mem_pressure","cpu_pct","rss"];
  const med={};
  for(const m of METRICS){
    const vs=ids.map(r=>ranks[r][m]).filter(v=>v!=null).sort((a,b)=>a-b);
    med[m]=vs.length?vs[Math.floor(vs.length/2)]:null}
  let html=`<table><tr><th class="num">rank</th>`+
    METRICS.map(m=>`<th>${esc(m)}</th>`).join("")+`</tr>`;
  for(const r of ids){
    html+=`<tr><td class="num">${esc(r)}</td>`;
    for(const m of METRICS){
      const v=ranks[r][m];
      // zero median (e.g. 3 wedged ranks at 0% cpu, 1 spinning) must
      // still flag the nonzero outlier — treat it as "infinitely hot"
      const ratio=(v==null||med[m]==null)?null:
        med[m]>0?v/med[m]:(v>0?2:1);
      const label=v==null?"—":(m==="rss"?fmtB(v):m==="mem_pressure"?pct(v):
        m==="cpu_pct"?v.toFixed(0)+"%":fmtMs(v));
      html+=`<td style="background:${heatColor(ratio)}">${label}
        ${ratio!=null&&ratio>1.15?`<span class="muted">(${ratio.toFixed(2)}×)</span>`:""}</td>`}
    html+="</tr>"}
  el.innerHTML=html+"</table>"}

let summaryLoaded=false,summaryTick=0;
async function maybeSummary(){
  if(summaryLoaded||(summaryTick++%5))return;
  try{
    const r=await fetch("/api/summary");if(!r.ok)return;
    const s=await r.json();if(!s||!s.sections)return;
    summaryLoaded=true;renderSummary(s)
  }catch(e){}}
function renderSummary(s){
  const el=document.getElementById("summary");
  const p=s.primary_diagnosis||{};
  const secs=s.sections||{};
  const chips=Object.keys(secs).map(k=>
    `<span class="badge">${esc(k)}: ${esc((secs[k]||{}).status||"—")}</span>`).join(" ");
  const topo=(s.meta||{}).topology||{};
  const eff=((secs.step_time||{}).global||{}).efficiency;
  el.style.display="";
  el.innerHTML=`<h2>Final summary <span class="badge">run finished</span></h2>
    <div class="finding sev-${esc(p.severity||"info")}">
      <b>${esc(p.kind||"NO_DATA")}</b>
      <span class="muted">[${esc(p.severity||"")}]</span><br>${esc(p.summary||"")}
      ${p.action?`<br><span class="muted">→ ${esc(p.action)}</span>`:""}</div>
    <div style="margin:.4rem 0">${chips}</div>
    <div class="muted">world ${esc(topo.world_size!=null?topo.world_size:"?")}
      · mode ${esc(topo.mode||"?")}
      ${eff?` · ${Number(eff.achieved_tflops_median).toFixed(1)} TFLOP/s`+
        (eff.mfu_median!=null?` · MFU ${(eff.mfu_median*100).toFixed(0)}%`:""):""}</div>`}

function renderProcess(d){
  const p=d.process;badge("proc-badge",d.ts,p&&p.latest_ts);
  const el=document.getElementById("process");
  if(!p||!p.ranks||!p.ranks.length){el.innerHTML='<span class="muted">no process telemetry</span>';return}
  let rows=`<table><tr><th class="num">rank</th><th>host</th><th class="num">pid</th>
    <th class="num">cpu</th><th class="num">rss</th><th class="num">threads</th><th></th></tr>`;
  for(const s of p.ranks){
    const hot=s.rank===p.busiest_rank?' style="color:#ffd27f"':"";
    rows+=`<tr><td class="num">${esc(s.rank)}</td><td>${esc(s.hostname)}</td>
      <td class="num">${esc(s.pid==null?"—":s.pid)}</td>
      <td class="num"${hot}>${s.cpu_pct==null?"n/a":s.cpu_pct.toFixed(0)+"%"}</td>
      <td class="num">${fmtB(s.rss_bytes)}</td>
      <td class="num">${esc(s.num_threads==null?"—":s.num_threads)}</td>
      <td>${s.stale?'<span class="badge stale">stale</span>':""}</td></tr>`}
  el.innerHTML=rows+`</table><div class="muted">total rss ${fmtB(p.total_rss_bytes)}</div>`}

async function tick(){
 try{
  const r=await fetch("/api/live");const d=await r.json();
  const meta=document.getElementById("meta");
  meta.textContent=
    `session ${d.session} · updated ${new Date(d.ts*1000).toLocaleTimeString()}`;
  meta.className="muted";
  renderFindings(d);renderStepTime(d);renderMemory(d);
  renderSystem(d);renderProcess(d);renderHeatmap(d);
  document.getElementById("stdout").textContent=
    (d.stdout||[]).map(l=>l.line).join("\\n");
  maybeSummary();
 }catch(e){const meta=document.getElementById("meta");
   meta.textContent="poll failed: "+e;meta.className="err"}
 setTimeout(tick,1000);
}
tick();
</script></body></html>"""


def wait_until_ready(
    host: str, port: int, timeout: float = 10.0
) -> bool:
    """Poll the dashboard's ``/healthz`` until it answers — the server
    readiness probe (reference role: nicegui's startup wait), so
    watchers, tests, and launch tooling never race the bind."""
    import time
    import urllib.request

    deadline = time.monotonic() + timeout
    url = f"http://{host}:{port}/healthz"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=1.0) as resp:
                if resp.status == 200:
                    return True
        except OSError:
            pass
        time.sleep(0.05)
    return False


class BrowserDisplayDriver(BaseDisplayDriver):
    """Serves the dashboard from inside the aggregator process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._db_path: Optional[Path] = None
        self._session = ""
        self._session_dir: Optional[Path] = None

    @property
    def host(self) -> str:
        return self._host

    def start(self, context: Optional[Any] = None) -> None:
        try:
            if context is not None:
                self._db_path = context.db_path
                self._session = context.settings.session_id
                self._session_dir = context.settings.session_dir
            driver = self

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, fmt, *args):  # silence
                    pass

                def _send(self, code: int, body: bytes, ctype: str) -> None:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):  # noqa: N802
                    try:
                        if self.path == "/" or self.path.startswith("/index"):
                            self._send(200, _PAGE.encode(), "text/html; charset=utf-8")
                        elif self.path.startswith("/healthz"):
                            import time as _time

                            self._send(
                                200,
                                json.dumps({
                                    "ok": True,
                                    "session": driver._session,
                                    "ts": _time.time(),
                                }).encode(),
                                "application/json",
                            )
                        elif self.path.startswith("/api/live"):
                            from traceml_tpu.renderers.web_payload import (
                                build_web_payload,
                            )

                            payload = build_web_payload(
                                driver._db_path, driver._session
                            ) if driver._db_path else {}
                            self._send(
                                200,
                                json.dumps(payload).encode(),
                                "application/json",
                            )
                        elif self.path.startswith("/api/summary"):
                            data = None
                            if driver._session_dir is not None:
                                data = read_json(
                                    driver._session_dir / "final_summary.json"
                                )
                            self._send(
                                200 if data else 404,
                                json.dumps(data or {"error": "not ready"}).encode(),
                                "application/json",
                            )
                        else:
                            self._send(404, b"not found", "text/plain")
                    except BrokenPipeError:
                        pass
                    except Exception as exc:
                        try:
                            self._send(
                                500, str(exc).encode(), "text/plain"
                            )
                        except Exception:
                            pass

            self._httpd = ThreadingHTTPServer(
                (self._host, self._requested_port), Handler
            )
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="traceml-dashboard",
                daemon=True,
            )
            self._thread.start()
            print(f"[TraceML] dashboard: http://{self._host}:{self.port}/")
        except Exception as exc:
            get_error_log().warning("browser dashboard start failed", exc)
            self._httpd = None

    def tick(self, context: Optional[Any] = None) -> None:
        pass  # pull-based: the page polls /api/live

    def stop(self) -> None:
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass
            self._httpd = None
