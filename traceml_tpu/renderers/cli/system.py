"""System CLI panels: per-node table, device table, and the multi-node
cluster rollup (reference: renderers/system/renderer.py +
cli_cluster.py:360 — the cluster table is the multi-node view the
round-1 build lacked)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from rich.console import Group
from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from traceml_tpu.renderers.views import SystemView
from traceml_tpu.utils.formatting import fmt_bytes, fmt_pct


def _node_table(view: SystemView) -> Table:
    table = Table(expand=True, box=None)
    table.add_column("node")
    table.add_column("cpu", justify="right")
    table.add_column("host mem", justify="right")
    table.add_column("load", justify="right")
    table.add_column("", justify="right")  # staleness flag
    for n in view.nodes:
        used, total = n.memory_used_bytes, n.memory_total_bytes
        frac = used / total if used and total else None
        mem = f"{fmt_bytes(used)} / {fmt_bytes(total)}"
        if frac is not None:
            mem += f" ({fmt_pct(frac)})"
        table.add_row(
            f"{n.hostname} (#{n.node_rank})",
            f"{n.cpu_pct:.0f}%" if n.cpu_pct is not None else "n/a",
            mem,
            f"{n.load_1m:.1f}" if n.load_1m is not None else "—",
            Text("stale", style="yellow") if n.stale else "",
        )
    return table


def _device_table(view: SystemView) -> Optional[Table]:
    rows = [(n, d) for n in view.nodes for d in n.devices]
    if not rows:
        return None
    table = Table(expand=True, box=None, title="devices")
    table.add_column("node")
    table.add_column("dev", justify="right")
    table.add_column("kind")
    table.add_column("mem", justify="right")
    table.add_column("util", justify="right")
    table.add_column("temp", justify="right")
    table.add_column("power", justify="right")
    for n, d in rows:
        util = f"{d.utilization_pct:.0f}%" if d.utilization_pct is not None else "—"
        temp = f"{d.temperature_c:.0f}°C" if d.temperature_c is not None else "—"
        power = f"{d.power_w:.0f}W" if d.power_w is not None else "—"
        mem = (
            f"{fmt_bytes(d.memory_used_bytes)} / {fmt_bytes(d.memory_total_bytes)}"
            if d.memory_used_bytes is not None
            else "—"
        )
        table.add_row(n.hostname, str(d.device_id), d.device_kind, mem, util, temp, power)
    return table


def system_panel(payload: Dict[str, Any]) -> Panel:
    view: Optional[SystemView] = (payload.get("views") or {}).get("system")
    if view is None:
        return Panel(Text("no system telemetry", style="dim"), title="system")
    parts = [_node_table(view)]
    devices = _device_table(view)
    if devices is not None:
        parts.append(devices)
    return Panel(Group(*parts), title="system")


def cluster_panel(payload: Dict[str, Any]) -> Optional[Panel]:
    """min/median/max rollups across nodes — only rendered for clusters
    (reference: system/cli_cluster.py SystemCLIClusterBuilder.build)."""
    view: Optional[SystemView] = (payload.get("views") or {}).get("system")
    if view is None or not view.is_cluster:
        return None
    table = Table(expand=True, box=None)
    table.add_column("metric")
    table.add_column("min", justify="right")
    table.add_column("median", justify="right")
    table.add_column("max", justify="right")
    table.add_column("max node")
    fmt = {
        "cpu_pct": lambda v: f"{v:.0f}%",
        "memory_pct": lambda v: f"{v:.0f}%",
        "load_1m": lambda v: f"{v:.1f}",
    }
    for r in view.rollups:
        f = fmt.get(r.metric, lambda v: f"{v:.2f}")
        table.add_row(r.metric, f(r.min_value), f(r.median_value), f(r.max_value), r.max_node)
    sub = f"{len(view.nodes)}/{view.expected_nodes} nodes"
    if view.missing_nodes:
        sub += f" · {view.missing_nodes} MISSING"
    return Panel(table, title="cluster", subtitle=sub)
