"""Grad-accumulation / multi-dispatch folding under one trace_step
(SURVEY §7 hard-parts list: microbatch folding semantics under pjit).

N microbatch dispatches inside one step must fold into ONE step row
with the compute slot counting N occurrences and the step envelope's
device end tracking the LAST dispatch."""

import jax
import jax.numpy as jnp

import traceml_tpu
from traceml_tpu.samplers.step_time_sampler import _aggregate_step
from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils import timing as T


def test_microbatches_fold_into_one_step():
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        fn = traceml_tpu.wrap_step_fn(lambda x: (x * 2).sum())
        x = jnp.ones((32, 32))
        with traceml_tpu.trace_step():
            for _ in range(4):  # grad-accum microbatches
                out = fn(x)
        jax.block_until_ready(out)
        batch = captured[-1]
        computes = [e for e in batch.events if e.name == T.COMPUTE_TIME]
        assert len(computes) == 4
        # envelope marker is the LAST dispatch's marker (shared object)
        env = next(e for e in batch.events if e.name == T.STEP_TIME)
        assert env.marker is computes[-1].marker
        # the sampler folds them into one row: compute count == 4,
        # cpu_ms summed over the microbatches
        batch.force_resolve()
        row, _ = _aggregate_step(batch.events, None)
        slot = row["events"][T.COMPUTE_TIME]
        assert slot["count"] == 4
        assert slot["cpu_ms"] >= sum(e.cpu_ms for e in computes) * 0.99
    finally:
        st.on_batch_flushed.remove(captured.append)


def test_two_wrapped_fns_in_one_step():
    """Multi-model steps: each wrapped fn contributes compute events to
    the same step; the last dispatched one owns the envelope end."""
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        f1 = traceml_tpu.wrap_step_fn(lambda x: x.sum())
        f2 = traceml_tpu.wrap_step_fn(lambda x: (x + 1).mean())
        x = jnp.ones((16, 16))
        with traceml_tpu.trace_step():
            f1(x)
            out = f2(x)
        jax.block_until_ready(out)
        batch = captured[-1]
        computes = [e for e in batch.events if e.name == T.COMPUTE_TIME]
        assert len(computes) == 2
        env = next(e for e in batch.events if e.name == T.STEP_TIME)
        assert env.marker is computes[-1].marker
    finally:
        st.on_batch_flushed.remove(captured.append)
