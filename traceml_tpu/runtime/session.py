"""Session id generation (reference: src/traceml_ai/runtime/session.py:16-33)."""

from __future__ import annotations

import datetime
import os
import re


def generate_session_id(run_name: str | None = None) -> str:
    ts = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
    suffix = os.urandom(2).hex()
    if run_name:
        safe = re.sub(r"[^A-Za-z0-9_.-]+", "-", run_name)[:48]
        return f"{safe}_{ts}_{suffix}"
    return f"session_{ts}_{suffix}"
