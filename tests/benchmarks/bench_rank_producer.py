"""Rank-side producer path: seed collect/encode/backup vs the r10
zero-copy path (columnar accumulation + single-encode publish).

The SEED arm vendors the pre-r10 producer exactly, on top of primitives
that still exist unchanged (``rows_to_columns``,
``build_columnar_envelope``, ``encode``):

* database: row deque + append counter ONLY (``_SeedDatabase``) — the
  pre-r10 store had no columnar accumulators, so the seed arm must not
  pay (or benefit from) their ``add_record`` cost;
* sender: ``collect_since`` per table → ``rows_to_columns`` transpose
  per tick → envelope → whole-batch ``encode`` (one encode for the
  wire);
* writer: its OWN ``collect_since`` traversal, one ``encode`` + length
  prefix PER ROW to the per-table backup file (the second traversal and
  the second-through-Nth encode of every row).

The NEW arm is the real :class:`TelemetryPublisher` →
``DBIncrementalSender`` (columnar accumulators) → ``preencode`` once →
wire splice + v2 backup frame reuse.

Golden first: one warm-up pass drives the identical row stream through
both arms and compares (a) every decoded wire envelope — meta minus
timestamp, materialized tables — and (b) every backup row per table,
before any timing is reported.  Speed means nothing if the bytes moved.

Three timed regimes (min over repeats, fresh state each):

* **steady state** — ticks at step_time+memory+system cadence (one
  publish per 1s sampler interval — the runtime default — over
  64 steps/s training: 64 step rows, 1 memory row, 1 system row per
  tick); ``publish_speedup`` is the per-tick publish CPU ratio (ISSUE
  r10 acceptance: >=3x), with the append phase reported separately
  (the new arm moves transpose work into ``add_record``, so the
  full-tick ratio is also emitted);
* **burst drain** — 3000 rows appended then drained by ONE publish;
  append and drain are timed separately (``burst_speedup`` is the
  drain ratio, >=2x; the append side is reported so the accumulator's
  added ``add_record`` cost is visible, not hidden);
* **idle ticks** — no new data: the O(1) dirty gate vs the seed's
  per-table scan.

Pytest lane floors are conservative; acceptance numbers come from
``python tests/benchmarks/bench_rank_producer.py`` and are recorded in
BENCH_LOCAL_r10.json.
"""

import json
import struct
import sys
import threading
import time
from collections import deque
from itertools import islice
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
# standalone `python tests/benchmarks/bench_rank_producer.py` support
sys.path.insert(1, str(Path(__file__).parent.parent.parent))
import bench_common  # noqa: E402

from traceml_tpu.database.database import Database  # noqa: E402
from traceml_tpu.database.database_writer import (  # noqa: E402
    ENVELOPE_FILE,
    iter_backup_tables,
)
from traceml_tpu.runtime.sender import TelemetryPublisher  # noqa: E402
from traceml_tpu.samplers.base_sampler import BaseSampler  # noqa: E402
from traceml_tpu.telemetry.control import is_control_message  # noqa: E402
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_columnar_envelope,
    normalize_telemetry_envelope,
)
from traceml_tpu.utils import msgpack_codec  # noqa: E402

pytestmark = pytest.mark.slow

_LEN = struct.Struct(">I")
_IDENTITY = SenderIdentity(session_id="bench", global_rank=0, platform="tpu")

# steady-state cadence: one publish per 1s tick (the runtime default
# sampler_interval_sec) over 64 steps/s training (~15 ms/step — routine
# for small-model TPU training, and the regime the paper's high-rank
# ingest work targets); memory/system samplers contribute one row per
# tick each
STEP_ROWS_PER_TICK = 64
MEM_ROWS_PER_TICK = 1
SYS_ROWS_PER_TICK = 1
STEADY_TICKS = 300
WARMUP_TICKS = 40  # untimed: first-write mkdir, allocator + cache warm
BURST_ROWS = 3000
IDLE_TICKS = 2000
REPEATS = 5


# -- the identical row stream both arms consume -------------------------


def _step_row(i):
    return {
        "step": i,
        "timestamp": 1700000000.0 + i * 0.0625,
        "clock": "device",
        "events": {
            "step_time": {"cpu_ms": 62.5, "device_ms": 61.0, "count": 1},
            "compute": {"cpu_ms": 2.0, "device_ms": 55.0, "count": 1},
            "data_load": {"cpu_ms": 4.5, "device_ms": None, "count": 1},
        },
    }


def _mem_row(i):
    return {
        "timestamp": 1700000000.0 + i * 0.25,
        "step": i // 4,
        "host_mem_gb": 12.5 + (i % 7) * 0.01,
        "device_mem_gb": 27.0 + (i % 5) * 0.02,
        "device_pct": 84.0,
    }


def _sys_row(i):
    return {
        "timestamp": 1700000000.0 + i * 0.5,
        "cpu_pct": 31.0 + (i % 11),
        "net_tx_mbps": 120.0,
        "net_rx_mbps": 95.0,
    }


class _StreamSampler(BaseSampler):
    """Deterministic sampler: rows are injected by the driver."""

    def __init__(self, name, disk_backup_dir):
        self.name = name
        super().__init__(disk_backup_dir=disk_backup_dir)

    def _sample(self):  # rows come from the driver, not a tick
        pass


def _append_tick(samplers, tick):
    step, mem, sysm = samplers
    base = tick * STEP_ROWS_PER_TICK
    for j in range(STEP_ROWS_PER_TICK):
        step.db.add_record("step_time", _step_row(base + j))
    for j in range(MEM_ROWS_PER_TICK):
        mem.db.add_record("memory", _mem_row(tick * MEM_ROWS_PER_TICK + j))
    for j in range(SYS_ROWS_PER_TICK):
        sysm.db.add_record("system", _sys_row(tick * SYS_ROWS_PER_TICK + j))


# -- vendored seed producer (pre-r10 publish path) ----------------------


class _SeedTable:
    __slots__ = ("rows", "appended")

    def __init__(self, maxlen):
        self.rows = deque(maxlen=maxlen)
        self.appended = 0


class _SeedDatabase:
    """The pre-r10 store: row deque + monotonic append counter, no
    columnar accumulators — ``add_record`` and ``collect_since`` are the
    seed ``Database`` verbatim, so the seed arm pays its true append
    cost (and none of the accumulator's)."""

    def __init__(self, max_rows_per_table=3000):
        self._max = int(max_rows_per_table)
        self._tables = {}
        self._lock = threading.Lock()

    def add_record(self, table, row):
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = _SeedTable(self._max)
            t.rows.append(row)
            t.appended += 1

    def table_names(self):
        with self._lock:
            return list(self._tables.keys())

    def collect_since(self, table, cursor):
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                return [], cursor
            new = t.appended - cursor
            new_cursor = t.appended
            if new <= 0:
                return [], new_cursor
            take = min(new, len(t.rows))
            rows = list(islice(reversed(t.rows), take))
        rows.reverse()
        return rows, new_cursor


class _SeedSender:
    def __init__(self, name, db):
        self._name = name
        self._db = db
        self._cursors = {}

    def collect_payload(self):
        tables = {}
        for table in self._db.table_names():
            cursor = self._cursors.get(table, 0)
            rows, new_cursor = self._db.collect_since(table, cursor)
            if rows:
                tables[table] = rows
            self._cursors[table] = new_cursor
        if not tables:
            return None
        return build_columnar_envelope(
            self._name, tables, identity=_IDENTITY
        ).to_wire()


class _SeedWriter:
    """The pre-r10 DatabaseWriter flush loop: second traversal of the
    same rows, one encode + length prefix PER ROW."""

    def __init__(self, name, db, out_dir, flush_every=20):
        self._db = db
        self._dir = Path(out_dir) / name
        self._cursors = {}
        self._flush_every = flush_every
        self._calls = 0

    def flush(self, force=False):
        self._calls += 1
        if not force and self._calls % self._flush_every:
            return 0
        written = 0
        self._dir.mkdir(parents=True, exist_ok=True)
        for table in self._db.table_names():
            cursor = self._cursors.get(table, 0)
            rows, new_cursor = self._db.collect_since(table, cursor)
            if not rows:
                self._cursors[table] = new_cursor
                continue
            buf = bytearray()
            for row in rows:
                frame = msgpack_codec.encode(row)
                buf += _LEN.pack(len(frame))
                buf += frame
            with open(self._dir / f"{table}.msgpack", "ab") as fh:
                fh.write(buf)
            self._cursors[table] = new_cursor
            written += len(rows)
        return written


class _SeedProducer:
    def __init__(self, samplers, out_dir, sink):
        self._units = [
            (_SeedSender(s.name, s.db), _SeedWriter(s.name, s.db, out_dir))
            for s in samplers
        ]
        self._sink = sink

    def publish(self, force_flush=False):
        batch = []
        for sender, writer in self._units:
            writer.flush(force=force_flush)
            payload = sender.collect_payload()
            if payload is not None:
                batch.append(payload)
        if batch:
            self._sink.append(msgpack_codec.encode(batch))
        return len(batch)


class _CaptureClient:
    """TCPClient stand-in: encodes exactly like send_batch, keeps bytes."""

    def __init__(self, sink):
        self._sink = sink

    def send_batch(self, payloads):
        self._sink.append(msgpack_codec.encode_batch(payloads))
        return True


def _mk_arm(kind, out_dir):
    samplers = [
        _StreamSampler("step", out_dir),
        _StreamSampler("mem", out_dir),
        _StreamSampler("sys", out_dir),
    ]
    sink = []
    if kind == "seed":
        # seed arm bypasses the samplers' own sender/writer entirely
        # AND swaps in the accumulator-free pre-r10 database
        for s in samplers:
            s.db = _SeedDatabase()
        producer = _SeedProducer(samplers, out_dir, sink)
    else:
        producer = TelemetryPublisher(
            samplers,
            _CaptureClient(sink),
            _IDENTITY,
            stats_interval_s=1e9,  # keep stats out of the golden stream
        )
    return samplers, producer, sink


# -- golden comparison ---------------------------------------------------


def _decoded_envelopes(sink):
    payloads, errors = msgpack_codec.decode_batch(sink)
    assert errors == 0
    out = []
    for p in payloads:
        if is_control_message(p):
            continue
        env = normalize_telemetry_envelope(p)
        assert env is not None, p
        # timestamp and seq are stamped at publish time (seq is the
        # durable-replay dedup counter, time_ns-based), not payload
        # content — both arms' tables/meta must match without them
        meta = {
            k: v for k, v in env.meta.items() if k not in ("timestamp", "seq")
        }
        out.append((meta, {t: env.tables[t] for t in env.table_names()}))
    return out


def _backup_rows(out_dir, samplers):
    got = {}
    for s in samplers:
        base = Path(out_dir) / s.name
        if not base.exists():
            continue
        for f in sorted(base.glob("*.msgpack")):
            for table, row in iter_backup_tables(f):
                key = (s.name, table if table is not None else f.stem)
                got.setdefault(key, []).append(row)
    return got


def _drive(kind, out_dir, ticks, burst_rows, publish_seed=None):
    samplers, producer, sink = _mk_arm(kind, out_dir)
    is_seed = kind == "seed"
    for tick in range(ticks):
        _append_tick(samplers, tick)
        producer.publish()
    # burst then one draining publish
    for i in range(burst_rows):
        samplers[0].db.add_record("step_time", _step_row(10**6 + i))
    producer.publish()
    # final force flush so both backups hold the full stream
    if is_seed:
        producer.publish(force_flush=True)
    else:
        producer.publish(final=True)
    return samplers, sink


def _golden(tmp):
    seed_dir, new_dir = tmp / "g_seed", tmp / "g_new"
    seed_samplers, seed_sink = _drive("seed", seed_dir, 40, 200)
    new_samplers, new_sink = _drive("new", new_dir, 40, 200)

    seed_envs = _decoded_envelopes(seed_sink)
    new_envs = _decoded_envelopes(new_sink)
    assert len(seed_envs) == len(new_envs), (len(seed_envs), len(new_envs))
    for (sm, st), (nm, nt) in zip(seed_envs, new_envs):
        assert sm == nm, (sm, nm)
        assert st == nt
    assert _backup_rows(seed_dir, seed_samplers) == _backup_rows(
        new_dir, new_samplers
    )
    return len(seed_envs)


# -- timed regimes -------------------------------------------------------


def _time_steady(kind, out_dir):
    samplers, producer, _sink = _mk_arm(kind, out_dir)
    for tick in range(WARMUP_TICKS):
        _append_tick(samplers, tick)
        producer.publish()
    append_s = publish_s = 0.0
    for tick in range(WARMUP_TICKS, WARMUP_TICKS + STEADY_TICKS):
        t0 = time.perf_counter()
        _append_tick(samplers, tick)
        t1 = time.perf_counter()
        producer.publish()
        t2 = time.perf_counter()
        append_s += t1 - t0
        publish_s += t2 - t1
    return append_s, publish_s


def _time_burst(kind, out_dir):
    """(append_s, drain_s): the 3000 ``add_record`` calls and the ONE
    publish that drains them, timed separately — the accumulator moves
    transpose work into the append side, so folding the two together
    would hide that cost (and dilute the drain comparison)."""
    samplers, producer, _sink = _mk_arm(kind, out_dir)
    for tick in range(10):  # warm the same code paths, drained each tick
        _append_tick(samplers, tick)
        producer.publish()
    db = samplers[0].db
    t0 = time.perf_counter()
    for i in range(BURST_ROWS):
        db.add_record("step_time", _step_row(10**6 + i))
    t1 = time.perf_counter()
    producer.publish()
    t2 = time.perf_counter()
    return t1 - t0, t2 - t1


def _time_idle(kind, out_dir):
    samplers, producer, _sink = _mk_arm(kind, out_dir)
    # one real publish so cursors/accumulators are warm, buffers drained
    _append_tick(samplers, 0)
    producer.publish()
    if kind == "seed":
        producer.publish(force_flush=True)
    else:
        producer.publish(final=True)
    t0 = time.perf_counter()
    for _ in range(IDLE_TICKS):
        producer.publish()
    return time.perf_counter() - t0


def _best(fn, tmp, tag, key=None):
    """Min-of-REPEATS for BOTH arms, interleaved seed/new per repeat so
    host-speed drift during the run lands on the two arms symmetrically
    (running all of one arm then all of the other lets a slow spell
    inflate exactly one side of the ratio)."""
    seed_times, new_times = [], []
    for r in range(REPEATS):
        seed_times.append(fn("seed", tmp / f"{tag}_seed_{r}"))
        new_times.append(fn("new", tmp / f"{tag}_new_{r}"))
    if isinstance(seed_times[0], tuple):
        k = key or sum
        return min(seed_times, key=k), min(new_times, key=k)
    return min(seed_times), min(new_times)


def _run_case(tmp):
    envelopes = _golden(tmp)
    bench_common.emit(
        "rank_producer", "golden_envelopes", envelopes, "envelopes"
    )

    # steady best = lowest publish time (the metric under test);
    # burst best = lowest drain time
    (seed_append, seed_publish), (new_append, new_publish) = _best(
        _time_steady, tmp, "steady", key=lambda t: t[1]
    )
    (seed_bappend, seed_drain), (new_bappend, new_drain) = _best(
        _time_burst, tmp, "burst", key=lambda t: t[1]
    )
    seed_idle, new_idle = _best(_time_idle, tmp, "idle")

    us = 1e6
    r = {
        "seed_publish_us_per_tick": seed_publish / STEADY_TICKS * us,
        "new_publish_us_per_tick": new_publish / STEADY_TICKS * us,
        "publish_speedup": seed_publish / new_publish,
        "seed_tick_us": (seed_append + seed_publish) / STEADY_TICKS * us,
        "new_tick_us": (new_append + new_publish) / STEADY_TICKS * us,
        "tick_speedup": (seed_append + seed_publish)
        / (new_append + new_publish),
        "seed_burst_append_ms": seed_bappend * 1e3,
        "new_burst_append_ms": new_bappend * 1e3,
        "seed_burst_drain_ms": seed_drain * 1e3,
        "new_burst_drain_ms": new_drain * 1e3,
        "burst_speedup": seed_drain / new_drain,
        "seed_idle_us_per_tick": seed_idle / IDLE_TICKS * us,
        "new_idle_us_per_tick": new_idle / IDLE_TICKS * us,
        "idle_speedup": seed_idle / new_idle,
    }
    units = {
        "publish_speedup": "x",
        "tick_speedup": "x",
        "burst_speedup": "x",
        "idle_speedup": "x",
    }
    for metric, value in r.items():
        unit = units.get(
            metric, "us" if metric.endswith("_us_per_tick") or metric.endswith("_us") else "ms"
        )
        bench_common.emit("rank_producer", metric, value, unit)
    return r


def test_rank_producer_bench(tmp_path):
    r = _run_case(tmp_path)
    # conservative CI floors; acceptance numbers live in BENCH_LOCAL_r10
    assert r["publish_speedup"] >= 1.5, r
    assert r["burst_speedup"] >= 1.2, r
    assert r["idle_speedup"] >= 2.0, r
    assert r["tick_speedup"] >= 1.0, r


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        results = _run_case(Path(td))
    print(json.dumps(results, indent=2, sort_keys=True))
