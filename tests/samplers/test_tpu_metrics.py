"""libtpu monitoring reader (utils/tpu_metrics.py) against a fake
``libtpu.sdk.tpumonitoring`` — the real SDK only answers on local TPU
chips, so CI drives the parsing/gating contract through an injected
module (same technique as the torch_xla fakes)."""

import sys
import types

import pytest


@pytest.fixture()
def fake_tpumonitoring(monkeypatch):
    mon = types.ModuleType("libtpu.sdk.tpumonitoring")
    mon._metrics = {
        "duty_cycle_pct": ["87.5", "92.0"],
        "tensorcore_util": ["40.0", "41.5"],
        "hbm_capacity_usage": ["123456"],
    }
    mon.list_supported_metrics = lambda: list(mon._metrics)

    class _Metric:
        def __init__(self, data):
            self._data = data

        def data(self):  # the nanobind binding exposes data() as a method
            return self._data

    def get_metric(name):
        if name not in mon._metrics:
            raise KeyError(name)
        return _Metric(mon._metrics[name])

    mon.get_metric = get_metric
    sdk = types.ModuleType("libtpu.sdk")
    sdk.tpumonitoring = mon
    libtpu = types.ModuleType("libtpu")
    libtpu.sdk = sdk
    monkeypatch.setitem(sys.modules, "libtpu", libtpu)
    monkeypatch.setitem(sys.modules, "libtpu.sdk", sdk)
    monkeypatch.setitem(sys.modules, "libtpu.sdk.tpumonitoring", mon)
    return mon


def test_duty_cycle_parsed_per_chip(fake_tpumonitoring):
    from traceml_tpu.utils.tpu_metrics import TpuMetricsReader

    r = TpuMetricsReader()
    assert r.duty_cycle_by_device() == [87.5, 92.0]
    assert r.tensorcore_util_by_device() == [40.0, 41.5]


def test_unsupported_metric_returns_none(fake_tpumonitoring):
    from traceml_tpu.utils.tpu_metrics import TpuMetricsReader

    fake_tpumonitoring._metrics.pop("duty_cycle_pct")
    fake_tpumonitoring.list_supported_metrics = (
        lambda: list(fake_tpumonitoring._metrics)
    )
    r = TpuMetricsReader()
    assert r.duty_cycle_by_device() is None


def test_reader_degrades_on_broken_metric(fake_tpumonitoring):
    from traceml_tpu.utils.tpu_metrics import TpuMetricsReader

    def broken(name):
        raise RuntimeError("tpu went away")

    r = TpuMetricsReader()
    fake_tpumonitoring.get_metric = broken
    assert r.duty_cycle_by_device() is None  # degrades, never raises


def test_system_sampler_fills_utilization_from_duty_cycle(
    fake_tpumonitoring, monkeypatch
):
    """_device_rows stitches duty cycle onto the memory-backend rows."""
    from traceml_tpu.samplers import system_sampler as ss
    from traceml_tpu.utils.step_memory import FakeMemoryBackend

    sampler = ss.SystemSampler(
        memory_backend=FakeMemoryBackend([[
            {"device_id": 0, "device_kind": "TPU v5e",
             "current_bytes": 1, "peak_bytes": 1, "limit_bytes": 2},
            {"device_id": 1, "device_kind": "TPU v5e",
             "current_bytes": 1, "peak_bytes": 1, "limit_bytes": 2},
        ]]),
    )
    from traceml_tpu.utils.tpu_metrics import TpuMetricsReader

    sampler._tpu_metrics = TpuMetricsReader()  # bypass the jax gate
    rows = sampler._device_rows(ts=1.0)
    assert [r["utilization_pct"] for r in rows] == [87.5, 92.0]


def test_mismatched_duty_enumeration_attaches_nothing(
    fake_tpumonitoring, monkeypatch
):
    """libtpu enumerates the whole host; a process owning a subset must
    not inherit another process's chips' duty cycles positionally."""
    from traceml_tpu.samplers import system_sampler as ss
    from traceml_tpu.utils.step_memory import FakeMemoryBackend
    from traceml_tpu.utils.tpu_metrics import TpuMetricsReader

    sampler = ss.SystemSampler(
        memory_backend=FakeMemoryBackend([[
            {"device_id": 4, "device_kind": "TPU v5e",
             "current_bytes": 1, "peak_bytes": 1, "limit_bytes": 2},
        ]]),
    )
    sampler._tpu_metrics = TpuMetricsReader()  # fake answers 2 chips
    rows = sampler._device_rows(ts=1.0)
    assert [r["utilization_pct"] for r in rows] == [None]
