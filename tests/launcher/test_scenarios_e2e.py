"""Scenario acceptance: injected faults → expected verdicts, through the
full CLI pipeline (reference: the src/dev/demo DDP scripts are the
ground-truth precision/recall harness — SURVEY.md §4).

The multi-rank input-straggler case is the BASELINE.json
``ddp_minimal`` analogue: 4 rank processes, one with an injected input
delay, aggregated over TCP, diagnosed from the cross-rank window.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

SHIM = """
import sys
from traceml_tpu.dev.demo.scenarios import run_scenario
run_scenario({name!r}, steps={steps})
"""


def _run(tmp_path, name, steps, nprocs=1, extra_args=()):
    script = tmp_path / f"{name}.py"
    script.write_text(SHIM.format(name=name, steps=steps))
    logs = tmp_path / "logs"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [
            sys.executable, "-m", "traceml_tpu", "run",
            "--mode", "summary", "--logs-dir", str(logs),
            "--run-name", name, "--sampler-interval", "0.25",
            "--finalize-timeout", "45", "--nprocs", str(nprocs),
            *extra_args, str(script),
        ],
        env=env, capture_output=True, text=True, timeout=300, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # the logs dir holds session DIRS plus the cross-run baseline store
    # file (traceml_baselines.sqlite) — only directories are sessions
    session = next(p for p in logs.iterdir() if p.is_dir())
    payload = json.loads((session / "final_summary.json").read_text())
    return payload


def test_input_straggler_four_ranks(tmp_path):
    payload = _run(tmp_path, "input_straggler", steps=60, nprocs=4)
    primary = payload["primary_diagnosis"]
    assert primary["kind"] == "INPUT_STRAGGLER", primary
    assert primary["ranks"] == [3]
    # all four ranks reported
    assert payload["meta"]["topology"]["world_size"] == 4
    assert sorted(payload["meta"]["topology"]["ranks_seen"]) == [0, 1, 2, 3]


def test_recompile_storm_detected(tmp_path):
    payload = _run(tmp_path, "recompile", steps=60)
    kinds = {i["kind"] for i in payload["sections"]["step_time"]["issues"]}
    assert "COMPILE_BOUND" in kinds, kinds


def test_collective_straggler_four_ranks(tmp_path):
    payload = _run(tmp_path, "collective_straggler", steps=60, nprocs=4)
    st = payload["sections"]["step_time"]
    # the collective phase is measured for real (nonzero in the window)
    coll = (st["global"]["phases"] or {}).get("collective")
    assert coll and coll["median_ms"] > 5.0, st["global"]["phases"].keys()
    kinds = {i["kind"] for i in st["issues"]}
    assert "COLLECTIVE_STRAGGLER" in kinds, (st["diagnosis"], kinds)
    issue = next(i for i in st["issues"] if i["kind"] == "COLLECTIVE_STRAGGLER")
    assert issue["ranks"] == [3]


def test_input_bound_single_rank(tmp_path):
    payload = _run(tmp_path, "input_bound", steps=50)
    st = payload["sections"]["step_time"]
    assert st["diagnosis"]["kind"] == "INPUT_BOUND", st["diagnosis"]
    # occupancy corroborates: the chip idles while the host fetches
    occ = st["global"]["median_occupancy"]
    assert occ is None or occ < 0.9


# NOTE: no compute_straggler E2E here on purpose.  With 4 rank
# processes timesharing this CI host's single core, every rank's wall
# time is scheduler-dominated and the injected extra matmuls on one
# rank don't produce a reliable cross-rank signal.  The attribution
# math itself is unit-tested at scale in
# tests/diagnostics/test_step_time_threshold_matrix.py.


def test_memory_creep_scenario_grows(tmp_path):
    # 80 steps is far below the 800-row creep gate — the E2E asserts the
    # GROWTH is visible in the summary (the rule's threshold matrix is
    # unit-tested at scale).  The fast MLP steps also outpace the
    # memory sampler's 0.2 s throttle, so only a handful of rows exist:
    # growth is the robust signal, windowed trend needs ≥25 rows.
    payload = _run(tmp_path, "memory_creep", steps=80)
    sm = payload["sections"]["step_memory"]
    assert sm["status"] == "OK"
    rank0 = sm["global"]["per_rank"]["0"]
    assert (rank0["growth_bytes"] or 0) > 20 << 20, rank0  # ≥20 MiB leaked


def test_checkpoint_stall_phase_measured(tmp_path):
    payload = _run(tmp_path, "checkpoint_stall", steps=40)
    phases = payload["sections"]["step_time"]["global"]["phases"]
    ckpt = phases.get("checkpoint")
    assert ckpt and ckpt["median_ms"] is not None, phases.keys()
    # the save happens every 5th step; window medians are over per-rank
    # AVERAGES so the phase is present with a nonzero mean
    assert ckpt["mean_ms"] > 0, ckpt


def test_comm_bound_collectives_section(tmp_path):
    # every rank's gradient sync is a slow host-blocking all-reduce —
    # the collectives domain (fallback recorders, no profiler) must
    # produce a populated section with the per-step overlap series and
    # call the run COMM_BOUND
    payload = _run(tmp_path, "comm_bound", steps=40)
    sec = payload["sections"]["collectives"]
    assert sec["status"] == "OK", sec
    g = sec["global"]
    assert g["n_steps"] >= 10, g
    # a fully exposed sync: low overlap efficiency, all_reduce present
    assert g["overlap_efficiency"] < 0.5, g
    assert "all_reduce" in g["per_op"], g["per_op"].keys()
    series = g["overlap_efficiency_series"]
    assert series and len(series) == len(g["series_steps"])
    assert all(0.0 <= v <= 1.0 for v in series)
    assert sec["diagnosis"]["kind"] == "COMM_BOUND", sec["diagnosis"]
    # the compute-only scenarios must stay silent on this rule — pinned
    # by test_healthy_not_misdiagnosed below via the primary check
    assert sec["diagnosis"]["severity"] in ("warning", "critical")


def test_healthy_not_misdiagnosed(tmp_path):
    payload = _run(tmp_path, "healthy", steps=60)
    primary = payload["primary_diagnosis"]
    # The healthy scenario must not trip any INJECTED-fault verdict.
    # Environment findings (e.g. HIGH_HOST_CPU on a saturated CI box)
    # are legitimate observations, not misdiagnoses.
    assert primary["kind"] not in (
        "INPUT_BOUND",
        "INPUT_STRAGGLER",
        "COMPUTE_STRAGGLER",
        "COMPILE_BOUND",
        "MEMORY_CREEP_EARLY",
        "MEMORY_CREEP_CONFIRMED",
        "COMM_BOUND",
        "POOR_OVERLAP",
        # liveness: a healthy run where every rank finishes cleanly must
        # never read as a dead or preempted world
        "RANK_LOST",
        "LIKELY_PREEMPTED",
    ), primary
    st_primary = payload["sections"]["step_time"]["diagnosis"]
    assert st_primary["kind"] in (
        "COMPUTE_BOUND",
        "NO_CLEAR_PERFORMANCE_BOTTLENECK",
        "RESIDUAL_HEAVY",  # tiny models on CPU have real dispatch residue
        "HEALTHY",
        "INSUFFICIENT_STEP_TIME_DATA",
    ), st_primary
