"""The bench's device-timing physicality gate.

A tunneled PJRT client can report buffers ready on enqueue, which makes
``block_until_ready`` a no-op and turns "step time" into dispatch
throughput — the resulting overhead ratio is tunnel-latency noise, not a
tracer measurement.  ``bench.py`` refuses to certify any device timing
whose implied FLOP/s exceeds what one chip can physically sustain.
"""

import bench


class _Leaf:
    def __init__(self, size):
        self.size = size


class _State:
    def __init__(self, n_params):
        self.params = {"w": _Leaf(n_params)}


def test_impossible_throughput_rejected():
    # 150M params, 8192 tokens → ~7.4 TFLOP/step; 5 ms (ABOVE the
    # min-step floor, so this exercises the FLOP/s branch, not the
    # floor) implies ~1.5 PFLOP/s — past any single chip
    flops = bench._step_flops(_State(150_000_000), [_Batch(16, 512)])
    assert flops == 6.0 * 150_000_000 * 16 * 512
    assert 5e-3 >= bench._DEVICE_MIN_STEP_S
    assert not bench._device_measurement_physical(5e-3, flops)


def test_realistic_throughput_accepted():
    # the same step at 40 ms implies ~185 TFLOP/s — a real chip
    flops = bench._step_flops(_State(150_000_000), [_Batch(16, 512)])
    assert bench._device_measurement_physical(40e-3, flops)


def test_sub_floor_steps_rejected_even_if_flops_ok():
    # tiny model, tiny step: physically possible FLOP/s but far below
    # the noise floor where a % overhead claim means anything
    flops = bench._step_flops(_State(1_000), [_Batch(1, 8)])
    assert not bench._device_measurement_physical(1e-3, flops)


class _Batch:
    def __init__(self, b, s):
        self.shape = (b, s)


def test_short_step_summary_shape():
    """Both backends publish the short lane through one helper — the
    schema (and steps_per_arm bookkeeping) cannot diverge (review r5)."""
    su = [0.0120, 0.0121, 0.0119]
    st = [0.0123, 0.0124, 0.0122]
    sd = [(t - u) / u * 100.0 for u, t in zip(su, st)]
    out = bench._short_step_summary(su, st, sd, steps_per_arm=128)
    assert set(out) == {
        "untraced_ms", "traced_ms", "median_delta_pct", "ci95_pct",
        "pairs", "steps_per_arm",
    }
    assert out["pairs"] == 3 and out["steps_per_arm"] == 128
    assert out["untraced_ms"] == 12.0
    assert out["ci95_pct"][0] <= out["median_delta_pct"] <= out["ci95_pct"][1]


def test_short_lane_gate_drops_fake_readiness():
    """The short lane's certification gate: dispatch-throughput 'steps'
    from a non-waiting tunnel (observed ~60 µs) are dropped; real
    dispatch-bound on-chip steps (~1 ms) and the CPU proxy pass."""
    # fake-readiness: one sub-floor sample poisons the lane
    assert not bench._short_lane_certified([1.2e-3, 60e-6, 1.1e-3], "tpu")
    # real on-chip dispatch-bound steps certify
    assert bench._short_lane_certified([1.2e-3, 1.0e-3, 1.1e-3], "tpu")
    # empty lane never certifies on device
    assert not bench._short_lane_certified([], "tpu")
    # the CPU proxy has no tunnel to lie to it — always certified
    assert bench._short_lane_certified([60e-6], "cpu")
