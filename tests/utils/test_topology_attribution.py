"""Topology attribution: reduction goldens, attribution fixtures, and
the back-compat pin (docs/developer_guide/topology-attribution.md).

Three contracts pinned here:

* ``reduce_cube`` is **bit-equal** to ``reduce_cube_reference`` (the
  scalar left-fold in ascending-rank order) for every aggregate, on
  ragged cubes with missing ranks and missing steps;
* ``attribute_ranks`` names the right physical structure on the four
  canonical fixtures — host outlier, DCN boundary side, model-axis
  shard imbalance, and unstructured noise (flat fallback: None);
* a session with NO mesh topology produces **byte-identical** diagnosis
  payloads to the pre-topology contract: ``to_dict`` has no
  ``attribution`` key, ``topology()`` has no ``"mesh"`` key, and the
  serialized step-time result is unchanged.
"""

import json
import random
import time

import numpy as np
import pytest

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.diagnostics.attribution import attach_attribution
from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
    SEVERITY_WARNING,
    STATUS_ISSUE,
)
from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows
from traceml_tpu.reporting.loaders import load_mesh_topology
from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    TelemetryEnvelope,
    build_telemetry_envelope,
)
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.columnar import reduce_window_by_grouping
from traceml_tpu.utils.step_time_window import (
    STEP_KEY,
    build_step_time_window,
)
from traceml_tpu.utils.topology import (
    AxisInfo,
    Grouping,
    MeshTopology,
    _coords_for_rank,
    attribute_ranks,
    candidate_groupings,
    capture_local_topology,
    parse_mesh_spec,
    reduce_cube,
    reduce_cube_reference,
    topology_from_rank_rows,
)


# -- fixtures ------------------------------------------------------------


def _mesh(spec, world, hosts_of=None, hostnames=None):
    axes = parse_mesh_spec(spec)
    assert axes, spec
    sizes = [a.size for a in axes]
    return MeshTopology(
        axes=axes,
        rank_coords={r: tuple(_coords_for_rank(r, sizes)) for r in range(world)},
        rank_hosts={r: (hosts_of(r) if hosts_of else 0) for r in range(world)},
        rank_hostnames=hostnames or {},
        source="env",
    )


def _step_row(step, ms):
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "clock": "host",
        "events": {T.STEP_TIME: {"cpu_ms": ms, "count": 1}},
    }


# -- reduction goldens ---------------------------------------------------


def _assert_bitwise(fast, ref):
    for key in ("sum", "count", "mean", "min", "max"):
        assert np.array_equal(fast[key], ref[key], equal_nan=True), key


def test_reduce_cube_matches_reference_bitwise_ragged():
    rng = np.random.default_rng(1234)
    r, s, g = 17, 23, 5
    cube = rng.uniform(1.0, 250.0, size=(r, s))
    group_index = rng.integers(0, g, size=r)
    mask = rng.random((r, s)) > 0.2
    mask[3, :] = False  # a rank with no data at all
    mask[:, 7] = False  # a step missing on every rank
    _assert_bitwise(
        reduce_cube(cube, group_index, g, mask=mask),
        reduce_cube_reference(cube, group_index, g, mask=mask),
    )
    # dense path too (mask=None)
    _assert_bitwise(
        reduce_cube(cube, group_index, g),
        reduce_cube_reference(cube, group_index, g),
    )


def test_reduce_cube_accumulation_order_is_rank_ascending():
    # values chosen so pairwise summation would differ from the
    # left-fold: tiny + huge + tiny loses the tiny terms in a different
    # order than (tiny + huge) + tiny
    cube = np.array([[1e-16], [1.0], [1e-16], [-1.0]])
    gi = np.zeros(4, dtype=np.int64)
    fast = reduce_cube(cube, gi, 1)
    ref = reduce_cube_reference(cube, gi, 1)
    assert fast["sum"][0, 0] == ref["sum"][0, 0]


def test_reduce_cube_empty_group_markers():
    cube = np.array([[1.0, 2.0], [3.0, 4.0]])
    red = reduce_cube(cube, np.array([0, 0]), 2)
    assert np.isnan(red["mean"][1]).all()
    assert (red["min"][1] == np.inf).all()
    assert (red["max"][1] == -np.inf).all()
    assert (red["count"][1] == 0).all()


def test_reduce_window_by_grouping_scalar_window():
    rank_rows = {
        r: [_step_row(s, 100.0 + (40.0 if r >= 2 else 0.0)) for s in range(8)]
        for r in range(4)
    }
    w = build_step_time_window(rank_rows, max_steps=8)
    topo = _mesh("data:2@dcn,fsdp:2", world=4)
    groupings = {g.kind: g for g in candidate_groupings(topo, list(range(4)))}
    out = reduce_window_by_grouping(w, groupings["dcn_side"], key=STEP_KEY)
    assert out["kind"] == "dcn_side" and out["axis"] == "data"
    assert [g["ranks"] for g in out["groups"]] == [[0, 1], [2, 3]]
    assert out["dispersion"] == pytest.approx([40.0] * 8)
    # the orthogonal axis mixes fast+slow into every group: no spread
    flat = reduce_window_by_grouping(w, groupings["axis"], key=STEP_KEY)
    assert flat["dispersion"] == pytest.approx([0.0] * 8)


def test_reduce_window_by_grouping_masks_unplaced_ranks():
    rank_rows = {
        r: [_step_row(s, 100.0 + r) for s in range(4)] for r in range(3)
    }
    w = build_step_time_window(rank_rows, max_steps=4)
    part = Grouping(kind="host", label="host", axis=None,
                    groups={0: [0], 1: [1]})  # rank 2 unplaced
    out = reduce_window_by_grouping(w, part, key=STEP_KEY)
    assert [g["ranks"] for g in out["groups"]] == [[0], [1]]
    assert out["groups"][0]["mean"] == pytest.approx([100.0] * 4)
    assert out["groups"][1]["mean"] == pytest.approx([101.0] * 4)


# -- capture -------------------------------------------------------------


def test_parse_mesh_spec_grammar():
    axes = parse_mesh_spec("data:4@dcn, fsdp:8")
    assert [(a.name, a.size, a.kind) for a in axes] == [
        ("data", 4, "dcn"), ("fsdp", 8, "ici"),
    ]
    # all-or-nothing on any malformed entry
    assert parse_mesh_spec("data:4,bogus") == []
    assert parse_mesh_spec("data:0") == []
    assert parse_mesh_spec("data:4@wat") == []
    assert parse_mesh_spec("") == []


def test_capture_local_topology_env_override(monkeypatch):
    from traceml_tpu.utils.topology import reset_recorded_mesh_for_tests

    # a prior test's make_mesh may have latched a process-global Mesh
    reset_recorded_mesh_for_tests()
    monkeypatch.setenv("TRACEML_MESH", "data:2@dcn,fsdp:2")
    payload = capture_local_topology(global_rank=3, world_size=4)
    assert payload["source"] == "env"
    assert payload["coords"] == [1, 1]  # row-major placement
    assert [a["kind"] for a in payload["axes"]] == ["dcn", "ici"]
    monkeypatch.setenv("TRACEML_MESH", "broken")
    assert capture_local_topology(0, 4) is None  # no recorded mesh either


# -- attribution fixtures ------------------------------------------------


def test_attribution_host_outlier():
    topo = _mesh(
        "data:2,fsdp:4", world=8, hosts_of=lambda r: r // 4,
        hostnames={4: "tpu-host-b"},
    )
    values = {r: 100.0 + (35.0 if r >= 4 else 0.0) for r in range(8)}
    attr = attribute_ranks(values, topo)
    assert attr is not None
    assert attr.kind == "host" and attr.ranks == [4, 5, 6, 7]
    assert attr.label == "all 4 ranks of host 1 (tpu-host-b)"
    assert attr.explained >= 0.99


def test_attribution_dcn_boundary_side():
    # single host: the host grouping never forms, the DCN axis explains
    topo = _mesh("data:2@dcn,fsdp:4", world=8)
    values = {r: 100.0 + (35.0 if r >= 4 else 0.0) for r in range(8)}
    attr = attribute_ranks(values, topo)
    assert attr is not None
    assert attr.kind == "dcn_side" and attr.axis == "data"
    assert attr.ranks == [4, 5, 6, 7] and attr.group == "1"
    assert "DCN boundary" in attr.label


def test_attribution_model_axis_imbalance():
    topo = _mesh("data:2,model:4", world=8)
    # model coord 2 (ranks 2 and 6) runs hot — an ICI-axis shard issue
    values = {r: 100.0 for r in range(8)}
    values[2] = values[6] = 160.0
    attr = attribute_ranks(values, topo)
    assert attr is not None
    assert attr.kind == "axis" and attr.axis == "model"
    assert attr.ranks == [2, 6]
    assert "shard imbalance" in attr.label


def test_attribution_flat_fallback_on_noise():
    topo = _mesh("data:2@dcn,fsdp:4", world=8, hosts_of=lambda r: r // 4)
    rng = random.Random(7)
    # one hot rank only: no grouping explains >= 60% of the variance
    values = {r: 100.0 + rng.uniform(-1, 1) for r in range(8)}
    values[5] = 180.0
    attr = attribute_ranks(values, topo)
    assert attr is None


def test_attribution_tie_breaks_toward_host():
    # host boundary == DCN boundary: both explain 100%; host is listed
    # first and ties break on strictly-greater, so host wins
    topo = _mesh("data:2@dcn,fsdp:4", world=8, hosts_of=lambda r: r // 4)
    values = {r: 100.0 + (35.0 if r >= 4 else 0.0) for r in range(8)}
    attr = attribute_ranks(values, topo)
    assert attr is not None and attr.kind == "host"


def test_attribution_degenerate_inputs():
    topo = _mesh("data:2,fsdp:2", world=4)
    assert attribute_ranks({}, topo) is None
    assert attribute_ranks({0: 1.0, 1: 2.0}, topo) is None  # < 3 ranks
    assert attribute_ranks({r: 5.0 for r in range(4)}, topo) is None  # no spread
    assert attribute_ranks({r: float(r) for r in range(4)}, None) is None


# -- attach_attribution --------------------------------------------------


def _result(ranks, summary="Rank skew detected"):
    return DiagnosticResult(
        domain="step_time",
        issues=[
            DiagnosticIssue(
                kind="COMPUTE_STRAGGLER",
                severity=SEVERITY_WARNING,
                status=STATUS_ISSUE,
                summary=summary,
                ranks=list(ranks),
            )
        ],
    )


def test_attach_attribution_annotates_subset_issue():
    topo = _mesh("data:2@dcn,fsdp:4", world=8)
    values = {r: 100.0 + (35.0 if r >= 4 else 0.0) for r in range(8)}
    result = attach_attribution(_result([4, 5, 6, 7]), topo, values)
    issue = result.diagnosis
    assert issue.attribution is not None
    assert issue.attribution["kind"] == "dcn_side"
    assert issue.summary.endswith(f"— {issue.attribution['label']}.")
    d = issue.to_dict()
    assert d["attribution"]["ranks"] == [4, 5, 6, 7]


def test_attach_attribution_skips_issue_outside_group():
    topo = _mesh("data:2@dcn,fsdp:4", world=8)
    values = {r: 100.0 + (35.0 if r >= 4 else 0.0) for r in range(8)}
    # issue blames rank 0 — the grouping explains ranks 4..7, not it
    result = attach_attribution(_result([0]), topo, values)
    assert result.diagnosis.attribution is None


def test_attach_attribution_none_topology_is_identity():
    result = _result([1, 2])
    before = json.dumps(result.to_dict(), sort_keys=True)
    out = attach_attribution(result, None, {1: 2.0, 2: 3.0})
    assert out is result
    assert json.dumps(out.to_dict(), sort_keys=True) == before


# -- back-compat pins ----------------------------------------------------


def test_issue_to_dict_omits_attribution_when_none():
    d = DiagnosticIssue(kind="X", summary="s").to_dict()
    assert "attribution" not in d
    assert "confidence_label" in d


def test_diagnose_without_topology_is_byte_identical():
    rng = random.Random(11)
    rank_rows = {
        r: [
            _step_row(s, 100.0 + (45.0 if r == 3 else 0.0) + rng.uniform(0, 1))
            for s in range(1, 61)
        ]
        for r in range(4)
    }
    base = json.dumps(
        diagnose_rank_rows(rank_rows, mode="summary").to_dict(), sort_keys=True
    )
    again = json.dumps(
        diagnose_rank_rows(rank_rows, mode="summary", topology=None).to_dict(),
        sort_keys=True,
    )
    assert base == again
    assert '"attribution"' not in base


def test_diagnose_with_topology_only_adds_attribution():
    rng = random.Random(11)
    rank_rows = {
        r: [
            _step_row(s, 100.0 + (45.0 if r >= 2 else 0.0) + rng.uniform(0, 1))
            for s in range(1, 61)
        ]
        for r in range(4)
    }
    topo = _mesh("data:2@dcn,fsdp:2", world=4)
    result = diagnose_rank_rows(rank_rows, mode="summary", topology=topo)
    attributed = [i for i in result.issues if i.attribution]
    assert attributed, [i.kind for i in result.issues]
    assert all(i.attribution["kind"] == "dcn_side" for i in attributed)
    # stripping the new fields recovers the flat result exactly
    flat = diagnose_rank_rows(rank_rows, mode="summary")
    stripped = json.loads(json.dumps(result.to_dict()))
    for issue in [stripped["diagnosis"], *stripped["issues"]]:
        if "attribution" in issue:
            label = issue.pop("attribution")["label"]
            assert issue["summary"].endswith(f"— {label}.")
            issue["summary"] = issue["summary"][: -len(f" — {label}.")]
    assert json.dumps(stripped, sort_keys=True) == json.dumps(
        flat.to_dict(), sort_keys=True
    )


# -- store / DB round-trip ----------------------------------------------


def _ident(rank=0, node=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank % 4,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=100 + rank,
    )


def _mesh_envelope(rank, coords, axes, node=0, world=4, source="env"):
    """The aggregator-side re-wrap of a MESH_TOPOLOGY control message
    (trace_aggregator._handle_control): identity meta minus seq, one
    row in the ``mesh_topology`` table."""
    meta = _ident(rank, node=node, world=world).to_meta()
    meta.pop("seq", None)
    meta["sampler"] = "mesh_topology"
    row = {
        "timestamp": time.time(),
        "source": source,
        "axes_json": json.dumps(axes),
        "coords_json": json.dumps(coords),
    }
    return TelemetryEnvelope(meta=meta, tables={"mesh_topology": [row]})


_AXES_2X2 = [
    {"name": "data", "size": 2, "kind": "dcn"},
    {"name": "fsdp", "size": 2, "kind": "ici"},
]


def test_store_without_mesh_rows_has_no_mesh_key(tmp_path):
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=20)
    w.ingest(
        build_telemetry_envelope(
            "step_time",
            {"step_time": [_step_row(s, 100.0) for s in range(5)]},
            _ident(0),
        )
    )
    assert w.force_flush()
    store.refresh()
    topo = store.topology()
    assert "mesh" not in topo
    assert store.mesh_topology() is None
    w.finalize()


def test_store_merges_mesh_rows_keep_latest(tmp_path):
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=20)
    for rank in range(4):
        w.ingest(_mesh_envelope(rank, _coords_for_rank(rank, [2, 2]),
                                _AXES_2X2, node=rank // 2))
    # rank 0 republishes (spool replay): latest row wins, still 4 ranks
    w.ingest(_mesh_envelope(0, [0, 0], _AXES_2X2, node=0))
    assert w.force_flush()
    store.refresh()
    topo = store.mesh_topology()
    assert topo is not None
    assert sorted(topo.rank_coords) == [0, 1, 2, 3]
    assert topo.rank_coords[3] == (1, 1)
    assert topo.rank_hosts == {0: 0, 1: 0, 2: 1, 3: 1}
    assert [a.kind for a in topo.axes] == ["dcn", "ici"]
    meta = store.topology()
    assert meta["mesh"]["ranks"] == 4 and meta["mesh"]["hosts"] == 2
    # one-shot loader sees the same merged view
    w.finalize()
    loaded = load_mesh_topology(db)
    assert loaded is not None
    assert loaded.rank_coords == topo.rank_coords


def test_loader_returns_none_for_pre_topology_db(tmp_path):
    import sqlite3

    db = tmp_path / "old.sqlite"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE step_time_samples (id INTEGER PRIMARY KEY)")
    conn.commit()
    conn.close()
    assert load_mesh_topology(db) is None


def test_topology_from_rank_rows_skips_malformed():
    rows = [
        {"global_rank": 0, "node_rank": 0, "hostname": "h0",
         "source": "env", "axes_json": json.dumps(_AXES_2X2),
         "coords_json": json.dumps([0, 0])},
        {"global_rank": 1, "node_rank": 0, "hostname": "h0",
         "source": "env", "axes_json": "not json", "coords_json": "[0,1]"},
    ]
    topo = topology_from_rank_rows(rows)
    assert topo is not None
    assert sorted(topo.rank_coords) == [0]


def test_payload_round_trip():
    topo = _mesh("data:2@dcn,fsdp:4", world=8, hosts_of=lambda r: r // 4)
    back = MeshTopology.from_payload(topo.to_payload())
    assert back is not None
    assert back.rank_coords == topo.rank_coords
    assert back.rank_hosts == topo.rank_hosts
    assert [a.to_dict() for a in back.axes] == [a.to_dict() for a in topo.axes]
