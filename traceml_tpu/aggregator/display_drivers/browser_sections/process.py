"""Process section (reference role: nicegui_sections/
process_section.py — per-rank process table + rollup KPIs).

Client-side rollups (busiest-rank highlight, total RSS, p95 cpu) are
presentation math over the renderer payload; imbalance verdicts stay
with the diagnosis engine.
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">Processes</h2><span class="sp"></span>
  <span id="proc-badge"></span></div>
<div class="kpis" id="proc-kpis" style="margin:.1rem 0 .6rem"></div>
<div id="process"></div>
"""

_JS = r"""
let procBuilt=false;
function buildProc(){
  document.getElementById("proc-kpis").innerHTML=
    kpiTile("proc-cpu","P95 CPU","var(--accent)")+
    kpiTile("proc-rss","TOTAL RSS","var(--violet)")+
    kpiTile("proc-busy","BUSIEST","#16a085");
  procBuilt=true}
function render_process(d){
  if(!procBuilt)buildProc();
  const p=d.process;badge("proc-badge",d.ts,p&&p.latest_ts);
  const el=document.getElementById("process");
  if(!p||!p.ranks||!p.ranks.length){
    el.innerHTML='<span class="muted">no process telemetry</span>';return}
  const cpus=p.ranks.map(s=>s.cpu_pct).filter(v=>v!=null).sort((a,b)=>a-b);
  const p95=cpus.length?cpus[Math.min(cpus.length-1,
    Math.floor(0.95*(cpus.length-1)))]:null;
  setKpi("proc-cpu",p95==null?null:p95.toFixed(0),"%");
  setKpi("proc-rss",fmtB(p.total_rss_bytes).split(" ")[0],
    fmtB(p.total_rss_bytes).split(" ")[1]);
  setKpi("proc-busy",p.busiest_rank==null?null:"r"+p.busiest_rank,"");
  let rows=`<table><tr><th class="num">rank</th><th>host</th><th class="num">pid</th>
    <th class="num">cpu</th><th class="num">rss</th><th class="num">threads</th><th></th></tr>`;
  for(const s of p.ranks){
    const hot=s.rank===p.busiest_rank?' style="color:#ffd27f"':"";
    rows+=`<tr><td class="num">${esc(s.rank)}</td><td>${esc(s.hostname)}</td>
      <td class="num">${esc(s.pid==null?"—":s.pid)}</td>
      <td class="num"${hot}>${s.cpu_pct==null?"n/a":s.cpu_pct.toFixed(0)+"%"}</td>
      <td class="num">${fmtB(s.rss_bytes)}</td>
      <td class="num">${esc(s.num_threads==null?"—":s.num_threads)}</td>
      <td>${s.stale?'<span class="badge stale">stale</span>':""}</td></tr>`}
  el.innerHTML=rows+"</table>"}
"""

SECTION = Section(
    id="process",
    title="Processes",
    html=_HTML,
    js=_JS,
    contract=(
        "ts",
        "process.latest_ts",
        "process.ranks.rank",
        "process.ranks.hostname",
        "process.ranks.pid",
        "process.ranks.cpu_pct",
        "process.ranks.rss_bytes",
        "process.ranks.num_threads",
        "process.ranks.stale",
        "process.busiest_rank",
        "process.total_rss_bytes",
    ),
)
