"""Fake inference loop driving the serving telemetry domain end to end:

    traceml-tpu run --mode summary examples/serve_demo.py healthy
    traceml-tpu run --mode summary examples/serve_demo.py saturated

No model, no accelerator — the point is the telemetry path: the five
request-lifecycle recorders feed the serving sampler, the aggregator
folds per-window aggregates, and the final summary gains a
``sections.serving`` block with TTFT percentiles, the prefill/decode
split, and per-replica tokens/s.

``healthy``:   one arrival per serviced request with idle slack — the
               queue drains every loop and the diagnosis stays quiet.
``saturated``: three arrivals per serviced request — the backlog grows
               for the whole run and QUEUE_SATURATED fires (critical:
               arrival rate exceeds service rate, TTFT is queue wait).

Deterministic by construction: fixed arrival ratio, fixed per-phase
sleeps, no randomness — CI asserts on the resulting summary.
"""

import sys
import time

import traceml_tpu

scenario = (sys.argv[1] if len(sys.argv) > 1 else "healthy").strip().lower()
if scenario not in ("healthy", "saturated"):
    raise SystemExit(f"unknown scenario {scenario!r} (healthy|saturated)")

traceml_tpu.init(mode="auto")

DURATION_S = 9.0       # ~9 one-second sampler windows per run
PROMPT_TOKENS = 128
PREFILL_S = 0.02       # fake prefill: one sleep, then the first token
DECODE_TOKENS = 16     # fake decode loop: one token per sleep
DECODE_TOKEN_S = 0.002

ARRIVALS_PER_LOOP = 3 if scenario == "saturated" else 1
IDLE_S = 0.0 if scenario == "saturated" else 0.03

next_id = 0
queue = []
served = 0
t_end = time.time() + DURATION_S
while time.time() < t_end:
    for _ in range(ARRIVALS_PER_LOOP):
        rid = f"req-{next_id}"
        next_id += 1
        traceml_tpu.record_request_enqueued(rid)
        queue.append(rid)
    rid = queue.pop(0)
    traceml_tpu.record_prefill_start(rid, prompt_tokens=PROMPT_TOKENS)
    time.sleep(PREFILL_S)
    traceml_tpu.record_prefill_end(rid)
    for _ in range(DECODE_TOKENS):
        time.sleep(DECODE_TOKEN_S)
        traceml_tpu.record_decode_token(rid)
    traceml_tpu.record_request_finished(rid)
    served += 1
    if IDLE_S:
        time.sleep(IDLE_S)

print(f"serve_demo[{scenario}]: {served} served, {len(queue)} still queued")
