import time

import pytest

from traceml_tpu.sdk import state as state_mod
from traceml_tpu.sdk.instrumentation import trace_step, trace_time
from traceml_tpu.utils import timing
from traceml_tpu.utils.step_memory import FakeMemoryBackend, StepMemoryTracker
from traceml_tpu.utils.timing import (
    DATALOADER_NEXT,
    GLOBAL_STEP_QUEUE,
    STEP_TIME,
    drain_step_memory_rows,
)


@pytest.fixture(autouse=True)
def fresh_state():
    st = state_mod.reset_state_for_tests()
    st.mem_tracker = StepMemoryTracker(
        FakeMemoryBackend(
            [[{"device_id": 0, "device_kind": "fake", "current_bytes": 100,
               "peak_bytes": 120, "limit_bytes": 1000}]]
        )
    )
    GLOBAL_STEP_QUEUE.drain()
    drain_step_memory_rows()
    yield st
    GLOBAL_STEP_QUEUE.drain()
    drain_step_memory_rows()


def test_trace_step_advances_counter_and_flushes(fresh_state):
    st = fresh_state
    with trace_step():
        time.sleep(0.005)
    assert st.current_step == 1
    batches = GLOBAL_STEP_QUEUE.drain()
    assert len(batches) == 1
    names = [e.name for e in batches[0].events]
    assert STEP_TIME in names
    step_ev = next(e for e in batches[0].events if e.name == STEP_TIME)
    assert step_ev.cpu_ms >= 5


def test_trace_step_emits_memory_rows(fresh_state):
    with trace_step():
        pass
    rows = drain_step_memory_rows()
    assert len(rows) == 1
    assert rows[0]["step"] == 1
    assert rows[0]["current_bytes"] == 100
    assert rows[0]["backend"] == "fake"


def test_nested_trace_step_is_inert(fresh_state):
    st = fresh_state
    with trace_step():
        with trace_step():
            pass
    assert st.current_step == 1
    assert len(GLOBAL_STEP_QUEUE.drain()) == 1


def test_trace_step_never_raises_with_broken_memtracker(fresh_state):
    st = fresh_state

    class Boom:
        def reset(self, step):
            raise RuntimeError("boom")

        def record(self, step):
            raise RuntimeError("boom")

    st.mem_tracker = Boom()
    with trace_step():
        pass  # must not raise
    assert st.current_step == 1


def test_trace_time_user_region(fresh_state):
    with trace_step():
        with trace_time("tokenize"):
            time.sleep(0.002)
    batch = GLOBAL_STEP_QUEUE.drain()[0]
    names = [e.name for e in batch.events]
    assert "user:tokenize" in names


def test_exception_propagates_but_flushes(fresh_state):
    st = fresh_state
    with pytest.raises(ValueError):
        with trace_step():
            raise ValueError("user error")
    assert st.current_step == 1
    assert len(GLOBAL_STEP_QUEUE.drain()) == 1
    assert not st.tls.in_step  # gate released


def test_dataloader_wrapper_times_next(fresh_state):
    from traceml_tpu.instrumentation.dataloader import wrap_dataloader

    def slow_gen():
        for i in range(3):
            time.sleep(0.004)
            yield i

    st = fresh_state
    items = []
    loader = wrap_dataloader(slow_gen())
    it = iter(loader)
    with trace_step():
        items.append(next(it))
    with trace_step():
        items.append(next(it))
    assert items == [0, 1]
    batches = GLOBAL_STEP_QUEUE.drain()
    dl_events = [
        e for b in batches for e in b.events if e.name == DATALOADER_NEXT
    ]
    assert len(dl_events) == 2
    assert all(e.cpu_ms >= 3 for e in dl_events)


def test_wrap_dataloader_duplicate_guard(fresh_state):
    from traceml_tpu.instrumentation.dataloader import wrap_dataloader

    inner = wrap_dataloader([1, 2, 3])
    outer = wrap_dataloader(inner)
    assert outer is inner
    assert list(outer) == [1, 2, 3]
