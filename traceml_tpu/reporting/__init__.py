"""Final reporting (reference: src/traceml_ai/reporting/)."""
