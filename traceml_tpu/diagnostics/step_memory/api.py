"""Step-memory diagnosis entrypoint
(reference: src/traceml_ai/diagnostics/step_memory/api.py:136-754)."""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from traceml_tpu.diagnostics.common import DiagnosticResult, run_rules
from traceml_tpu.diagnostics.step_memory.policy import DEFAULT_POLICY, StepMemoryPolicy
from traceml_tpu.diagnostics.step_memory.rules import (
    DEFAULT_RULES,
    build_memory_context,
    build_memory_context_from_columns,
)
from traceml_tpu.utils.columnar import MemoryColumns

DOMAIN = "step_memory"


def diagnose_rank_rows(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    ctx = build_memory_context(rank_rows, policy)
    result = run_rules(DOMAIN, DEFAULT_RULES, ctx)
    return _attribute(result, topology, {
        rank: float(
            (rows[-1].get("step_peak_bytes") or 0)
            or (rows[-1].get("current_bytes") or 0)
        )
        for rank, rows in rank_rows.items()
        if rows
    })


def diagnose_columns(
    rank_columns: Mapping[int, MemoryColumns],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    """Columnar fast path: diagnose straight from the snapshot store's
    per-rank memory ring buffers (no row-dict walk)."""
    ctx = build_memory_context_from_columns(rank_columns, policy)
    result = run_rules(DOMAIN, DEFAULT_RULES, ctx)
    return _attribute(result, topology, {
        rank: cols.last_used()
        for rank, cols in rank_columns.items()
        if len(cols) and cols.columnar_ok
    })


def _attribute(result, topology, per_rank_used):
    """Imbalance grouping over per-rank used bytes (last sample) — the
    memory analogue of the step-time straggler attribution."""
    if topology is None:
        return result
    from traceml_tpu.diagnostics.attribution import attach_attribution

    return attach_attribution(result, topology, per_rank_used)
