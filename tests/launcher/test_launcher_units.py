import json

from traceml_tpu.launcher.manifest import (
    analyze_script,
    update_run_manifest,
    write_run_manifest,
)
from traceml_tpu.config.yaml_loader import load_yaml_config
from traceml_tpu.launcher.commands import resolve_settings
from traceml_tpu.reporting.compare.command import build_compare_payload


def test_run_manifest_lifecycle(tmp_path):
    write_run_manifest(
        tmp_path, session_id="s", script="t.py", mode="summary", world_size=4
    )
    data = json.loads((tmp_path / "manifest.json").read_text())
    assert data["status"] == "starting"
    assert data["world_size"] == 4
    update_run_manifest(tmp_path, status="running")
    data = json.loads((tmp_path / "manifest.json").read_text())
    assert data["status"] == "running"
    assert data["session_id"] == "s"


def test_code_manifest_jax_hints(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import jax\nimport optax\n"
        "from jax.sharding import Mesh, PartitionSpec\n"
        "import jax.numpy as jnp\n"
        "opt = optax.adamw(1e-3)\n"
        "x = jax.device_put(jnp.ones(3).astype(jnp.bfloat16))\n"
    )
    info = analyze_script(script)
    assert info["framework"] == "jax"
    assert "gspmd" in info["parallelism_hints"]
    assert "adamw" in info["optimizer_hints"]
    assert "bf16" in info["precision_hints"]
    assert "explicit_device_put" in info["input_hints"]


def test_code_manifest_bad_script(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("def broken(:\n")
    info = analyze_script(script)
    assert "error" in info


def test_yaml_loader(tmp_path, monkeypatch):
    (tmp_path / "traceml.yaml").write_text(
        "mode: summary\nsampler_interval_sec: 0.5\ntrace_max_steps: 42\n"
        "unknown_key: zap\ndisk_backup: 'true'\n"
    )
    monkeypatch.chdir(tmp_path)
    cfg = load_yaml_config()
    assert cfg["mode"] == "summary"
    assert cfg["sampler_interval_sec"] == 0.5
    assert cfg["trace_max_steps"] == 42
    assert cfg["disk_backup"] is True
    assert "unknown_key" not in cfg


def test_resolve_settings_precedence(tmp_path, monkeypatch):
    (tmp_path / "traceml.yaml").write_text("mode: summary\nsampler_interval_sec: 0.7\n")
    monkeypatch.chdir(tmp_path)
    # CLI beats yaml
    s = resolve_settings({"mode": "cli", "nprocs": 2, "nnodes": 1,
                          "logs_dir": str(tmp_path)})
    assert s.mode == "cli"
    assert s.sampler_interval_sec == 0.7  # yaml survives for unset CLI
    assert s.expected_world_size == 2
    # multi-node default flips to summary (explicit port required)
    s = resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path),
                          "aggregator_port": 7777})
    assert s.mode == "summary"
    assert s.aggregator.bind_host == "0.0.0.0"


def _summary(step_ms, input_share, peak, kind="COMPUTE_BOUND", session="a"):
    return {
        "meta": {"session_id": session},
        "primary_diagnosis": {
            "kind": kind,
            "severity": "info" if kind in ("COMPUTE_BOUND",
                                           "NO_CLEAR_PERFORMANCE_BOTTLENECK")
            else "critical",
        },
        "sections": {
            "step_time": {
                "global": {
                    "phases": {
                        "step_time": {"median_ms": step_ms},
                        "input": {"median_ms": step_ms * input_share,
                                  "share_of_step": input_share},
                        "compute": {"median_ms": step_ms * (1 - input_share),
                                    "share_of_step": 1 - input_share},
                    }
                }
            },
            "step_memory": {
                "global": {"per_rank": {"0": {"step_peak_bytes": peak}}}
            },
        },
    }


def test_compare_regression_detected():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(115.0, 0.05, 8 << 30, session="b")
    payload = build_compare_payload(base, cand)
    assert payload["verdict"] == "REGRESSION"
    assert any(f["kind"] == "STEP_TIME_REGRESSION" for f in payload["findings"])


def test_compare_improvement_and_equivalent():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(90.0, 0.05, 8 << 30, session="b")
    assert build_compare_payload(base, cand)["verdict"] == "IMPROVEMENT"
    cand2 = _summary(101.0, 0.05, 8 << 30, session="c")  # 1% — noise
    assert build_compare_payload(base, cand2)["verdict"] == "EQUIVALENT"


def test_compare_diagnosis_change_and_memory():
    base = _summary(100.0, 0.05, 8 << 30)
    cand = _summary(100.0, 0.40, 10 << 30, kind="INPUT_BOUND", session="b")
    payload = build_compare_payload(base, cand)
    kinds = {f["kind"] for f in payload["findings"]}
    assert "DIAGNOSIS_CHANGED" in kinds
    assert "PHASE_SHIFT" in kinds
    assert "MEMORY_REGRESSION" in kinds
    assert payload["verdict"] == "REGRESSION"


def test_resolve_settings_env_bool_strings(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("TRACEML_CAPTURE_STDERR", "0")
    monkeypatch.setenv("TRACEML_DISK_BACKUP", "false")
    s = resolve_settings({"nprocs": 1, "nnodes": 1, "logs_dir": str(tmp_path)})
    assert s.capture_stderr is False
    assert s.disk_backup is False


def test_resolve_settings_multinode_requires_port(tmp_path, monkeypatch):
    import pytest as _pytest

    monkeypatch.chdir(tmp_path)
    with _pytest.raises(ValueError):
        resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path)})
    s = resolve_settings({"nnodes": 2, "nprocs": 1, "logs_dir": str(tmp_path),
                          "aggregator_port": 9999})
    assert s.aggregator.port == 9999


def test_compare_diagnosis_change_to_healthy_is_not_regression():
    base = _summary(100.0, 0.40, 8 << 30, kind="INPUT_BOUND")
    cand = _summary(90.0, 0.05, 8 << 30, kind="COMPUTE_BOUND", session="b")
    cand["primary_diagnosis"]["severity"] = "info"
    payload = build_compare_payload(base, cand)
    assert payload["verdict"] == "IMPROVEMENT"


def test_code_manifest_deep_extraction(tmp_path):
    script = tmp_path / "deep.py"
    script.write_text(
        "import torch\n"
        "from torch.utils.data import DataLoader\n"
        "from transformers import TrainingArguments\n"
        "import peft\n"
        "loader = DataLoader(ds, batch_size=32, num_workers=0, pin_memory=True)\n"
        "args = TrainingArguments(output_dir='x', bf16=True,\n"
        "                         gradient_accumulation_steps=4,\n"
        "                         per_device_train_batch_size=8)\n"
        "loss.item()\n"
    )
    info = analyze_script(script)
    assert info["dataloader_args"][0]["num_workers"] == 0
    assert info["dataloader_args"][0]["pin_memory"] is True
    assert "single_worker_dataloader" in info["input_hints"]
    assert info["hf_training_args"]["gradient_accumulation_steps"] == 4
    assert "bf16" in info["precision_hints"]
    assert "lora/qlora" in info["uses"]
    assert "item" in info["sync_call_hints"]


def test_code_manifest_jax_donation(tmp_path):
    script = tmp_path / "j.py"
    script.write_text(
        "import jax\n"
        "step = jax.jit(f, donate_argnums=(0,))\n"
        "jax.block_until_ready(x)\n"
    )
    info = analyze_script(script)
    assert "buffer_donation" in info["uses"]
    assert "block_until_ready" in info["sync_call_hints"]


def test_code_manifest_multiple_dataloaders_not_merged(tmp_path):
    script = tmp_path / "two.py"
    script.write_text(
        "import torch\nfrom torch.utils.data import DataLoader\n"
        "train = DataLoader(a, num_workers=8)\n"
        "val = DataLoader(b)\n"  # torch default: 0 workers
    )
    info = analyze_script(script)
    assert len(info["dataloader_args"]) == 2
    # the val loader (default num_workers=0) still flags single-worker
    assert "single_worker_dataloader" in info["input_hints"]
