"""Per-section comparers
(reference: src/traceml_ai/reporting/compare/ section comparers —
~2.1k LoC of per-domain comparison; rebuilt here against OUR summary
schema, reporting/SCHEMA.md).

Each comparer consumes the same section from two ``final_summary.json``
payloads and returns a :class:`SectionComparison`:

* ``status`` — ``OK`` (both sides present), ``MISSING_BASELINE`` /
  ``MISSING_CANDIDATE`` (one side absent or NO_DATA), ``NO_DATA``
  (neither side has the section), ``INSUFFICIENT`` (present but the
  window is too small to trust);
* ``metrics`` — named {baseline, candidate, delta, delta_rel,
  significance} rows, per-metric tiers from the shared policy;
* ``findings`` — ranked finding dicts feeding the verdict ladder;
* ``per_rank`` — per-rank (or per-node) delta rows for the renderers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from traceml_tpu.reporting.compare.policy import (
    DEFAULT_POLICY,
    ComparePolicy,
    classify,
    diagnosis_rank,
)
from traceml_tpu.utils.formatting import fmt_bytes, fmt_ms

OK = "OK"
NO_DATA = "NO_DATA"
MISSING_BASELINE = "MISSING_BASELINE"
MISSING_CANDIDATE = "MISSING_CANDIDATE"
INSUFFICIENT = "INSUFFICIENT"


@dataclasses.dataclass
class SectionComparison:
    section: str
    status: str
    metrics: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    findings: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    per_rank: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    note: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _section(summary: Dict[str, Any], name: str) -> Optional[Dict[str, Any]]:
    sec = (summary.get("sections") or {}).get(name)
    # missing status (hand-built or older artifacts) counts as usable
    if not isinstance(sec, dict) or sec.get("status", "OK") != "OK":
        return None
    return sec


def _presence(b: Optional[dict], c: Optional[dict], name: str) -> Optional[SectionComparison]:
    """Shared missing-data handling; None means both present."""
    if b is None and c is None:
        return SectionComparison(section=name, status=NO_DATA)
    if b is None:
        return SectionComparison(
            section=name,
            status=MISSING_BASELINE,
            note="baseline run has no usable data for this section",
        )
    if c is None:
        return SectionComparison(
            section=name,
            status=MISSING_CANDIDATE,
            note="candidate run has no usable data for this section",
        )
    return None


def _metric(
    baseline: Optional[float],
    candidate: Optional[float],
    significance: str,
) -> Dict[str, Any]:
    delta = None
    delta_rel = None
    if baseline is not None and candidate is not None:
        delta = candidate - baseline
        if baseline:
            delta_rel = delta / baseline
    return {
        "baseline": baseline,
        "candidate": candidate,
        "delta": delta,
        "delta_rel": delta_rel,
        "significance": significance,
    }


# ---------------------------------------------------------------------------
# step time
# ---------------------------------------------------------------------------

def compare_step_time(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    policy: ComparePolicy = DEFAULT_POLICY,
) -> SectionComparison:
    b, c = _section(baseline, "step_time"), _section(candidate, "step_time")
    missing = _presence(b, c, "step_time")
    if missing is not None:
        return missing

    bg, cg = b.get("global") or {}, c.get("global") or {}
    out = SectionComparison(section="step_time", status=OK)
    bn, cn = bg.get("n_steps"), cg.get("n_steps")
    # gate only on DECLARED small windows; absent counts stay comparable
    if bn is not None and cn is not None and min(bn, cn) < policy.min_steps:
        out.status = INSUFFICIENT
        out.note = (
            f"window too small to compare ({bn} vs {cn} steps, "
            f"need ≥{policy.min_steps})"
        )
        return out
    if bg.get("clock") != cg.get("clock"):
        out.note = (
            f"clock changed ({bg.get('clock')} → {cg.get('clock')}); "
            "absolute deltas may not be comparable"
        )

    b_phases, c_phases = bg.get("phases") or {}, cg.get("phases") or {}
    b_step = (b_phases.get("step_time") or {}).get("median_ms")
    c_step = (c_phases.get("step_time") or {}).get("median_ms")
    step_delta_rel = None
    if b_step and c_step:
        step_delta_rel = (c_step - b_step) / b_step
    sig = classify(step_delta_rel, policy.step_avg_minor, policy.step_avg_major)
    out.metrics["step_median_ms"] = _metric(b_step, c_step, sig)
    if sig != "negligible":
        direction = "slower" if step_delta_rel > 0 else "faster"
        out.findings.append(
            {
                "kind": "STEP_TIME_"
                + ("REGRESSION" if step_delta_rel > 0 else "IMPROVEMENT"),
                "section": "step_time",
                "significance": sig,
                "summary": (
                    f"Median step is {abs(step_delta_rel) * 100:.1f}% {direction} "
                    f"({fmt_ms(b_step)} → {fmt_ms(c_step)})."
                ),
                "metric": "step_median_ms",
            }
        )

    # phase share shifts
    b_shares = {
        k: v.get("share_of_step")
        for k, v in b_phases.items()
        if k != "step_time" and v.get("share_of_step") is not None
    }
    c_shares = {
        k: v.get("share_of_step")
        for k, v in c_phases.items()
        if k != "step_time" and v.get("share_of_step") is not None
    }
    for key in sorted(set(b_shares) | set(c_shares)):
        b_v, c_v = b_shares.get(key, 0.0), c_shares.get(key, 0.0)
        shift_pp = (c_v - b_v) * 100.0
        sig = classify(shift_pp, policy.phase_shift_minor_pp, policy.phase_shift_major_pp)
        out.metrics[f"share.{key}"] = _metric(b_v, c_v, sig)
        if sig != "negligible":
            out.findings.append(
                {
                    "kind": "PHASE_SHIFT",
                    "section": "step_time",
                    "significance": sig,
                    "summary": (
                        f"Phase '{key}' share moved {shift_pp:+.1f} pp "
                        f"({b_v * 100:.1f}% → {c_v * 100:.1f}%)."
                    ),
                    "metric": f"share.{key}",
                    "phase": key,
                    "direction": "up" if shift_pp > 0 else "down",
                }
            )

    # per-rank step deltas → straggler appearance/disappearance
    b_rank = (b_phases.get("step_time") or {}).get("per_rank_avg_ms") or {}
    c_rank = (c_phases.get("step_time") or {}).get("per_rank_avg_ms") or {}
    worst_rank, worst_rel = None, 0.0
    for rank in sorted(set(b_rank) & set(c_rank), key=lambda r: int(r)):
        b_v, c_v = b_rank[rank], c_rank[rank]
        rel = (c_v - b_v) / b_v if b_v else None
        out.per_rank[str(rank)] = {
            "baseline_ms": b_v,
            "candidate_ms": c_v,
            "delta_rel": rel,
        }
        if rel is not None and abs(rel) > abs(worst_rel):
            worst_rank, worst_rel = rank, rel
    if (
        worst_rank is not None
        and step_delta_rel is not None
        and abs(worst_rel - step_delta_rel) >= policy.step_avg_major
    ):
        out.findings.append(
            {
                "kind": "RANK_DIVERGENCE",
                "section": "step_time",
                "significance": "major",
                "summary": (
                    f"Rank {worst_rank} moved {worst_rel * 100:+.1f}% vs the "
                    f"run-level {step_delta_rel * 100:+.1f}% — a rank-local "
                    "change (data shard, host, or interconnect), not a "
                    "global one."
                ),
                "metric": "per_rank.step_time",
                "rank": worst_rank,
            }
        )
    return out


# ---------------------------------------------------------------------------
# step memory
# ---------------------------------------------------------------------------

def _mem_stats(summary: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    sec = _section(summary, "step_memory")
    if sec is None:
        return {}
    return (sec.get("global") or {}).get("per_rank") or {}


def compare_step_memory(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    policy: ComparePolicy = DEFAULT_POLICY,
) -> SectionComparison:
    b, c = _section(baseline, "step_memory"), _section(candidate, "step_memory")
    missing = _presence(b, c, "step_memory")
    if missing is not None:
        return missing
    out = SectionComparison(section="step_memory", status=OK)
    b_rank, c_rank = _mem_stats(baseline), _mem_stats(candidate)

    def peak(stats: Dict[str, Any]) -> Optional[int]:
        peaks = [v.get("step_peak_bytes") or 0 for v in stats.values()]
        return max(peaks) if peaks else None

    b_peak, c_peak = peak(b_rank), peak(c_rank)
    delta = (c_peak - b_peak) if b_peak is not None and c_peak is not None else None
    sig = classify(delta, policy.memory_minor_bytes, policy.memory_major_bytes)
    out.metrics["peak_bytes"] = _metric(b_peak, c_peak, sig)
    if sig != "negligible":
        out.findings.append(
            {
                "kind": "MEMORY_" + ("REGRESSION" if delta > 0 else "IMPROVEMENT"),
                "section": "step_memory",
                "significance": sig,
                "summary": (
                    f"Peak device memory {'grew' if delta > 0 else 'shrank'} "
                    f"{fmt_bytes(abs(delta))} "
                    f"({fmt_bytes(b_peak)} → {fmt_bytes(c_peak)})."
                ),
                "metric": "peak_bytes",
            }
        )

    # per-rank peaks + skew shift
    common = sorted(set(b_rank) & set(c_rank), key=lambda r: int(r))
    for rank in common:
        b_v = b_rank[rank].get("step_peak_bytes")
        c_v = c_rank[rank].get("step_peak_bytes")
        out.per_rank[str(rank)] = {
            "baseline_bytes": b_v,
            "candidate_bytes": c_v,
            "delta_bytes": (c_v - b_v)
            if b_v is not None and c_v is not None
            else None,
        }

    def skew_pp(stats: Dict[str, Any]) -> Optional[float]:
        import statistics as st

        peaks = [v.get("step_peak_bytes") for v in stats.values()]
        peaks = [p for p in peaks if p]
        if len(peaks) < 2:
            return None
        med = st.median(peaks)
        return (max(peaks) - min(peaks)) / med * 100.0 if med else None

    b_skew, c_skew = skew_pp(b_rank), skew_pp(c_rank)
    if b_skew is not None and c_skew is not None:
        shift = c_skew - b_skew
        sig = classify(shift, policy.memory_skew_minor_pp, policy.memory_skew_major_pp)
        out.metrics["rank_skew_pp"] = _metric(b_skew, c_skew, sig)
        if sig != "negligible" and shift > 0:
            out.findings.append(
                {
                    "kind": "MEMORY_IMBALANCE_GREW",
                    "section": "step_memory",
                    "significance": sig,
                    "summary": (
                        f"Cross-rank peak-memory skew grew {shift:+.1f} pp "
                        f"({b_skew:.1f}% → {c_skew:.1f}% of the median)."
                    ),
                    "metric": "rank_skew_pp",
                }
            )
    return out


# ---------------------------------------------------------------------------
# system
# ---------------------------------------------------------------------------

def compare_system(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    policy: ComparePolicy = DEFAULT_POLICY,
) -> SectionComparison:
    b, c = _section(baseline, "system"), _section(candidate, "system")
    missing = _presence(b, c, "system")
    if missing is not None:
        return missing
    out = SectionComparison(section="system", status=OK)
    b_nodes = (b.get("global") or {}).get("nodes") or {}
    c_nodes = (c.get("global") or {}).get("nodes") or {}
    for node in sorted(set(b_nodes) & set(c_nodes), key=str):
        b_n, c_n = b_nodes[node], c_nodes[node]
        b_cpu, c_cpu = b_n.get("cpu_pct_mean"), c_n.get("cpu_pct_mean")
        cpu_pp = (c_cpu - b_cpu) if b_cpu is not None and c_cpu is not None else None
        b_mem, c_mem = b_n.get("memory_used_bytes"), c_n.get("memory_used_bytes")
        mem_d = (c_mem - b_mem) if b_mem is not None and c_mem is not None else None
        out.per_rank[str(node)] = {
            "hostname": c_n.get("hostname") or b_n.get("hostname"),
            "cpu_pp": cpu_pp,
            "memory_delta_bytes": mem_d,
        }
        cpu_sig = classify(cpu_pp, policy.system_cpu_minor_pp, policy.system_cpu_major_pp)
        if cpu_sig != "negligible":
            out.findings.append(
                {
                    "kind": "HOST_CPU_SHIFT",
                    "section": "system",
                    "significance": cpu_sig,
                    "summary": (
                        f"Node {node} mean host CPU moved {cpu_pp:+.0f} pp "
                        f"({b_cpu:.0f}% → {c_cpu:.0f}%)."
                    ),
                    "metric": f"node.{node}.cpu_pct_mean",
                }
            )
        mem_sig = classify(
            mem_d, policy.system_memory_minor_bytes, policy.system_memory_major_bytes
        )
        if mem_sig != "negligible":
            out.findings.append(
                {
                    "kind": "HOST_MEMORY_SHIFT",
                    "section": "system",
                    "significance": mem_sig,
                    "summary": (
                        f"Node {node} host memory moved "
                        f"{'+' if mem_d > 0 else '-'}{fmt_bytes(abs(mem_d))}."
                    ),
                    "metric": f"node.{node}.memory_used_bytes",
                }
            )
    out.metrics["nodes_compared"] = _metric(
        float(len(b_nodes)), float(len(c_nodes)), "negligible"
    )
    return out


# ---------------------------------------------------------------------------
# process
# ---------------------------------------------------------------------------

def compare_process(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    policy: ComparePolicy = DEFAULT_POLICY,
) -> SectionComparison:
    b, c = _section(baseline, "process"), _section(candidate, "process")
    missing = _presence(b, c, "process")
    if missing is not None:
        return missing
    out = SectionComparison(section="process", status=OK)
    b_rank = (b.get("global") or {}).get("per_rank") or {}
    c_rank = (c.get("global") or {}).get("per_rank") or {}
    for rank in sorted(set(b_rank) & set(c_rank), key=lambda r: int(r)):
        b_r, c_r = b_rank[rank], c_rank[rank]
        b_cpu, c_cpu = b_r.get("cpu_pct"), c_r.get("cpu_pct")
        cpu_pp = (c_cpu - b_cpu) if b_cpu is not None and c_cpu is not None else None
        b_rss, c_rss = b_r.get("rss_bytes"), c_r.get("rss_bytes")
        rss_d = (c_rss - b_rss) if b_rss is not None and c_rss is not None else None
        out.per_rank[str(rank)] = {"cpu_pp": cpu_pp, "rss_delta_bytes": rss_d}
        cpu_sig = classify(cpu_pp, policy.process_cpu_minor_pp, policy.process_cpu_major_pp)
        if cpu_sig != "negligible":
            out.findings.append(
                {
                    "kind": "PROCESS_CPU_SHIFT",
                    "section": "process",
                    "significance": cpu_sig,
                    "summary": (
                        f"Rank {rank} process CPU moved {cpu_pp:+.0f} pp "
                        f"({b_cpu:.0f}% → {c_cpu:.0f}%)."
                    ),
                    "metric": f"rank.{rank}.cpu_pct",
                }
            )
        rss_sig = classify(
            rss_d, policy.process_rss_minor_bytes, policy.process_rss_major_bytes
        )
        if rss_sig != "negligible":
            out.findings.append(
                {
                    "kind": "PROCESS_RSS_" + ("GREW" if rss_d > 0 else "SHRANK"),
                    "section": "process",
                    "significance": rss_sig,
                    "summary": (
                        f"Rank {rank} host RSS "
                        f"{'grew' if rss_d > 0 else 'shrank'} "
                        f"{fmt_bytes(abs(rss_d))}."
                    ),
                    "metric": f"rank.{rank}.rss_bytes",
                }
            )
    return out


# ---------------------------------------------------------------------------
# diagnosis transitions (cross-section)
# ---------------------------------------------------------------------------

def compare_diagnoses(
    baseline: Dict[str, Any], candidate: Dict[str, Any]
) -> List[Dict[str, Any]]:
    findings: List[Dict[str, Any]] = []
    b_primary = baseline.get("primary_diagnosis") or {}
    c_primary = candidate.get("primary_diagnosis") or {}
    b_kind, c_kind = b_primary.get("kind"), c_primary.get("kind")
    if b_kind != c_kind:
        regressed = diagnosis_rank(c_kind) > diagnosis_rank(b_kind)
        pathological = c_primary.get("severity") in ("warning", "critical")

        def _lbl(p):
            lab = p.get("confidence_label")
            return f" ({lab} confidence)" if lab else ""

        finding = {
            "kind": "DIAGNOSIS_" + ("REGRESSION" if regressed else "CHANGED"),
            "section": "diagnosis",
            "significance": "major" if regressed and pathological else "minor",
            "summary": (
                f"Primary diagnosis changed: {b_kind}{_lbl(b_primary)}"
                f" → {c_kind}{_lbl(c_primary)}."
            ),
            "metric": "primary_diagnosis",
            "baseline": b_kind,
            "candidate": c_kind,
        }
        # the transition is only as trustworthy as its weaker side: the
        # MIN of the two evidence-derived confidences rides along so the
        # verdict ladder can weight it (VERDICT r4 item 9)
        confs = [
            p.get("confidence")
            for p in (b_primary, c_primary)
            if isinstance(p.get("confidence"), (int, float))
        ]
        if confs:
            from traceml_tpu.diagnostics.common import confidence_label

            finding["confidence"] = min(confs)
            finding["confidence_label"] = confidence_label(min(confs))
        findings.append(finding)
    return findings


ALL_COMPARERS = {
    "step_time": compare_step_time,
    "step_memory": compare_step_memory,
    "system": compare_system,
    "process": compare_process,
}
