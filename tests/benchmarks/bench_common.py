"""Shared output harness for the micro-benchmarks in this directory.

Every bench emits one JSON line per measurement via :func:`emit` so runs
can be diffed/collected uniformly (the BENCH_LOCAL_* records at the repo
root are built from these lines)::

    {"bench": "<suite>", "metric": "<name>", "value": <float>,
     "unit": "<unit>", "ts": <unix time>, ...extra}
"""

import json
import time


def emit(bench: str, metric: str, value: float, unit: str, **extra) -> dict:
    record = {
        "bench": bench,
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "ts": round(time.time(), 3),
    }
    record.update(extra)
    print(json.dumps(record, sort_keys=True), flush=True)
    return record
