"""Ring attention vs single-device reference on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from traceml_tpu.ops.attention import causal_attention_reference
from traceml_tpu.ops.ring_attention import make_ring_attention
from traceml_tpu.parallel.mesh import make_mesh


def _qkv(B, S, H, D, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) * 0.4 for k in ks)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_ring_matches_reference(ring):
    if len(jax.devices()) < ring:
        pytest.skip("not enough devices")
    mesh = make_mesh({"context": ring}, devices=jax.devices()[:ring])
    q, k, v = _qkv(B=2, S=128, H=2, D=32)
    ref = causal_attention_reference(q, k, v)
    ring_fn = make_ring_attention(mesh, "context")
    with mesh:
        out = ring_fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_causality_across_shards():
    """Perturbing the LAST shard's keys must not affect earlier shards'
    outputs (causality crosses device boundaries correctly)."""
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=64, H=2, D=16, seed=3)
    ring_fn = make_ring_attention(mesh, "context")
    with mesh:
        out1 = ring_fn(q, k, v)
        k2 = k.at[:, -16:].add(1.0)  # last device's shard
        out2 = ring_fn(q, k2, v)
    np.testing.assert_allclose(
        np.asarray(out1[:, :48]), np.asarray(out2[:, :48]), atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[:, 48:]), np.asarray(out2[:, 48:]))


def test_ring_bf16():
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=64, H=2, D=16, dtype=jnp.bfloat16)
    ref = causal_attention_reference(q, k, v).astype(jnp.float32)
    ring_fn = make_ring_attention(mesh, "context")
    with mesh:
        out = ring_fn(q, k, v).astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=4e-2, rtol=4e-2
    )


def test_ring_gradients_match_reference():
    """d(loss)/d(q,k,v) through the ring collective must equal the
    single-device reference gradient — the backward pipeline rides
    ppermute's transpose, and a silent mismatch there corrupts training
    rather than crashing it."""
    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(B=1, S=64, H=2, D=16, seed=11)
    ring_fn = make_ring_attention(mesh, "context")

    def loss_ring(q, k, v):
        with mesh:
            return (ring_fn(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (causal_attention_reference(q, k, v) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), atol=3e-5, rtol=3e-5
        )
