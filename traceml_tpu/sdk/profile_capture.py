"""On-demand XLA profiler capture — grab a trace from a RUNNING job.

No reference counterpart (the reference's CUDA world leans on external
nsight; on TPU the XLA profiler trace IS the performance tool, so the
tracer makes it reachable without restarting the run).  Same file-IPC
shape as the final-summary protocol (sdk/protocol.py): an operator (or
``traceml-tpu profile <session_dir>``) drops
``control/profile_request.json``; each rank's
:class:`ProfileCaptureService` — driven by the SDK's per-step flush
callback on the training thread — notices it, brackets the next N steps
with ``jax.profiler.start_trace/stop_trace`` into
``<session>/profiles/<stamp>/rank_<r>/``, and the primary rank writes
``control/profile_response.json``.

Design constraints:

* **Fail-open** — a broken profiler (unsupported runtime, disk full)
  must answer with an error response, never raise into training.
* **Cheap when idle** — the request probe is one ``os.stat`` every
  ``check_every`` steps (sub-µs amortized); no extra thread.
* **Step-aligned** — capture starts at a step FLUSH edge (so the trace
  holds whole steps) and stops N flushes later.  Short traces keep the
  artifact small; the XLA trace of even a few steps holds the full
  fusion/overlap story.
* **Multi-rank** — every rank captures its own process trace (XLA
  profiling is per-process); the request may restrict via ``ranks``.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.sdk.protocol import control_dir
from traceml_tpu.utils.atomic_io import atomic_write_json, read_json
from traceml_tpu.utils.error_log import get_error_log

PROFILE_REQUEST_FILE = "profile_request.json"
PROFILE_RESPONSE_FILE = "profile_response.json"
_DEFAULT_STEPS = 5
_MAX_STEPS = 200  # bound the artifact even against a typo'd request


def profile_request_path(session_dir: Path) -> Path:
    return control_dir(session_dir) / PROFILE_REQUEST_FILE


def profile_response_path(session_dir: Path) -> Path:
    return control_dir(session_dir) / PROFILE_RESPONSE_FILE


def write_profile_request(
    session_dir: Path, steps: int = _DEFAULT_STEPS, ranks=None
) -> float:
    """Operator side: ask the running job for a trace.  Returns the
    request timestamp (pass to :func:`read_profile_response` matching).

    ``ranks`` must be None (all ranks) or a NON-EMPTY list of rank ids —
    an empty list would name no captor and the request could only time
    out, so it is rejected here rather than silently dropped."""
    if ranks is not None:
        ranks = [int(r) for r in ranks]
        if not ranks:
            raise ValueError(
                "ranks must be None (all ranks) or a non-empty list"
            )
    ts = time.time()
    atomic_write_json(
        profile_request_path(session_dir),
        {"requested_at": ts, "steps": int(steps), "ranks": ranks},
    )
    return ts


def read_profile_response(
    session_dir: Path, for_request: Optional[float] = None
) -> Optional[Dict[str, Any]]:
    """The response matching ``for_request`` (the timestamp returned by
    :func:`write_profile_request`, echoed back verbatim by the service —
    exact-match, so neither clock skew between hosts nor a stale
    previous response can satisfy a new request), or any response when
    ``for_request`` is None."""
    resp = read_json(profile_response_path(session_dir))
    if not resp:
        return None
    if for_request is not None and resp.get("requested_at") != for_request:
        return None
    return resp


class ProfileCaptureService:
    """Per-rank request watcher + capture state machine.

    Wire up by appending :meth:`on_step_flushed` to
    ``TraceState.on_step_flushed`` (the runtime does this in
    ``start()``).  All work happens on the training thread at step-flush
    edges — starting/stopping the XLA profiler from another thread would
    tear mid-step.
    """

    def __init__(
        self,
        session_dir: Path,
        rank: int = 0,
        check_every: int = 5,
        world_size: Optional[int] = None,
    ) -> None:
        self._session_dir = Path(session_dir)
        self._rank = int(rank)
        self._check_every = max(1, int(check_every))
        self._world_size = int(world_size) if world_size else None
        self._flushes = 0
        self._handled_mtime = 0.0
        self._remaining = 0
        self._trace_dir: Optional[Path] = None
        self._request: Dict[str, Any] = {}
        self._steps = 0
        self._primary = 0

    # -- the per-step hook (training thread) ---------------------------
    def on_step_flushed(self, step: int) -> None:
        try:
            if self._remaining > 0:
                self._remaining -= 1
                if self._remaining == 0:
                    self._finish(ok=True)
                return
            self._flushes += 1
            if self._flushes % self._check_every:
                return
            self._maybe_start()
        except Exception as exc:  # never raise into the training loop
            get_error_log().warning("profile capture hook failed", exc)
            self._remaining = 0

    # -- internals -----------------------------------------------------
    def _handled_marker_path(self) -> Path:
        return (
            control_dir(self._session_dir)
            / f".profile_handled_rank_{self._rank}.json"
        )

    def _maybe_start(self) -> None:
        req_path = profile_request_path(self._session_dir)
        try:
            mtime = os.stat(req_path).st_mtime
        except OSError:
            return
        if mtime <= self._handled_mtime:
            return
        self._handled_mtime = mtime
        req = read_json(req_path) or {}
        # per-rank handled marker: a request this rank already handled
        # in a PREVIOUS life of the session dir (restart/resume) must
        # not replay as an unsolicited capture.  Per-rank (not the
        # shared response file) because the primary can finish and
        # respond while a slower rank has not even started its capture —
        # a shared answered-check would silently drop that rank's trace.
        # An unhandled request is honored regardless of age: the
        # operator may legitimately file it before the first step.
        marker = self._handled_marker_path()
        prior = read_json(marker)
        if prior is not None and prior.get("requested_at") == req.get(
            "requested_at"
        ):
            return
        try:
            atomic_write_json(
                marker, {"requested_at": req.get("requested_at")}
            )
        except Exception:
            pass  # worst case: a restart replays one capture
        steps = min(_MAX_STEPS, max(1, int(req.get("steps") or _DEFAULT_STEPS)))
        ranks = req.get("ranks")
        if ranks is not None:
            try:
                ranks = [int(r) for r in ranks]
            except (TypeError, ValueError):
                ranks = []
            live = [
                r for r in ranks
                if self._world_size is None or 0 <= r < self._world_size
            ]
            if not live:
                # nobody will ever capture this request — the
                # conventional responder (rank 0) answers with an error
                # instead of leaving the operator's CLI to time out
                # with a misleading "is the job stepping?" message
                if self._rank == 0:
                    self._respond(
                        ok=False,
                        error=f"ranks {ranks!r} names no live rank "
                              f"(world_size={self._world_size})",
                        trace_dir=None, req=req, steps=steps, primary=0,
                    )
                return
            if self._rank not in live:
                return
            self._primary = min(live)
        else:
            self._primary = 0
        # stamp from the REQUEST time, not each rank's local now: ranks
        # reach their flush edges at different instants, and a wall-clock
        # stamp would scatter one capture across two profiles/<stamp>/
        # dirs whenever ranks straddle a second boundary
        req_ts = float(req.get("requested_at") or time.time())
        stamp = time.strftime("%Y%m%d_%H%M%S", time.localtime(req_ts))
        trace_dir = self._session_dir / "profiles" / stamp / f"rank_{self._rank}"
        try:
            import jax

            trace_dir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(trace_dir))
        except Exception as exc:
            get_error_log().warning("profile capture start failed", exc)
            self._respond(
                ok=False, error=repr(exc), trace_dir=None, req=req,
                steps=steps, primary=self._primary,
            )
            return
        self._request = req
        self._trace_dir = trace_dir
        self._remaining = steps
        self._steps = steps

    def _finish(self, ok: bool, truncated: bool = False) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            get_error_log().warning("profile capture stop failed", exc)
            ok = False
        self._respond(
            ok=ok,
            error=None if ok else "stop_trace failed",
            trace_dir=self._trace_dir,
            req=self._request,
            truncated=truncated,
            steps=self._steps,
            primary=self._primary,
        )
        self._trace_dir = None
        self._request = {}

    def close(self) -> None:
        """Shutdown path (runtime.stop): finish an in-flight capture so
        the profiler is never left tracing through teardown and the
        waiting operator gets an answer (a truncated trace of the steps
        that did run, not a timeout)."""
        if self._remaining > 0:
            self._remaining = 0
            self._finish(ok=True, truncated=True)

    def _respond(
        self, ok, error, trace_dir, req, truncated=False,
        steps: Optional[int] = None, primary: int = 0,
    ) -> None:
        # one response per request, written by the primary PARTICIPATING
        # rank (responses from N ranks would race the same file; the
        # caller computes primary from the LIVE rank set so a request
        # naming dead ranks still gets its answer)
        if self._rank != primary:
            return
        try:
            atomic_write_json(
                profile_response_path(self._session_dir),
                {
                    # echoed verbatim: the operator's exact-match key
                    "requested_at": req.get("requested_at"),
                    "completed_at": time.time(),
                    "ok": bool(ok),
                    # the CLAMPED step count actually captured, not the
                    # requested value (a typo'd steps=10**6 is bounded
                    # by _MAX_STEPS and the response must say so)
                    "steps": steps if steps is not None else req.get("steps"),
                    "error": error,
                    "trace_dir": str(trace_dir.parent) if trace_dir else None,
                    "truncated": bool(truncated),
                    "rank": self._rank,
                },
            )
        except Exception as exc:
            get_error_log().warning("profile capture respond failed", exc)


def request_profile_and_wait(
    session_dir: Path,
    steps: int = _DEFAULT_STEPS,
    timeout: float = 60.0,
    poll_interval: float = 0.25,
    ranks=None,
) -> Optional[Dict[str, Any]]:
    """Operator convenience: request + poll until the job answers (the
    job must be stepping — capture engages at step-flush edges)."""
    ts = write_profile_request(session_dir, steps=steps, ranks=ranks)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        resp = read_profile_response(session_dir, for_request=ts)
        if resp is not None:
            return resp
        time.sleep(poll_interval)
    return None
