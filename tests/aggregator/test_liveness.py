"""Rank liveness: tracker state machine, snapshot/seed resume, and the
diagnostics rules that turn a rank_status snapshot into verdicts
(docs/developer_guide/fault-tolerance.md)."""

from traceml_tpu.aggregator.liveness import (
    STATE_ACTIVE,
    STATE_FINISHED,
    STATE_LOST,
    STATE_STALE,
    RankLivenessTracker,
)
from traceml_tpu.diagnostics.liveness import diagnose_rank_status


def _tracker():
    return RankLivenessTracker(stale_after=10.0, lost_after=30.0)


# -- state machine -------------------------------------------------------


def test_states_by_silence_age():
    t = _tracker()
    t.observe(0, ts=100.0)
    assert t.state_of(0, now=105.0) == STATE_ACTIVE
    assert t.state_of(0, now=110.0) == STATE_STALE  # >= stale_after
    assert t.state_of(0, now=129.9) == STATE_STALE
    assert t.state_of(0, now=130.0) == STATE_LOST  # >= lost_after


def test_finished_is_terminal():
    t = _tracker()
    t.observe(1, ts=100.0)
    t.mark_finished(1, ts=101.0)
    # a finished rank is never STALE/LOST no matter how silent
    assert t.state_of(1, now=101.0 + 10_000) == STATE_FINISHED


def test_observe_is_max_monotonic():
    t = _tracker()
    t.observe(0, ts=100.0, progress=True)
    t.observe(0, ts=90.0, progress=True)  # late/reordered envelope
    snap = t.snapshot(now=100.0)["ranks"]["0"]
    assert snap["last_seen"] == 100.0
    assert snap["last_progress"] == 100.0
    assert snap["first_seen"] == 100.0


def test_progress_tracked_separately_from_seen():
    t = _tracker()
    t.observe(0, ts=100.0, progress=True)  # step_time envelope
    t.observe(0, ts=120.0)  # heartbeat only
    snap = t.snapshot(now=121.0)["ranks"]["0"]
    assert snap["last_seen"] == 120.0
    assert snap["last_progress"] == 100.0


def test_never_seen_rank_defaults_active():
    # a rank with no history can't be aged: silence is measured from
    # last_seen, and an unseen rank has none (never_seen ranks are the
    # diagnostics layer's job, via expected_world_size)
    t = _tracker()
    assert t.state_of(7, now=1e9) == STATE_ACTIVE
    assert t.ranks() == []


def test_snapshot_seed_roundtrip_preserves_states():
    t = _tracker()
    t.observe(0, ts=100.0, progress=True)
    t.observe(1, ts=100.0)
    t.mark_finished(1, ts=105.0)
    snap = t.snapshot(now=140.0)
    assert snap["ranks"]["0"]["state"] == STATE_LOST
    assert snap["ranks"]["1"]["state"] == STATE_FINISHED
    assert snap["thresholds"]["lost_after_sec"] == 30.0

    # crash-resume: a fresh incarnation seeded from the file derives
    # the same states — finished stays finished, history is intact
    t2 = _tracker()
    t2.seed(snap)
    assert t2.state_of(0, now=140.0) == STATE_LOST
    assert t2.state_of(1, now=140.0) == STATE_FINISHED
    assert t2.snapshot(now=140.0)["ranks"]["0"]["last_progress"] == 100.0


def test_seed_tolerates_garbage():
    t = _tracker()
    t.seed({})
    t.seed({"ranks": "nope"})
    t.seed({"ranks": {"x": {"last_seen": "y"}, "2": None, "3": {}}})
    assert t.ranks() == []


# -- diagnostics rules over a snapshot -----------------------------------


def _snap(ranks, now=1000.0, stale=10.0, lost=30.0, world=None):
    return {
        "ts": now,
        "session_id": "s",
        "expected_world_size": world if world is not None else len(ranks),
        "thresholds": {"stale_after_sec": stale, "lost_after_sec": lost},
        "ranks": ranks,
    }


def _rank(state, last_seen, last_progress=None, finished=False):
    return {
        "state": state,
        "first_seen": 0.0,
        "last_seen": last_seen,
        "last_progress": last_progress,
        "finished": finished,
    }


def test_healthy_world_is_healthy():
    snap = _snap({
        "0": _rank(STATE_ACTIVE, 999.0, 999.0),
        "1": _rank(STATE_FINISHED, 998.0, 998.0, finished=True),
    })
    res = diagnose_rank_status(snap)
    assert res.diagnosis.kind == "HEALTHY", res.diagnosis


def test_lost_rank_is_critical_rank_lost():
    snap = _snap({
        "0": _rank(STATE_ACTIVE, 999.0, 999.0),
        "1": _rank(STATE_LOST, 900.0, 850.0),  # idled before vanishing
    })
    res = diagnose_rank_status(snap)
    assert res.diagnosis.kind == "RANK_LOST"
    assert res.diagnosis.severity == "critical"
    assert res.diagnosis.ranks == [1]
    # not preempted: there was a 50s progress gap before the silence
    assert "LIKELY_PREEMPTED" not in {i.kind for i in res.issues}


def test_died_mid_stride_adds_likely_preempted():
    snap = _snap({
        "0": _rank(STATE_ACTIVE, 999.0, 999.0),
        "1": _rank(STATE_LOST, 900.0, 898.0),  # progress right up to silence
    })
    kinds = {i.kind for i in diagnose_rank_status(snap).issues}
    assert {"RANK_LOST", "LIKELY_PREEMPTED"} <= kinds


def test_never_seen_rank_counts_as_lost():
    snap = _snap({"0": _rank(STATE_ACTIVE, 999.0, 999.0)}, world=4)
    res = diagnose_rank_status(snap)
    assert res.diagnosis.kind == "RANK_LOST"
    assert res.diagnosis.evidence["never_seen_ranks"] == [1, 2, 3]


def test_stale_world_warns():
    snap = _snap({
        "0": _rank(STATE_STALE, 985.0, 985.0),
        "1": _rank(STATE_STALE, 985.0, 985.0),
        "2": _rank(STATE_ACTIVE, 999.0, 999.0),
    })
    res = diagnose_rank_status(snap)
    assert res.diagnosis.kind == "WORLD_STALE"
    assert res.diagnosis.severity == "warning"


def test_missing_snapshot_degrades_to_info():
    res = diagnose_rank_status(None)
    assert res.diagnosis.kind == "NO_LIVENESS_DATA"
    assert res.healthy
