"""process projection → ``process_samples`` + ``process_device_samples``
(reference: aggregator/sqlite_writers/process.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "process_samples"
TABLE_DEVICE = "process_device_samples"
RETENTION_TABLES = (TABLE, TABLE_DEVICE)


def accepts_sampler(name: str) -> bool:
    return name == "process"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            cpu_pct REAL,
            rss_bytes INTEGER,
            vms_bytes INTEGER,
            num_threads INTEGER
        )"""
    )
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE_DEVICE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            device_id INTEGER,
            device_kind TEXT,
            memory_used_bytes INTEGER,
            memory_peak_bytes INTEGER,
            memory_total_bytes INTEGER
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_rank "
        f"ON {TABLE} (session_id, global_rank, timestamp)"
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE_DEVICE}_rank "
        f"ON {TABLE_DEVICE} (session_id, global_rank, device_id, timestamp)"
    )


def insert_sql(table: str) -> str:
    if table == TABLE:
        return (
            f"INSERT INTO {TABLE} (session_id, global_rank, local_rank,"
            " world_size, local_world_size, node_rank, hostname, pid, timestamp,"
            " cpu_pct, rss_bytes, vms_bytes, num_threads)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)"
        )
    return (
        f"INSERT INTO {TABLE_DEVICE} (session_id, global_rank, local_rank,"
        " world_size, local_world_size, node_rank, hostname, pid, timestamp,"
        " device_id, device_kind, memory_used_bytes, memory_peak_bytes,"
        " memory_total_bytes) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    ident = identity_tuple(env)
    out: Dict[str, List[Tuple]] = {}
    v = env.column_view("process")
    if v:
        ts = v.floats("timestamp")
        cpu = v.floats("cpu_pct")
        rss = v.ints("rss_bytes")
        vms = v.ints("vms_bytes")
        threads = v.ints("num_threads")
        out[TABLE] = [
            ident + (ts[i], cpu[i], rss[i], vms[i], threads[i])
            for i in range(len(v))
        ]
    v = env.column_view("process_device")
    if v:
        ts = v.floats("timestamp")
        dev_id = v.ints("device_id")
        kind = v.strs("device_kind", "unknown")
        used = v.ints("memory_used_bytes")
        peak = v.ints("memory_peak_bytes")
        total = v.ints("memory_total_bytes")
        out[TABLE_DEVICE] = [
            ident + (ts[i], dev_id[i], kind[i], used[i], peak[i], total[i])
            for i in range(len(v))
        ]
    return out
