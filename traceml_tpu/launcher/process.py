"""Process supervision utilities
(reference: src/traceml_ai/launcher/process.py:30-300)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from traceml_tpu.utils.atomic_io import read_json


def spawn(
    argv: List[str],
    env: Optional[Dict[str, str]] = None,
    cwd: Optional[str] = None,
    stdout=None,
    stderr=None,
) -> subprocess.Popen:
    """Start a child in its own process group so we can terminate the
    whole tree."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    kwargs = {}
    if os.name == "posix":
        kwargs["start_new_session"] = True
    return subprocess.Popen(
        argv,
        env=full_env,
        cwd=cwd,
        stdout=stdout,
        stderr=stderr,
        **kwargs,
    )


def terminate(proc: subprocess.Popen, grace_sec: float = 10.0) -> int:
    """SIGTERM the process group, escalate to SIGKILL after the grace
    period; returns the exit code."""
    if proc.poll() is not None:
        return proc.returncode
    try:
        if os.name == "posix":
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        else:  # pragma: no cover
            proc.terminate()
    except (ProcessLookupError, PermissionError):
        pass
    deadline = time.monotonic() + grace_sec
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return proc.returncode
        time.sleep(0.1)
    try:
        if os.name == "posix":
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        else:  # pragma: no cover
            proc.kill()
    except (ProcessLookupError, PermissionError):
        pass
    proc.wait(timeout=10)
    return proc.returncode


def wait_for_ready_file(path: Path, timeout: float = 30.0) -> Optional[dict]:
    """Poll the aggregator's ready file for the bound port
    (replaces the reference's TCP-listen poll — the file also carries
    the ephemeral port, which a connect probe cannot discover)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        data = read_json(path)
        if data and data.get("port"):
            return data
        time.sleep(0.1)
    return None


def python_argv(module: str) -> List[str]:
    return [sys.executable, "-m", module]
