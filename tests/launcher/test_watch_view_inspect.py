"""CLI attach/read commands over a finished session: watch (exits when
the manifest says completed), view (text + json), inspect (decode
msgpack backups) — previously untested surfaces."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def _finished_session(tmp_path):
    """Build a real finished session: DB + summary + completed manifest."""
    from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
    from traceml_tpu.launcher import manifest as mf
    from traceml_tpu.reporting.final import generate_summary
    from traceml_tpu.runtime.settings import TraceMLSettings
    from traceml_tpu.telemetry.envelope import (
        SenderIdentity,
        build_telemetry_envelope,
    )
    from traceml_tpu.utils import timing as T

    session = tmp_path / "sess"
    session.mkdir()
    w = SQLiteWriter(session / "telemetry.sqlite")
    w.start()
    ident = SenderIdentity(session_id="sess", global_rank=0)
    rows = [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {
             T.STEP_TIME: {"cpu_ms": 50.0, "device_ms": 50.0, "count": 1},
             T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 45.0, "count": 1},
         }}
        for s in range(1, 40)
    ]
    w.ingest(build_telemetry_envelope("step_time", {"step_time": rows}, ident))
    w.force_flush()
    w.finalize()
    settings = TraceMLSettings(session_id="sess", logs_dir=tmp_path)
    generate_summary(session / "telemetry.sqlite", session, settings)
    mf.write_run_manifest(
        session, session_id="sess", script="x.py", mode="summary",
        world_size=1, status=mf.STATUS_COMPLETED,
    )
    return session


def _cli(args, timeout=60):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    return subprocess.run(
        [sys.executable, "-m", "traceml_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_watch_exits_on_completed_session(tmp_path):
    session = _finished_session(tmp_path)
    proc = _cli(["watch", str(session), "--interval", "0.2"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "VERDICT" in proc.stdout  # the final summary is printed


def test_watch_missing_session(tmp_path):
    proc = _cli(["watch", str(tmp_path / "nope")])
    assert proc.returncode == 1


def test_view_text_and_json(tmp_path):
    session = _finished_session(tmp_path)
    text = _cli(["view", str(session)])
    assert text.returncode == 0
    assert "VERDICT" in text.stdout
    as_json = _cli(["view", str(session), "--format", "json"])
    assert as_json.returncode == 0
    payload = json.loads(as_json.stdout)
    assert payload["schema"].startswith("traceml-tpu/")
    assert payload["sections"]["step_time"]["status"] == "OK"


def test_inspect_decodes_backups(tmp_path):
    from traceml_tpu.database import Database, DatabaseWriter

    db = Database()
    w = DatabaseWriter("step_time", db, tmp_path / "data", flush_every=1)
    db.add_records("steps", [{"step": i, "ms": 10.0 * i} for i in range(5)])
    assert w.flush(force=True) == 5
    proc = _cli(["inspect", str(tmp_path / "data"), "--limit", "3"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "steps" in proc.stdout
    assert "step" in proc.stdout


def test_inspect_domain_filter_and_overlap_column(tmp_path):
    """--domain keeps only the named table and collectives rows gain a
    derived overlap_efficiency column (zero-duration rows read 1.0)."""
    from traceml_tpu.database import Database, DatabaseWriter

    db = Database()
    w = DatabaseWriter("mixed", db, tmp_path / "data", flush_every=1)
    db.add_records("steps", [{"step": i, "ms": 10.0 * i} for i in range(3)])
    db.add_records(
        "collectives",
        [
            {"step": 1, "op": "all_reduce", "dtype": "float32",
             "count": 2, "bytes": 4096, "group_size": 8,
             "duration_ms": 4.0, "exposed_ms": 1.0},
            {"step": 2, "op": "all_gather", "dtype": "bfloat16",
             "count": 1, "bytes": 0, "group_size": 8,
             "duration_ms": 0.0, "exposed_ms": 0.0},
        ],
    )
    assert w.flush(force=True) == 5
    proc = _cli(
        ["inspect", str(tmp_path / "data"), "--domain", "collectives"]
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    # only the collectives table's rows survive the filter (legacy
    # per-table backups are matched by file stem)
    assert rows and all("op" in r for r in rows)
    assert "steps.msgpack" not in proc.stdout
    by_step = {r["step"]: r for r in rows}
    assert by_step[1]["overlap_efficiency"] == 0.75  # 1 − 1/4
    assert by_step[2]["overlap_efficiency"] == 1.0   # zero comm ≠ NaN
    # unknown domain → helpful non-zero exit
    miss = _cli(["inspect", str(tmp_path / "data"), "--domain", "nope"])
    assert miss.returncode == 1
    assert "no rows for domain" in miss.stdout
