"""The runnable Lightning example, smoke-run against the fake packages
(VERDICT r4 item 8: the only integration without a runnable example).

The example itself targets real lightning; here the fake layout proves
the script's API usage (LightningModule subclass, Trainer(max_epochs=1,
callbacks=[...]), fit over a DataLoader) drives the TraceML callback
end-to-end and produces one timed batch per training step.
"""

import runpy
import sys
from pathlib import Path

import pytest

from traceml_tpu.utils import timing as T

REPO = Path(__file__).resolve().parents[2]
FAKES = Path(__file__).resolve().parents[1] / "fakes"
EXAMPLE = REPO / "examples" / "integrations" / "lightning_minimal.py"


@pytest.fixture()
def fake_lightning(monkeypatch):
    import traceml_tpu.integrations.lightning as L

    monkeypatch.syspath_prepend(str(FAKES))
    monkeypatch.setattr(L, "_cached_callback_cls", None)
    yield
    for name in [
        m for m in sys.modules
        if m == "_fake_lightning_impl"
        or m.startswith(("lightning", "pytorch_lightning"))
    ]:
        del sys.modules[name]


def test_lightning_example_runs_against_fake(fake_lightning, monkeypatch):
    from traceml_tpu.sdk.state import get_state

    captured = []
    st = get_state()
    st.on_batch_flushed.append(captured.append)
    # keep the smoke fast: 2048/16 = 128 batches is overkill here
    import torch

    real_dataset = torch.utils.data.TensorDataset
    monkeypatch.setattr(
        torch.utils.data, "TensorDataset",
        lambda x, y: real_dataset(x[:64], y[:64]),
    )
    try:
        runpy.run_path(str(EXAMPLE), run_name="__main__")
    finally:
        st.on_batch_flushed.remove(captured.append)
    assert captured, "no timed batches — callback never drove a step"
    names = [e.name for e in captured[0].events]
    assert T.FORWARD_TIME in names
    assert T.BACKWARD_TIME in names
    assert T.OPTIMIZER_STEP in names
