"""View-builder unit battery — the typed schema every surface renders
(panels, browser payload).  Mirrors the reference's renderer compute
tests (reference: tests/renderers/*)."""

from traceml_tpu.renderers import views as V
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import build_step_time_window


def _step_rows(n=30, step_ms=100.0, input_ms=10.0, rank_offset=0.0):
    return [
        {
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms + rank_offset, "count": 1},
                T.DATALOADER_NEXT: {"cpu_ms": input_ms, "device_ms": None, "count": 1},
                T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 80.0, "count": 1},
            },
        }
        for s in range(1, n + 1)
    ]


def test_step_time_view_shapes():
    rank_rows = {0: _step_rows(), 1: _step_rows(rank_offset=20.0)}
    window = build_step_time_window(rank_rows)
    view = V.build_step_time_view(window, world_size=4, latest_ts=30.0)
    assert view.clock == "device"
    assert view.coverage.world_size == 4
    assert view.coverage.ranks_present == 2
    assert view.coverage.incomplete  # 2 of 4 ranks
    keys = [p.key for p in view.phases]
    assert keys[0] == "step_time" and keys[-1] == "residual"
    assert "compute" in keys and "input" in keys
    # per-rank series + stacking series aligned to the steps tail
    assert set(view.step_series) == {"0", "1"}
    assert len(view.steps) == len(view.step_series["0"])
    assert set(view.phase_stack) >= {"compute", "input", "residual"}
    assert len(view.phase_stack["compute"]) == len(view.steps)
    # rank 1 is slower → worst rank for the step envelope
    step = next(p for p in view.phases if p.key == "step_time")
    assert step.worst_rank == 1
    # round-trips to plain JSON types
    d = view.as_dict()
    assert d["coverage"]["incomplete"] is True


def test_step_time_view_efficiency_block():
    window = build_step_time_window({0: _step_rows(), 1: _step_rows()})
    stats = {0: {"flops_per_step": 10e12, "flops_source": "manual",
                 "device_kind": "TPU v5p", "peak_flops": 459e12}}
    view = V.build_step_time_view(window, world_size=2, model_stats=stats)
    eff = view.efficiency
    assert eff is not None and eff["mfu_median"] is not None
    assert eff["peak_tflops"] == 459.0
    # unknown chip → achieved only, no MFU ratio
    stats[0]["peak_flops"] = None
    view = V.build_step_time_view(window, world_size=2, model_stats=stats)
    assert view.efficiency["mfu_median"] is None
    assert view.efficiency["achieved_tflops_median"] > 0
    # no stats → no block
    view = V.build_step_time_view(window, world_size=2)
    assert view.efficiency is None


def test_step_time_view_none_passthrough():
    assert V.build_step_time_view(None) is None


def _mem_rows(cur, limit=16 << 30, n=5):
    return [
        {
            "step": i,
            "timestamp": float(i),
            "device_id": 0,
            "device_kind": "tpu v5e",
            "current_bytes": cur + i * (1 << 20),
            "peak_bytes": cur,
            "step_peak_bytes": cur,
            "limit_bytes": limit,
        }
        for i in range(1, n + 1)
    ]


def test_memory_view_pressure_and_growth():
    view = V.build_memory_view({0: _mem_rows(8 << 30), 1: _mem_rows(15 << 30)})
    assert [s.rank for s in view.ranks] == [0, 1]
    assert view.worst_pressure_rank == 1
    r1 = view.ranks[1]
    assert r1.pressure > 0.9
    assert r1.growth_bytes == 4 << 20  # 4 steps × 1 MiB
    assert len(r1.history) == 5
    assert view.total_current_bytes > 23 << 30


def test_memory_view_empty():
    assert V.build_memory_view({}) is None
    assert V.build_memory_view({0: []}) is None


def _host_row(node, host, cpu, ts, used=4 << 30, total=8 << 30):
    return {
        "node_rank": node,
        "hostname": host,
        "cpu_pct": cpu,
        "memory_used_bytes": used,
        "memory_total_bytes": total,
        "memory_pct": used / total * 100,
        "load_1m": 1.0,
        "timestamp": ts,
    }


def test_system_view_cluster_rollups_two_nodes():
    now = 1000.0
    host = {
        0: [_host_row(0, "node-a", 20.0, now - 1)],
        1: [_host_row(1, "node-b", 90.0, now - 1)],
    }
    devices = {
        (0, 0): [{"device_id": 0, "device_kind": "tpu", "memory_used_bytes": 1,
                  "memory_total_bytes": 2, "utilization_pct": 55.0,
                  "temperature_c": None, "power_w": None, "timestamp": now - 1}],
    }
    view = V.build_system_view(host, devices, expected_nodes=3, now=now)
    assert view.is_cluster
    assert [n.hostname for n in view.nodes] == ["node-a", "node-b"]
    assert view.nodes[0].devices[0].utilization_pct == 55.0
    assert view.nodes[1].devices == []
    assert view.missing_nodes == 1
    cpu = next(r for r in view.rollups if r.metric == "cpu_pct")
    assert cpu.min_value == 20.0 and cpu.max_value == 90.0
    assert cpu.max_node == "node-b"
    assert not view.nodes[0].stale
    d = view.as_dict()
    assert d["is_cluster"] is True


def test_system_view_single_node_no_rollups():
    view = V.build_system_view({0: [_host_row(0, "solo", 10.0, 999.0)]}, now=1000.0)
    assert not view.is_cluster
    assert view.rollups == []


def test_system_view_staleness():
    view = V.build_system_view(
        {0: [_host_row(0, "n", 10.0, 100.0)]}, now=200.0
    )
    assert view.nodes[0].stale


def test_process_view_busiest_and_stale():
    now = 50.0
    procs = {
        0: [{"hostname": "h", "pid": 10, "cpu_pct": 30.0, "rss_bytes": 1 << 30,
             "vms_bytes": 2 << 30, "num_threads": 8, "timestamp": now - 1}],
        1: [{"hostname": "h", "pid": 11, "cpu_pct": 95.0, "rss_bytes": 2 << 30,
             "vms_bytes": 3 << 30, "num_threads": 8, "timestamp": now - 20}],
    }
    view = V.build_process_view(procs, now=now)
    assert view.busiest_rank == 1
    assert view.total_rss_bytes == 3 << 30
    assert not view.ranks[0].stale
    assert view.ranks[1].stale


def test_all_views_json_serializable():
    """The browser endpoint json.dumps() the views verbatim — one numpy
    scalar anywhere in as_dict() would 500 /api/live."""
    import json

    rank_rows = {0: _step_rows(), 1: _step_rows(rank_offset=20.0)}
    window = build_step_time_window(rank_rows)
    views = [
        V.build_step_time_view(window, world_size=2, latest_ts=30.0),
        V.build_memory_view({0: _mem_rows(8 << 30)}),
        V.build_system_view(
            {0: [_host_row(0, "a", 10.0, 1.0)],
             1: [_host_row(1, "b", 90.0, 1.0)]},
            expected_nodes=2, now=2.0,
        ),
        V.build_process_view(
            {0: [{"hostname": "h", "pid": 1, "cpu_pct": 5.0,
                  "rss_bytes": 1, "vms_bytes": 1, "num_threads": 1,
                  "timestamp": 1.0}]},
            now=2.0,
        ),
    ]
    for view in views:
        payload = json.dumps(view.as_dict())  # must not raise
        assert json.loads(payload)  # and round-trips


def test_phase_stat_median_rank_attribution():
    """Every phase names both ends of its spread: the worst rank AND
    the rank closest to the cross-rank median (report parity, r4)."""
    from traceml_tpu.utils import timing as T
    from traceml_tpu.utils.step_time_window import build_step_time_window
    from traceml_tpu.renderers.views import build_step_time_view

    def row(step, ms):
        return {"step": step, "clock": "device", "events": {
            T.STEP_TIME: {"cpu_ms": ms, "device_ms": ms, "count": 1}}}

    rows = {
        0: [row(s, 100.0) for s in range(1, 31)],
        1: [row(s, 101.0) for s in range(1, 31)],   # the median-closest
        2: [row(s, 160.0) for s in range(1, 31)],   # the worst
    }
    view = build_step_time_view(build_step_time_window(rows))
    step = next(p for p in view.phases if p.key == "step_time")
    assert step.worst_rank == 2
    assert step.median_rank == 1
    d = view.as_dict()
    assert d["phases"][0]["median_rank"] == 1
