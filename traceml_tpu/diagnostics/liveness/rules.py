"""Liveness rules: RANK_LOST, LIKELY_PREEMPTED, WORLD_STALE.

All consume one :class:`LivenessContext` built from a persisted
``rank_status.json`` snapshot (states as written by the aggregator —
never re-derived from wall clock, see aggregator/liveness.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from traceml_tpu.aggregator.liveness import (
    STATE_ACTIVE,
    STATE_LOST,
    STATE_STALE,
)
from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    confidence_from,
)
from traceml_tpu.diagnostics.liveness.policy import LivenessPolicy


@dataclasses.dataclass
class RankInfo:
    rank: int
    state: str
    last_seen: Optional[float] = None
    last_progress: Optional[float] = None
    first_seen: Optional[float] = None
    finished: bool = False


@dataclasses.dataclass
class LivenessContext:
    policy: LivenessPolicy
    snapshot_ts: float
    expected_world_size: int
    lost_after_sec: float
    ranks: List[RankInfo]
    # ranks the launcher expected that never sent a single byte —
    # killed before first contact, or never scheduled at all
    never_seen: List[int]

    def by_state(self, state: str) -> List[RankInfo]:
        return [r for r in self.ranks if r.state == state]


def build_context(
    snapshot: Dict[str, Any], policy: LivenessPolicy
) -> LivenessContext:
    raw_ranks = snapshot.get("ranks") or {}
    thresholds = snapshot.get("thresholds") or {}
    ranks: List[RankInfo] = []
    seen: set = set()
    for rank_s, info in raw_ranks.items():
        try:
            rank = int(rank_s)
        except (TypeError, ValueError):
            continue
        if not isinstance(info, dict):
            continue
        seen.add(rank)
        ranks.append(
            RankInfo(
                rank=rank,
                state=str(info.get("state", STATE_ACTIVE)),
                last_seen=info.get("last_seen"),
                last_progress=info.get("last_progress"),
                first_seen=info.get("first_seen"),
                finished=bool(info.get("finished")),
            )
        )
    expected = int(snapshot.get("expected_world_size") or len(seen) or 1)
    never_seen = sorted(set(range(expected)) - seen)
    return LivenessContext(
        policy=policy,
        snapshot_ts=float(snapshot.get("ts") or 0.0),
        expected_world_size=expected,
        lost_after_sec=float(thresholds.get("lost_after_sec") or 30.0),
        ranks=sorted(ranks, key=lambda r: r.rank),
        never_seen=never_seen,
    )


def _silent_for(ctx: LivenessContext, r: RankInfo) -> Optional[float]:
    if r.last_seen is None or ctx.snapshot_ts <= 0:
        return None
    return max(0.0, ctx.snapshot_ts - r.last_seen)


class RankLostRule:
    """A non-finished rank fell silent past the LOST threshold while
    the rest of the world kept reporting — its telemetry stream (and
    almost certainly its training process) is gone.  Ranks that never
    made first contact count too."""

    def evaluate(self, ctx: LivenessContext) -> List[DiagnosticIssue]:
        lost = [r for r in ctx.by_state(STATE_LOST) if not r.finished]
        all_lost = sorted([r.rank for r in lost] + ctx.never_seen)
        if not all_lost:
            return []
        world = max(1, ctx.expected_world_size)
        share = len(all_lost) / world
        silences = {
            str(r.rank): round(s, 1)
            for r in lost
            if (s := _silent_for(ctx, r)) is not None
        }
        evidence: Dict[str, Any] = {
            "lost_ranks": all_lost[:32],
            "expected_world_size": world,
            "lost_after_sec": ctx.lost_after_sec,
            "silent_for_sec": silences,
        }
        if ctx.never_seen:
            evidence["never_seen_ranks"] = ctx.never_seen[:32]
        return [
            DiagnosticIssue(
                kind="RANK_LOST",
                severity=SEVERITY_CRITICAL,
                summary=(
                    f"{len(all_lost)} of {world} rank(s) went silent past "
                    f"the {ctx.lost_after_sec:.0f}s liveness threshold "
                    f"without finishing — their telemetry has a data gap "
                    "from last contact onward."
                ),
                action=(
                    "Check the lost ranks' hosts/logs for OOM kills, "
                    "preemption notices, or crashes; cross-rank metrics "
                    "after the loss point cover survivors only."
                ),
                metric="lost_rank_share",
                score=float(share),
                ranks=all_lost[:64],
                confidence=confidence_from(
                    # the state machine already applied the threshold;
                    # margin comes from how far past LOST the silence ran
                    max(
                        [s for s in silences.values()] or [ctx.lost_after_sec]
                    ),
                    ctx.lost_after_sec,
                    coverage=min(1.0, len(ctx.ranks) / world),
                ),
                evidence=evidence,
            )
        ]


class LikelyPreemptedRule:
    """Refines RANK_LOST: the rank was making step progress right up to
    its final contact, then vanished mid-stride — the abrupt-kill
    profile (preemption, OOM kill, hardware loss), as opposed to a rank
    that idled or hung before going silent."""

    def evaluate(self, ctx: LivenessContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        abrupt: List[RankInfo] = []
        for r in ctx.by_state(STATE_LOST):
            if r.finished or r.last_progress is None or r.last_seen is None:
                continue
            if r.last_seen - r.last_progress <= p.preempt_stride_sec:
                abrupt.append(r)
        if not abrupt:
            return []
        ranks = [r.rank for r in abrupt]
        gaps = {
            str(r.rank): round(r.last_seen - r.last_progress, 1)
            for r in abrupt
        }
        return [
            DiagnosticIssue(
                kind="LIKELY_PREEMPTED",
                severity=SEVERITY_WARNING,
                summary=(
                    f"{len(ranks)} lost rank(s) were stepping normally "
                    "until their final contact (progress within "
                    f"{p.preempt_stride_sec:.0f}s of last heartbeat) — "
                    "abrupt termination (preemption/OOM kill) is the "
                    "likely cause, not a hang."
                ),
                action=(
                    "Check the scheduler/cloud console for preemption or "
                    "eviction events on these hosts; if preemptible "
                    "capacity, consider checkpointing more frequently."
                ),
                metric="preempt_profile_ranks",
                score=float(len(ranks) / max(1, ctx.expected_world_size)),
                ranks=ranks[:64],
                confidence=confidence_from(
                    1.0, 1.0, coverage=min(1.0, len(ctx.ranks) / max(1, ctx.expected_world_size))
                ),
                evidence={
                    "progress_to_silence_gap_sec": gaps,
                    "preempt_stride_sec": p.preempt_stride_sec,
                },
            )
        ]


class WorldStaleRule:
    """A large share of the world simultaneously STALE (silent but not
    yet LOST) — the network-partition / aggregator-overload profile
    rather than individual rank death."""

    def evaluate(self, ctx: LivenessContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        stale = [r for r in ctx.by_state(STATE_STALE) if not r.finished]
        world = max(1, ctx.expected_world_size)
        share = len(stale) / world
        if share < p.stale_share_warn:
            return []
        ranks = [r.rank for r in stale]
        return [
            DiagnosticIssue(
                kind="WORLD_STALE",
                severity=SEVERITY_WARNING,
                summary=(
                    f"{len(stale)} of {world} rank(s) are simultaneously "
                    "stale (heartbeats missing but below the LOST "
                    "threshold) — a shared cause (network partition, "
                    "aggregator overload) is more likely than "
                    "independent rank failures."
                ),
                action=(
                    "Check aggregator host load and the network path "
                    "between ranks and the aggregator; individual rank "
                    "verdicts are unreliable while most of the world is "
                    "silent."
                ),
                metric="stale_rank_share",
                score=float(share),
                ranks=ranks[:64],
                confidence=confidence_from(share, p.stale_share_warn),
                evidence={"stale_ranks": ranks[:32], "stale_share": round(share, 3)},
            )
        ]


DEFAULT_RULES = (
    RankLostRule(),
    LikelyPreemptedRule(),
    WorldStaleRule(),
)
