"""Columnar window engine: struct-of-arrays ring buffers + vectorized
window builds for the live analytics path.

The scalar pipeline in :mod:`traceml_tpu.utils.step_time_window` is the
golden reference; this module is a drop-in fast path that must produce
**byte-identical** payloads.  Three pieces:

* :class:`StepTimeColumns` / :class:`MemoryColumns` — per-rank numpy
  ring buffers (preallocated to 2x the retention bound, compacted with a
  memmove when the write head reaches the end) that the snapshot store
  fills in lockstep with its row deques.  Appends that the vectorized
  build cannot represent exactly (duplicate or out-of-order steps, a
  ``None`` step id, malformed event payloads, non-integer byte counts)
  set a sticky ``columnar_ok = False`` flag on the rank's buffer.
* :func:`build_columnar_step_time_window` — the vectorized equivalent of
  ``build_step_time_window``: suffix alignment via unique-counts +
  ``searchsorted``, clock selection as a boolean reduction, residual
  clamp and per-phase averages/medians as numpy reductions over a
  ``(rank, phase, aligned_step)`` cube.  Raises :class:`ColumnarFallback`
  when any participating rank is flagged, so the caller reruns the
  scalar reference on the row deques instead.
* :class:`ColumnarStepTimeWindow` — a ``StepTimeWindow`` whose
  ``rank_windows`` materialize per-rank lists lazily from the cube, so
  diagnosis rules that only touch a few phases never pay for the rest.

Exactness rules the implementation leans on (and the golden tests pin):

* ``np.cumsum(xs)[-1]`` reproduces Python's left-fold ``sum(xs)``
  exactly (``np.sum`` does NOT — it reduces pairwise);
* substituting ``0.0`` for a missing value is exact for the non-negative
  duration folds used here (``x + 0.0 == x``);
* ``np.median`` and ``statistics.median`` agree for float input (odd
  length picks the same element; even length computes ``(a + b) / 2``
  both ways);
* occupancy numerator/denominator pairs are precomputed at append time
  by the scalar :func:`row_occupancy_parts`, so the events-dict
  iteration order inside the fold is preserved by construction;
* every value escaping into a payload goes through ``.tolist()`` /
  ``float()`` first — ``np.float64`` is not JSON serializable and its
  ``__round__`` differs from the float one.

Round 19 adds the *incremental window engine* (the ``*WindowCache``
classes at the bottom of this module): each domain window keeps a
persistent aligned-cube / per-slot cache owned by the snapshot store,
and a dirty tick extends it by only the newly appended/aligned columns.
Any condition the delta path cannot represent exactly — realignment,
ring eviction crossing the window start, a clock flip, a flagged buffer
— invalidates back to the full build above, which stays the golden
reference: incremental output is bit-identical to a from-scratch build
every tick (pinned by tests/utils/test_incremental_window.py).

Kill switches: ``TRACEML_COLUMNAR_WINDOW=0`` forces the scalar path;
``TRACEML_INCR_WINDOW=0`` forces full rebuilds (cache never consulted);
``TRACEML_VECTOR_DIAGNOSIS=0`` forces the scalar rule-evaluation arm
(and disables the per-(domain, version) diagnosis cache).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from traceml_tpu.config import flags
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import (
    ACCOUNTED_PHASES,
    ALL_KEYS,
    PHASES,
    RESIDUAL_KEY,
    STEP_KEY,
    RankWindow,
    StepCombinedTimeMetric,
    StepTimeWindow,
    row_occupancy_parts,
)

# event layout inside the value cube: 0 = the step envelope, 1.. = the
# accounted phases in PHASES order (the order the scalar fold uses)
EVENT_NAMES = (T.STEP_TIME,) + tuple(PHASES.values())
N_EVENTS = len(EVENT_NAMES)
_EVENT_INDEX = {name: i for i, name in enumerate(EVENT_NAMES)}
KEY_INDEX = {k: i for i, k in enumerate(ALL_KEYS)}

_NAN = float("nan")


def columnar_window_enabled() -> bool:
    return flags.COLUMNAR_WINDOW.enabled()


def incr_window_enabled() -> bool:
    return flags.INCR_WINDOW.enabled()


def vector_diagnosis_enabled() -> bool:
    return flags.VECTOR_DIAGNOSIS.enabled()


# vectorized-diagnosis fallback accounting: the vector arm never spams
# the log on a pathological session — the first fallback per domain is
# warned once, the rest are counted and surfaced through the tick
# profiler (the r09 shed-warning pattern)
_VECTOR_FALLBACKS: Dict[str, int] = {}
_VECTOR_FALLBACK_WARNED: set = set()


def note_vector_fallback(domain: str) -> None:
    _VECTOR_FALLBACKS[domain] = _VECTOR_FALLBACKS.get(domain, 0) + 1
    if domain not in _VECTOR_FALLBACK_WARNED:
        _VECTOR_FALLBACK_WARNED.add(domain)
        logging.getLogger(__name__).warning(
            "vectorized %s diagnosis fell back to the scalar arm "
            "(further fallbacks counted, not logged)", domain,
        )


def vector_fallback_counts() -> Dict[str, int]:
    return dict(_VECTOR_FALLBACKS)


class ColumnarFallback(Exception):
    """Raised when the columnar build cannot reproduce the scalar path
    exactly; the caller must rerun the scalar reference on the rows."""


class _CompactRing:
    """Arrays sized ``2 * cap`` with ``[start, end)`` live; when the
    write head hits ``2 * cap`` the live span is memmoved to the front.
    Appends beyond ``cap`` drop the oldest entry, mirroring the snapshot
    store's ``deque(maxlen=cap)`` exactly, so views are always
    contiguous and eviction is an O(1) ``start`` bump."""

    __slots__ = ("cap", "_start", "_end", "appended_total", "evicted_total")

    def __init__(self, cap: int) -> None:
        self.cap = max(1, int(cap))
        self._start = 0
        self._end = 0
        # monotone lifetime counters — the incremental window caches
        # compare these against their last-build values to detect new
        # rows and evictions without touching the arrays
        self.appended_total = 0
        self.evicted_total = 0

    def __len__(self) -> int:
        return self._end - self._start

    def _arrays(self):  # pragma: no cover - subclass responsibility
        raise NotImplementedError

    def _next_slot(self) -> int:
        if self._end == 2 * self.cap:
            n = len(self)
            lo = self._end - n
            for a in self._arrays():
                a[:n] = a[lo : self._end]
            self._start, self._end = 0, n
        if len(self) == self.cap:
            self._start += 1
            self.evicted_total += 1
        i = self._end
        self._end += 1
        self.appended_total += 1
        return i

    def evict_head(self, n: int) -> None:
        """Drop the oldest ``n`` entries (retention-trim lockstep with
        the snapshot store's deque eviction)."""
        if n > 0:
            dropped = min(n, len(self))
            self._start = min(self._start + n, self._end)
            self.evicted_total += dropped

    def _reset(self) -> None:
        self.evicted_total += len(self)
        self._start = 0
        self._end = 0


class StepTimeColumns(_CompactRing):
    """Per-rank step-time columns mirroring the store's row deque."""

    __slots__ = ("_steps", "_vals", "_clock_ok", "_occ", "_last_step", "columnar_ok")

    def __init__(self, cap: int) -> None:
        super().__init__(cap)
        n = 2 * self.cap
        self._steps = np.empty(n, dtype=np.int64)
        # (row, event, {cpu_ms, device_ms}); NaN == not reported
        self._vals = np.empty((n, N_EVENTS, 2), dtype=np.float64)
        self._clock_ok = np.empty(n, dtype=np.bool_)
        # (row, {device_busy_ms, host_ms}) from row_occupancy_parts;
        # NaN pair == parts unavailable for the row
        self._occ = np.empty((n, 2), dtype=np.float64)
        self._last_step: Optional[int] = None
        self.columnar_ok = True

    def _arrays(self):
        return (self._steps, self._vals, self._clock_ok, self._occ)

    def clear(self) -> None:
        self._reset()
        self._last_step = None
        self.columnar_ok = True

    def append(self, row: Mapping[str, Any]) -> None:
        # always consume a slot, even for rows we cannot represent, so
        # the ring stays 1:1 with the store's deque and eviction math
        # holds; a flagged rank's columns are never read
        i = self._next_slot()
        if not self.columnar_ok:
            return
        try:
            step = int(row["step"])
            if self._last_step is not None and step <= self._last_step:
                raise ColumnarFallback("duplicate or out-of-order step")
            events = row.get("events") or {}
            vals = self._vals[i]
            vals.fill(_NAN)
            for name, ev in events.items():
                j = _EVENT_INDEX.get(name)
                if j is None:
                    continue
                # scalar _row_value treats a truthy non-mapping as an
                # error; float() raises on non-numeric values
                cpu = ev.get("cpu_ms")
                if cpu is not None:
                    vals[j, 0] = float(cpu)
                dev = ev.get("device_ms")
                if dev is not None:
                    vals[j, 1] = float(dev)
            env = events.get(T.STEP_TIME) or {}
            self._clock_ok[i] = (
                row.get("clock") == "device" and env.get("device_ms") is not None
            )
            parts = row_occupancy_parts(events)
            if parts is None:
                self._occ[i, 0] = _NAN
                self._occ[i, 1] = _NAN
            else:
                self._occ[i, 0] = parts[0]
                self._occ[i, 1] = parts[1]
            self._steps[i] = step
            self._last_step = step
        except Exception:
            self.columnar_ok = False

    # live views — valid until the next append/evict/clear
    def steps_view(self) -> np.ndarray:
        return self._steps[self._start : self._end]

    def vals_view(self) -> np.ndarray:
        return self._vals[self._start : self._end]

    def occ_view(self) -> np.ndarray:
        return self._occ[self._start : self._end]

    def clock_all_device(self) -> bool:
        return bool(self._clock_ok[self._start : self._end].all())

    def clock_tail_device(self, k: int) -> bool:
        """True when the newest ``k`` live rows are all device-clocked —
        the incremental tick's O(new) "still all-device" check (old live
        rows were already all-device and rows never mutate)."""
        return bool(self._clock_ok[self._end - k : self._end].all())


# MemoryColumns layout: one int64 matrix, -1 == NULL.  Integer columns
# (not float) so byte counts survive exactly into view payloads
# (history / growth_bytes are ints in the scalar path).
C_STEP, C_DEV, C_CUR, C_PEAK, C_SPEAK, C_LIM = range(6)
_MEM_FIELDS = (
    ("step", C_STEP),
    ("device_id", C_DEV),
    ("current_bytes", C_CUR),
    ("peak_bytes", C_PEAK),
    ("step_peak_bytes", C_SPEAK),
    ("limit_bytes", C_LIM),
)
# int64 -> float64 is exact below 2**53; byte counts near that bound
# (8 PiB) flag the rank instead of silently losing precision
_MAX_EXACT_INT = 2 ** 53


class MemoryColumns(_CompactRing):
    """Per-rank step-memory columns mirroring the store's row deque."""

    __slots__ = ("_data", "columnar_ok")

    def __init__(self, cap: int) -> None:
        super().__init__(cap)
        self._data = np.empty((2 * self.cap, 6), dtype=np.int64)
        self.columnar_ok = True

    def _arrays(self):
        return (self._data,)

    def clear(self) -> None:
        self._reset()
        self.columnar_ok = True

    def append(self, row: Mapping[str, Any]) -> None:
        i = self._next_slot()
        if not self.columnar_ok:
            return
        try:
            out = self._data[i]
            for field, c in _MEM_FIELDS:
                if c == C_DEV:
                    # scalar context does int(row.get("device_id", 0));
                    # a None device would crash there, so fall back
                    v = row.get(field, 0)
                    if v is None or not isinstance(v, int):
                        raise ColumnarFallback(field)
                    out[c] = v
                    continue
                v = row.get(field)
                if v is None:
                    out[c] = -1
                elif isinstance(v, int) and not isinstance(v, bool):
                    # negatives would collide with the -1 NULL sentinel;
                    # huge ints would lose exactness in float64 math
                    if v < 0 or v >= _MAX_EXACT_INT:
                        raise ColumnarFallback(field)
                    out[c] = v
                else:
                    raise ColumnarFallback(field)
        except Exception:
            self.columnar_ok = False

    def data_view(self) -> np.ndarray:
        return self._data[self._start : self._end]

    def column(self, c: int) -> np.ndarray:
        return self._data[self._start : self._end, c]

    def last_used(self) -> float:
        """``step_peak_bytes or current_bytes or 0`` of the newest row —
        the scalar rules' ``rows[-1]`` read (-1 == NULL, falsy like the
        scalar ``or`` chain treats None and 0)."""
        d = self.data_view()
        if d.shape[0] == 0:
            return 0.0
        sp, cur = int(d[-1, C_SPEAK]), int(d[-1, C_CUR])
        return float(sp if sp > 0 else (cur if cur > 0 else 0))


class _ColumnarData:
    """Raw arrays behind a built window (the ``window.col`` namespace
    the renderers/diagnostics fast paths read).  ``medians`` are
    computed lazily from the cube on first access — diagnosis rules
    that never touch a median don't pay the (R, 11, S) partition, and
    the incremental tick skips it entirely unless a consumer asks."""

    __slots__ = (
        "ranks",
        "steps",
        "series_cube",
        "averages",
        "_medians",
        "occupancy",
        "occ_num",
        "occ_host",
    )

    def __init__(
        self, ranks, steps, series_cube, averages, medians, occupancy,
        occ_num=None, occ_host=None,
    ):
        self.ranks: List[int] = ranks
        self.steps: np.ndarray = steps  # (S,) int64 aligned step ids
        self.series_cube: np.ndarray = series_cube  # (R, 11, S) ALL_KEYS order
        self.averages: np.ndarray = averages  # (R, 11)
        self._medians: Optional[np.ndarray] = medians  # (R, 11) or lazy
        self.occupancy: np.ndarray = occupancy  # (R,), NaN == None
        # zero-filled occupancy numerator/denominator parts (R, S) — kept
        # so the incremental cache can re-fold occupancy after a window
        # slide without re-reading the rings
        self.occ_num: Optional[np.ndarray] = occ_num
        self.occ_host: Optional[np.ndarray] = occ_host

    @property
    def medians(self) -> np.ndarray:
        m = self._medians
        if m is None:
            m = self._medians = np.median(self.series_cube, axis=2)
        return m


class _LazySeries(dict):
    """``RankWindow.series`` stand-in: materializes a phase's list from
    the cube on first access.  Consumers only use ``series[key]`` /
    ``series.get``; iteration/equality materialize everything first so
    the dict contract still holds."""

    __slots__ = ("_cube",)

    def __init__(self, cube_r: np.ndarray) -> None:
        super().__init__()
        self._cube = cube_r  # (11, S)

    def __missing__(self, key: str) -> List[float]:
        ki = KEY_INDEX.get(key)
        if ki is None:
            raise KeyError(key)
        vals = self._cube[ki].tolist()
        dict.__setitem__(self, key, vals)
        return vals

    def _materialize_all(self) -> None:
        for k in ALL_KEYS:
            self[k]

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key) -> bool:
        return key in KEY_INDEX or dict.__contains__(self, key)

    def __iter__(self):
        self._materialize_all()
        return dict.__iter__(self)

    def __len__(self) -> int:
        self._materialize_all()
        return dict.__len__(self)

    def keys(self):
        self._materialize_all()
        return dict.keys(self)

    def values(self):
        self._materialize_all()
        return dict.values(self)

    def items(self):
        self._materialize_all()
        return dict.items(self)

    def __eq__(self, other):
        self._materialize_all()
        if isinstance(other, _LazySeries):
            other._materialize_all()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)

    __hash__ = None


class _LazyRankWindows(Mapping):
    """``StepTimeWindow.rank_windows`` stand-in: builds each rank's
    ``RankWindow`` from the cube on first access and caches it."""

    def __init__(self, col: _ColumnarData, steps_list: List[int], clock: str) -> None:
        self._col = col
        self._steps = steps_list
        self._clock = clock
        self._index = {r: i for i, r in enumerate(col.ranks)}
        self._cache: Dict[int, RankWindow] = {}

    def __getitem__(self, rank: int) -> RankWindow:
        w = self._cache.get(rank)
        if w is None:
            i = self._index[rank]
            col = self._col
            occ = float(col.occupancy[i])
            w = RankWindow(
                rank=rank,
                steps=self._steps,
                series=_LazySeries(col.series_cube[i]),
                averages=dict(zip(ALL_KEYS, col.averages[i].tolist())),
                medians=dict(zip(ALL_KEYS, col.medians[i].tolist())),
                clock=self._clock,
                occupancy=occ if occ == occ else None,
            )
            self._cache[rank] = w
        return w

    def __iter__(self):
        return iter(self._col.ranks)

    def __len__(self) -> int:
        return len(self._col.ranks)


class ColumnarStepTimeWindow(StepTimeWindow):
    """A ``StepTimeWindow`` carrying its backing arrays in ``col``."""

    def __init__(self, *, col: _ColumnarData, **kwargs) -> None:
        super().__init__(**kwargs)
        self.col = col

    @property
    def occupancy_by_rank(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for r, v in zip(self.col.ranks, self.col.occupancy.tolist()):
            if v == v:  # not NaN
                out[r] = v
        return out


def _left_fold_last(cube: np.ndarray) -> np.ndarray:
    """Exact sequential left-fold sum along the LAST axis.
    ``np.cumsum`` runs ``np.add.accumulate`` — a strictly sequential
    left-to-right scan per lane (unlike ``np.sum``'s pairwise tree), so
    the last prefix IS the left fold, bit for bit, at C speed.  Shared
    by the full build and the incremental tick so their averages cannot
    diverge.  The ``.copy()`` frees the (…, S) prefix array instead of
    pinning it behind the returned view for the payload's lifetime."""
    if cube.shape[-1] == 1:
        return np.copy(cube[..., 0])
    return np.cumsum(cube, axis=-1)[..., -1].copy()


def _select_clamp_slab(cube_raw: np.ndarray, clock: str) -> np.ndarray:
    """(R, n, N_EVENTS, 2) raw gathered values → (R, 11, n) series slab:
    clock selection, missing → 0.0, residual clamp, explicit accounted
    left-fold in PHASES order (exactly the scalar accumulation).  Every
    output column depends only on its own raw column, which is what lets
    the incremental tick compute bit-identical columns one slab at a
    time."""
    if clock == "device":
        dev = cube_raw[..., 1]
        cpu = cube_raw[..., 0]
        sel = np.where(np.isnan(dev), cpu, dev)
    else:
        sel = cube_raw[..., 0]
    sel = np.where(np.isnan(sel), 0.0, sel)  # missing -> 0.0, like the scalar `or 0.0`
    step = sel[:, :, 0]  # (R, n)
    phases = sel[:, :, 1:]  # (R, n, 9)
    clamped = np.where(
        (step > 0)[:, :, None], np.minimum(phases, step[:, :, None]), phases
    )
    accounted = clamped[:, :, 0].copy()
    for k in range(1, len(ACCOUNTED_PHASES)):
        accounted += clamped[:, :, k]
    residual = np.maximum(0.0, step - accounted)
    slab = np.empty((step.shape[0], len(ALL_KEYS), step.shape[1]), dtype=np.float64)
    slab[:, 0] = step
    slab[:, 1 : 1 + len(ACCOUNTED_PHASES)] = np.moveaxis(clamped, 2, 1)
    slab[:, len(ALL_KEYS) - 1] = residual
    return slab


def _zeroed_occ_parts(occ_parts: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """(R, n, 2) raw occupancy parts → zero-filled (num, host) pair."""
    num = np.where(np.isnan(occ_parts[:, :, 0]), 0.0, occ_parts[:, :, 0])
    host = np.where(np.isnan(occ_parts[:, :, 1]), 0.0, occ_parts[:, :, 1])
    return num, host


def _occupancy_from_sums(
    num_sum: np.ndarray, host_sum: np.ndarray
) -> np.ndarray:
    """Shared tail of :func:`_occupancy_fold` and the incremental
    cache's mirror fold: sum/sum with the scalar path's 1.0 clamp, NaN
    where no host time."""
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(host_sum > 0, np.minimum(num_sum / host_sum, 1.0), np.nan)


def _occupancy_fold(num: np.ndarray, host: np.ndarray) -> np.ndarray:
    """Per-rank occupancy from zero-filled (R, S) part planes — the
    scalar fold's sum/sum with the 1.0 clamp, NaN where no host time."""
    return _occupancy_from_sums(_left_fold_last(num), _left_fold_last(host))


def _fold_step_major(arr_t: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Exact left fold over ``[lo, hi)`` of a STEP-MAJOR mirror
    ``(step, ...)``.  Per lane this performs the identical add sequence
    as :func:`_left_fold_last` over the lane-major cube — same bits —
    but each ``arr_t[j]`` slice is contiguous, so the loop runs at
    memcpy speed instead of gathering one strided element per lane."""
    acc = arr_t[lo].copy()
    for j in range(lo + 1, hi):
        acc += arr_t[j]
    return acc


def _step_time_metrics(
    averages: np.ndarray, ranks: List[int]
) -> Dict[str, StepCombinedTimeMetric]:
    """Cross-rank metrics from the (R, 11) averages (native floats
    throughout; first-max tie-break matching the scalar ``max()``)."""
    metrics: Dict[str, StepCombinedTimeMetric] = {}
    avg_rows = averages.tolist()  # R x 11 native floats
    for ki, key in enumerate(ALL_KEYS):
        col_vals = [row[ki] for row in avg_rows]
        med = float(np.median(averages[:, ki]))
        wi = int(np.argmax(averages[:, ki]))  # first max == scalar max() tie-break
        worst = col_vals[wi]
        metrics[key] = StepCombinedTimeMetric(
            key=key,
            per_rank_avg_ms=dict(zip(ranks, col_vals)),
            median_ms=med,
            worst_ms=worst,
            worst_rank=ranks[wi],
            skew_pct=(worst - med) / med if med > 0 else 0.0,
        )
    return metrics


def build_columnar_step_time_window(
    rank_cols: Mapping[int, StepTimeColumns],
    max_steps: int,
) -> Optional[ColumnarStepTimeWindow]:
    """Vectorized ``build_step_time_window`` over per-rank columns.

    Raises :class:`ColumnarFallback` if any non-empty rank is flagged.
    """
    items = [(r, c) for r, c in sorted(rank_cols.items(), key=lambda kv: kv[0]) if len(c)]
    if not items:
        return None
    for _, c in items:
        if not c.columnar_ok:
            raise ColumnarFallback("flagged rank buffer")
    ranks = [int(r) for r, _ in items]
    R = len(items)

    # 1. suffix alignment: steps present in EVERY rank, last max_steps.
    # Per-rank step columns are strictly ascending and unique (flagged
    # otherwise), so counts==R identifies the intersection.
    step_views = [c.steps_view() for _, c in items]
    if R == 1:
        common = np.array(step_views[0][-max_steps:], dtype=np.int64)
    else:
        uniq, counts = np.unique(np.concatenate(step_views), return_counts=True)
        common = uniq[counts == R][-max_steps:]
    S = int(common.size)
    if S == 0:
        return None

    # 2. clock selection: "device" only if EVERY buffered row (not just
    # the aligned suffix — matching select_clock) is device-clocked
    clock = "device" if all(c.clock_all_device() for _, c in items) else "host"

    # 3. gather the aligned (rank, step, event, clock) values
    cube_raw = np.empty((R, S, N_EVENTS, 2), dtype=np.float64)
    occ_parts = np.empty((R, S, 2), dtype=np.float64)
    for i, (_, c) in enumerate(items):
        idx = np.searchsorted(c.steps_view(), common)
        cube_raw[i] = c.vals_view()[idx]
        occ_parts[i] = c.occ_view()[idx]

    # 4. clock select + residual clamp + accounted left-fold (shared
    # with the incremental tick — see _select_clamp_slab)
    series_cube = _select_clamp_slab(cube_raw, clock)

    # 5. per-rank averages: an exact left-fold sum (medians are lazy on
    # _ColumnarData — most consumers never touch them)
    averages = _left_fold_last(series_cube) / S

    # 6. occupancy: fold the precomputed (device_busy, host) parts
    occ_num, occ_host = _zeroed_occ_parts(occ_parts)
    occupancy = _occupancy_fold(occ_num, occ_host)

    # 7. cross-rank metrics (native floats throughout)
    metrics = _step_time_metrics(averages, ranks)

    phases_present = [
        k
        for j, k in enumerate(ACCOUNTED_PHASES)
        if bool((series_cube[:, 1 + j, :] > 0).any())
    ]

    steps_list = common.tolist()
    col = _ColumnarData(
        ranks=ranks,
        steps=common,
        series_cube=series_cube,
        averages=averages,
        medians=None,
        occupancy=occupancy,
        occ_num=occ_num,
        occ_host=occ_host,
    )
    return ColumnarStepTimeWindow(
        col=col,
        clock=clock,
        steps=steps_list,
        ranks=list(ranks),
        rank_windows=_LazyRankWindows(col, steps_list, clock),
        metrics=metrics,
        phases_present=phases_present,
        n_steps=S,
    )


def window_to_plain(w: Optional[StepTimeWindow]) -> Optional[Dict[str, Any]]:
    """Canonical plain-dict form of a window for golden comparisons
    (dataclass ``__eq__`` is class-sensitive, so a scalar and a columnar
    window never compare equal directly)."""
    if w is None:
        return None
    return {
        "clock": w.clock,
        "steps": list(w.steps),
        "ranks": list(w.ranks),
        "n_steps": w.n_steps,
        "phases_present": list(w.phases_present),
        "metrics": {k: dataclasses.asdict(m) for k, m in w.metrics.items()},
        "rank_windows": {
            r: {
                "rank": rw.rank,
                "steps": list(rw.steps),
                "series": {k: list(rw.series[k]) for k in ALL_KEYS},
                "averages": dict(rw.averages),
                "medians": dict(rw.medians),
                "clock": rw.clock,
                "occupancy": rw.occupancy,
            }
            for r, rw in w.rank_windows.items()
        },
    }


def window_series_cube(
    window: StepTimeWindow, key: str = STEP_KEY
) -> "tuple[List[int], np.ndarray]":
    """``(ranks, (rank × step) cube)`` for one series key of a window,
    rows in ``window.ranks`` order.  Columnar windows hand out a view of
    the value cube; scalar windows materialize the same array from their
    per-rank series lists, so the topology reduction below works on
    either path.  The cube is dense by construction — suffix alignment
    keeps only steps present in EVERY rank."""
    if key not in KEY_INDEX:
        raise KeyError(key)
    col = getattr(window, "col", None)
    if col is not None:
        return list(col.ranks), col.series_cube[:, KEY_INDEX[key], :]
    ranks = list(window.ranks)
    cube = np.array(
        [window.rank_windows[r].series[key] for r in ranks],
        dtype=np.float64,
    ).reshape(len(ranks), window.n_steps)
    return ranks, cube


def reduce_window_by_grouping(
    window: StepTimeWindow, grouping: Any, key: str = STEP_KEY
) -> Dict[str, Any]:
    """(rank × step) → (axis-group × step): reshape one series of a
    window along a topology grouping (``utils.topology.Grouping`` —
    host / axis-coordinate / DCN-side) and return per-group aggregates
    plus a per-step dispersion series.

    Ranks outside the grouping are masked out rather than folded into a
    catch-all group.  Output::

        {"kind", "axis", "steps": [...],
         "groups": [{"key", "ranks", "mean": [...S], "min": [...S],
                     "max": [...S]}, ...],       # grouping-key order
         "dispersion": [...S]}                   # max-min of group means

    ``dispersion`` is the step-wise spread of the group means — the
    signal the attribution scorer explains: near-zero means the grouping
    does not separate the ranks on this series.
    """
    from traceml_tpu.utils.topology import reduce_cube

    ranks, cube = window_series_cube(window, key)
    row_of = {int(r): i for i, r in enumerate(ranks)}
    keys = sorted(grouping.groups, key=lambda k: str(k))
    group_index = np.zeros(len(ranks), dtype=np.int64)
    member = np.zeros(len(ranks), dtype=bool)
    for g, k in enumerate(keys):
        for r in grouping.groups[k]:
            i = row_of.get(int(r))
            if i is not None:
                group_index[i] = g
                member[i] = True
    mask = np.broadcast_to(member[:, None], cube.shape)
    red = reduce_cube(cube, group_index, len(keys), mask=mask)
    means = red["mean"]
    with np.errstate(invalid="ignore"):
        spread = np.nanmax(means, axis=0) - np.nanmin(means, axis=0)
    return {
        "kind": grouping.kind,
        "axis": grouping.axis,
        "steps": list(window.steps),
        "groups": [
            {
                "key": str(k),
                "ranks": sorted(int(r) for r in grouping.groups[k]),
                "mean": means[g].tolist(),
                "min": red["min"][g].tolist(),
                "max": red["max"][g].tolist(),
            }
            for g, k in enumerate(keys)
        ],
        "dispersion": np.where(np.isfinite(spread), spread, 0.0).tolist(),
    }


class MemorySeries:
    """One (rank, device) step-memory series, sorted by step — the
    single representation every step-memory rule consumes, buildable
    from row dicts (scalar reference) or from :class:`MemoryColumns`.

    Values are float64 with NaN for NULL; both construction paths yield
    bit-identical arrays for the same data (int64 -> float64 is exact
    below 2**53, and MemoryColumns flags anything larger)."""

    __slots__ = ("rank", "dev", "steps", "current", "peak", "step_peak", "limit")

    def __init__(self, rank, dev, steps, current, peak, step_peak, limit):
        # stable sort by (step or 0), matching the scalar context's
        # rows.sort(key=lambda r: (r.get("step") or 0))
        order = np.argsort(np.where(np.isnan(steps), 0.0, steps), kind="stable")
        self.rank = rank
        self.dev = dev
        self.steps = steps[order]
        self.current = current[order]
        self.peak = peak[order]
        self.step_peak = step_peak[order]
        self.limit = limit[order]

    @classmethod
    def from_rows(cls, rank: int, dev: int, rows: List[Mapping[str, Any]]) -> "MemorySeries":
        def col(key: str) -> np.ndarray:
            return np.array(
                [
                    float(r[key]) if r.get(key) is not None else _NAN
                    for r in rows
                ],
                dtype=np.float64,
            )

        return cls(
            rank,
            dev,
            col("step"),
            col("current_bytes"),
            col("peak_bytes"),
            col("step_peak_bytes"),
            col("limit_bytes"),
        )

    @classmethod
    def from_int_columns(
        cls, rank: int, dev: int, data: np.ndarray
    ) -> "MemorySeries":
        """``data``: the (n, 6) int64 slice of a MemoryColumns buffer
        already filtered to one device; -1 == NULL."""

        def col(c: int) -> np.ndarray:
            a = data[:, c].astype(np.float64)
            a[data[:, c] == -1] = _NAN
            return a

        return cls(rank, dev, col(C_STEP), col(C_CUR), col(C_PEAK), col(C_SPEAK), col(C_LIM))

    def __len__(self) -> int:
        return int(self.steps.shape[0])

    @staticmethod
    def _opt(v: float) -> Optional[float]:
        return None if v != v else v

    def last_values(self):
        """(step_peak, current, limit) of the final (sorted) row as
        Optional floats — what the scalar rules read via rows[-1]."""
        return (
            self._opt(float(self.step_peak[-1])),
            self._opt(float(self.current[-1])),
            self._opt(float(self.limit[-1])),
        )

    def used_series(self) -> np.ndarray:
        """Per-row ``step_peak or current or 0`` (NaN-aware truthiness,
        so NULL and 0 both fall through, like the scalar `or` chain)."""
        sp, cur = self.step_peak, self.current
        sp_ok = ~np.isnan(sp) & (sp != 0)
        cur_ok = ~np.isnan(cur) & (cur != 0)
        return np.where(sp_ok, sp, np.where(cur_ok, cur, 0.0))

    def latest_pressure(self) -> Optional[float]:
        """used/limit of the newest row where both are truthy."""
        used = self.used_series()
        lim = self.limit
        ok = (used != 0) & ~np.isnan(lim) & (lim != 0)
        idx = np.flatnonzero(ok)
        if idx.size == 0:
            return None
        i = int(idx[-1])
        return float(used[i]) / float(lim[i])

    def last_used(self) -> float:
        sp, cur, _ = self.last_values()
        return float(sp or cur or 0)

    def current_list(self) -> List[float]:
        """``float(current_bytes or 0)`` per row — the creep series."""
        cur = self.current
        return np.where(np.isnan(cur), 0.0, cur).tolist()


# ---------------------------------------------------------------------------
# Collectives domain (round 11): per-rank (step, op, dtype) rows →
# per-step overlap-efficiency window.
# ---------------------------------------------------------------------------

# canonical op vocabulary — mirrors instrumentation/collectives.OP_KINDS
# (pinned equal by tests/utils/test_collectives_window.py so the two
# layers can't silently fork)
COLLECTIVE_OPS = (
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "p2p",
    "other",
)
_COLL_OP_INDEX = {op: i for i, op in enumerate(COLLECTIVE_OPS)}
_COLL_DTYPE_VOCAB_MAX = 64  # per-buffer dtype vocabulary bound

# int column layout
CC_STEP, CC_COUNT, CC_BYTES, CC_GROUP = range(4)


class CollectivesColumns(_CompactRing):
    """Per-rank collectives columns mirroring the store's row deque.

    One appended row per (step, op, dtype) aggregate from the sampler;
    steps are non-decreasing (several op/dtype rows share a step) —
    anything else flags the buffer for the scalar reference path."""

    __slots__ = (
        "_ints",
        "_floats",
        "_ops",
        "_dtypes",
        "_dtype_vocab",
        "_dtype_index",
        "_last_step",
        "columnar_ok",
    )

    def __init__(self, cap: int) -> None:
        super().__init__(cap)
        n = 2 * self.cap
        self._ints = np.empty((n, 4), dtype=np.int64)
        self._floats = np.empty((n, 2), dtype=np.float64)  # duration, exposed
        self._ops = np.empty(n, dtype=np.int8)
        self._dtypes = np.empty(n, dtype=np.int16)
        self._dtype_vocab: List[str] = []
        self._dtype_index: Dict[str, int] = {}
        self._last_step: Optional[int] = None
        self.columnar_ok = True

    def _arrays(self):
        return (self._ints, self._floats, self._ops, self._dtypes)

    def clear(self) -> None:
        self._reset()
        self._last_step = None
        self.columnar_ok = True
        # the dtype vocab survives a clear on purpose: codes in the ring
        # are gone, and re-coding the same strings is stable either way

    def append(self, row: Mapping[str, Any]) -> None:
        # always consume a slot (ring stays 1:1 with the row deque)
        i = self._next_slot()
        if not self.columnar_ok:
            return
        try:
            step = int(row["step"])
            if isinstance(row["step"], bool):
                raise ColumnarFallback("bool step")
            if self._last_step is not None and step < self._last_step:
                raise ColumnarFallback("out-of-order step")
            op = row.get("op")
            oi = _COLL_OP_INDEX.get(op)
            if oi is None:
                oi = _COLL_OP_INDEX["other"]
            dtype = str(row.get("dtype", "") or "")
            di = self._dtype_index.get(dtype)
            if di is None:
                if len(self._dtype_vocab) >= _COLL_DTYPE_VOCAB_MAX:
                    raise ColumnarFallback("dtype vocabulary overflow")
                di = len(self._dtype_vocab)
                self._dtype_vocab.append(dtype)
                self._dtype_index[dtype] = di
            ints = self._ints[i]
            for c, key in ((CC_COUNT, "count"), (CC_BYTES, "bytes"), (CC_GROUP, "group_size")):
                v = row.get(key, 0)
                if v is None:
                    v = 0
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ColumnarFallback(key)
                if v < 0 or v >= _MAX_EXACT_INT:
                    raise ColumnarFallback(key)
                ints[c] = v
            ints[CC_STEP] = step
            dur = float(row.get("duration_ms", 0.0) or 0.0)
            exp = float(row.get("exposed_ms", 0.0) or 0.0)
            if dur < 0.0 or exp < 0.0 or exp > dur:
                raise ColumnarFallback("exposure outside duration")
            self._floats[i, 0] = dur
            self._floats[i, 1] = exp
            self._ops[i] = oi
            self._dtypes[i] = di
            self._last_step = step
        except Exception:
            self.columnar_ok = False

    # live views — valid until the next append/evict/clear
    def steps_view(self) -> np.ndarray:
        return self._ints[self._start : self._end, CC_STEP]

    def ints_view(self) -> np.ndarray:
        return self._ints[self._start : self._end]

    def floats_view(self) -> np.ndarray:
        return self._floats[self._start : self._end]

    def ops_view(self) -> np.ndarray:
        return self._ops[self._start : self._end]

    def dtypes_view(self) -> np.ndarray:
        return self._dtypes[self._start : self._end]

    def dtype_name(self, code: int) -> str:
        return self._dtype_vocab[code]


def _overlap_efficiency(total_ms: float, exposed_ms: float) -> float:
    """Share of comm time hidden behind compute: ``1 − exposed/total``.
    A zero-comm step is perfectly hidden by definition → 1.0, not NaN."""
    if total_ms > 0.0:
        return 1.0 - exposed_ms / total_ms
    return 1.0


@dataclasses.dataclass
class CollectivesWindow:
    """Cross-rank collectives aggregate over the last ``n_steps`` steps.

    Steps are the UNION of the ranks' steps (ragged participation — a
    rank that skips a collective still leaves the step in the window).
    ``per_step`` series are aligned to ``steps``; ``overlap_efficiency``
    is ``1 − exposed/total`` with zero-comm steps defined as 1.0."""

    steps: List[int]
    n_steps: int
    ranks: List[int]
    group_size: int
    per_step: Dict[str, List[float]]
    per_op: Dict[str, Dict[str, float]]
    per_rank: Dict[int, Dict[str, float]]
    totals: Dict[str, float]


def build_collectives_window_rows(
    rank_rows: Mapping[int, Any],
    max_steps: int,
) -> Optional[CollectivesWindow]:
    """Scalar reference fold over row dicts — the golden path the
    columnar build below must reproduce bit-identically.  Ranks are
    folded in sorted order, rows in arrival order."""
    items = [(r, list(rows)) for r, rows in sorted(rank_rows.items()) if rows]
    if not items:
        return None
    all_steps = sorted({int(row["step"]) for _, rows in items for row in rows})
    steps = all_steps[-max_steps:]
    lo = steps[0]
    idx = {s: i for i, s in enumerate(steps)}
    S = len(steps)

    count = [0] * S
    nbytes = [0] * S
    dur = [0.0] * S
    exp = [0.0] * S
    ar_fp32 = [0] * S
    per_op: Dict[str, Dict[str, float]] = {}
    per_rank: Dict[int, Dict[str, float]] = {}
    group = 1
    for rank, rows in items:
        r_dur = 0.0
        r_exp = 0.0
        r_bytes = 0
        for row in rows:
            s = int(row["step"])
            if s < lo:
                continue
            i = idx[s]
            c = int(row.get("count", 0) or 0)
            b = int(row.get("bytes", 0) or 0)
            d = float(row.get("duration_ms", 0.0) or 0.0)
            e = float(row.get("exposed_ms", 0.0) or 0.0)
            op = row.get("op") if row.get("op") in _COLL_OP_INDEX else "other"
            count[i] += c
            nbytes[i] += b
            dur[i] += d
            exp[i] += e
            if op == "all_reduce" and str(row.get("dtype", "")) == "float32":
                ar_fp32[i] += b
            slot = per_op.get(op)
            if slot is None:
                slot = per_op[op] = {
                    "count": 0, "bytes": 0, "duration_ms": 0.0, "exposed_ms": 0.0,
                }
            slot["count"] += c
            slot["bytes"] += b
            slot["duration_ms"] += d
            slot["exposed_ms"] += e
            group = max(group, int(row.get("group_size", 1) or 1))
            r_dur += d
            r_exp += e
            r_bytes += b
        per_rank[rank] = {
            "duration_ms": r_dur,
            "exposed_ms": r_exp,
            "bytes": r_bytes,
            "overlap_efficiency": _overlap_efficiency(r_dur, r_exp),
        }

    total_dur = 0.0
    total_exp = 0.0
    for v in dur:
        total_dur += v
    for v in exp:
        total_exp += v
    return CollectivesWindow(
        steps=steps,
        n_steps=S,
        ranks=[r for r, _ in items],
        group_size=group,
        per_step={
            "count": count,
            "bytes": nbytes,
            "duration_ms": dur,
            "exposed_ms": exp,
            "overlap_efficiency": [
                _overlap_efficiency(dur[i], exp[i]) for i in range(S)
            ],
            "allreduce_fp32_bytes": ar_fp32,
        },
        per_op=per_op,
        per_rank=per_rank,
        totals={
            "count": sum(count),
            "bytes": sum(nbytes),
            "duration_ms": total_dur,
            "exposed_ms": total_exp,
            "overlap_efficiency": _overlap_efficiency(total_dur, total_exp),
        },
    )


def build_columnar_collectives_window(
    rank_cols: Mapping[int, CollectivesColumns],
    max_steps: int,
) -> Optional[CollectivesWindow]:
    """Vectorized ``build_collectives_window_rows`` over per-rank columns.

    Exactness: per-slot accumulation uses ``np.add.at`` — unbuffered,
    element-order application, so repeated step slots accumulate in row
    order exactly like the scalar ``acc[i] += v`` fold; ranks are
    processed in sorted order, matching the scalar traversal.  Raises
    :class:`ColumnarFallback` if any non-empty rank is flagged."""
    items = [
        (r, c) for r, c in sorted(rank_cols.items(), key=lambda kv: kv[0]) if len(c)
    ]
    if not items:
        return None
    for _, c in items:
        if not c.columnar_ok:
            raise ColumnarFallback("flagged rank buffer")

    uniq = np.unique(np.concatenate([c.steps_view() for _, c in items]))
    common = uniq[-max_steps:]
    S = int(common.size)
    lo = int(common[0])

    count = np.zeros(S, dtype=np.int64)
    nbytes = np.zeros(S, dtype=np.int64)
    dur = np.zeros(S, dtype=np.float64)
    exp = np.zeros(S, dtype=np.float64)
    ar_fp32 = np.zeros(S, dtype=np.int64)
    n_ops = len(COLLECTIVE_OPS)
    op_count = np.zeros(n_ops, dtype=np.int64)
    op_bytes = np.zeros(n_ops, dtype=np.int64)
    op_dur = np.zeros(n_ops, dtype=np.float64)
    op_exp = np.zeros(n_ops, dtype=np.float64)
    op_seen = np.zeros(n_ops, dtype=np.bool_)
    per_rank: Dict[int, Dict[str, float]] = {}
    group = 1
    ar_code = _COLL_OP_INDEX["all_reduce"]

    for rank, c in items:
        steps = c.steps_view()
        mask = steps >= lo
        slots = np.searchsorted(common, steps[mask])
        ints = c.ints_view()[mask]
        floats = c.floats_view()[mask]
        ops = c.ops_view()[mask].astype(np.int64)
        np.add.at(count, slots, ints[:, CC_COUNT])
        np.add.at(nbytes, slots, ints[:, CC_BYTES])
        np.add.at(dur, slots, floats[:, 0])
        np.add.at(exp, slots, floats[:, 1])
        np.add.at(op_count, ops, ints[:, CC_COUNT])
        np.add.at(op_bytes, ops, ints[:, CC_BYTES])
        np.add.at(op_dur, ops, floats[:, 0])
        np.add.at(op_exp, ops, floats[:, 1])
        op_seen[ops] = True
        try:
            fp32_code = c._dtype_index["float32"]
        except KeyError:
            fp32_code = -1
        fp32_mask = (ops == ar_code) & (c.dtypes_view()[mask] == fp32_code)
        if fp32_mask.any():
            np.add.at(ar_fp32, slots[fp32_mask], ints[fp32_mask, CC_BYTES])
        if ints.shape[0]:
            group = max(group, int(ints[:, CC_GROUP].max()))
            r_dur = float(np.cumsum(floats[:, 0])[-1])
            r_exp = float(np.cumsum(floats[:, 1])[-1])
            r_bytes = int(np.cumsum(ints[:, CC_BYTES])[-1])
        else:
            r_dur = r_exp = 0.0
            r_bytes = 0
        per_rank[rank] = {
            "duration_ms": r_dur,
            "exposed_ms": r_exp,
            "bytes": r_bytes,
            "overlap_efficiency": _overlap_efficiency(r_dur, r_exp),
        }

    dur_l = dur.tolist()
    exp_l = exp.tolist()
    # totals fold over the per-step series, matching the scalar loop
    total_dur = float(np.cumsum(dur)[-1]) if S else 0.0
    total_exp = float(np.cumsum(exp)[-1]) if S else 0.0
    per_op: Dict[str, Dict[str, float]] = {}
    for oi, op in enumerate(COLLECTIVE_OPS):
        if not op_seen[oi]:
            continue
        per_op[op] = {
            "count": int(op_count[oi]),
            "bytes": int(op_bytes[oi]),
            "duration_ms": float(op_dur[oi]),
            "exposed_ms": float(op_exp[oi]),
        }
    return CollectivesWindow(
        steps=common.tolist(),
        n_steps=S,
        ranks=[r for r, _ in items],
        group_size=group,
        per_step={
            "count": count.tolist(),
            "bytes": nbytes.tolist(),
            "duration_ms": dur_l,
            "exposed_ms": exp_l,
            "overlap_efficiency": [
                _overlap_efficiency(dur_l[i], exp_l[i]) for i in range(S)
            ],
            "allreduce_fp32_bytes": ar_fp32.tolist(),
        },
        per_op=per_op,
        per_rank=per_rank,
        totals={
            "count": int(np.cumsum(count)[-1]) if S else 0,
            "bytes": int(np.cumsum(nbytes)[-1]) if S else 0,
            "duration_ms": total_dur,
            "exposed_ms": total_exp,
            "overlap_efficiency": _overlap_efficiency(total_dur, total_exp),
        },
    )


def collectives_window_to_plain(
    w: Optional[CollectivesWindow],
) -> Optional[Dict[str, Any]]:
    """Canonical plain-dict form for golden comparisons."""
    if w is None:
        return None
    return {
        "steps": list(w.steps),
        "n_steps": w.n_steps,
        "ranks": list(w.ranks),
        "group_size": w.group_size,
        "per_step": {k: list(v) for k, v in w.per_step.items()},
        "per_op": {k: dict(v) for k, v in sorted(w.per_op.items())},
        "per_rank": {r: dict(v) for r, v in sorted(w.per_rank.items())},
        "totals": dict(w.totals),
    }


# ---------------------------------------------------------------------------
# Serving domain (round 16): ragged per-request populations → per-window
# TTFT / e2e percentile window.  Requests are variable-length where steps
# were regular, so the ring grows a CSR companion: per-row (offset, len)
# into shared value buffers.
# ---------------------------------------------------------------------------

# int column layout
(
    SV_STEP,
    SV_ENQ,
    SV_DONE,
    SV_ACTIVE,
    SV_QDEPTH,
    SV_DTOK,
    SV_KVB,
    SV_KVL,
) = range(8)
_SV_COUNT_FIELDS = (
    (SV_ENQ, "requests_enqueued"),
    (SV_DONE, "requests_completed"),
    (SV_ACTIVE, "requests_active"),
    (SV_QDEPTH, "queue_depth"),
    (SV_DTOK, "decode_tokens"),
)
# float column layout
SF_PREFILL, SF_DECODE, SF_TPS, SF_OCC, SF_KVH = range(5)
# ragged column layout (CSR offset/len per row into one buffer each)
RG_TTFT, RG_E2E, RG_TOK = range(3)
_RG_FIELDS = ((RG_TTFT, "ttft_ms_list"), (RG_E2E, "e2e_ms_list"), (RG_TOK, "tokens_list"))

_EMPTY_F64 = np.empty(0, dtype=np.float64)


def parse_float_list(s: Optional[str]) -> List[float]:
    """Parse a ``%.3f`` comma-packed population (the serving sampler's
    ``pack_floats`` format).  THE one parser both the scalar reference
    fold and :class:`RaggedEventColumns` use, so parse(pack(x)) yields
    bit-identical floats on both paths.  Raises on malformed tokens —
    the ring turns that into :class:`ColumnarFallback`, the scalar fold
    treats the row's population as empty."""
    if not s:
        return []
    return [float(tok) for tok in s.split(",")]


def _parse_float_list_safe(s: Optional[str]) -> List[float]:
    try:
        return parse_float_list(s)
    except (TypeError, ValueError):
        return []


def _population_percentile(sorted_vals, q: float) -> float:
    """Index-style percentile (no interpolation) over an ascending
    sequence — same element selection for a Python list and an ndarray,
    and the same formula samplers/serving_sampler.percentile uses."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    return float(sorted_vals[min(n - 1, int(n * q))])


class _RaggedBuffer:
    """Growable value store behind one CSR column.

    Rows address their values by *virtual* offset — a monotone counter
    over everything ever appended — so row eviction is free (the head
    values just go dead) and compaction only rebases ``_virt0``, never
    touches the offsets stored in the ring.  Because rows append values
    contiguously and are only evicted from the head, any suffix of live
    rows maps to ONE contiguous physical slice (zero-copy reads)."""

    __slots__ = ("_vals", "_virt0", "_virt_end")

    def __init__(self, cap_hint: int) -> None:
        self._vals = np.empty(max(16, int(cap_hint)), dtype=np.float64)
        self._virt0 = 0  # virtual offset of physical index 0
        self._virt_end = 0  # next virtual offset

    def append(self, vals: List[float], live_min_virt: int) -> int:
        """Store ``vals``; returns their virtual offset.  ``live_min_virt``
        is the oldest live row's offset — everything before it is dead
        and reclaimable when the buffer needs room."""
        n = len(vals)
        end_phys = self._virt_end - self._virt0
        if end_phys + n > self._vals.shape[0]:
            live_phys = live_min_virt - self._virt0
            if live_phys > 0:  # memmove live span to the front, rebase
                live_n = end_phys - live_phys
                self._vals[:live_n] = self._vals[live_phys:end_phys]
                self._virt0 = live_min_virt
                end_phys = live_n
            if end_phys + n > self._vals.shape[0]:
                grown = np.empty(
                    max(2 * self._vals.shape[0], end_phys + n), dtype=np.float64
                )
                grown[:end_phys] = self._vals[:end_phys]
                self._vals = grown
        off = self._virt_end
        if n:
            self._vals[end_phys : end_phys + n] = vals
        self._virt_end += n
        return off

    def view_span(self, virt_a: int, virt_b: int) -> np.ndarray:
        return self._vals[virt_a - self._virt0 : virt_b - self._virt0]

    @property
    def virt_end(self) -> int:
        return self._virt_end


class RaggedEventColumns(_CompactRing):
    """Per-replica serving columns mirroring the store's row deque.

    Scalar columns ride the usual 2x-cap compacted arrays; the ragged
    per-request populations (TTFT ms, e2e ms, tokens) live in CSR form —
    per-row (virtual offset, length) pairs in ``_ragged`` pointing into
    three :class:`_RaggedBuffer` value stores.  Row eviction (ring full
    or retention trim) keeps the two in lockstep for free: offsets are
    virtual, so dead head values are reclaimed lazily on the buffers'
    next compaction.  Appends the vectorized build cannot reproduce
    exactly — bool/duplicate/out-of-order window seq, non-int counts,
    counts outside [0, 2**53), negative phase times, malformed packed
    lists, or a population length disagreeing with
    ``requests_completed`` — set sticky ``columnar_ok = False``."""

    __slots__ = (
        "_ints",
        "_floats",
        "_ragged",
        "_bufs",
        "_last_step",
        "columnar_ok",
    )

    def __init__(self, cap: int) -> None:
        super().__init__(cap)
        n = 2 * self.cap
        self._ints = np.empty((n, 8), dtype=np.int64)
        self._floats = np.empty((n, 5), dtype=np.float64)
        self._ragged = np.empty((n, 3, 2), dtype=np.int64)  # (row, col, {off, len})
        # value capacity hint: ~8 completed requests per window row
        self._bufs = tuple(_RaggedBuffer(8 * self.cap) for _ in range(3))
        self._last_step: Optional[int] = None
        self.columnar_ok = True

    def _arrays(self):
        return (self._ints, self._floats, self._ragged)

    def clear(self) -> None:
        self._reset()
        self._last_step = None
        self.columnar_ok = True
        # value buffers rebase lazily; virtual offsets of cleared rows
        # are simply never read again

    def _live_min_virt(self, col: int, newest: int) -> int:
        """Oldest live row's virtual offset for ``col`` (the compaction
        floor), excluding the not-yet-filled slot ``newest``."""
        if self._start < newest:
            return int(self._ragged[self._start, col, 0])
        return self._bufs[col].virt_end

    def append(self, row: Mapping[str, Any]) -> None:
        # always consume a slot (ring stays 1:1 with the row deque)
        i = self._next_slot()
        if not self.columnar_ok:
            return
        try:
            if isinstance(row["step"], bool):
                raise ColumnarFallback("bool step")
            step = int(row["step"])
            if self._last_step is not None and step <= self._last_step:
                raise ColumnarFallback("duplicate or out-of-order window seq")
            ints = self._ints[i]
            for c, key in _SV_COUNT_FIELDS:
                v = row.get(key, 0)
                if v is None:
                    v = 0
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ColumnarFallback(key)
                if v < 0 or v >= _MAX_EXACT_INT:
                    raise ColumnarFallback(key)
                ints[c] = v
            for c, key in ((SV_KVB, "kv_bytes"), (SV_KVL, "kv_limit_bytes")):
                v = row.get(key, -1)
                if v is None:
                    v = -1
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ColumnarFallback(key)
                if v < -1 or v >= _MAX_EXACT_INT:
                    raise ColumnarFallback(key)
                ints[c] = v
            flts = self._floats[i]
            pre = float(row.get("prefill_ms", 0.0) or 0.0)
            dec = float(row.get("decode_ms", 0.0) or 0.0)
            if pre < 0.0 or dec < 0.0:
                raise ColumnarFallback("negative phase time")
            flts[SF_PREFILL] = pre
            flts[SF_DECODE] = dec
            flts[SF_TPS] = float(row.get("tokens_per_s", 0.0) or 0.0)
            flts[SF_OCC] = float(row.get("batch_occupancy", 0.0) or 0.0)
            kvh = row.get("kv_headroom")
            flts[SF_KVH] = float(kvh) if kvh is not None else -1.0
            done = int(ints[SV_DONE])
            for c, key in _RG_FIELDS:
                vals = parse_float_list(row.get(key))  # raises → fallback
                if len(vals) != done:
                    raise ColumnarFallback(f"{key} length != requests_completed")
                off = self._bufs[c].append(vals, self._live_min_virt(c, i))
                self._ragged[i, c, 0] = off
                self._ragged[i, c, 1] = len(vals)
            ints[SV_STEP] = step
            self._last_step = step
        except Exception:
            self.columnar_ok = False

    # live views — valid until the next append/evict/clear
    def steps_view(self) -> np.ndarray:
        return self._ints[self._start : self._end, SV_STEP]

    def ints_view(self) -> np.ndarray:
        return self._ints[self._start : self._end]

    def floats_view(self) -> np.ndarray:
        return self._floats[self._start : self._end]

    def ragged_suffix(self, col: int, k: int) -> np.ndarray:
        """Concatenated population of live rows ``k..`` for ragged
        column ``col``.  Window seqs are strictly increasing, so every
        window tail is a row suffix — and a row suffix is ONE contiguous
        physical slice (values were appended in row order and only the
        head is ever evicted)."""
        n = len(self)
        if n == 0 or k >= n:
            return _EMPTY_F64
        i0 = self._start + k
        i1 = self._end - 1
        a = int(self._ragged[i0, col, 0])
        b = int(self._ragged[i1, col, 0] + self._ragged[i1, col, 1])
        return self._bufs[col].view_span(a, b)


@dataclasses.dataclass
class ServingWindow:
    """Cross-replica serving aggregate over the last ``n_steps`` window
    seqs.  Steps are the UNION of the replicas' window seqs; ``per_step``
    series align to ``steps``.  Latency percentiles re-rank the
    concatenated RAW per-request populations — never percentiles of the
    row-level percentiles."""

    steps: List[int]
    n_steps: int
    ranks: List[int]
    per_step: Dict[str, List[float]]
    per_rank: Dict[int, Dict[str, float]]
    totals: Dict[str, float]


def _serving_totals(
    enq, done, dtok, qd, pre, dec, per_rank, kv_min, ttft_sorted, e2e_sorted
) -> Dict[str, float]:
    """Shared totals assembly: every fold here is over per-step series
    or already-identical per-rank values, so the scalar and columnar
    builds compute bit-identical totals by construction."""
    total_pre = 0.0
    total_dec = 0.0
    for v in pre:
        total_pre += v
    for v in dec:
        total_dec += v
    phase = total_pre + total_dec
    tps = 0.0
    for r in per_rank:
        tps += per_rank[r]["tokens_per_s"]
    return {
        "requests_enqueued": sum(enq),
        "requests_completed": sum(done),
        "decode_tokens": sum(dtok),
        "queue_depth_last": sum(per_rank[r]["queue_depth"] for r in per_rank),
        "queue_depth_max": max(qd) if qd else 0,
        "prefill_ms": total_pre,
        "decode_ms": total_dec,
        "decode_share": (total_dec / phase) if phase > 0.0 else 0.0,
        "tokens_per_s": tps,
        "kv_headroom_min": kv_min,
        "ttft_p50_ms": _population_percentile(ttft_sorted, 0.50),
        "ttft_p95_ms": _population_percentile(ttft_sorted, 0.95),
        "ttft_p99_ms": _population_percentile(ttft_sorted, 0.99),
        "e2e_p50_ms": _population_percentile(e2e_sorted, 0.50),
        "e2e_p95_ms": _population_percentile(e2e_sorted, 0.95),
        "e2e_p99_ms": _population_percentile(e2e_sorted, 0.99),
    }


def build_serving_window_rows(
    rank_rows: Mapping[int, Any],
    max_steps: int,
) -> Optional[ServingWindow]:
    """Scalar reference fold over serving row dicts — the golden path
    the columnar build below must reproduce bit-identically.  Ranks in
    sorted order, rows in arrival order; malformed packed lists count
    as empty populations (the columnar ring would have flagged them)."""
    items = [(r, list(rows)) for r, rows in sorted(rank_rows.items()) if rows]
    if not items:
        return None
    all_steps = sorted({int(row["step"]) for _, rows in items for row in rows})
    steps = all_steps[-max_steps:]
    lo = steps[0]
    idx = {s: i for i, s in enumerate(steps)}
    S = len(steps)

    enq = [0] * S
    done = [0] * S
    qd = [0] * S
    dtok = [0] * S
    tps = [0.0] * S
    pre = [0.0] * S
    dec = [0.0] * S
    ttft_all: List[float] = []
    e2e_all: List[float] = []
    per_rank: Dict[int, Dict[str, float]] = {}
    kv_min = -1.0
    for rank, rows in items:
        r_done = 0
        r_tok = 0
        r_tps = 0.0
        r_rows = 0
        r_ttft: List[float] = []
        r_qd = 0
        r_active = 0
        r_kvh = -1.0
        for row in rows:
            s = int(row["step"])
            if s < lo:
                continue
            i = idx[s]
            e = int(row.get("requests_enqueued", 0) or 0)
            d = int(row.get("requests_completed", 0) or 0)
            q = int(row.get("queue_depth", 0) or 0)
            t = int(row.get("decode_tokens", 0) or 0)
            v_tps = float(row.get("tokens_per_s", 0.0) or 0.0)
            enq[i] += e
            done[i] += d
            qd[i] += q
            dtok[i] += t
            tps[i] += v_tps
            pre[i] += float(row.get("prefill_ms", 0.0) or 0.0)
            dec[i] += float(row.get("decode_ms", 0.0) or 0.0)
            t_vals = _parse_float_list_safe(row.get("ttft_ms_list"))
            e_vals = _parse_float_list_safe(row.get("e2e_ms_list"))
            ttft_all.extend(t_vals)
            e2e_all.extend(e_vals)
            r_ttft.extend(t_vals)
            r_done += d
            r_tok += t
            r_tps += v_tps
            r_rows += 1
            r_qd = q
            r_active = int(row.get("requests_active", 0) or 0)
            kvh = row.get("kv_headroom")
            kvh = float(kvh) if kvh is not None else -1.0
            if kvh >= 0.0:
                r_kvh = kvh
                kv_min = kvh if kv_min < 0.0 else min(kv_min, kvh)
        r_ttft.sort()
        per_rank[rank] = {
            "requests_completed": r_done,
            "requests_active": r_active,
            "decode_tokens": r_tok,
            "tokens_per_s": (r_tps / r_rows) if r_rows else 0.0,
            "queue_depth": r_qd,
            "ttft_p99_ms": _population_percentile(r_ttft, 0.99),
            "kv_headroom": r_kvh,
        }

    ttft_all.sort()
    e2e_all.sort()
    return ServingWindow(
        steps=steps,
        n_steps=S,
        ranks=[r for r, _ in items],
        per_step={
            "requests_enqueued": enq,
            "requests_completed": done,
            "queue_depth": qd,
            "decode_tokens": dtok,
            "tokens_per_s": tps,
            "prefill_ms": pre,
            "decode_ms": dec,
        },
        per_rank=per_rank,
        totals=_serving_totals(
            enq, done, dtok, qd, pre, dec, per_rank, kv_min, ttft_all, e2e_all
        ),
    )


def build_columnar_serving_window(
    rank_cols: Mapping[int, RaggedEventColumns],
    max_steps: int,
) -> Optional[ServingWindow]:
    """Vectorized ``build_serving_window_rows`` over per-replica ragged
    columns.  Per-slot accumulation uses ``np.add.at`` in sorted-rank
    order (the scalar traversal); window seqs are strictly increasing
    per replica, so the ``>= lo`` tail is a row suffix and each ragged
    population is ONE contiguous slice.  Raises :class:`ColumnarFallback`
    if any non-empty replica buffer is flagged."""
    items = [
        (r, c) for r, c in sorted(rank_cols.items(), key=lambda kv: kv[0]) if len(c)
    ]
    if not items:
        return None
    for _, c in items:
        if not c.columnar_ok:
            raise ColumnarFallback("flagged replica buffer")

    uniq = np.unique(np.concatenate([c.steps_view() for _, c in items]))
    common = uniq[-max_steps:]
    S = int(common.size)
    lo = int(common[0])

    enq = np.zeros(S, dtype=np.int64)
    done = np.zeros(S, dtype=np.int64)
    qd = np.zeros(S, dtype=np.int64)
    dtok = np.zeros(S, dtype=np.int64)
    tps = np.zeros(S, dtype=np.float64)
    pre = np.zeros(S, dtype=np.float64)
    dec = np.zeros(S, dtype=np.float64)
    ttft_parts: List[np.ndarray] = []
    e2e_parts: List[np.ndarray] = []
    per_rank: Dict[int, Dict[str, float]] = {}
    kv_min = -1.0
    for rank, c in items:
        steps = c.steps_view()
        k = int(np.searchsorted(steps, lo, side="left"))
        slots = np.searchsorted(common, steps[k:])
        ints = c.ints_view()[k:]
        flts = c.floats_view()[k:]
        np.add.at(enq, slots, ints[:, SV_ENQ])
        np.add.at(done, slots, ints[:, SV_DONE])
        np.add.at(qd, slots, ints[:, SV_QDEPTH])
        np.add.at(dtok, slots, ints[:, SV_DTOK])
        np.add.at(tps, slots, flts[:, SF_TPS])
        np.add.at(pre, slots, flts[:, SF_PREFILL])
        np.add.at(dec, slots, flts[:, SF_DECODE])
        r_ttft = c.ragged_suffix(RG_TTFT, k)
        ttft_parts.append(r_ttft)
        e2e_parts.append(c.ragged_suffix(RG_E2E, k))
        n_rows = int(ints.shape[0])
        if n_rows:
            r_done = int(np.cumsum(ints[:, SV_DONE])[-1])
            r_tok = int(np.cumsum(ints[:, SV_DTOK])[-1])
            r_tps = float(np.cumsum(flts[:, SF_TPS])[-1]) / n_rows
            r_qd = int(ints[-1, SV_QDEPTH])
            r_active = int(ints[-1, SV_ACTIVE])
        else:
            r_done = r_tok = r_qd = r_active = 0
            r_tps = 0.0
        kvh = flts[:, SF_KVH]
        kv_ok = kvh >= 0.0
        r_kvh = -1.0
        if kv_ok.any():
            r_kvh = float(kvh[np.flatnonzero(kv_ok)[-1]])
            m = float(kvh[kv_ok].min())
            kv_min = m if kv_min < 0.0 else min(kv_min, m)
        per_rank[rank] = {
            "requests_completed": r_done,
            "requests_active": r_active,
            "decode_tokens": r_tok,
            "tokens_per_s": r_tps,
            "queue_depth": r_qd,
            "ttft_p99_ms": _population_percentile(np.sort(r_ttft), 0.99),
            "kv_headroom": r_kvh,
        }

    ttft_sorted = np.sort(np.concatenate(ttft_parts)) if ttft_parts else _EMPTY_F64
    e2e_sorted = np.sort(np.concatenate(e2e_parts)) if e2e_parts else _EMPTY_F64
    enq_l = enq.tolist()
    done_l = done.tolist()
    qd_l = qd.tolist()
    dtok_l = dtok.tolist()
    return ServingWindow(
        steps=common.tolist(),
        n_steps=S,
        ranks=[r for r, _ in items],
        per_step={
            "requests_enqueued": enq_l,
            "requests_completed": done_l,
            "queue_depth": qd_l,
            "decode_tokens": dtok_l,
            "tokens_per_s": tps.tolist(),
            "prefill_ms": pre.tolist(),
            "decode_ms": dec.tolist(),
        },
        per_rank=per_rank,
        totals=_serving_totals(
            enq_l,
            done_l,
            dtok_l,
            qd_l,
            pre.tolist(),
            dec.tolist(),
            per_rank,
            kv_min,
            ttft_sorted,
            e2e_sorted,
        ),
    )


def serving_window_to_plain(w: Optional[ServingWindow]) -> Optional[Dict[str, Any]]:
    """Canonical plain-dict form for golden comparisons."""
    if w is None:
        return None
    return {
        "steps": list(w.steps),
        "n_steps": w.n_steps,
        "ranks": list(w.ranks),
        "per_step": {k: list(v) for k, v in w.per_step.items()},
        "per_rank": {r: dict(v) for r, v in sorted(w.per_rank.items())},
        "totals": dict(w.totals),
    }


# ---------------------------------------------------------------------------
# Incremental window engine (round 19): persistent per-domain caches that
# turn a steady-state dirty tick into O(Δ) work.  The full builds above
# stay the golden reference — every code path below either reproduces
# their output bit-identically or invalidates back to them.
# ---------------------------------------------------------------------------

_EMPTY_I64 = np.empty(0, dtype=np.int64)

# invalidation reasons (the observability vocabulary surfaced through
# WindowBuildStats; tests pin these strings)
INVALIDATE_COLD = "cold_start"
INVALIDATE_RANKS = "rank_set_changed"
INVALIDATE_CLOCK = "clock_flip"
INVALIDATE_EVICTED = "window_evicted"
INVALIDATE_SIZE = "window_size_changed"
INVALIDATE_REALIGNED = "realigned"
INVALIDATE_FALLBACK = "fallback"


class _CacheInvalid(Exception):
    """Internal: the delta path cannot represent this tick exactly —
    fall back to a full rebuild (carrying the reason for the stats)."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


class WindowBuildStats:
    """Per-domain window-build counters: how many ticks were served
    incrementally vs full rebuilds, why the cache invalidated, and the
    last build's wall time.  Surfaced through the snapshot store →
    ``payload_with_versions`` meta → dashboard / final report, so a
    session silently degrading to full rebuilds is visible."""

    __slots__ = (
        "incr_ticks", "full_rebuilds", "invalidations",
        "last_build_ms", "last_path",
    )

    def __init__(self) -> None:
        self.incr_ticks = 0
        self.full_rebuilds = 0
        self.invalidations: Dict[str, int] = {}
        self.last_build_ms = 0.0
        self.last_path = ""

    def note_incr(self, ms: float) -> None:
        self.incr_ticks += 1
        self.last_build_ms = ms
        self.last_path = "incremental"

    def note_full(self, ms: float, reason: str) -> None:
        self.full_rebuilds += 1
        self.invalidations[reason] = self.invalidations.get(reason, 0) + 1
        self.last_build_ms = ms
        self.last_path = "full"

    def snapshot(self) -> Dict[str, Any]:
        return {
            "incr_ticks": self.incr_ticks,
            "full_rebuilds": self.full_rebuilds,
            "invalidations": dict(self.invalidations),
            "last_build_ms": self.last_build_ms,
            "last_path": self.last_path,
        }


#: tick-profiler stage vocabulary (docs/developer_guide/diagnosis-engine.md);
#: tests pin these strings the same way they pin INVALIDATE_*
TICK_STAGES = (
    "refresh", "build", "diagnose", "attribute", "view", "serialize",
)


class TickProfile:
    """Per-stage warm-tick profiler: cumulative nanoseconds per
    (domain, stage) plus counters (diagnosis cache hits/misses, rule
    evaluations, vector fallbacks, attribution grouping reuse).

    Extends r19's :class:`WindowBuildStats` from "where did the window
    build go" to "where did the whole tick go": refresh → build →
    diagnose → attribute → view → serialize.  Lives on the snapshot
    store and is surfaced through the same ``window_build`` meta
    fragment / final-report channel, so per-stage overhead is visible
    without attaching a profiler (the T3 motivation: the observer's own
    cost must itself be observable)."""

    __slots__ = ("ticks", "stage_ns", "counters")

    def __init__(self) -> None:
        self.ticks = 0
        self.stage_ns: Dict[str, Dict[str, int]] = {}
        self.counters: Dict[str, int] = {}

    def note_tick(self) -> None:
        self.ticks += 1

    def note_stage(self, domain: str, stage: str, ns: int) -> None:
        per = self.stage_ns.setdefault(domain, {})
        per[stage] = per.get(stage, 0) + int(ns)

    def bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    def snapshot(self) -> Dict[str, Any]:
        return {
            "ticks": self.ticks,
            "stage_ns": {
                d: {s: per[s] for s in sorted(per)}
                for d, per in sorted(self.stage_ns.items())
            },
            "counters": {k: self.counters[k] for k in sorted(self.counters)},
        }


class _WindowCacheBase:
    """Shared build shell: try the delta tick, invalidate to the full
    (golden) build on any condition the delta path cannot represent
    exactly, re-prime the cache from the full result, and keep the
    counters honest.  ``ColumnarFallback`` propagates to the caller
    (the store runs the scalar reference) after noting the reason."""

    def __init__(self) -> None:
        self.stats = WindowBuildStats()
        self._valid = False

    def invalidate(self) -> None:
        self._valid = False

    # subclass hooks -----------------------------------------------------
    def _tick(self, rank_cols, max_steps):  # pragma: no cover - abstract
        raise NotImplementedError

    def _full_build(self, rank_cols, max_steps):  # pragma: no cover
        raise NotImplementedError

    def _prime(self, window, rank_cols, max_steps):  # pragma: no cover
        raise NotImplementedError

    # --------------------------------------------------------------------
    def build(self, rank_cols, max_steps: int):
        t0 = time.perf_counter()
        try:
            window = self._tick(rank_cols, max_steps)
        except _CacheInvalid as inv:
            self._valid = False
            try:
                window = self._full_build(rank_cols, max_steps)
            except ColumnarFallback:
                self.stats.note_full(
                    (time.perf_counter() - t0) * 1000.0, INVALIDATE_FALLBACK
                )
                raise
            self._prime(window, rank_cols, max_steps)
            self.stats.note_full(
                (time.perf_counter() - t0) * 1000.0, inv.reason
            )
            return window
        except ColumnarFallback:
            self._valid = False
            self.stats.note_full(
                (time.perf_counter() - t0) * 1000.0, INVALIDATE_FALLBACK
            )
            raise
        self.stats.note_incr((time.perf_counter() - t0) * 1000.0)
        return window

    @staticmethod
    def _sorted_items(rank_cols):
        items = [
            (int(r), c)
            for r, c in sorted(rank_cols.items(), key=lambda kv: kv[0])
            if len(c)
        ]
        for _, c in items:
            if not c.columnar_ok:
                raise ColumnarFallback("flagged rank buffer")
        return items


class StepTimeWindowCache(_WindowCacheBase):
    """Persistent aligned-cube cache for the step_time window.

    The cache owns a (rank, 11, step) series-cube buffer with slack
    along the step axis (2x the window, compacted with a memmove like
    :class:`_CompactRing`).  A dirty tick:

    * gathers/clamps ONLY the newly-common aligned columns and appends
      them (each column depends only on its own raw values, so a column
      built at tick t is bit-identical to the same column inside a
      from-scratch cube);
    * slides the window head past ring-evicted steps (head-only
      eviction + strictly-ascending per-rank steps mean a surviving
      cached step is still common — mid-window membership changes are
      impossible, so a slide is exact, not an approximation);
    * re-folds averages/occupancy over the cached cube with the same
      exact left-fold the full build uses (float window sums cannot be
      delta-updated bit-exactly — ``(a+b)-a != b`` in IEEE — but the
      fold over the cached cube is cheap); medians stay lazy.

    Invalidation → full rebuild: rank-set change, clock flip, window
    length change, the whole cache evicted, or ``ColumnarFallback``.

    Aliasing contract: emitted windows hand out views into the cache
    buffers and are valid until the next ``build()`` — the same
    lifetime the ring views already have.  Consumers (LiveComputer)
    serialize within the tick.
    """

    def __init__(self) -> None:
        super().__init__()
        self._max_steps = 0
        self._ranks: List[int] = []
        self._clock = "host"
        self._last_aligned = 0
        self._cap = 0
        self._lo = 0
        self._hi = 0
        self._steps: Optional[np.ndarray] = None
        self._cube: Optional[np.ndarray] = None
        self._num: Optional[np.ndarray] = None
        self._host: Optional[np.ndarray] = None
        self._phase_any: Optional[np.ndarray] = None
        # step-major mirrors of cube/num/host: the per-tick re-folds
        # walk contiguous (R, …) slices instead of strided lanes
        # (same adds, same order, same bits — see _fold_step_major)
        self._cube_t: Optional[np.ndarray] = None
        self._num_t: Optional[np.ndarray] = None
        self._host_t: Optional[np.ndarray] = None
        # per-rank (sorted order) bookkeeping that lets the warm tick
        # skip binary searches: appended_total snapshot at last tick,
        # and whether the rank's newest row WAS the aligned tail
        self._seen_appended: List[int] = []
        self._aligned: List[bool] = []

    def _full_build(self, rank_cols, max_steps):
        return build_columnar_step_time_window(rank_cols, max_steps)

    def _tick(self, rank_cols, max_steps):
        if not self._valid:
            raise _CacheInvalid(INVALIDATE_COLD)
        if int(max_steps) != self._max_steps:
            raise _CacheInvalid(INVALIDATE_SIZE)
        items = self._sorted_items(rank_cols)
        if [r for r, _ in items] != self._ranks:
            raise _CacheInvalid(INVALIDATE_RANKS)
        R = len(items)
        la = self._last_aligned
        dev_cached = self._clock == "device"

        head_floor = None
        svs: List[np.ndarray] = []
        tails: List[np.ndarray] = []
        lasts: List[int] = []
        new_app: List[int] = []
        any_empty_tail = False
        for i, (_, c) in enumerate(items):
            sv = c.steps_view()
            svs.append(sv)
            first = int(sv[0])
            if head_floor is None or first > head_floor:
                head_floor = first
            lasts.append(int(sv[-1]))
            n_new = c.appended_total - self._seen_appended[i]
            new_app.append(c.appended_total)
            k = n_new if n_new < sv.size else sv.size
            if k > 0:
                if int(sv[sv.size - k]) <= la:
                    # a cleared-and-restarted rank re-reported an old
                    # step; the intersection delta cannot express that
                    raise _CacheInvalid(INVALIDATE_REALIGNED)
                if dev_cached and not c.clock_tail_device(k):
                    raise _CacheInvalid(INVALIDATE_CLOCK)
            if self._aligned[i]:
                # the rank's newest row WAS the aligned tail, so its
                # candidate rows are exactly the surviving appends since
                # last tick (strict per-rank ascent puts them above la)
                # — no binary search needed on the warm path
                t = sv[sv.size - k :] if k > 0 else _EMPTY_I64
            else:
                # rank ran ahead of the aligned tail last tick: older
                # rows above la are candidates too
                t = sv[int(np.searchsorted(sv, la, side="right")):]
            tails.append(t)
            if t.size == 0:
                any_empty_tail = True
        if dev_cached:
            clock = "device"
        else:
            # host → device flips only when every host-clocked row has
            # evicted; the scan short-circuits on the first host row
            for _, c in items:
                if not c.clock_all_device():
                    break
            else:
                raise _CacheInvalid(INVALIDATE_CLOCK)
            clock = "host"
        # every check that can invalidate has passed — commit counters
        self._seen_appended = new_app

        # newly-common steps: present in EVERY rank's post-cache tail
        # (a step at or below the cached tail cannot gain membership —
        # per-rank steps are strictly ascending)
        if any_empty_tail:
            new_common = _EMPTY_I64
        elif R == 1:
            new_common = tails[0]
        else:
            uniq, counts = np.unique(np.concatenate(tails), return_counts=True)
            new_common = uniq[counts == R]

        if new_common.size:
            new_common = new_common[-self._max_steps:]
            n_new = int(new_common.size)
            self._ensure_capacity(n_new)
            hi = self._hi
            cube_raw = np.empty((R, n_new, N_EVENTS, 2), dtype=np.float64)
            occ_parts = np.empty((R, n_new, 2), dtype=np.float64)
            for i, (_, c) in enumerate(items):
                t = tails[i]
                base = svs[i].size - t.size
                if t.size == n_new:
                    # new_common ⊆ every tail, so equal size means equal
                    # content — the gather is a plain tail slice (the
                    # warm steady-state path: one new step, all ranks)
                    cube_raw[i] = c.vals_view()[base:]
                    occ_parts[i] = c.occ_view()[base:]
                else:
                    idx = base + np.searchsorted(t, new_common)
                    cube_raw[i] = c.vals_view()[idx]
                    occ_parts[i] = c.occ_view()[idx]
            slab = _select_clamp_slab(cube_raw, clock)
            self._cube[:, :, hi : hi + n_new] = slab
            num, host = _zeroed_occ_parts(occ_parts)
            self._num[:, hi : hi + n_new] = num
            self._host[:, hi : hi + n_new] = host
            self._cube_t[hi : hi + n_new] = np.moveaxis(slab, 2, 0)
            self._num_t[hi : hi + n_new] = num.T
            self._host_t[hi : hi + n_new] = host.T
            for j in range(len(ACCOUNTED_PHASES)):
                self._phase_any[j, hi : hi + n_new] = (
                    slab[:, 1 + j, :] > 0
                ).any(axis=0)
            self._steps[hi : hi + n_new] = new_common
            self._hi = hi + n_new
            self._last_aligned = int(new_common[-1])
        new_la = self._last_aligned
        self._aligned = [l == new_la for l in lasts]

        # slide the head past evicted steps, then clamp to the window
        lo = self._lo + int(
            np.searchsorted(
                self._steps[self._lo : self._hi], head_floor, side="left"
            )
        )
        self._lo = max(lo, self._hi - self._max_steps)
        if self._hi == self._lo:
            return None  # intersection empty — matches the full build
        return self._emit(clock)

    def _ensure_capacity(self, n_new: int) -> None:
        if self._hi + n_new <= self._cap:
            return
        live = self._hi - self._lo
        lo, hi = self._lo, self._hi
        # live + n_new <= 2*max_steps == cap by construction (new
        # columns are pre-clamped to the window length)
        self._steps[:live] = self._steps[lo:hi]
        self._cube[:, :, :live] = self._cube[:, :, lo:hi]
        self._num[:, :live] = self._num[:, lo:hi]
        self._host[:, :live] = self._host[:, lo:hi]
        self._cube_t[:live] = self._cube_t[lo:hi]
        self._num_t[:live] = self._num_t[lo:hi]
        self._host_t[:live] = self._host_t[lo:hi]
        self._phase_any[:, :live] = self._phase_any[:, lo:hi]
        self._lo, self._hi = 0, live

    def _emit(self, clock: str) -> ColumnarStepTimeWindow:
        lo, hi = self._lo, self._hi
        S = hi - lo
        steps = self._steps[lo:hi]
        cube = self._cube[:, :, lo:hi]
        averages = _fold_step_major(self._cube_t, lo, hi) / S
        occupancy = _occupancy_from_sums(
            _fold_step_major(self._num_t, lo, hi),
            _fold_step_major(self._host_t, lo, hi),
        )
        metrics = _step_time_metrics(averages, self._ranks)
        phases_present = [
            k
            for j, k in enumerate(ACCOUNTED_PHASES)
            if bool(self._phase_any[j, lo:hi].any())
        ]
        steps_list = steps.tolist()
        col = _ColumnarData(
            ranks=list(self._ranks),
            steps=steps,
            series_cube=cube,
            averages=averages,
            medians=None,
            occupancy=occupancy,
        )
        return ColumnarStepTimeWindow(
            col=col,
            clock=clock,
            steps=steps_list,
            ranks=list(self._ranks),
            rank_windows=_LazyRankWindows(col, steps_list, clock),
            metrics=metrics,
            phases_present=phases_present,
            n_steps=S,
        )

    def _prime(self, window, rank_cols, max_steps) -> None:
        if window is None:
            self._valid = False
            return
        col = window.col
        R = len(col.ranks)
        S = window.n_steps
        self._max_steps = int(max_steps)
        self._cap = 2 * self._max_steps
        self._steps = np.empty(self._cap, dtype=np.int64)
        self._cube = np.empty((R, len(ALL_KEYS), self._cap), dtype=np.float64)
        self._num = np.empty((R, self._cap), dtype=np.float64)
        self._host = np.empty((R, self._cap), dtype=np.float64)
        self._cube_t = np.empty(
            (self._cap, R, len(ALL_KEYS)), dtype=np.float64
        )
        self._num_t = np.empty((self._cap, R), dtype=np.float64)
        self._host_t = np.empty((self._cap, R), dtype=np.float64)
        self._phase_any = np.empty(
            (len(ACCOUNTED_PHASES), self._cap), dtype=np.bool_
        )
        self._steps[:S] = col.steps
        self._cube[:, :, :S] = col.series_cube
        self._num[:, :S] = col.occ_num
        self._host[:, :S] = col.occ_host
        self._cube_t[:S] = np.moveaxis(col.series_cube, 2, 0)
        self._num_t[:S] = col.occ_num.T
        self._host_t[:S] = col.occ_host.T
        for j in range(len(ACCOUNTED_PHASES)):
            self._phase_any[j, :S] = (col.series_cube[:, 1 + j, :] > 0).any(
                axis=0
            )
        self._lo, self._hi = 0, S
        self._ranks = list(col.ranks)
        self._clock = window.clock
        self._last_aligned = int(col.steps[-1])
        items = self._sorted_items(rank_cols)
        self._seen_appended = [c.appended_total for _, c in items]
        self._aligned = [
            int(c.steps_view()[-1]) == self._last_aligned for _, c in items
        ]
        self._valid = True


class _SlotWindowCacheBase(_WindowCacheBase):
    """Shared machinery for the union-aligned (collectives/serving)
    caches: per-step slot arrays with 2x slack, a delta scan that
    classifies newly appended rows against the cached window, and a
    conservative eviction guard keyed on the rings' monotone
    ``appended_total``/``evicted_total`` counters.

    Slot exactness: a cached slot value equals the fold (in sorted-rank,
    row-order — ``np.add.at`` element order) over ALL live rows carrying
    that step.  A new row landing on a cached step makes the slot
    "touched"; touched and new slots are recomputed from scratch from
    the raw rows, so partial-sum merging (which would change IEEE fold
    grouping) never happens.

    Invalidation: a mid-window union insert (new step ≤ cached max not
    already cached) or a below-window insert while the union is still
    shorter than the window ("realigned"), and any eviction whose
    surviving head sits at/above the cached window start
    ("window_evicted" — the evicted rows might have contributed to
    cached slots).  Evictions strictly below the window are provably
    harmless: head-only eviction + non-decreasing steps mean every
    evicted step ≤ the surviving oldest step < window start."""

    def __init__(self) -> None:
        super().__init__()
        self._max_steps = 0
        self._cap = 0
        self._lo = 0
        self._hi = 0
        self._steps: Optional[np.ndarray] = None
        self._ranks: List[int] = []
        self._seen_appended: List[int] = []
        self._seen_evicted: List[int] = []

    def _slot_arrays(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def _scan_delta(self, items, max_steps: int):
        """Classify each rank's newly appended rows.  Returns
        ``(touched_steps, new_union_steps)`` or raises
        :class:`_CacheInvalid`.  Seen counters advance only when every
        check passed (a raise re-primes them anyway)."""
        if [r for r, _ in items] != self._ranks:
            raise _CacheInvalid(INVALIDATE_RANKS)
        lo_step = int(self._steps[self._lo])
        cached_max = int(self._steps[self._hi - 1])
        cached_S = self._hi - self._lo
        cs = self._steps[self._lo : self._hi]
        touched: set = set()
        tail_parts: List[np.ndarray] = []
        new_app: List[int] = []
        new_ev: List[int] = []
        for i, (_, c) in enumerate(items):
            new_app.append(c.appended_total)
            new_ev.append(c.evicted_total)
            sv = c.steps_view()
            if c.evicted_total != self._seen_evicted[i] and int(sv[0]) >= lo_step:
                raise _CacheInvalid(INVALIDATE_EVICTED)
            n_new = c.appended_total - self._seen_appended[i]
            if n_new <= 0:
                continue
            ns = sv[max(0, sv.size - n_new):]
            pos = int(np.searchsorted(ns, cached_max, side="right"))
            old_part = ns[:pos]
            if old_part.size:
                below = old_part[old_part < lo_step]
                if below.size and cached_S < max_steps:
                    # the union (== cached window) would grow downward
                    raise _CacheInvalid(INVALIDATE_REALIGNED)
                within = old_part[old_part >= lo_step]
                if within.size:
                    at = np.searchsorted(cs, within)
                    if bool((cs[at] != within).any()):
                        # mid-window union insert
                        raise _CacheInvalid(INVALIDATE_REALIGNED)
                    touched.update(int(x) for x in within)
            if pos < ns.size:
                tail_parts.append(ns[pos:])
        if tail_parts:
            if len(tail_parts) > 1:
                new_steps = np.unique(np.concatenate(tail_parts))
            else:
                new_steps = np.unique(tail_parts[0])
        else:
            new_steps = _EMPTY_I64
        self._seen_appended = new_app
        self._seen_evicted = new_ev
        return touched, new_steps

    def _ensure_slot_capacity(self, n_new: int) -> None:
        if self._hi + n_new <= self._cap:
            return
        live = self._hi - self._lo
        lo, hi = self._lo, self._hi
        self._steps[:live] = self._steps[lo:hi]
        for a in self._slot_arrays():
            a[:live] = a[lo:hi]
        self._lo, self._hi = 0, live

    def _append_and_touch(self, touched, new_steps):
        """Append zeroed slots for the new union steps (pre-clamped to
        the window), slide the window, and mark them for recompute."""
        if new_steps.size:
            new_steps = new_steps[-self._max_steps:]
            n_new = int(new_steps.size)
            self._ensure_slot_capacity(n_new)
            hi = self._hi
            self._steps[hi : hi + n_new] = new_steps
            for a in self._slot_arrays():
                a[hi : hi + n_new] = 0
            self._hi = hi + n_new
            self._lo = max(self._lo, self._hi - self._max_steps)
            touched.update(int(x) for x in new_steps)
        return touched

    def _prime_common(self, window, rank_cols) -> None:
        self._lo, self._hi = 0, window.n_steps
        items = self._sorted_items(rank_cols)
        self._ranks = [r for r, _ in items]
        self._seen_appended = [c.appended_total for _, c in items]
        self._seen_evicted = [c.evicted_total for _, c in items]
        self._valid = True


class CollectivesWindowCache(_SlotWindowCacheBase):
    """Incremental collectives window: per-step count/bytes/duration/
    exposed/allreduce-fp32 slots are cached and delta-maintained; the
    per-op / per-rank / group aggregates are re-folded each tick over
    the live row suffixes with the exact full-build fold (their fold
    start moves with the window head, so a cached partial sum cannot be
    reused bit-exactly — the refold over ring views is still far
    cheaper than the full gather + per-slot scatter)."""

    def __init__(self) -> None:
        super().__init__()
        self._count: Optional[np.ndarray] = None
        self._bytes: Optional[np.ndarray] = None
        self._ar: Optional[np.ndarray] = None
        self._dur: Optional[np.ndarray] = None
        self._exp: Optional[np.ndarray] = None

    def _slot_arrays(self):
        return (self._count, self._bytes, self._ar, self._dur, self._exp)

    def _full_build(self, rank_cols, max_steps):
        return build_columnar_collectives_window(rank_cols, max_steps)

    def _tick(self, rank_cols, max_steps):
        if not self._valid:
            raise _CacheInvalid(INVALIDATE_COLD)
        if int(max_steps) != self._max_steps:
            raise _CacheInvalid(INVALIDATE_SIZE)
        items = self._sorted_items(rank_cols)
        if not items:
            raise _CacheInvalid(INVALIDATE_RANKS)
        touched, new_steps = self._scan_delta(items, self._max_steps)
        touched = self._append_and_touch(touched, new_steps)
        lo_step = int(self._steps[self._lo])
        buf = self._steps[: self._hi]
        for s in sorted(touched):
            if s < lo_step:
                continue
            self._recompute_slot(int(np.searchsorted(buf, s)), s, items)
        return self._emit(items)

    def _recompute_slot(self, j: int, s: int, items) -> None:
        # from-scratch fold over ALL live rows carrying step s, in
        # sorted-rank row order — np.add.at element order, so the slot
        # is bit-identical to the full build's scatter
        ar_code = _COLL_OP_INDEX["all_reduce"]
        cnt = 0
        byt = 0
        ar = 0
        d_acc = 0.0
        e_acc = 0.0
        for _, c in items:
            sv = c.steps_view()
            a = int(np.searchsorted(sv, s, side="left"))
            b = int(np.searchsorted(sv, s, side="right"))
            if a == b:
                continue
            ints = c.ints_view()
            flts = c.floats_view()
            ops = c.ops_view()
            dts = c.dtypes_view()
            fp32 = c._dtype_index.get("float32", -1)
            for t in range(a, b):
                cnt += int(ints[t, CC_COUNT])
                byt += int(ints[t, CC_BYTES])
                d_acc += float(flts[t, 0])
                e_acc += float(flts[t, 1])
                if int(ops[t]) == ar_code and int(dts[t]) == fp32:
                    ar += int(ints[t, CC_BYTES])
        self._count[j] = cnt
        self._bytes[j] = byt
        self._ar[j] = ar
        self._dur[j] = d_acc
        self._exp[j] = e_acc

    def _emit(self, items) -> CollectivesWindow:
        lo, hi = self._lo, self._hi
        S = hi - lo
        common = self._steps[lo:hi]
        lo_step = int(common[0])
        n_ops = len(COLLECTIVE_OPS)
        op_count = np.zeros(n_ops, dtype=np.int64)
        op_bytes = np.zeros(n_ops, dtype=np.int64)
        op_dur = np.zeros(n_ops, dtype=np.float64)
        op_exp = np.zeros(n_ops, dtype=np.float64)
        op_seen = np.zeros(n_ops, dtype=np.bool_)
        per_rank: Dict[int, Dict[str, float]] = {}
        group = 1
        for rank, c in items:
            sv = c.steps_view()
            k = int(np.searchsorted(sv, lo_step, side="left"))
            ints = c.ints_view()[k:]
            floats = c.floats_view()[k:]
            ops = c.ops_view()[k:].astype(np.int64)
            np.add.at(op_count, ops, ints[:, CC_COUNT])
            np.add.at(op_bytes, ops, ints[:, CC_BYTES])
            np.add.at(op_dur, ops, floats[:, 0])
            np.add.at(op_exp, ops, floats[:, 1])
            op_seen[ops] = True
            if ints.shape[0]:
                group = max(group, int(ints[:, CC_GROUP].max()))
                r_dur = float(np.cumsum(floats[:, 0])[-1])
                r_exp = float(np.cumsum(floats[:, 1])[-1])
                r_bytes = int(np.cumsum(ints[:, CC_BYTES])[-1])
            else:
                r_dur = r_exp = 0.0
                r_bytes = 0
            per_rank[rank] = {
                "duration_ms": r_dur,
                "exposed_ms": r_exp,
                "bytes": r_bytes,
                "overlap_efficiency": _overlap_efficiency(r_dur, r_exp),
            }

        count = self._count[lo:hi]
        nbytes = self._bytes[lo:hi]
        dur = self._dur[lo:hi]
        exp = self._exp[lo:hi]
        dur_l = dur.tolist()
        exp_l = exp.tolist()
        total_dur = float(np.cumsum(dur)[-1]) if S else 0.0
        total_exp = float(np.cumsum(exp)[-1]) if S else 0.0
        per_op: Dict[str, Dict[str, float]] = {}
        for oi, op in enumerate(COLLECTIVE_OPS):
            if not op_seen[oi]:
                continue
            per_op[op] = {
                "count": int(op_count[oi]),
                "bytes": int(op_bytes[oi]),
                "duration_ms": float(op_dur[oi]),
                "exposed_ms": float(op_exp[oi]),
            }
        return CollectivesWindow(
            steps=common.tolist(),
            n_steps=S,
            ranks=list(self._ranks),
            group_size=group,
            per_step={
                "count": count.tolist(),
                "bytes": nbytes.tolist(),
                "duration_ms": dur_l,
                "exposed_ms": exp_l,
                "overlap_efficiency": [
                    _overlap_efficiency(dur_l[i], exp_l[i]) for i in range(S)
                ],
                "allreduce_fp32_bytes": self._ar[lo:hi].tolist(),
            },
            per_op=per_op,
            per_rank=per_rank,
            totals={
                "count": int(np.cumsum(count)[-1]) if S else 0,
                "bytes": int(np.cumsum(nbytes)[-1]) if S else 0,
                "duration_ms": total_dur,
                "exposed_ms": total_exp,
                "overlap_efficiency": _overlap_efficiency(total_dur, total_exp),
            },
        )

    def _prime(self, window, rank_cols, max_steps) -> None:
        if window is None:
            self._valid = False
            return
        self._max_steps = int(max_steps)
        self._cap = 2 * self._max_steps
        S = window.n_steps
        self._steps = np.empty(self._cap, dtype=np.int64)
        self._count = np.empty(self._cap, dtype=np.int64)
        self._bytes = np.empty(self._cap, dtype=np.int64)
        self._ar = np.empty(self._cap, dtype=np.int64)
        self._dur = np.empty(self._cap, dtype=np.float64)
        self._exp = np.empty(self._cap, dtype=np.float64)
        self._steps[:S] = np.asarray(window.steps, dtype=np.int64)
        ps = window.per_step
        self._count[:S] = np.asarray(ps["count"], dtype=np.int64)
        self._bytes[:S] = np.asarray(ps["bytes"], dtype=np.int64)
        self._ar[:S] = np.asarray(ps["allreduce_fp32_bytes"], dtype=np.int64)
        self._dur[:S] = np.asarray(ps["duration_ms"], dtype=np.float64)
        self._exp[:S] = np.asarray(ps["exposed_ms"], dtype=np.float64)
        self._prime_common(window, rank_cols)


class ServingWindowCache(_SlotWindowCacheBase):
    """Incremental serving window: per-seq enqueue/complete/queue-depth/
    decode-token/tps/prefill/decode slots are cached and delta-
    maintained; per-replica aggregates, KV headroom, and the latency
    percentiles (order statistics over RAW populations — value-
    determined, so a refold over the ragged CSR suffixes reproduces the
    full build's bits) are re-folded each tick."""

    def __init__(self) -> None:
        super().__init__()
        self._enq: Optional[np.ndarray] = None
        self._done: Optional[np.ndarray] = None
        self._qd: Optional[np.ndarray] = None
        self._dtok: Optional[np.ndarray] = None
        self._tps: Optional[np.ndarray] = None
        self._pre: Optional[np.ndarray] = None
        self._dec: Optional[np.ndarray] = None

    def _slot_arrays(self):
        return (
            self._enq, self._done, self._qd, self._dtok,
            self._tps, self._pre, self._dec,
        )

    def _full_build(self, rank_cols, max_steps):
        return build_columnar_serving_window(rank_cols, max_steps)

    def _tick(self, rank_cols, max_steps):
        if not self._valid:
            raise _CacheInvalid(INVALIDATE_COLD)
        if int(max_steps) != self._max_steps:
            raise _CacheInvalid(INVALIDATE_SIZE)
        items = self._sorted_items(rank_cols)
        if not items:
            raise _CacheInvalid(INVALIDATE_RANKS)
        touched, new_steps = self._scan_delta(items, self._max_steps)
        touched = self._append_and_touch(touched, new_steps)
        lo_step = int(self._steps[self._lo])
        buf = self._steps[: self._hi]
        for s in sorted(touched):
            if s < lo_step:
                continue
            self._recompute_slot(int(np.searchsorted(buf, s)), s, items)
        return self._emit(items)

    def _recompute_slot(self, j: int, s: int, items) -> None:
        e_acc = 0
        d_acc = 0
        q_acc = 0
        t_acc = 0
        tps_acc = 0.0
        pre_acc = 0.0
        dec_acc = 0.0
        for _, c in items:
            sv = c.steps_view()
            a = int(np.searchsorted(sv, s, side="left"))
            b = int(np.searchsorted(sv, s, side="right"))
            if a == b:
                continue
            ints = c.ints_view()
            flts = c.floats_view()
            for t in range(a, b):
                e_acc += int(ints[t, SV_ENQ])
                d_acc += int(ints[t, SV_DONE])
                q_acc += int(ints[t, SV_QDEPTH])
                t_acc += int(ints[t, SV_DTOK])
                tps_acc += float(flts[t, SF_TPS])
                pre_acc += float(flts[t, SF_PREFILL])
                dec_acc += float(flts[t, SF_DECODE])
        self._enq[j] = e_acc
        self._done[j] = d_acc
        self._qd[j] = q_acc
        self._dtok[j] = t_acc
        self._tps[j] = tps_acc
        self._pre[j] = pre_acc
        self._dec[j] = dec_acc

    def _emit(self, items) -> ServingWindow:
        lo, hi = self._lo, self._hi
        S = hi - lo
        common = self._steps[lo:hi]
        lo_step = int(common[0])
        ttft_parts: List[np.ndarray] = []
        e2e_parts: List[np.ndarray] = []
        per_rank: Dict[int, Dict[str, float]] = {}
        kv_min = -1.0
        for rank, c in items:
            sv = c.steps_view()
            k = int(np.searchsorted(sv, lo_step, side="left"))
            ints = c.ints_view()[k:]
            flts = c.floats_view()[k:]
            r_ttft = c.ragged_suffix(RG_TTFT, k)
            ttft_parts.append(r_ttft)
            e2e_parts.append(c.ragged_suffix(RG_E2E, k))
            n_rows = int(ints.shape[0])
            if n_rows:
                r_done = int(np.cumsum(ints[:, SV_DONE])[-1])
                r_tok = int(np.cumsum(ints[:, SV_DTOK])[-1])
                r_tps = float(np.cumsum(flts[:, SF_TPS])[-1]) / n_rows
                r_qd = int(ints[-1, SV_QDEPTH])
                r_active = int(ints[-1, SV_ACTIVE])
            else:
                r_done = r_tok = r_qd = r_active = 0
                r_tps = 0.0
            kvh = flts[:, SF_KVH]
            kv_ok = kvh >= 0.0
            r_kvh = -1.0
            if kv_ok.any():
                r_kvh = float(kvh[np.flatnonzero(kv_ok)[-1]])
                m = float(kvh[kv_ok].min())
                kv_min = m if kv_min < 0.0 else min(kv_min, m)
            per_rank[rank] = {
                "requests_completed": r_done,
                "requests_active": r_active,
                "decode_tokens": r_tok,
                "tokens_per_s": r_tps,
                "queue_depth": r_qd,
                "ttft_p99_ms": _population_percentile(np.sort(r_ttft), 0.99),
                "kv_headroom": r_kvh,
            }

        ttft_sorted = (
            np.sort(np.concatenate(ttft_parts)) if ttft_parts else _EMPTY_F64
        )
        e2e_sorted = (
            np.sort(np.concatenate(e2e_parts)) if e2e_parts else _EMPTY_F64
        )
        enq_l = self._enq[lo:hi].tolist()
        done_l = self._done[lo:hi].tolist()
        qd_l = self._qd[lo:hi].tolist()
        dtok_l = self._dtok[lo:hi].tolist()
        return ServingWindow(
            steps=common.tolist(),
            n_steps=S,
            ranks=list(self._ranks),
            per_step={
                "requests_enqueued": enq_l,
                "requests_completed": done_l,
                "queue_depth": qd_l,
                "decode_tokens": dtok_l,
                "tokens_per_s": self._tps[lo:hi].tolist(),
                "prefill_ms": self._pre[lo:hi].tolist(),
                "decode_ms": self._dec[lo:hi].tolist(),
            },
            per_rank=per_rank,
            totals=_serving_totals(
                enq_l,
                done_l,
                dtok_l,
                qd_l,
                self._pre[lo:hi].tolist(),
                self._dec[lo:hi].tolist(),
                per_rank,
                kv_min,
                ttft_sorted,
                e2e_sorted,
            ),
        )

    def _prime(self, window, rank_cols, max_steps) -> None:
        if window is None:
            self._valid = False
            return
        self._max_steps = int(max_steps)
        self._cap = 2 * self._max_steps
        S = window.n_steps
        self._steps = np.empty(self._cap, dtype=np.int64)
        self._enq = np.empty(self._cap, dtype=np.int64)
        self._done = np.empty(self._cap, dtype=np.int64)
        self._qd = np.empty(self._cap, dtype=np.int64)
        self._dtok = np.empty(self._cap, dtype=np.int64)
        self._tps = np.empty(self._cap, dtype=np.float64)
        self._pre = np.empty(self._cap, dtype=np.float64)
        self._dec = np.empty(self._cap, dtype=np.float64)
        self._steps[:S] = np.asarray(window.steps, dtype=np.int64)
        ps = window.per_step
        self._enq[:S] = np.asarray(ps["requests_enqueued"], dtype=np.int64)
        self._done[:S] = np.asarray(ps["requests_completed"], dtype=np.int64)
        self._qd[:S] = np.asarray(ps["queue_depth"], dtype=np.int64)
        self._dtok[:S] = np.asarray(ps["decode_tokens"], dtype=np.int64)
        self._tps[:S] = np.asarray(ps["tokens_per_s"], dtype=np.float64)
        self._pre[:S] = np.asarray(ps["prefill_ms"], dtype=np.float64)
        self._dec[:S] = np.asarray(ps["decode_ms"], dtype=np.float64)
        self._prime_common(window, rank_cols)
