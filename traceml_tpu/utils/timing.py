"""TPU-native step-phase timing core
(reference concept: src/traceml_ai/utils/timing.py:44-265).

The reference brackets each phase with a pair of CUDA events and later
resolves them without synchronization via non-blocking ``event.query()``.
TPU/XLA has no user-visible device events, but it has something equally
useful: **async dispatch + per-array readiness**.  A jitted call returns
immediately; its output ``jax.Array``s expose a non-blocking
``is_ready()``.  Because a TPU core executes enqueued programs serially,
the host time at which a phase's outputs become ready is the device-side
end of that phase, and consecutive readiness edges delimit device
occupancy:

    device_ms(phase_k) = ready(phase_k) − max(ready(phase_{k−1}),
                                              dispatch(phase_k))

So each :class:`TimeEvent` records host enter/exit times and, optionally,
a :class:`DeviceMarker` — a strong reference to the *smallest* output leaf
of the phase's dispatched computation (smallest to keep pinned buffer
bytes negligible; output buffers are never donation targets, so holding
one is safe).  A background resolver (see utils/marker_resolver.py) polls
``is_ready()`` at millisecond cadence and stamps ``ready_at``.  Nothing on
the hot path blocks, synchronizes, or raises — the reference's core
contract (architecture.md:61 "never synchronize") holds.

Accuracy note: ``ready_at`` is quantized by the resolver poll interval
(~2 ms default), a deliberate trade against always-on profiler overhead.
The reference carries the mirror-image caveat for very short steps
(architecture.md:73,89).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

from traceml_tpu.utils.error_log import get_error_log

# --- internal phase vocabulary (reference: utils/step_time_window.py:41-56,
# extended with TPU-only phases: compile / compute / collective) ------------
INTERNAL_PREFIX = "_traceml_internal:"
STEP_TIME = INTERNAL_PREFIX + "step_time"
DATALOADER_NEXT = INTERNAL_PREFIX + "dataloader_next"
H2D_TIME = INTERNAL_PREFIX + "h2d_time"
FORWARD_TIME = INTERNAL_PREFIX + "forward_time"
BACKWARD_TIME = INTERNAL_PREFIX + "backward_time"
OPTIMIZER_STEP = INTERNAL_PREFIX + "optimizer_step"
COMPUTE_TIME = INTERNAL_PREFIX + "compute_time"  # fused fwd+bwd+opt (JAX jit)
COMPILE_TIME = INTERNAL_PREFIX + "compile_time"
COLLECTIVE_TIME = INTERNAL_PREFIX + "collective_time"
CHECKPOINT_TIME = INTERNAL_PREFIX + "checkpoint_time"  # save stalls (orbax)

ALL_PHASES = (
    STEP_TIME,
    DATALOADER_NEXT,
    H2D_TIME,
    FORWARD_TIME,
    BACKWARD_TIME,
    OPTIMIZER_STEP,
    COMPUTE_TIME,
    COMPILE_TIME,
    COLLECTIVE_TIME,
    CHECKPOINT_TIME,
)

_QUEUE_MAX = 2048  # reference: bounded step/global queues maxsize 2048


def _now() -> float:
    return time.perf_counter()


class DeviceMarker:
    """A readiness probe over dispatched device work.

    Wraps one or more objects exposing ``is_ready() -> bool`` (jax.Array
    does; tests use fakes).  ``poll(now)`` is non-blocking and idempotent:
    once every handle reports ready, the handle refs are dropped (so
    buffers are not pinned past resolution) and ``ready_at`` is stamped
    with the observation time.
    """

    __slots__ = (
        "_handles", "dispatched_at", "ready_at", "late_stamp", "submitted",
        "step_end_hint",
    )

    def __init__(self, handles: Sequence[Any], dispatched_at: Optional[float] = None):
        self._handles: Optional[List[Any]] = [
            h for h in handles if hasattr(h, "is_ready")
        ]
        # True for markers expected to resolve ~at step end (the fused
        # compute/envelope marker): the resolver may then sleep through
        # most of the expected step instead of fine-polling.  Intra-step
        # phase markers (h2d, collective, user regions) leave this False
        # — they become ready mid-step and need the fine cadence.
        self.step_end_hint = False
        self.dispatched_at = _now() if dispatched_at is None else dispatched_at
        self.ready_at: Optional[float] = None
        self.late_stamp = False
        self.submitted = False  # resolver dedupe flag
        if not self._handles:
            # nothing to wait on → ready at dispatch
            self.ready_at = self.dispatched_at
            self._handles = None

    @property
    def resolved(self) -> bool:
        return self.ready_at is not None

    def poll(self, now: Optional[float] = None, late: bool = False) -> bool:
        """Stamping readiness check.

        ``ready_at`` is the OBSERVATION time, so only fine-cadence pollers
        (the marker resolver, step-boundary inline sweeps) may call this —
        a coarse caller would silently inflate device durations.  Coarse
        last-resort callers (shutdown drains) must pass ``late=True`` so
        downstream can discount the stamp quality.
        """
        if self.ready_at is not None:
            return True
        handles = self._handles
        if handles is None:
            return True
        try:
            for h in handles:
                if not h.is_ready():
                    return False
        except Exception:
            # A deleted/donated buffer can make is_ready raise; treat as
            # completed at observation time — fail open, never raise.
            pass
        self.ready_at = _now() if now is None else now
        self.late_stamp = late
        self._handles = None
        if self.step_end_hint and not late:
            # feed the resolver's sleep-to-completion schedule (see
            # overhead_governor.observe_marker_lifetime)
            from traceml_tpu.utils.overhead_governor import get_governor

            get_governor().observe_marker_lifetime(
                self.ready_at - self.dispatched_at
            )
        return True


def smallest_ready_index(leaves: Sequence[Any]) -> Optional[int]:
    """Index of the smallest ``is_ready``-capable leaf, or None.

    THE leaf-selection policy — every caller (pytree path below, the
    treedef-cached hot path in sdk/step_fn.py) routes through this so
    the policy can't silently fork.
    """
    best_i: Optional[int] = None
    best_size = 1 << 62
    for i, x in enumerate(leaves):
        if not hasattr(x, "is_ready"):
            continue
        try:
            size = int(x.size)
        except Exception:
            size = 1 << 60
        if best_i is None or size < best_size:
            best_i, best_size = i, size
    return best_i


def smallest_leaf(tree: Any) -> List[Any]:
    """Pick the smallest array leaf of a pytree as the readiness handle.

    One output leaf is enough on TPU: an XLA program's outputs materialize
    together when the program retires, so the scalar loss is as good a
    completion probe as the full state — and pins ~0 bytes.
    """
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = [tree]
    idx = smallest_ready_index(leaves)
    return [leaves[idx]] if idx is not None else []


class TimeEvent:
    """One timed phase occurrence inside one step."""

    __slots__ = (
        "name",
        "step",
        "cpu_start",
        "cpu_end",
        "marker",
        "meta",
    )

    def __init__(self, name: str, step: int) -> None:
        self.name = name
        self.step = step
        self.cpu_start: float = _now()
        self.cpu_end: Optional[float] = None
        self.marker: Optional[DeviceMarker] = None
        self.meta: Optional[Dict[str, Any]] = None

    def close(self) -> None:
        if self.cpu_end is None:
            self.cpu_end = _now()

    def attach_marker(self, outputs: Any) -> None:
        """Attach a device-readiness marker from a phase's outputs."""
        try:
            handles = smallest_leaf(outputs)
            if handles:
                self.marker = DeviceMarker(handles)
        except Exception as exc:
            get_error_log().warning("attach_marker failed", exc)

    @property
    def cpu_ms(self) -> Optional[float]:
        if self.cpu_end is None:
            return None
        return (self.cpu_end - self.cpu_start) * 1000.0

    def is_resolved(self) -> bool:
        """Non-stamping check: True when host side is closed and the
        device marker (if any) has already been stamped by a fine-cadence
        poller.  Never stamps — see DeviceMarker.poll."""
        if self.cpu_end is None:
            return False
        if self.marker is None:
            return True
        return self.marker.resolved

    def try_resolve(self, late: bool = True) -> bool:
        """Stamping resolution for last-resort paths (shutdown drain,
        resolve-timeout).  Marks the stamp as late by default
        (reference: TimeEvent.try_resolve, timing.py:66 — there the CUDA
        event carries the true device time, so stamping cadence doesn't
        matter; here it does)."""
        if self.cpu_end is None:
            return False
        if self.marker is None:
            return True
        return self.marker.poll(late=late)

    @property
    def device_ready_at(self) -> Optional[float]:
        if self.marker is None:
            return None
        return self.marker.ready_at

    def to_row(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "step": self.step,
            "cpu_start": self.cpu_start,
            "cpu_end": self.cpu_end,
            "cpu_ms": self.cpu_ms,
            "device_ready_at": self.device_ready_at,
            "has_marker": self.marker is not None,
        }


class StepTimeBatch:
    """All events of one completed step (reference: timing.py:94-106)."""

    __slots__ = ("step", "events", "flushed_at")

    def __init__(self, step: int, events: List[TimeEvent]) -> None:
        self.step = step
        self.events = events
        self.flushed_at = _now()

    def resolved(self) -> bool:
        """Non-stamping: safe to call at any cadence."""
        return all(e.is_resolved() for e in self.events)

    def force_resolve(self) -> None:
        """Stamp any still-pending markers (late-quality stamps)."""
        for e in self.events:
            e.try_resolve(late=True)


class StepEventBuffer:
    """Per-step accumulation buffer, flushed into the global queue at
    step exit (reference: flush_buffers.py:13)."""

    def __init__(self) -> None:
        self._events: List[TimeEvent] = []
        self._lock = threading.Lock()

    def add(self, event: TimeEvent) -> None:
        with self._lock:
            self._events.append(event)

    def flush(self, step: int) -> Optional[StepTimeBatch]:
        with self._lock:
            events, self._events = self._events, []
        if not events:
            return None
        return StepTimeBatch(step, events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class BoundedDropQueue:
    """Thread-safe bounded queue; drops (and counts) on overflow rather
    than blocking user code (reference: timing.py:133-146).  Shared by
    the step-batch and step-memory streams so both get identical drop
    accounting."""

    def __init__(self, label: str, maxsize: int = _QUEUE_MAX) -> None:
        self._label = label
        # deque, not queue.Queue: append/popleft are GIL-atomic and ~10×
        # cheaper than Queue's lock+notify, and this queue is written on
        # the per-step hot path.  The len() check races benignly (a
        # concurrent writer can overshoot the bound by #threads items).
        self._q: Deque[Any] = collections.deque()
        self._maxsize = maxsize
        self.dropped = 0
        self._warned = False

    def put(self, item: Any) -> bool:
        if len(self._q) >= self._maxsize:
            self.dropped += 1
            if not self._warned:
                self._warned = True
                get_error_log().warning(
                    f"{self._label} queue full; dropping (sampler stalled?)"
                )
            return False
        self._q.append(item)
        return True

    def drain(self, max_items: Optional[int] = None) -> List[Any]:
        out: List[Any] = []
        q = self._q
        while max_items is None or len(out) < max_items:
            try:
                out.append(q.popleft())
            except IndexError:
                break
        return out

    def qsize(self) -> int:
        return len(self._q)


# kept as an alias for the step-batch use of the shared queue class
BoundedStepQueue = BoundedDropQueue

# Global step queue shared by sdk flush and the StepTimeSampler.
GLOBAL_STEP_QUEUE = BoundedDropQueue("step_time")

# Global step-memory queue (rows produced by StepMemoryTracker).
GLOBAL_STEP_MEMORY_QUEUE = BoundedDropQueue("step_memory")


def push_step_memory_row(row: Dict[str, Any]) -> bool:
    return GLOBAL_STEP_MEMORY_QUEUE.put(row)


def drain_step_memory_rows(max_items: int = 10000) -> List[Dict[str, Any]]:
    return GLOBAL_STEP_MEMORY_QUEUE.drain(max_items)


class timed_region:
    """Context manager timing one phase; optional device marker at exit
    (reference: timing.py:184-265).

    Usage::

        with timed_region(FORWARD_TIME, step=3, sink=buffer.add) as tr:
            out = forward(...)
            tr.mark(out)        # optional: device-side completion probe
    """

    __slots__ = ("event", "_sink", "_on_close")

    def __init__(
        self,
        name: str,
        step: int,
        sink: Optional[Callable[[TimeEvent], None]] = None,
        on_close: Optional[Callable[[TimeEvent], None]] = None,
    ) -> None:
        self.event = TimeEvent(name, step)
        self._sink = sink
        self._on_close = on_close

    def mark(self, outputs: Any) -> Any:
        self.event.attach_marker(outputs)
        return outputs

    def __enter__(self) -> "timed_region":
        self.event.cpu_start = _now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            self.event.close()
            if self._sink is not None:
                self._sink(self.event)
            if self._on_close is not None:
                self._on_close(self.event)
        except Exception as err:  # never raise into user code
            get_error_log().warning("timed_region exit failed", err)
        return False
