"""Ray Train integration (gated — ray is not in this image)
(reference: src/traceml_ai/integrations/ray.py:36-352: aggregator as a
rank-0-node actor + per-worker in-process runtime via lifecycle).

Usage::

    from traceml_tpu.integrations.ray import traceml_train_loop

    def my_loop(config):
        ...  # normal Ray Train loop

    trainer = TorchTrainer(traceml_train_loop(my_loop), ...)

The wrapper starts an in-process runtime on every Ray worker (identity
from Ray's world rank env), points it at an aggregator that the rank-0
worker hosts, and stops everything when the loop returns.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

from traceml_tpu.runtime import lifecycle
from traceml_tpu.runtime.settings import (
    AggregatorEndpoint,
    TraceMLSettings,
    settings_from_env,
)
from traceml_tpu.utils.error_log import get_error_log


def _require_ray():
    try:
        import ray  # noqa: F401

        return ray
    except Exception as exc:  # pragma: no cover - ray absent here
        raise ImportError("ray is required for the Ray integration") from exc


def traceml_train_loop(
    user_loop: Callable[[Any], Any],
    settings: Optional[TraceMLSettings] = None,
) -> Callable[[Any], Any]:
    """Wrap a Ray Train per-worker loop with TraceML runtime lifecycle."""

    def wrapped(config: Any) -> Any:
        base = settings or settings_from_env()
        rank = int(os.environ.get("RANK", os.environ.get("WORLD_RANK", 0)))
        agg = None
        run_settings = base
        try:
            if rank == 0 and not base.aggregator.port:
                # rank 0 hosts the aggregator; its bound port is shared
                # through the session dir ready-file (workers on other
                # nodes read it over the shared filesystem Ray provides)
                agg = lifecycle.start_aggregator(base)
                if agg is not None and agg.port:
                    from traceml_tpu.aggregator.trace_aggregator import (
                        write_ready_file,
                    )

                    write_ready_file(base, agg.port)
            if not run_settings.aggregator.port:
                from traceml_tpu.launcher.process import wait_for_ready_file

                ready = wait_for_ready_file(
                    base.session_dir / "aggregator_ready.json", timeout=30
                )
                if ready:
                    import dataclasses

                    run_settings = dataclasses.replace(
                        base,
                        aggregator=AggregatorEndpoint(
                            connect_host=base.aggregator.connect_host,
                            bind_host=base.aggregator.bind_host,
                            port=int(ready["port"]),
                        ),
                    )
            lifecycle.start_runtime(run_settings)
            from traceml_tpu.sdk.initial import init as sdk_init

            sdk_init(mode="auto")
            return user_loop(config)
        finally:
            try:
                lifecycle.stop_runtime()
            except Exception as exc:
                get_error_log().warning("ray worker runtime stop failed", exc)
            if agg is not None:
                try:
                    agg.stop()
                except Exception as exc:
                    get_error_log().warning("ray aggregator stop failed", exc)

    return wrapped
