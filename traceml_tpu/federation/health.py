"""Shard health probing with capped-backoff
(docs/developer_guide/federation.md).

One daemon thread polls every shard's ``GET /api/sessions`` — the same
document the rollup merges, so a single probe per interval buys three
things at once:

* **liveness** — a shard that stops answering flips to ``alive=False``
  and its probe interval backs off exponentially (capped), so a dead
  aggregator costs the router a bounded trickle of connection attempts,
  not a hot retry loop;
* **the location map** — each index names the sessions the shard
  actually serves, which overrides the hash-ring guess for sessions
  placed before the ring changed (the ring stays the fallback for
  sessions no shard has claimed yet);
* **a stale rollup fallback** — the last good index is retained, so a
  dead shard's sessions degrade to marked-stale fleet rows instead of
  vanishing or erroring the page.

The router's own proxy traffic also feeds the monitor passively:
``note_success``/``note_failure`` flip state without waiting for the
next probe tick, so a shard crash surfaces at the first failed fetch.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

#: backoff cap as a multiple of the base probe interval
_BACKOFF_CAP_MULT = 16
#: absolute ceiling on the probe interval, seconds
_BACKOFF_CAP_S = 30.0


class ShardState:
    """Mutable per-shard record; reads/writes go through the monitor's
    lock, snapshots hand out copies."""

    __slots__ = (
        "shard", "alive", "fail_count", "last_ok_ts",
        "last_index", "next_probe_mono",
    )

    def __init__(self, shard: str) -> None:
        self.shard = shard
        self.alive = False  # unknown until the first probe answers
        self.fail_count = 0
        self.last_ok_ts: Optional[float] = None
        self.last_index: Optional[Dict[str, Any]] = None
        self.next_probe_mono = 0.0  # probe immediately on start

    def summary(self) -> Dict[str, Any]:
        return {
            "shard": self.shard,
            "alive": self.alive,
            "fail_count": self.fail_count,
            "last_ok_ts": self.last_ok_ts,
            "sessions": len((self.last_index or {}).get("sessions") or []),
        }


def _default_fetch_index(shard: str, timeout: float) -> Dict[str, Any]:
    """GET the shard's fleet index (raises on any failure)."""
    req = urllib.request.Request(
        f"http://{shard}/api/sessions",
        headers={"Accept": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = json.loads(resp.read().decode("utf-8"))
    if not isinstance(data, dict):
        raise ValueError("fleet index is not an object")
    return data


class HealthMonitor:
    """Probes shards on a capped-backoff schedule; thread-safe."""

    def __init__(
        self,
        shards: List[str],
        probe_s: float = 2.0,
        fetch_index: Optional[Callable[[str, float], Dict[str, Any]]] = None,
    ) -> None:
        self.probe_s = max(0.05, float(probe_s))
        self._fetch_index = fetch_index or _default_fetch_index
        self._lock = threading.Lock()
        self._states: Dict[str, ShardState] = {
            s: ShardState(s) for s in shards
        }
        # session id → owning shard, learned from shard indexes; latest
        # claim wins (a session never legitimately lives on two shards)
        self._locations: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="traceml-fleet-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    # -- probing ---------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                due = [
                    st.shard
                    for st in self._states.values()
                    if st.next_probe_mono <= now
                ]
            for shard in due:
                if self._stop.is_set():
                    return
                self.probe(shard)
            # short slice so stop() and backoff-expiry are both prompt
            self._stop.wait(min(self.probe_s, 0.25))

    def probe(self, shard: str) -> bool:
        """Probe one shard now (also callable from tests, which makes
        the schedule deterministic)."""
        timeout = min(max(self.probe_s, 0.25), 2.0)
        try:
            index = self._fetch_index(shard, timeout)
        except Exception:
            self.note_failure(shard)
            return False
        self.note_success(shard, index)
        return True

    def note_success(
        self, shard: str, index: Optional[Dict[str, Any]] = None
    ) -> None:
        """Record a good exchange with ``shard`` (probe or proxy)."""
        with self._lock:
            st = self._states.get(shard)
            if st is None:
                return
            st.alive = True
            st.fail_count = 0
            st.last_ok_ts = time.time()
            st.next_probe_mono = time.monotonic() + self.probe_s
            if index is not None:
                st.last_index = index
                for entry in index.get("sessions") or []:
                    sid = (entry or {}).get("session")
                    if isinstance(sid, str):
                        self._locations[sid] = shard

    def note_failure(self, shard: str) -> None:
        """Record a failed exchange; backoff doubles per consecutive
        failure up to the cap, so a dead shard is cheap to keep probing
        and a recovered one is noticed within the cap."""
        with self._lock:
            st = self._states.get(shard)
            if st is None:
                return
            st.alive = False
            st.fail_count += 1
            delay = min(
                self.probe_s * (2 ** min(st.fail_count, 10)),
                self.probe_s * _BACKOFF_CAP_MULT,
                _BACKOFF_CAP_S,
            )
            st.next_probe_mono = time.monotonic() + delay

    # -- reads -----------------------------------------------------------

    def is_alive(self, shard: str) -> bool:
        with self._lock:
            st = self._states.get(shard)
            return bool(st is not None and st.alive)

    def is_down(self, shard: str, threshold: int = 2) -> bool:
        """True once ``shard`` has failed ``threshold`` consecutive
        exchanges — the router's short-circuit-to-stale trigger (one
        transient failure must not flip live traffic to stale rows)."""
        with self._lock:
            st = self._states.get(shard)
            return bool(
                st is not None
                and not st.alive
                and st.fail_count >= int(threshold)
            )

    def location_of(self, session_id: str) -> Optional[str]:
        """The shard that last claimed ``session_id`` in its index, or
        None when no shard has (the caller falls back to the ring)."""
        with self._lock:
            return self._locations.get(session_id)

    def last_index(self, shard: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            st = self._states.get(shard)
            return st.last_index if st is not None else None

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                self._states[s].summary() for s in sorted(self._states)
            ]
