"""Collectives window engine: golden equivalence vs the scalar path,
plus the domain's core invariants.

Contract (docs/developer_guide/collectives-domain.md): for any input the
scalar builder accepts, the columnar engine either produces a
bit-identical window (``collectives_window_to_plain`` compares the full
payload) or raises ``ColumnarFallback``.  Domain invariants pinned here:

* ragged participation — steps are the UNION across ranks, a rank that
  skipped a collective still leaves the step in the window
* zero-comm steps read overlap efficiency 1.0, never NaN
* a dtype mix round-trips and only fp32 all-reduce bytes feed the
  ALLREDUCE_QUANTIZABLE series
* ring eviction stays in lockstep with a deque of the same maxlen
* ``TRACEML_COLLECTIVES=0`` kills recording and sampler registration
* ``COLLECTIVE_OPS`` (columnar vocabulary) == ``OP_KINDS`` (recorder)
"""

import math
import random
from collections import deque

import pytest

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.diagnostics.collectives.api import diagnose_collectives_window
from traceml_tpu.instrumentation import collectives as IC
from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore
from traceml_tpu.samplers.collectives_sampler import aggregate_collective_records
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils.columnar import (
    COLLECTIVE_OPS,
    CollectivesColumns,
    ColumnarFallback,
    build_collectives_window_rows,
    build_columnar_collectives_window,
    collectives_window_to_plain,
)


# -- row factories -------------------------------------------------------


def _row(step, op="all_reduce", dtype="float32", count=1, nbytes=1 << 20,
         group=8, dur=4.0, exposed=None):
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "op": op,
        "dtype": dtype,
        "count": count,
        "bytes": nbytes,
        "group_size": group,
        "duration_ms": dur,
        "exposed_ms": dur if exposed is None else exposed,
    }


def _rand_rows(rng, steps, ops=("all_reduce", "all_gather", "reduce_scatter"),
               dtypes=("float32", "bfloat16")):
    rows = []
    for s in steps:
        for op in ops:
            if rng.random() < 0.3:
                continue  # ragged op participation within a step
            dur = rng.uniform(0.0, 8.0)
            rows.append(
                _row(
                    s,
                    op=op,
                    dtype=rng.choice(dtypes),
                    count=rng.randint(1, 4),
                    nbytes=rng.randint(0, 1 << 22),
                    group=rng.choice((4, 8)),
                    dur=dur,
                    exposed=dur * rng.random(),
                )
            )
    return rows


def _cols_for(rank_rows, cap=512):
    out = {}
    for rank, rows in rank_rows.items():
        c = CollectivesColumns(cap)
        for row in rows:
            c.append(row)
        out[rank] = c
    return out


def _assert_golden(rank_rows, max_steps, cap=512):
    scalar = build_collectives_window_rows(rank_rows, max_steps=max_steps)
    columnar = build_columnar_collectives_window(
        _cols_for(rank_rows, cap), max_steps
    )
    assert collectives_window_to_plain(scalar) == collectives_window_to_plain(
        columnar
    )
    return columnar


# -- golden edge cases ---------------------------------------------------


def test_vocabulary_pinned_to_recorder():
    # the columnar op vocabulary and the recorder's canonical kinds must
    # stay the same tuple — a new op kind needs both sides updated
    assert COLLECTIVE_OPS == IC.OP_KINDS


def test_ragged_participation_union_of_steps():
    rng = random.Random(21)
    rank_rows = {
        r: _rand_rows(rng, range(rng.randint(0, 6), 40)) for r in range(6)
    }
    # one rank reports only even steps — union keeps the odd ones
    rank_rows[6] = _rand_rows(rng, range(0, 40, 2))
    w = _assert_golden(rank_rows, max_steps=30)
    assert w is not None and w.n_steps == 30
    assert w.ranks == list(range(7))


def test_zero_comm_steps_efficiency_one_not_nan():
    rows = [
        _row(1, dur=4.0, exposed=1.0),
        _row(2, dur=0.0, exposed=0.0),  # a step with zero comm time
        _row(3, dur=2.0, exposed=2.0),
    ]
    w = _assert_golden({0: rows}, max_steps=10)
    effs = w.per_step["overlap_efficiency"]
    assert not any(math.isnan(e) for e in effs)
    assert effs[1] == 1.0
    assert effs[0] == 0.75 and effs[2] == 0.0
    # an all-zero window keeps the invariant at the totals level too
    w0 = build_collectives_window_rows(
        {0: [_row(1, dur=0.0, exposed=0.0)]}, max_steps=10
    )
    assert w0.totals["overlap_efficiency"] == 1.0


def test_dtype_mix_and_fp32_allreduce_series():
    rows = [
        _row(1, op="all_reduce", dtype="float32", nbytes=100),
        _row(1, op="all_reduce", dtype="bfloat16", nbytes=7),
        _row(1, op="all_gather", dtype="float32", nbytes=1000),  # not AR
        _row(2, op="all_reduce", dtype="float32", nbytes=200),
        _row(2, op="all_reduce", dtype="int8", nbytes=13),
    ]
    w = _assert_golden({0: rows}, max_steps=10)
    assert w.per_step["allreduce_fp32_bytes"] == [100, 200]
    assert w.per_op["all_reduce"]["bytes"] == 100 + 7 + 200 + 13
    assert w.per_op["all_gather"]["bytes"] == 1000


def test_unknown_op_folds_into_other():
    rows = [_row(1, op="fancy_ring_exchange"), _row(1, op="all_reduce")]
    w = _assert_golden({0: rows}, max_steps=10)
    assert "other" in w.per_op and "all_reduce" in w.per_op


def test_ring_eviction_matches_deque_maxlen():
    rng = random.Random(22)
    cap = 16
    cols = CollectivesColumns(cap)
    rows = deque(maxlen=cap)
    step = 0
    for i in range(3 * cap + 5):  # force several compactions
        step += rng.randint(0, 2)  # non-decreasing, repeats allowed
        row = _row(
            step,
            op=rng.choice(COLLECTIVE_OPS),
            dur=rng.uniform(0, 5),
            exposed=0.0,
        )
        cols.append(row)
        rows.append(row)
        scalar = build_collectives_window_rows({0: list(rows)}, max_steps=12)
        columnar = build_columnar_collectives_window({0: cols}, 12)
        assert collectives_window_to_plain(
            scalar
        ) == collectives_window_to_plain(columnar)
    assert len(cols) == cap


# -- fallback flagging ---------------------------------------------------


def test_out_of_order_step_flags_fallback():
    cols = CollectivesColumns(16)
    cols.append(_row(5))
    cols.append(_row(3))
    assert not cols.columnar_ok
    with pytest.raises(ColumnarFallback):
        build_columnar_collectives_window({0: cols}, 10)


def test_malformed_values_flag_fallback():
    for bad in (
        _row(1, nbytes=-4),                      # negative volume
        _row(1, nbytes=2**60),                   # beyond exact float64
        _row(1, dur=3.0, exposed=5.0),           # exposed > duration
        dict(_row(1), count="two"),              # non-int count
        dict(_row(1), step=True),                # bool step
    ):
        cols = CollectivesColumns(16)
        cols.append(bad)
        assert not cols.columnar_ok


def test_dtype_vocab_overflow_flags_fallback():
    cols = CollectivesColumns(256)
    for i in range(70):  # _COLL_DTYPE_VOCAB_MAX is 64
        cols.append(_row(i + 1, dtype=f"custom{i}"))
    assert not cols.columnar_ok


# -- sampler aggregation -------------------------------------------------


def test_aggregate_collective_records_merges_by_step_op_dtype():
    recs = [
        {"step": 1, "ts": 1.0, "op": "all_reduce", "dtype": "float32",
         "bytes": 100, "group_size": 8, "duration_ms": 2.0, "exposed_ms": 1.0},
        {"step": 1, "ts": 1.1, "op": "all_reduce", "dtype": "float32",
         "bytes": 50, "group_size": 4, "duration_ms": 1.0, "exposed_ms": 0.5},
        {"step": 1, "ts": 1.2, "op": "all_gather", "dtype": "float32",
         "bytes": 10, "group_size": 8, "duration_ms": 0.5, "exposed_ms": 0.0},
        {"step": 2, "ts": 2.0, "op": "all_reduce", "dtype": "float32",
         "bytes": 100, "group_size": 8, "duration_ms": 2.0, "exposed_ms": 2.0},
    ]
    rows = aggregate_collective_records(recs)
    key = {(r["step"], r["op"], r["dtype"]): r for r in rows}
    assert len(rows) == 3
    ar1 = key[(1, "all_reduce", "float32")]
    assert ar1["count"] == 2 and ar1["bytes"] == 150
    assert ar1["duration_ms"] == 3.0 and ar1["exposed_ms"] == 1.5
    assert ar1["group_size"] == 8  # max across merged records


# -- kill switch ---------------------------------------------------------


def test_kill_switch_disables_recording_and_sampler(tmp_path, monkeypatch):
    monkeypatch.setenv("TRACEML_COLLECTIVES", "0")
    monkeypatch.setattr(IC, "_lax_patched", False)
    assert not IC.collectives_enabled()
    assert IC.record_collective("all_reduce", duration_ms=1.0) is False
    assert IC.patch_lax_collectives() is False

    from traceml_tpu.runtime.identity import RuntimeIdentity
    from traceml_tpu.runtime.sampler_registry import build_samplers
    from traceml_tpu.runtime.settings import TraceMLSettings

    settings = TraceMLSettings(session_id="s", logs_dir=tmp_path)
    ident = RuntimeIdentity(global_rank=0, local_rank=0)
    names = {type(s).__name__ for s in build_samplers(settings, ident)}
    assert "CollectivesSampler" not in names

    # the gate is checked per build (not at registration): re-enabling
    # the env brings the sampler back without re-registering
    monkeypatch.setenv("TRACEML_COLLECTIVES", "1")
    names = {type(s).__name__ for s in build_samplers(settings, ident)}
    assert "CollectivesSampler" in names


def test_record_collective_enqueues_and_clamps(monkeypatch):
    monkeypatch.delenv("TRACEML_COLLECTIVES", raising=False)
    IC.GLOBAL_COLLECTIVES_QUEUE.drain()
    assert IC.record_collective(
        "psum", nbytes=64, dtype="float32", group_size=8,
        duration_ms=2.0, exposed_ms=5.0, step=7,
    )
    (rec,) = IC.GLOBAL_COLLECTIVES_QUEUE.drain()
    assert rec["op"] == "all_reduce"  # alias normalized
    assert rec["exposed_ms"] == 2.0   # clamped to duration
    assert rec["step"] == 7


# -- store-level integration (ingest → cursor read → trim lockstep) ------


def _ident(rank=0):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank,
        world_size=2,
        node_rank=0,
        hostname="host-0",
        pid=100 + rank,
    )


def _ingest(w, rank, rows):
    w.ingest(
        build_telemetry_envelope("collectives", {"collectives": rows}, _ident(rank))
    )


def test_store_columnar_window_matches_scalar_rows(tmp_path):
    rng = random.Random(23)
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=40)
    for rank in (0, 1):
        _ingest(w, rank, _rand_rows(rng, range(1, 31)))
    assert w.force_flush()
    store.refresh()

    assert store.has_collectives_rows()
    win = store.build_collectives_window(max_steps=20)
    scalar = build_collectives_window_rows(
        store.collectives_rows(), max_steps=20
    )
    assert collectives_window_to_plain(win) == collectives_window_to_plain(
        scalar
    )

    # incremental append advances the window identically (dirty-gated
    # cursor read + ring/deque lockstep through eviction)
    for rank in (0, 1):
        _ingest(w, rank, _rand_rows(rng, range(31, 41)))
    assert w.force_flush()
    store.refresh()
    win2 = store.build_collectives_window(max_steps=20)
    scalar2 = build_collectives_window_rows(
        store.collectives_rows(), max_steps=20
    )
    assert collectives_window_to_plain(win2) == collectives_window_to_plain(
        scalar2
    )
    assert win2.steps[-1] == 40
    w.finalize()
    store.close()


# -- diagnosis fixtures --------------------------------------------------


def test_comm_bound_fires_on_comm_heavy_window():
    rows = [_row(s, dur=30.0, exposed=30.0) for s in range(1, 31)]
    w = build_collectives_window_rows({0: rows, 1: rows}, max_steps=60)
    result = diagnose_collectives_window(w, mode="summary", step_time_ms=100.0)
    # 60 ms exposed across 2 ranks ÷ 100 ms step = 0.6 ≥ 0.40 critical
    assert result.diagnosis.kind == "COMM_BOUND"
    assert result.diagnosis.severity == "critical"


def test_comm_bound_silent_on_compute_only_window():
    rows = [
        _row(s, dtype="bfloat16", nbytes=4096, dur=0.05, exposed=0.05)
        for s in range(1, 31)
    ]
    w = build_collectives_window_rows({0: rows}, max_steps=60)
    result = diagnose_collectives_window(w, mode="summary", step_time_ms=100.0)
    assert all(i.kind != "COMM_BOUND" for i in result.issues)
    assert result.healthy


def test_poor_overlap_fires_with_step_headroom():
    rows = [
        _row(s, dur=10.0, exposed=(9.0 if s <= 20 else 0.5))
        for s in range(1, 31)
    ]
    w = build_collectives_window_rows({0: rows}, max_steps=60)
    result = diagnose_collectives_window(w, mode="summary")
    kinds = {i.kind for i in result.issues}
    assert "POOR_OVERLAP" in kinds
    # no step-time denominator was provided → COMM_BOUND must stay quiet
    assert "COMM_BOUND" not in kinds


def test_allreduce_quantizable_info_on_stable_fp32_payload():
    rows = [
        _row(s, op="all_reduce", dtype="float32", nbytes=2 << 20,
             dur=5.0, exposed=0.0)
        for s in range(1, 31)
    ]
    w = build_collectives_window_rows({0: rows}, max_steps=60)
    result = diagnose_collectives_window(w, mode="summary")
    quant = [i for i in result.issues if i.kind == "ALLREDUCE_QUANTIZABLE"]
    assert quant and quant[0].severity == "info"


def test_insufficient_data_below_min_steps():
    rows = [_row(s) for s in range(1, 4)]
    w = build_collectives_window_rows({0: rows}, max_steps=60)
    result = diagnose_collectives_window(w, mode="summary")
    assert result.diagnosis.kind == "INSUFFICIENT_COLLECTIVES_DATA"
    assert diagnose_collectives_window(None).diagnosis.kind == (
        "INSUFFICIENT_COLLECTIVES_DATA"
    )
