"""Sampler base (reference: src/traceml_ai/samplers/base_sampler.py:23-93).

Every sampler owns a bounded in-memory :class:`Database` and an
incremental sender; the runtime tick calls ``sample()`` (errors logged,
never raised) and the publisher collects each sender's new rows.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from traceml_tpu.database import Database, DBIncrementalSender, DatabaseWriter
from traceml_tpu.utils.error_log import get_error_log


class BaseSampler:
    name: str = "base"

    def __init__(self, disk_backup_dir: Optional[Path] = None) -> None:
        self.db = Database()
        self.sender = DBIncrementalSender(self.name, self.db)
        self.writer = DatabaseWriter(self.name, self.db, disk_backup_dir)
        self.sample_errors = 0

    def sample(self) -> None:
        """Called on every runtime tick; must be cheap and non-raising."""
        try:
            self._sample()
        except Exception as exc:
            self.sample_errors += 1
            get_error_log().warning(f"sampler {self.name} sample failed", exc)

    def _sample(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def drain(self) -> None:
        """Final sample pass during shutdown (drain-on-stop samplers)."""
        self.sample()

    def stop(self) -> None:
        """Last-chance backup flush.  In envelope mode the writer only
        holds what the publisher fed it — if rows landed after the final
        publish (or the publisher died mid-window), collect them into one
        last envelope here so the on-disk backup is complete, then force
        the buffer out."""
        try:
            if self.writer.envelope_mode and self.sender.dirty():
                payload = self.sender.collect_payload()
                if payload is not None:
                    from traceml_tpu.utils import msgpack_codec

                    self.writer.append_envelope(msgpack_codec.preencode(payload))
        except Exception as exc:
            get_error_log().warning(f"sampler {self.name} final collect failed", exc)
        try:
            self.writer.flush(force=True)
        except Exception:
            pass
