"""Shared edge cache for proxied shard responses
(docs/developer_guide/federation.md).

The router generalizes the r13 serving-tier cache shape — ``[token,
raw, gzip]`` per entry, TTL-bounded — across the extra hop: however
many viewers poll one hot session through the router, the owning shard
sees at most ~one upstream fetch per (session, version) per TTL
window.

Three entry classes share the store, distinguished by key prefix:

* ``("live", sid)`` — the assembled full payload.  Expired entries are
  *revalidated*, not dropped: the refresh fetch carries
  ``If-None-Match: "<token>"`` and a 304 renews the entry for free, so
  an idle session costs the shard a header exchange per TTL, never a
  body.
* ``("delta", sid, since)`` — one delta response per client version
  vector.  Viewers at the same ``since`` inside one TTL window share a
  single upstream fetch (the common case: every tab of one dashboard
  converges to the current token within a poll).  Idle 204s cache the
  same way — an idle fleet costs ~one upstream poll per session per
  TTL regardless of viewer count.
* ``("summary", sid)`` — the final-summary body, revalidated by its
  content-hash ETag like ``live``.

Entries hold the *decoded* body (hop compression is stripped at fetch
time); the gzip form clients negotiate is compressed once per entry
and shared, exactly like ``SessionPublisher.full_body``.
"""

from __future__ import annotations

import gzip
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

#: responses smaller than this are not worth gzipping (mirrors
#: renderers/serving.GZIP_MIN_BYTES; duplicated to keep the federation
#: tier importable without the renderer stack)
GZIP_MIN_BYTES = 256

#: bound on distinct cached responses — a hostile client cycling fake
#: ``since`` tokens must not grow the router's memory unboundedly
DEFAULT_MAX_ENTRIES = 4096


class CacheEntry:
    """One cached upstream response: status + validator + body forms."""

    __slots__ = (
        "status", "token", "body", "gzip_body", "built_mono", "headers"
    )

    def __init__(
        self,
        status: int,
        token: Optional[str],
        body: bytes,
        headers: Dict[str, str],
        built_mono: float,
    ) -> None:
        self.status = status
        self.token = token
        self.body = body
        self.gzip_body: Optional[bytes] = None
        self.built_mono = built_mono
        self.headers = headers

    def gzipped(self) -> Optional[bytes]:
        """The shared gzip form (lazily built; None below the floor)."""
        if len(self.body) < GZIP_MIN_BYTES:
            return None
        if self.gzip_body is None:
            self.gzip_body = gzip.compress(self.body, mtime=0)
        return self.gzip_body


class EdgeCache:
    """TTL + LRU bounded response cache; thread-safe (every router
    handler thread reads and writes through it)."""

    def __init__(
        self, ttl: float = 0.5, max_entries: int = DEFAULT_MAX_ENTRIES
    ) -> None:
        self.ttl = max(0.0, float(ttl))
        self.max_entries = max(16, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, CacheEntry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.revalidations = 0

    def get(self, key: Tuple) -> Tuple[Optional[CacheEntry], bool]:
        """(entry or None, fresh).  A stale entry is still returned —
        the caller revalidates it upstream (If-None-Match) or serves it
        marked stale when the owning shard is down."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None, False
            self._entries.move_to_end(key)
            fresh = (now - entry.built_mono) <= self.ttl
            if fresh:
                self.hits += 1
            else:
                self.misses += 1
            return entry, fresh

    def put(
        self,
        key: Tuple,
        status: int,
        token: Optional[str],
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ) -> CacheEntry:
        entry = CacheEntry(
            status, token, body, dict(headers or {}), time.monotonic()
        )
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
        return entry

    def renew(self, key: Tuple) -> None:
        """Refresh an entry's TTL after an upstream 304 revalidation —
        the body is proven current, only the clock moves."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.built_mono = time.monotonic()
                self.revalidations += 1

    def invalidate_session(self, session_id: str) -> None:
        """Drop every entry belonging to one session (shard flap —
        the replacement shard may serve different content)."""
        with self._lock:
            doomed = [
                k for k in self._entries if len(k) > 1 and k[1] == session_id
            ]
            for k in doomed:
                del self._entries[k]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "revalidations": self.revalidations,
            }
