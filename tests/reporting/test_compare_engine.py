"""Compare-engine battery — improvement / regression / mixed /
insufficient-data scenarios (mirrors the reference's compare scenario
coverage; reference: reporting/compare/verdict.py:24-38 ladder)."""

from traceml_tpu.reporting.compare.command import (
    build_compare_payload,
    render_compare_text,
)
from traceml_tpu.reporting.compare.policy import DEFAULT_POLICY, classify
from traceml_tpu.reporting.compare.sections import (
    compare_step_memory,
    compare_step_time,
    compare_system,
)


def _summary(
    step_ms=100.0,
    input_share=0.1,
    per_rank=None,
    peaks=None,
    n_steps=40,
    diagnosis=("HEALTHY", "info"),
    cpu_mean=30.0,
    rss=1 << 30,
    proc_cpu=50.0,
    session="s",
):
    per_rank = per_rank or {"0": step_ms, "1": step_ms}
    peaks = peaks or {"0": 4 << 30, "1": 4 << 30}
    return {
        "meta": {"session_id": session},
        "primary_diagnosis": {"kind": diagnosis[0], "severity": diagnosis[1]},
        "sections": {
            "step_time": {
                "status": "OK",
                "global": {
                    "clock": "device",
                    "n_steps": n_steps,
                    "phases": {
                        "step_time": {
                            "median_ms": step_ms,
                            "per_rank_avg_ms": per_rank,
                        },
                        "input": {
                            "median_ms": step_ms * input_share,
                            "share_of_step": input_share,
                        },
                    },
                },
            },
            "step_memory": {
                "status": "OK",
                "global": {
                    "per_rank": {
                        r: {"step_peak_bytes": p} for r, p in peaks.items()
                    }
                },
            },
            "system": {
                "status": "OK",
                "global": {
                    "nodes": {
                        "0": {
                            "hostname": "n0",
                            "cpu_pct_mean": cpu_mean,
                            "memory_used_bytes": 8 << 30,
                        }
                    }
                },
            },
            "process": {
                "status": "OK",
                "global": {
                    "per_rank": {
                        "0": {"cpu_pct": proc_cpu, "rss_bytes": rss},
                        "1": {"cpu_pct": proc_cpu, "rss_bytes": rss},
                    }
                },
            },
        },
    }


def test_equivalent_runs():
    p = build_compare_payload(_summary(), _summary(session="t"))
    assert p["verdict"] == "EQUIVALENT"
    assert p["findings"] == []
    assert p["sections"]["step_time"]["status"] == "OK"
    assert "EQUIVALENT" in render_compare_text(p)


def test_major_step_regression():
    p = build_compare_payload(_summary(step_ms=100.0), _summary(step_ms=120.0))
    assert p["verdict"] == "REGRESSION"
    assert p["findings"][0]["kind"] == "STEP_TIME_REGRESSION"
    assert p["findings"][0]["significance"] == "major"
    assert abs(p["step_delta_rel"] - 0.2) < 1e-9


def test_major_step_improvement():
    p = build_compare_payload(_summary(step_ms=120.0), _summary(step_ms=100.0))
    assert p["verdict"] == "IMPROVEMENT"
    assert p["findings"][0]["kind"] == "STEP_TIME_IMPROVEMENT"


def test_minor_regression_is_likely():
    p = build_compare_payload(_summary(step_ms=100.0), _summary(step_ms=104.0))
    assert p["verdict"] == "LIKELY_REGRESSION"


def test_mixed_signals():
    # step improves (major) but memory regresses (minor → regression class)
    p = build_compare_payload(
        _summary(step_ms=120.0, peaks={"0": 4 << 30, "1": 4 << 30}),
        _summary(step_ms=100.0, peaks={"0": (4 << 30) + (300 << 20), "1": 4 << 30}),
    )
    assert p["verdict"] == "MIXED"
    kinds = {f["kind"] for f in p["findings"]}
    assert "STEP_TIME_IMPROVEMENT" in kinds
    assert "MEMORY_REGRESSION" in kinds or "MEMORY_IMBALANCE_GREW" in kinds


def test_insufficient_window():
    p = build_compare_payload(_summary(n_steps=4), _summary(n_steps=40))
    assert p["verdict"] == "INSUFFICIENT_DATA"
    assert p["sections"]["step_time"]["status"] == "INSUFFICIENT"


def test_missing_section_partial_data():
    b = _summary()
    c = _summary()
    c["sections"]["step_memory"] = {"status": "NO_DATA"}
    p = build_compare_payload(b, c)
    assert p["sections"]["step_memory"]["status"] == "MISSING_CANDIDATE"
    assert p["verdict"] == "PARTIAL_DATA"


def test_missing_step_time_is_insufficient():
    b = _summary()
    del b["sections"]["step_time"]
    c = _summary()
    del c["sections"]["step_time"]
    p = build_compare_payload(b, c)
    assert p["verdict"] == "INSUFFICIENT_DATA"


def test_rank_divergence_detected():
    # rank 1 alone slows 30% while the run-level median stays put
    p = build_compare_payload(
        _summary(per_rank={"0": 100.0, "1": 100.0}),
        _summary(per_rank={"0": 100.0, "1": 130.0}),
    )
    kinds = [f["kind"] for f in p["findings"]]
    assert "RANK_DIVERGENCE" in kinds
    rd = next(f for f in p["findings"] if f["kind"] == "RANK_DIVERGENCE")
    assert rd["rank"] == "1"
    assert p["verdict"] == "REGRESSION"


def test_memory_skew_growth():
    comp = compare_step_memory(
        _summary(peaks={"0": 4 << 30, "1": 4 << 30}),
        _summary(peaks={"0": 4 << 30, "1": (4 << 30) + (200 << 20)}),
    )
    assert "rank_skew_pp" in comp.metrics
    kinds = [f["kind"] for f in comp.findings]
    assert "MEMORY_IMBALANCE_GREW" in kinds


def test_diagnosis_regression_drives_verdict():
    p = build_compare_payload(
        _summary(diagnosis=("HEALTHY", "info")),
        _summary(diagnosis=("INPUT_STRAGGLER", "warning")),
    )
    kinds = [f["kind"] for f in p["findings"]]
    assert "DIAGNOSIS_REGRESSION" in kinds
    assert p["verdict"] == "REGRESSION"


def test_diagnosis_change_to_healthy_not_regression():
    p = build_compare_payload(
        _summary(diagnosis=("INPUT_STRAGGLER", "warning")),
        _summary(diagnosis=("HEALTHY", "info")),
    )
    changed = next(f for f in p["findings"] if f["metric"] == "primary_diagnosis")
    assert changed["kind"] == "DIAGNOSIS_CHANGED"
    assert changed["significance"] == "minor"


def test_system_cpu_shift():
    comp = compare_system(_summary(cpu_mean=20.0), _summary(cpu_mean=60.0))
    kinds = [f["kind"] for f in comp.findings]
    assert "HOST_CPU_SHIFT" in kinds
    assert comp.per_rank["0"]["cpu_pp"] == 40.0


def test_process_rss_growth():
    p = build_compare_payload(
        _summary(rss=1 << 30), _summary(rss=(1 << 30) + (2 << 30))
    )
    kinds = [f["kind"] for f in p["findings"]]
    assert "PROCESS_RSS_GREW" in kinds


def test_phase_share_shift_reported():
    comp = compare_step_time(
        _summary(input_share=0.10), _summary(input_share=0.25), DEFAULT_POLICY
    )
    shift = next(f for f in comp.findings if f["kind"] == "PHASE_SHIFT")
    assert shift["phase"] == "input"
    assert shift["direction"] == "up"
    assert shift["significance"] == "major"


def test_clock_change_noted():
    b = _summary()
    c = _summary()
    c["sections"]["step_time"]["global"]["clock"] = "host"
    comp = compare_step_time(b, c, DEFAULT_POLICY)
    assert "clock changed" in comp.note


def test_classify_tiers():
    assert classify(None, 1, 2) == "negligible"
    assert classify(0.5, 1, 2) == "negligible"
    assert classify(-1.5, 1, 2) == "minor"
    assert classify(2.5, 1, 2) == "major"


# -- confidence-weighted ladder (VERDICT r4 item 9) -------------------------

def _summary_conf(step_ms, kind, severity, conf):
    s = _summary(step_ms=step_ms, diagnosis=(kind, severity))
    if conf is not None:
        from traceml_tpu.diagnostics.common import confidence_label

        s["primary_diagnosis"]["confidence"] = conf
        s["primary_diagnosis"]["confidence_label"] = confidence_label(conf)
    return s


def test_low_confidence_diagnosis_regression_loses_to_major_improvement():
    """A low-confidence DIAGNOSIS_REGRESSION must not outrank a solid
    STEP_TIME_IMPROVEMENT: verdict IMPROVEMENT, transition still listed."""
    p = build_compare_payload(
        _summary_conf(120.0, "HEALTHY", "info", 0.9),
        _summary_conf(100.0, "INPUT_STRAGGLER", "warning", 0.4),
    )
    kinds = [f["kind"] for f in p["findings"]]
    assert "DIAGNOSIS_REGRESSION" in kinds
    assert "STEP_TIME_IMPROVEMENT" in kinds
    trans = next(f for f in p["findings"] if f["kind"] == "DIAGNOSIS_REGRESSION")
    assert trans["confidence_label"] == "low"  # min of both sides
    assert p["verdict"] == "IMPROVEMENT"


def test_high_confidence_diagnosis_regression_forces_mixed():
    """The same transition held with HIGH confidence on both sides keeps
    its weight: major improvement + major regression = MIXED."""
    p = build_compare_payload(
        _summary_conf(120.0, "HEALTHY", "info", 0.95),
        _summary_conf(100.0, "INPUT_STRAGGLER", "warning", 0.9),
    )
    assert p["verdict"] == "MIXED"


def test_unlabeled_diagnosis_regression_keeps_full_weight():
    """No confidence recorded → pre-confidence behavior (MIXED)."""
    p = build_compare_payload(
        _summary_conf(120.0, "HEALTHY", "info", None),
        _summary_conf(100.0, "INPUT_STRAGGLER", "warning", None),
    )
    assert p["verdict"] == "MIXED"


def test_low_confidence_pathological_transition_is_likely_not_regression():
    p = build_compare_payload(
        _summary_conf(100.0, "HEALTHY", "info", 0.5),
        _summary_conf(100.0, "INPUT_STRAGGLER", "warning", 0.5),
    )
    assert p["verdict"] == "LIKELY_REGRESSION"


def test_rank_findings_orders_low_confidence_last():
    from traceml_tpu.reporting.compare.verdict import rank_findings

    low = {"kind": "DIAGNOSIS_REGRESSION", "significance": "major",
           "confidence_label": "low", "section": "diagnosis"}
    high = {"kind": "MEMORY_REGRESSION", "significance": "major",
            "confidence_label": "high", "section": "step_memory"}
    minor = {"kind": "PROCESS_RSS_GREW", "significance": "minor",
             "section": "process"}
    ranked = rank_findings([low, minor, high])
    assert ranked[0] is high     # confident major first
    assert ranked[-1] is not high
