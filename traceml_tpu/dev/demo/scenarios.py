"""Scenario library: each scenario is a small flax training run with ONE
injected pathology, runnable standalone (``python -m
traceml_tpu.dev.demo.scenarios <name>``) or under ``traceml-tpu run``.

Scenarios and their expected verdicts:

* ``healthy``           → COMPUTE_BOUND / NO_CLEAR_PERFORMANCE_BOTTLENECK
* ``input_bound``       → INPUT_BOUND (slow dataloader on every rank)
* ``input_straggler``   → INPUT_STRAGGLER (slow dataloader on ONE rank —
  needs multi-rank, e.g. ``traceml-tpu run --nprocs 4``; the injected
  rank is RANK env–gated, reference: mlp_ddp_input_straggler.py:34-38)
* ``compute_straggler`` → COMPUTE_STRAGGLER (extra matmuls on one rank)
* ``collective_straggler`` → COLLECTIVE_STRAGGLER (one rank's explicit
  gradient-sync collective is slow — degraded ICI link analogue; uses
  ``instrument_collective`` so the time lands in the first-class
  ``collective`` phase AND the collectives telemetry domain)
* ``comm_bound``        → COMM_BOUND (every rank's gradient sync is a
  slow, host-blocking — fully exposed — all-reduce; the collectives
  domain reports low overlap efficiency and a dominant exposed share)
* ``checkpoint_stall``  → checkpoint phase visible (a blocking save
  every few steps; with orbax installed the auto-patch times a REAL
  PyTreeCheckpointer save, else a wrap_checkpoint'd stand-in)
* ``memory_creep``      → MEMORY_CREEP_* (a list leaks one array/step)
* ``recompile``         → COMPILE_BOUND (shape churn every few steps)
"""

from __future__ import annotations

import os
import sys
import time
from typing import Iterator, Optional

import numpy as np


def _rank() -> int:
    return int(os.environ.get("RANK", 0))


def _make_model(hidden: int = 256):
    import jax

    from traceml_tpu.models.mlp import TinyMLP, make_mlp_train_step

    model = TinyMLP(hidden=hidden, depth=3)
    init, train_step = make_mlp_train_step(model)
    params, opt_state = init(
        jax.random.PRNGKey(0), np.zeros((1, 64), np.float32)
    )
    return params, opt_state, train_step


def _batches(
    n: int,
    delay_s: float = 0.0,
    delay_rank: Optional[int] = None,
    batch: int = 64,
) -> Iterator[tuple]:
    rng = np.random.default_rng(_rank())
    for _ in range(n):
        if delay_s and (delay_rank is None or _rank() == delay_rank):
            time.sleep(delay_s)
        x = rng.normal(size=(batch, 64)).astype(np.float32)
        y = rng.normal(size=(batch, 1)).astype(np.float32)
        yield x, y


def run_scenario(name: str, steps: int = 80) -> None:
    import jax
    import jax.numpy as jnp

    import traceml_tpu

    traceml_tpu.init(mode="auto")
    params, opt_state, train_step = _make_model()
    step = traceml_tpu.wrap_step_fn(train_step)

    if name == "healthy":
        loader = _batches(steps)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)
                # keep the device busy so compute dominates
                for _ in range(3):
                    params, opt_state, loss = step(params, opt_state, x, y)

    elif name == "input_bound":
        loader = _batches(steps, delay_s=0.06)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)

    elif name == "input_straggler":
        # rank (world_size-1) eats a 0.32 s input delay per step.  The
        # delay is sized for the worst CI host: with 4 rank processes
        # timesharing one core, scheduler noise can inflate the slow
        # rank's *compute* delta by >100 ms, and the clean-straggler
        # dominance gate (1.25×) needs the injected input delta to stay
        # clearly on top of that.
        world = int(os.environ.get("WORLD_SIZE", 1))
        loader = _batches(steps, delay_s=0.32, delay_rank=world - 1)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)

    elif name == "compute_straggler":
        # deterministic per-rank compute delay (VERDICT r4 item 2): the
        # slow rank's step function carries a pure_callback that sleeps
        # INSIDE the jitted program, so its output leaf — the marker the
        # compute phase is timed on — becomes ready ~120 ms late.  A
        # sleep burns no core, so on a 1-core CI host the other ranks'
        # steps are unaffected — unlike the previous extra-matmul
        # injection, whose contention slowed every timesharing rank and
        # produced no reliable cross-rank skew (the reference's
        # analogous demo injects a delay the same way:
        # src/dev/demo/mlp_ddp_compute_straggler.py).
        world = int(os.environ.get("WORLD_SIZE", 1))
        slow_rank = world - 1
        if _rank() == slow_rank:
            def _dawdle(loss_val):
                time.sleep(0.12)
                return loss_val

            def slow_train_step(params, opt_state, x, y):
                params, opt_state, loss = train_step(params, opt_state, x, y)
                loss = jax.pure_callback(
                    _dawdle,
                    jax.ShapeDtypeStruct(loss.shape, loss.dtype),
                    loss,
                )
                return params, opt_state, loss

            step = traceml_tpu.wrap_step_fn(slow_train_step)
        loader = _batches(steps)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)

    elif name == "collective_straggler":
        # each rank dispatches an explicit "gradient sync" outside the
        # fused step; the last rank's link is slow (ICI degradation
        # analogue).  instrument_collective keeps the wrap_collective
        # phase timing AND records the sync in the collectives domain.
        world = int(os.environ.get("WORLD_SIZE", 1))
        slow_rank = world - 1

        sync_op = jax.jit(lambda t: t * (1.0 / max(1, world)))

        def gradient_sync(tree):
            time.sleep(0.12 if _rank() == slow_rank else 0.02)
            return jax.tree_util.tree_map(sync_op, tree)

        timed_sync = traceml_tpu.instrument_collective(
            gradient_sync, op="all_reduce", group_size=max(1, world)
        )
        loader = _batches(steps)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)
                params = timed_sync(params)

    elif name == "comm_bound":
        # every rank's gradient sync is slow and host-blocking — fully
        # exposed comm, no overlap.  The collectives domain should
        # report COMM_BOUND (exposed share of the step well past the
        # warn bar) with near-zero overlap efficiency; the compute-only
        # scenarios above must stay silent on this rule.
        world = int(os.environ.get("WORLD_SIZE", 1))
        sync_op = jax.jit(lambda t: t * (1.0 / max(1, world)))

        def gradient_sync(tree):
            time.sleep(0.03)
            return jax.tree_util.tree_map(sync_op, tree)

        sync = traceml_tpu.instrument_collective(
            gradient_sync, op="all_reduce", group_size=max(1, world)
        )
        loader = _batches(steps)
        for x, y in traceml_tpu.wrap_dataloader(loader):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)
                params = sync(params)

    elif name == "checkpoint_stall":
        # blocking save every 5 steps; time lands in the checkpoint
        # phase (not residual)
        import tempfile

        try:
            import orbax.checkpoint as ocp

            ckpt_root = tempfile.mkdtemp(prefix="traceml_ckpt_")
            ckptr = ocp.PyTreeCheckpointer()  # auto-patched by init

            def save(tree, i):
                ckptr.save(f"{ckpt_root}/step{i}", tree)
        except Exception:  # orbax missing: a wrap_checkpoint'd stand-in
            def _slow_save(tree, i):
                time.sleep(0.05)

            save = traceml_tpu.wrap_checkpoint(_slow_save)

        loader = _batches(steps)
        for i, (x, y) in enumerate(traceml_tpu.wrap_dataloader(loader)):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)
                if i % 5 == 4:
                    save({"params": params}, i)

    elif name == "memory_creep":
        leak = []  # grows forever — the classic retained-arrays leak
        # a REAL leak outlives the loop — stash on the module so the
        # forced end-of-run memory sample still sees it.  Without this,
        # `leak` is GC'd when this function returns; under full-core
        # contention the sampler can starve down to (first, forced-
        # final) samples only, and a freed leak then reads as ~-3 MiB
        # "growth" (first sample carries step transients) — observed
        # as the loaded-lane recall flake in the r5 precision run.
        sys.modules[__name__]._memory_creep_leak = leak
        loader = _batches(steps)
        for i, (x, y) in enumerate(traceml_tpu.wrap_dataloader(loader)):
            with traceml_tpu.trace_step():
                x, y = jax.device_put(x), jax.device_put(y)
                params, opt_state, loss = step(params, opt_state, x, y)
                leak.append(jnp.ones((256, 1024)) * i)  # 1 MiB/step
                # realistic step cadence: a compiled CPU step is ~5 ms,
                # finishing all 80 steps inside the memory tracker's
                # 0.2 s throttle window — creep needs intermediate
                # samples, not just the forced end-of-run one
                time.sleep(0.015)

    elif name == "recompile":
        loader = _batches(steps)
        for i, (x, y) in enumerate(traceml_tpu.wrap_dataloader(loader)):
            with traceml_tpu.trace_step():
                # shape churn: ragged batch sizes defeat the jit cache
                ragged = 17 + (i % 7)
                x = jax.device_put(x[:ragged])
                y = jax.device_put(y[:ragged])
                params, opt_state, loss = step(params, opt_state, x, y)

    else:
        raise SystemExit(f"unknown scenario {name!r}; see module docstring")

    print(f"scenario {name} done at step {traceml_tpu.current_step()}, "
          f"loss={float(loss):.4f}")


if __name__ == "__main__":
    run_scenario(sys.argv[1] if len(sys.argv) > 1 else "healthy",
                 steps=int(sys.argv[2]) if len(sys.argv) > 2 else 80)
