"""collectives projection → ``collectives_samples``.

One row per (rank, step, op, dtype): stable identity columns + the
per-step aggregates the sampler emits (count / bytes / group_size /
duration_ms / exposed_ms).  Overlap efficiency is derived downstream
(utils/columnar.py) from the duration/exposed sums — storing the raw
sums keeps the fold exact and re-foldable over any window.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "collectives_samples"
RETENTION_TABLES = (TABLE,)


def accepts_sampler(name: str) -> bool:
    return name == "collectives"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            step INTEGER,
            timestamp REAL,
            op TEXT,
            dtype TEXT,
            count INTEGER,
            bytes INTEGER,
            group_size INTEGER,
            duration_ms REAL,
            exposed_ms REAL
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_rank_step "
        f"ON {TABLE} (session_id, global_rank, step)"
    )


def insert_sql(table: str) -> str:
    return (
        f"INSERT INTO {TABLE} (session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid, step, timestamp, op,"
        " dtype, count, bytes, group_size, duration_ms, exposed_ms)"
        " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    ident = identity_tuple(env)
    tables: Dict[str, List[Tuple]] = {}
    v = env.column_view("collectives")
    if v:
        steps = v.ints("step")
        ts = v.floats("timestamp")
        ops = v.strs("op", "other")
        dtypes = v.strs("dtype", "")
        counts = v.ints("count")
        nbytes = v.ints("bytes")
        groups = v.ints("group_size")
        dur = v.floats("duration_ms")
        exp = v.floats("exposed_ms")
        tables[TABLE] = [
            ident
            + (
                steps[i],
                ts[i],
                ops[i],
                dtypes[i],
                counts[i] or 0,
                nbytes[i] or 0,
                groups[i] or 1,
                dur[i] or 0.0,
                exp[i] or 0.0,
            )
            for i in range(len(v))
        ]
    return tables
