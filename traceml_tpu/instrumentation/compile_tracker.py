"""Process-wide XLA compile attribution via ``jax.monitoring``.

JAX emits duration events for every compilation: ``jaxpr_trace`` →
``jaxpr_to_mlir_module`` → ``backend_compile``.  A registered listener
turns each backend compile into a first-class ``compile_time`` event in
the step buffer (with a lowering/backend split in ``meta``), attributed
to whatever step is currently open.

This replaces an earlier AOT ``lower()/compile()`` wrapper design: the
listener keeps jit's C++ fast-path dispatch (the AOT ``Compiled.call``
re-flattens pytrees in Python — measured ~5 ms/step on a 65-leaf train
state) and it observes ALL compilations in the process, including ones
in code we never wrapped — exactly what a recompile-storm diagnosis
needs.

Fail-open: listener errors are swallowed; events fire synchronously on
the dispatching thread, so the TLS step gate works unchanged.
"""

from __future__ import annotations

import threading
from typing import Optional

from traceml_tpu.sdk.state import TraceState, get_state
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import COMPILE_TIME, TimeEvent, _now

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_MLIR_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"

# Sub-threshold compiles (tiny op dispatches like a first jnp.ones) cost
# nothing and would flood the event stream; only meaningful compiles
# become step events.
MIN_COMPILE_MS = 2.0

_lock = threading.Lock()
_installed = False


# lowering durations older than this cannot belong to the backend
# compile that just fired (a lowering that never backend-compiled, e.g.
# a persistent-cache hit or bare AOT .lower(), must not leak into the
# next unrelated compile's attribution)
_LOWER_STALENESS_S = 30.0


class _PendingLower(threading.local):
    """Per-thread accumulator for lowering durations between backend
    compiles (the events arrive as a trace → mlir → backend sequence on
    the dispatching thread)."""

    def __init__(self) -> None:
        self.lower_s = 0.0
        self.first_ts = 0.0


_pending = _PendingLower()


def _listener(event: str, duration: float, **kwargs) -> None:
    try:
        if event in (_TRACE_EVENT, _MLIR_EVENT):
            if _pending.lower_s == 0.0:
                _pending.first_ts = _now()
            _pending.lower_s += float(duration)
            return
        if event != _BACKEND_EVENT:
            return
        lower_s, _pending.lower_s = _pending.lower_s, 0.0
        if lower_s and _now() - _pending.first_ts > _LOWER_STALENESS_S:
            lower_s = 0.0  # stale orphaned lowering; don't misattribute
        st: TraceState = get_state()
        total_s = float(duration) + lower_s
        if total_s * 1000.0 < MIN_COMPILE_MS:
            return
        ev = TimeEvent(COMPILE_TIME, st.current_step)
        # the compile just FINISHED; reconstruct the span
        ev.cpu_end = _now()
        ev.cpu_start = ev.cpu_end - total_s
        ev.meta = {
            "lower_ms": lower_s * 1000.0,
            "backend_compile_ms": float(duration) * 1000.0,
            "fun_name": str(kwargs.get("fun_name", "")),
        }
        st.buffer.add(ev)
        st.compile_events_seen += 1
    except Exception as exc:  # never raise into jax internals
        try:
            get_error_log().warning("compile listener failed", exc)
        except Exception:
            pass


def install_compile_tracker() -> bool:
    """Register the listener once per process.  Idempotent."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring as mon

            mon.register_event_duration_secs_listener(_listener)
            _installed = True
            return True
        except Exception as exc:
            get_error_log().warning("compile tracker install failed", exc)
            return False


def compile_tracker_installed() -> bool:
    return _installed
