/* Fast length-prefixed frame codec for the telemetry wire.
 *
 * The aggregator's ingest path decodes every frame each rank sends each
 * tick; at pod scale (hundreds of ranks x many frames) the Python
 * struct/slice loop shows up.  This extension provides:
 *
 *   drain_frames(buffer: bytes, offset: int, max_frame: int)
 *       -> (frames: list[bytes], consumed: int)
 *     one pass over the buffer, returning all complete frames and the
 *     total consumed prefix (the caller compacts its rolling buffer).
 *     Raises ValueError on a frame length above max_frame.
 *
 *   pack_frames(bodies: sequence[bytes]) -> bytes
 *     one allocation for the whole batch: [len][body][len][body]...
 *
 * Framing: 4-byte big-endian length + body, identical to the Python
 * implementation in transport/tcp_transport.py (which remains the
 * fallback when the extension isn't built).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>
#include <stdint.h>

static uint32_t read_be32(const unsigned char *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static void write_be32(unsigned char *p, uint32_t v) {
    p[0] = (unsigned char)(v >> 24);
    p[1] = (unsigned char)(v >> 16);
    p[2] = (unsigned char)(v >> 8);
    p[3] = (unsigned char)v;
}

static PyObject *drain_frames(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t offset;
    Py_ssize_t max_frame;
    if (!PyArg_ParseTuple(args, "y*nn", &view, &offset, &max_frame)) {
        return NULL;
    }
    const unsigned char *buf = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len;
    if (offset < 0 || offset > len) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "offset out of range");
        return NULL;
    }
    PyObject *frames = PyList_New(0);
    if (frames == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    Py_ssize_t pos = offset;
    while (len - pos >= 4) {
        uint32_t n = read_be32(buf + pos);
        if ((Py_ssize_t)n > max_frame) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_Format(PyExc_ValueError,
                         "frame length %u exceeds bound %zd", n, max_frame);
            return NULL;
        }
        if (len - pos - 4 < (Py_ssize_t)n) {
            break; /* incomplete frame */
        }
        PyObject *frame =
            PyBytes_FromStringAndSize((const char *)(buf + pos + 4),
                                      (Py_ssize_t)n);
        if (frame == NULL) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        if (PyList_Append(frames, frame) < 0) {
            Py_DECREF(frame);
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        Py_DECREF(frame);
        pos += 4 + (Py_ssize_t)n;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", frames, pos);
}

static PyObject *pack_frames(PyObject *self, PyObject *args) {
    PyObject *seq_in;
    if (!PyArg_ParseTuple(args, "O", &seq_in)) {
        return NULL;
    }
    PyObject *seq = PySequence_Fast(seq_in, "pack_frames expects a sequence");
    if (seq == NULL) {
        return NULL;
    }
    Py_ssize_t count = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyBytes_Check(item)) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError, "pack_frames expects bytes items");
            return NULL;
        }
        Py_ssize_t n = PyBytes_GET_SIZE(item);
        if (n > (Py_ssize_t)UINT32_MAX) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_ValueError, "frame too large");
            return NULL;
        }
        total += 4 + n;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    unsigned char *dst = (unsigned char *)PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        Py_ssize_t n = PyBytes_GET_SIZE(item);
        write_be32(dst, (uint32_t)n);
        memcpy(dst + 4, PyBytes_AS_STRING(item), (size_t)n);
        dst += 4 + n;
    }
    Py_DECREF(seq);
    return out;
}

static PyMethodDef Methods[] = {
    {"drain_frames", drain_frames, METH_VARARGS,
     "drain_frames(buffer, offset, max_frame) -> (list[bytes], consumed)"},
    {"pack_frames", pack_frames, METH_VARARGS,
     "pack_frames(bodies) -> bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_framing",
    "C fast path for telemetry frame packing/draining", -1, Methods,
};

PyMODINIT_FUNC PyInit__framing(void) { return PyModule_Create(&module); }
