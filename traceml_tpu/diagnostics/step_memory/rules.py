"""Step-memory rules
(reference: src/traceml_ai/diagnostics/step_memory/rules.py:60-196,
trend.py:31-376).

Context shape: per-rank per-device :class:`MemorySeries` (sorted
columnar step series of ``{step, current_bytes, step_peak_bytes,
limit_bytes}``), built either from row dicts or directly from the
snapshot store's :class:`~traceml_tpu.utils.columnar.MemoryColumns`
ring buffers — both paths yield identical series, so every rule has a
single implementation.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from traceml_tpu.analytics.trends.core import (
    compute_trend_evidence,
    compute_window_trend,
    summarize_across,
)
from traceml_tpu.diagnostics.common import (
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    DiagnosticIssue,
    confidence_from,
)
from traceml_tpu.diagnostics.step_memory import vector
from traceml_tpu.diagnostics.step_memory.policy import DEFAULT_POLICY, StepMemoryPolicy
from traceml_tpu.utils.columnar import MemoryColumns, MemorySeries
from traceml_tpu.utils.formatting import fmt_bytes


@dataclasses.dataclass
class MemoryContext:
    # (rank, device_id) → sorted columnar series
    series: Dict[tuple, MemorySeries]
    policy: StepMemoryPolicy = DEFAULT_POLICY
    # per-context creep-evidence cache: both creep rules share one scan
    creep_cache: Optional[List["_CreepEvidence"]] = None

    @property
    def ranks(self) -> List[int]:
        return sorted({r for r, _ in self.series})


def build_memory_context(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
) -> MemoryContext:
    groups: Dict[tuple, List[Mapping[str, Any]]] = {}
    for rank, rows in rank_rows.items():
        for row in rows:
            key = (int(rank), int(row.get("device_id", 0)))
            groups.setdefault(key, []).append(row)
    series = {
        key: MemorySeries.from_rows(key[0], key[1], rows)
        for key, rows in groups.items()
    }
    return MemoryContext(series=series, policy=policy)


def build_memory_context_from_columns(
    rank_columns: Mapping[int, MemoryColumns],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
) -> MemoryContext:
    """Columnar context build: splits each rank's ring buffer by device
    (first-encounter order, matching the row path's insertion order)
    with no per-row dict copies."""
    series: Dict[tuple, MemorySeries] = {}
    for rank, cols in rank_columns.items():
        data = cols.data_view()
        if data.shape[0] == 0:
            continue
        devs = data[:, 1]  # C_DEV
        uniq, first_idx = np.unique(devs, return_index=True)
        for d in uniq[np.argsort(first_idx, kind="stable")].tolist():
            key = (int(rank), int(d))
            series[key] = MemorySeries.from_int_columns(
                key[0], key[1], data[devs == d]
            )
    return MemoryContext(series=series, policy=policy)


class HighPressureRule:
    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for (rank, dev), s in ctx.series.items():
            if not len(s):
                continue
            pressure = s.latest_pressure()
            if pressure is None or pressure < p.pressure_warn:
                continue
            severity = (
                SEVERITY_CRITICAL
                if pressure >= p.pressure_critical
                else SEVERITY_WARNING
            )
            last_sp, last_cur, last_lim = s.last_values()
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_MEMORY_PRESSURE",
                    severity=severity,
                    summary=(
                        f"Rank {rank} device {dev} at {pressure * 100:.0f}% of "
                        f"HBM capacity "
                        f"({fmt_bytes(last_sp or last_cur)}"
                        f" / {fmt_bytes(last_lim)})."
                    ),
                    action=(
                        "Reduce per-chip footprint: smaller microbatch, "
                        "jax.checkpoint/remat, optimizer-state sharding "
                        "(ZeRO-style), bf16 activations, or shard the model "
                        "further."
                    ),
                    metric="memory_pressure",
                    score=pressure,
                    share_pct=pressure,
                    # pressure is a DIRECT capacity read, not a
                    # statistic over a window — margin alone drives it
                    confidence=confidence_from(pressure, p.pressure_warn),
                    ranks=[rank],
                    evidence={"device_id": dev},
                )
            )
        return issues


class ImbalanceRule:
    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        # latest used bytes per rank (max over that rank's devices)
        per_rank: Dict[int, float] = {}
        per_rank_pressure: Dict[int, float] = {}
        for (rank, _dev), s in ctx.series.items():
            if not len(s):
                continue
            per_rank[rank] = max(per_rank.get(rank, 0.0), s.last_used())
            pres = s.latest_pressure()
            if pres is not None:
                per_rank_pressure[rank] = max(
                    per_rank_pressure.get(rank, 0.0), pres
                )
        if len(per_rank) < 2:
            return []
        stats = (
            vector.median_worst_skew(per_rank) if vector.enabled() else None
        )
        if stats is not None:
            med, worst_rank, skew = stats
        else:  # scalar golden-reference arm
            med = statistics.median(per_rank.values())
            worst_rank = max(per_rank, key=lambda r: per_rank[r])
            skew = ((per_rank[worst_rank] - med) / med) if med > 0 else 0.0
        if med <= 0:
            return []
        if skew < p.imbalance_warn:
            return []
        # only interesting when somebody is actually under pressure
        if max(per_rank_pressure.values(), default=0.0) < p.imbalance_pressure_gate:
            return []
        severity = (
            SEVERITY_CRITICAL if skew >= p.imbalance_critical else SEVERITY_WARNING
        )
        return [
            DiagnosticIssue(
                kind="MEMORY_IMBALANCE",
                severity=severity,
                summary=(
                    f"Rank {worst_rank} holds {skew * 100:.0f}% more device "
                    f"memory than the median rank "
                    f"({fmt_bytes(per_rank[worst_rank])} vs {fmt_bytes(med)})."
                ),
                action=(
                    "Check sharding balance: uneven parameter/optimizer "
                    "partitions, rank-0-only buffers (eval/logging replicas), "
                    "or padding asymmetries."
                ),
                metric="memory_skew",
                score=skew,
                skew_pct=skew,
                confidence=confidence_from(skew, p.imbalance_warn),
                ranks=[worst_rank],
                evidence={"per_rank_bytes": {str(r): v for r, v in per_rank.items()}},
            )
        ]


@dataclasses.dataclass
class _CreepEvidence:
    rank: int
    dev: int
    banded: Any
    windowed: Any
    confirmed: bool
    cluster_wide: bool


def _collect_creep_evidence(ctx: MemoryContext) -> List[_CreepEvidence]:
    """Shared creep screen for the Early/Confirmed rules
    (reference heuristics: trend.py:105-200 — ≥800-row gate, banded
    growth + windowed still-rising slope, peak-pullback recovery veto,
    worst/median cross-rank split)."""
    if ctx.creep_cache is not None:
        return ctx.creep_cache
    p = ctx.policy
    candidates: List[_CreepEvidence] = []
    growth_by_key: Dict[tuple, float] = {}
    banded_by_key: Dict[tuple, Any] = {}
    window_by_key: Dict[tuple, Any] = {}
    for (rank, dev), s in ctx.series.items():
        # the row gate applies to EVERYTHING, including the cluster-wide
        # median — a freshly restarted rank's warmup growth over 60 rows
        # must not vote that the whole cluster is creeping
        if len(s) < p.creep_min_steps:
            continue
        series = s.current_list()
        banded = compute_trend_evidence(series)
        windowed = compute_window_trend(
            series,
            short_n=p.creep_short_window,
            long_n=p.creep_long_window,
            pullback_tolerance=p.creep_pullback_max,
        )
        if banded is None or windowed is None:
            continue
        growth_by_key[(rank, dev)] = banded.growth_pct
        banded_by_key[(rank, dev)] = banded
        window_by_key[(rank, dev)] = windowed
    growth_summary = summarize_across(growth_by_key)
    median_growing = (
        growth_summary is not None
        and growth_summary.median >= p.creep_median_growth_pct
    )
    for key, banded in banded_by_key.items():
        rank, dev = key
        windowed = window_by_key[key]
        if (
            banded.delta < p.creep_min_delta_bytes
            or banded.growth_pct < p.creep_min_growth_pct
            or windowed.slope_pct_per_100 < p.creep_min_slope_pct_per_100
            or windowed.recovered  # allocator pulled back — sawtooth, not leak
        ):
            continue
        confirmed = (
            banded.delta >= p.creep_confirmed_delta_bytes
            and banded.monotonic_band_growth
            and windowed.trend_pct > 0  # STILL rising in the tail
        )
        candidates.append(
            _CreepEvidence(
                rank=rank,
                dev=dev,
                banded=banded,
                windowed=windowed,
                confirmed=confirmed,
                cluster_wide=median_growing,
            )
        )
    ctx.creep_cache = candidates
    return candidates


_CREEP_ACTION = (
    "Hunt Python-side references to device arrays (growing metric lists, "
    "retained batches), check for per-step recompiles creating executables, "
    "and confirm donated buffers are actually donated."
)


def _creep_issue(
    c: _CreepEvidence, kind: str, severity: str,
    growth_warn: float = DEFAULT_POLICY.creep_min_growth_pct,
) -> DiagnosticIssue:
    scope = "cluster-wide (median rank is growing too)" if c.cluster_wide else (
        f"rank-local (rank {c.rank} only)"
    )
    return DiagnosticIssue(
        kind=kind,
        severity=severity,
        summary=(
            f"Rank {c.rank} device {c.dev} memory grew "
            f"{fmt_bytes(c.banded.delta)} (+{c.banded.growth_pct * 100:.1f}%) "
            f"over {c.banded.n} rows — {scope}"
            + (
                "; sustained and still rising, likely a leak."
                if kind == "MEMORY_CREEP_CONFIRMED"
                else "."
            )
        ),
        action=_CREEP_ACTION,
        metric="memory_creep",
        score=c.banded.growth_pct,
        # CONFIRMED required two independent trend engines to agree
        # plus monotone bands — that IS the agreement signal; EARLY
        # passed the screen only
        confidence=confidence_from(
            c.banded.growth_pct, growth_warn,
            agreement=(kind == "MEMORY_CREEP_CONFIRMED"),
        ),
        ranks=[c.rank],
        evidence={
            "device_id": c.dev,
            "trend": c.banded.to_dict(),
            "window": c.windowed.to_dict(),
            "cluster_wide": c.cluster_wide,
        },
    )


class CreepEarlyRule:
    """MEMORY_CREEP_EARLY — the screen passed but the confirmed bars
    (≥1 GiB, monotonic, still rising) have not been met yet."""

    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        return [
            _creep_issue(c, "MEMORY_CREEP_EARLY", SEVERITY_WARNING,
                         ctx.policy.creep_min_growth_pct)
            for c in _collect_creep_evidence(ctx)
            if not c.confirmed
        ]


class CreepConfirmedRule:
    """MEMORY_CREEP_CONFIRMED — large, monotonic, and still rising in
    the tail window."""

    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        return [
            _creep_issue(c, "MEMORY_CREEP_CONFIRMED", SEVERITY_CRITICAL,
                         ctx.policy.creep_min_growth_pct)
            for c in _collect_creep_evidence(ctx)
            if c.confirmed
        ]


DEFAULT_RULES = (
    HighPressureRule(),
    ImbalanceRule(),
    CreepEarlyRule(),
    CreepConfirmedRule(),
)
