"""Step-time hero section (reference role: nicegui_sections/
model_combined_section.py — phase ribbon + verdict + KPI strip).

The signature element is the phase RIBBON: selected-clock median phase
shares, recomposing as the bottleneck shifts.  The VERDICT is taken
verbatim from the diagnosis engine's step-time issue (payload
``diagnosis``) — the same text the CLI, final summary, and findings
rail show.  This card derives no classification of its own;
interpretation belongs to the engine (single source of truth, the same
stance the reference documents at model_combined_section.py:7-14).
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">Step time</h2><span class="sp"></span>
  <span class="cmeta" id="hero-win">waiting for steps</span>
  <span id="hero-badge"></span></div>
<div class="ribbon" id="hero-ribbon"></div>
<div class="legend" id="hero-legend" style="margin-top:.4rem"></div>
<div class="verdict" id="hero-verdict">analyzing step composition</div>
<div id="hero-sevrow" style="margin-bottom:.2rem"></div>
<div class="kpis" id="hero-kpis"></div>
"""

_JS = r"""
const HERO_KPIS=[
  ["median","MEDIAN STEP","var(--accent)"],
  ["worst","WORST STEP","#7d3dd2"],
  ["gap","RANK GAP","#f1c40f"],
  ["residual","RESIDUAL","#95a5a6"],
  ["rank","WORST RANK","#16a085"],
  ["mfu","MFU","var(--violet)"],
];
let heroBuilt=false;
function buildHero(){
  document.getElementById("hero-kpis").innerHTML=
    HERO_KPIS.map(([k,l,a])=>kpiTile(k,l,a)).join("");
  heroBuilt=true}
function render_hero(d){
  if(!heroBuilt)buildHero();
  const st=d.step_time;badge("hero-badge",d.ts,st&&st.latest_ts);
  if(st){
    const cov=st.coverage||{};
    document.getElementById("hero-win").textContent=
      `${st.n_steps} steps · ${st.clock} clock · `+
      `${cov.ranks_present}/${cov.world_size} ranks`+
      (cov.incomplete?" · INCOMPLETE":"");
    // ribbon: phase share of the step median (step row excluded)
    const phases=(st.phases||[]).filter(p=>p.key!=="step"&&p.share!=null);
    const tot=phases.reduce((a,p)=>a+p.share,0)||1;
    document.getElementById("hero-ribbon").innerHTML=phases.map(p=>{
      const w=(p.share/tot*100);
      return`<div class="pseg" style="background:${COLORS[p.key]||"#888"};width:${w.toFixed(2)}%">
        <span class="seglab">${w>=7?esc(p.key):""}</span></div>`}).join("");
    document.getElementById("hero-legend").innerHTML=phases.map(p=>
      `<span><i style="background:${COLORS[p.key]||"#888"}"></i>${esc(p.key)} ${pct(p.share)}</span>`).join("");
    // KPI strip
    const stepRow=(st.phases||[]).find(p=>p.key==="step");
    setKpi("median",stepRow?fmtMs(stepRow.median_ms).split(" ")[0]:null,
      stepRow?fmtMs(stepRow.median_ms).split(" ")[1]:"");
    setKpi("worst",stepRow?fmtMs(stepRow.worst_ms).split(" ")[0]:null,
      stepRow?fmtMs(stepRow.worst_ms).split(" ")[1]:"");
    setKpi("gap",stepRow&&stepRow.skew_pct!=null?(stepRow.skew_pct*100).toFixed(0):null,"%");
    const res=phases.find(p=>p.key==="residual");
    setKpi("residual",res?(res.share/tot*100).toFixed(0):null,"%");
    setKpi("rank",stepRow!=null&&stepRow.worst_rank!=null?"r"+stepRow.worst_rank:null,"");
    const eff=st.efficiency;
    setKpi("mfu",eff&&eff.mfu_median!=null?(eff.mfu_median*100).toFixed(0):
      (eff&&eff.achieved_tflops_median!=null?
        eff.achieved_tflops_median.toFixed(1):
        (eff&&eff.tokens_per_sec_median!=null?
          Math.round(eff.tokens_per_sec_median).toLocaleString():null)),
      eff&&eff.mfu_median!=null?"%":
        (eff&&eff.achieved_tflops_median!=null?"TF/s":
          (eff&&eff.tokens_per_sec_median!=null?"tok/s":"")));
  }
  // verdict: verbatim from the diagnosis engine — never derived here,
  // and CLEARED when the engine stops reporting (a resolved diagnosis
  // must not linger on screen)
  const diag=d.diagnosis;
  if(diag&&diag.summary){
    document.getElementById("hero-verdict").textContent=diag.summary;
    document.getElementById("hero-sevrow").innerHTML=
      `<span class="sevpill" style="background:${SEV[diag.severity]||"#555"}">${esc(diag.kind)}</span>`+
      (diag.confidence_label?` <span class="cmeta">${esc(diag.confidence_label)} confidence</span>`:"");
  }else{
    document.getElementById("hero-verdict").textContent=
      st?"step composition healthy":"analyzing step composition";
    document.getElementById("hero-sevrow").innerHTML="";
  }
}
"""

SECTION = Section(
    id="hero",
    title="Step time",
    html=_HTML,
    js=_JS,
    contract=(
        "ts",
        "step_time.latest_ts",
        "step_time.n_steps",
        "step_time.clock",
        "step_time.coverage.ranks_present",
        "step_time.coverage.world_size",
        "step_time.coverage.incomplete",
        "step_time.phases.key",
        "step_time.phases.share",
        "step_time.phases.median_ms",
        "step_time.phases.worst_ms",
        "step_time.phases.skew_pct",
        "step_time.phases.worst_rank",
        "step_time.efficiency.mfu_median",
        "step_time.efficiency.achieved_tflops_median",
        "step_time.efficiency.tokens_per_sec_median",
        "diagnosis.summary",
        "diagnosis.severity",
        "diagnosis.kind",
        "diagnosis.confidence_label",
    ),
)
