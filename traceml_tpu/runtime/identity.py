"""Runtime identity resolution
(reference: src/traceml_ai/runtime/identity.py:88-234, extended with the
TPU identity sources named in SURVEY.md §2.10: ``TPU_WORKER_ID``,
``MEGASCALE_*``, JAX process index).

Resolution precedence (first source that yields a rank wins):

1. torchrun-style env: RANK / WORLD_SIZE / LOCAL_RANK / LOCAL_WORLD_SIZE /
   GROUP_RANK|NODE_RANK
2. TPU pod env: TPU_WORKER_ID (+ TPU_WORKER_HOSTNAMES for world size)
3. MEGASCALE slice env: MEGASCALE_SLICE_ID / MEGASCALE_NUM_SLICES
4. live JAX distributed state (process_index/process_count) — only if
   jax is already imported AND initialized (never force backend init)
5. single-process defaults
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Dict, Optional

from traceml_tpu.telemetry.envelope import SenderIdentity


@dataclasses.dataclass(frozen=True)
class RuntimeIdentity:
    global_rank: int = 0
    local_rank: int = 0
    world_size: int = 1
    local_world_size: int = 1
    node_rank: int = 0
    hostname: str = dataclasses.field(default_factory=socket.gethostname)
    pid: int = dataclasses.field(default_factory=os.getpid)
    platform: str = "cpu"
    device_kind: str = "unknown"
    source: str = "defaults"

    def to_sender_identity(self, session_id: str) -> SenderIdentity:
        return SenderIdentity(
            session_id=session_id,
            global_rank=self.global_rank,
            local_rank=self.local_rank,
            world_size=self.world_size,
            local_world_size=self.local_world_size,
            node_rank=self.node_rank,
            hostname=self.hostname,
            pid=self.pid,
            platform=self.platform,
            device_kind=self.device_kind,
        )

    @property
    def is_global_primary(self) -> bool:
        return self.global_rank == 0

    @property
    def is_node_primary(self) -> bool:
        return self.local_rank == 0


def _device_info() -> Dict[str, str]:
    """platform/device_kind from live jax — only if already initialized."""
    from traceml_tpu.utils.step_memory import jax_is_initialized

    if not jax_is_initialized():
        return {}
    try:
        import jax

        devs = jax.local_devices()
        return {
            "platform": jax.default_backend(),
            "device_kind": str(devs[0].device_kind) if devs else "unknown",
        }
    except Exception:
        return {}


def resolve_runtime_identity(env: Optional[Dict[str, str]] = None) -> RuntimeIdentity:
    e = os.environ if env is None else env
    dev = _device_info()
    common = dict(
        hostname=socket.gethostname(),
        pid=os.getpid(),
        platform=dev.get("platform", "cpu"),
        device_kind=dev.get("device_kind", "unknown"),
    )

    # 1. torchrun-style env
    if "RANK" in e and "WORLD_SIZE" in e:
        try:
            rank = int(e["RANK"])
            world = int(e["WORLD_SIZE"])
            local_rank = int(e.get("LOCAL_RANK", rank))
            local_world = int(e.get("LOCAL_WORLD_SIZE", max(1, world)))
            node_rank = int(e.get("GROUP_RANK", e.get("NODE_RANK", 0)))
            return RuntimeIdentity(
                global_rank=rank,
                local_rank=local_rank,
                world_size=world,
                local_world_size=local_world,
                node_rank=node_rank,
                source="env:torchrun",
                **common,
            )
        except (ValueError, TypeError):
            pass

    # 2. TPU pod env (one process per host; local_rank 0)
    if "TPU_WORKER_ID" in e:
        try:
            worker = int(e["TPU_WORKER_ID"])
            hosts = [
                h for h in (e.get("TPU_WORKER_HOSTNAMES", "") or "").split(",") if h
            ]
            world = len(hosts) if hosts else int(e.get("TPU_WORKER_COUNT", 1) or 1)
            return RuntimeIdentity(
                global_rank=worker,
                local_rank=0,
                world_size=max(world, worker + 1),
                local_world_size=1,
                node_rank=worker,
                source="env:tpu_worker",
                **common,
            )
        except (ValueError, TypeError):
            pass

    # 3. MEGASCALE multi-slice
    if "MEGASCALE_SLICE_ID" in e:
        try:
            slice_id = int(e["MEGASCALE_SLICE_ID"])
            num_slices = int(e.get("MEGASCALE_NUM_SLICES", 1) or 1)
            return RuntimeIdentity(
                global_rank=slice_id,
                local_rank=0,
                world_size=max(num_slices, slice_id + 1),
                local_world_size=1,
                node_rank=slice_id,
                source="env:megascale",
                **common,
            )
        except (ValueError, TypeError):
            pass

    # 4. live JAX distributed state
    from traceml_tpu.utils.step_memory import jax_is_initialized

    if jax_is_initialized():
        try:
            import jax

            pi = jax.process_index()
            pc = jax.process_count()
            if pc > 1 or pi > 0:
                return RuntimeIdentity(
                    global_rank=pi,
                    local_rank=0,
                    world_size=pc,
                    local_world_size=1,
                    node_rank=pi,
                    source="jax:distributed",
                    **common,
                )
        except Exception:
            pass

    # 5. defaults
    return RuntimeIdentity(source="defaults", **common)
