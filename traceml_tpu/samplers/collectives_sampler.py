"""Collectives sampler — per-step collective-communication telemetry.

Drains the global collectives queue (fed by the fallback recorders in
instrumentation/collectives.py) plus any registered profiler trace
source, and aggregates the raw per-call records into one row per
``(step, op, dtype)``::

    {step, timestamp, op, dtype, count, bytes, group_size,
     duration_ms, exposed_ms}

``exposed_ms`` is the portion of the comm time NOT hidden behind
compute; downstream (utils/columnar.py) derives per-step overlap
efficiency ``1 − exposed/total`` from these sums.  Aggregating here
bounds row cardinality at (ops × dtypes) per step instead of one row
per collective call — at 8 collectives/step × 120 steps the wire cost
stays flat regardless of microbatch fan-out.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

from traceml_tpu.instrumentation.collectives import (
    GLOBAL_COLLECTIVES_QUEUE,
    drain_trace_sources,
    extract_collectives_from_trace_events,
)
from traceml_tpu.samplers.base_sampler import BaseSampler

TABLE = "collectives"


def aggregate_collective_records(
    records: List[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Fold raw per-call records into per-(step, op, dtype) rows.

    Deterministic output order (step, op, dtype) so the producer-side
    columnar accumulator sees stable shapes and goldens are exact.
    """
    slots: Dict[Tuple[int, str, str], Dict[str, Any]] = {}
    for rec in records:
        try:
            key = (int(rec["step"]), str(rec["op"]), str(rec.get("dtype", "")))
        except (KeyError, TypeError, ValueError):
            continue
        slot = slots.get(key)
        if slot is None:
            slot = slots[key] = {
                "step": key[0],
                "op": key[1],
                "dtype": key[2],
                "count": 0,
                "bytes": 0,
                "group_size": 1,
                "duration_ms": 0.0,
                "exposed_ms": 0.0,
            }
        slot["count"] += 1
        slot["bytes"] += int(rec.get("bytes", 0) or 0)
        slot["group_size"] = max(
            slot["group_size"], int(rec.get("group_size", 1) or 1)
        )
        slot["duration_ms"] += float(rec.get("duration_ms", 0.0) or 0.0)
        slot["exposed_ms"] += float(rec.get("exposed_ms", 0.0) or 0.0)
    return [slots[k] for k in sorted(slots)]


class CollectivesSampler(BaseSampler):
    name = "collectives"

    def __init__(self, *args: Any, **kw: Any):
        super().__init__(*args, **kw)
        self.rows_emitted = 0

    def _collect(self) -> List[Dict[str, Any]]:
        records = GLOBAL_COLLECTIVES_QUEUE.drain()
        trace_events = drain_trace_sources()
        if trace_events:
            records.extend(extract_collectives_from_trace_events(trace_events))
        return records

    def _sample(self) -> None:
        records = self._collect()
        if not records:
            return
        now = time.time()
        for row in aggregate_collective_records(records):
            row["timestamp"] = now
            self.db.add_record(TABLE, row)
            self.rows_emitted += 1

    def drain(self) -> None:
        """End-of-run: flush whatever is still queued."""
        self._sample()
