"""Domain-wiring contract checker (rules ``TLW001``/``TLW002``/``TLW000``).

A telemetry domain is not "done" when its sampler lands — rounds 9–14
established a hard cross-file contract: sampler → v2 wire → watermark-
retained SQLite writer → snapshot-store cursor/version → columnar ring →
renderer fragment → diagnostics package → DIAGNOSIS.md entry.  This pass
parses each layer's registry *as source* (AST / markdown, zero imports)
and reports any domain present in one layer but missing from another.

Layers parsed:

========== ===========================================================
sampler     ``SamplerSpec("<key>", …)`` calls in
            ``runtime/sampler_registry.py`` (+ the explicitly wired
            ``stdout_stderr`` sampler)
writer      module names in ``ALL_WRITERS`` of
            ``aggregator/sqlite_writers/__init__.py`` (``_writer``
            suffix stripped)
store       the ``DOMAINS`` tuple in ``reporting/snapshot_store.py``
ring        ``class <Name>Columns`` definitions in ``utils/columnar.py``
fragment    ``_FRAGMENT_KEYS`` dict keys in ``renderers/web_payload.py``
diag_pkg    subdirectories of ``diagnostics/``
diag_vector ``diagnostics/`` subdirectories carrying a ``vector.py``
            gate module (the r20 vectorized rule arm — every
            windowed diagnosis pack must ship one)
diagnosis   ``## <Title>`` headings in ``diagnostics/DIAGNOSIS.md``
========== ===========================================================

The expected shape lives in :data:`CONTRACT` — every canonical domain
names the layers it must appear in.  Adding a domain to any layer
without declaring it here is ``TLW001``; declaring it but missing a
required layer is ``TLW002``.  The contract is code on purpose: the
diff that adds a domain must also state, reviewably, how far it is
wired.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from traceml_tpu.analysis.common import Finding, SEVERITY_ERROR

RULE_LAYER_UNPARSEABLE = "TLW000"
RULE_UNDECLARED_DOMAIN = "TLW001"
RULE_MISSING_LAYER = "TLW002"

LAYERS = (
    "sampler", "writer", "store", "ring", "fragment", "diag_pkg",
    "diag_vector", "diagnosis",
)

#: canonical domain → layers it must be wired through.  ``topology``
#: ships as a control message (no sampler) and rides the payload meta
#: fragment (no fragment key of its own); ``stdout`` has no ring or
#: diagnosis; ``model_stats`` is a store-side join fed by control
#: messages; ``liveness`` is aggregator-side only (rank_status.json →
#: diagnostics), with no sampler/writer/ring/fragment.
CONTRACT: Dict[str, Set[str]] = {
    "step_time": {
        "sampler", "writer", "store", "ring", "fragment", "diag_pkg",
        "diag_vector", "diagnosis",
    },
    "step_memory": {
        "sampler", "writer", "store", "ring", "fragment", "diag_pkg",
        "diag_vector", "diagnosis",
    },
    "collectives": {
        "sampler", "writer", "store", "ring", "fragment", "diag_pkg",
        "diag_vector", "diagnosis",
    },
    "serving": {
        "sampler", "writer", "store", "ring", "fragment", "diag_pkg",
        "diag_vector", "diagnosis",
    },
    "system": {"sampler", "writer", "store", "fragment", "diag_pkg",
               "diagnosis"},
    "process": {"sampler", "writer", "store", "fragment", "diag_pkg",
                "diagnosis"},
    "stdout": {"sampler", "writer", "store", "fragment"},
    "topology": {"writer", "store"},
    "model_stats": {"store"},
    "liveness": {"diag_pkg", "diagnosis"},
    # rollup tiers have no sampler/writer/ring of their own: folds are a
    # side effect of the retention prune (aggregator/rollup.py inside
    # sqlite_writer._prune_partition); the store serves stitched reads
    # and the payload surfaces them as the ``history`` fragment
    "rollup": {"store", "fragment"},
}

#: per-layer translation of layer-local names to canonical domains
ALIASES: Dict[str, Dict[str, str]] = {
    "sampler": {"stdout_stderr": "stdout"},
    "writer": {"mesh_topology": "topology"},
    # RaggedEventColumns is the serving domain's ring: CSR-style ragged
    # per-request latency lists riding the same compacting ring engine
    "ring": {"memory": "step_memory", "ragged_event": "serving"},
    "fragment": {"memory": "step_memory", "history": "rollup"},
}

#: layer names that are infrastructure, not domains
IGNORED: Dict[str, Set[str]] = {
    "fragment": {"header", "meta", "diagnosis"},
    "diag_pkg": {"__pycache__"},
    "diag_vector": {"__pycache__"},
    "diagnosis": set(),
}

#: layer → file parsed (relative to the package root)
LAYER_FILES: Dict[str, str] = {
    "sampler": "runtime/sampler_registry.py",
    "writer": "aggregator/sqlite_writers/__init__.py",
    "store": "reporting/snapshot_store.py",
    "ring": "utils/columnar.py",
    "fragment": "renderers/web_payload.py",
    "diag_pkg": "diagnostics",
    "diag_vector": "diagnostics",
    "diagnosis": "diagnostics/DIAGNOSIS.md",
}


def _parse(path: Path) -> Optional[ast.Module]:
    try:
        return ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    except (OSError, SyntaxError):
        return None


def _parse_sampler_layer(path: Path) -> Optional[Set[str]]:
    tree = _parse(path)
    if tree is None:
        return None
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "SamplerSpec"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            keys.add(node.args[0].value)
    return keys or None


def _parse_writer_layer(path: Path) -> Optional[Set[str]]:
    tree = _parse(path)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "ALL_WRITERS" in names and isinstance(
                node.value, (ast.List, ast.Tuple)
            ):
                out = set()
                for elt in node.value.elts:
                    if isinstance(elt, ast.Name):
                        out.add(re.sub(r"_writer$", "", elt.id))
                    elif isinstance(elt, ast.Attribute):
                        out.add(re.sub(r"_writer$", "", elt.attr))
                return out or None
    return None


def _parse_store_layer(path: Path) -> Optional[Set[str]]:
    tree = _parse(path)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "DOMAINS" in names and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                out = {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
                return out or None
    return None


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


def _parse_ring_layer(path: Path) -> Optional[Set[str]]:
    tree = _parse(path)
    if tree is None:
        return None
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name.endswith("Columns"):
            out.add(_snake(node.name[: -len("Columns")]))
    return out or None


def _parse_fragment_layer(path: Path) -> Optional[Set[str]]:
    tree = _parse(path)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if "_FRAGMENT_KEYS" in names and isinstance(
                node.value, ast.Dict
            ):
                out = {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
                return out or None
    return None


def _parse_diag_pkg_layer(path: Path) -> Optional[Set[str]]:
    if not path.is_dir():
        return None
    return {
        p.name
        for p in path.iterdir()
        if p.is_dir() and (p / "__init__.py").exists()
    } or None


def _parse_diag_vector_layer(path: Path) -> Optional[Set[str]]:
    """Diagnosis packs shipping a vectorized gate arm (``vector.py``)."""
    if not path.is_dir():
        return None
    return {
        p.name
        for p in path.iterdir()
        if p.is_dir() and (p / "vector.py").exists()
    } or None


#: DIAGNOSIS.md section title → canonical domain
_DIAGNOSIS_TITLES = {
    "step time": "step_time",
    "step memory": "step_memory",
    "collectives": "collectives",
    "system": "system",
    "process": "process",
    "liveness": "liveness",
}


def _parse_diagnosis_layer(path: Path) -> Optional[Set[str]]:
    try:
        text = path.read_text(encoding="utf-8")
    except OSError:
        return None
    out: Set[str] = set()
    for m in re.finditer(r"^##\s+([^(\n]+)", text, re.M):
        title = m.group(1).strip().lower()
        domain = _DIAGNOSIS_TITLES.get(title)
        if domain is None:
            # unknown headings ("Run-level promotion", …) are prose, but
            # a heading that snake-cases onto a contract domain counts
            slug = re.sub(r"\W+", "_", title).strip("_")
            domain = slug if slug in CONTRACT else None
        if domain is not None:
            out.add(domain)
    return out or None


_PARSERS = {
    "sampler": _parse_sampler_layer,
    "writer": _parse_writer_layer,
    "store": _parse_store_layer,
    "ring": _parse_ring_layer,
    "fragment": _parse_fragment_layer,
    "diag_pkg": _parse_diag_pkg_layer,
    "diag_vector": _parse_diag_vector_layer,
    "diagnosis": _parse_diagnosis_layer,
}


def run_wiring_pass(
    package_root: Path,
    contract: Optional[Dict[str, Set[str]]] = None,
    layer_files: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Check every layer registry against :data:`CONTRACT` (overridable
    for fixture trees in tests)."""
    contract = CONTRACT if contract is None else contract
    layer_files = LAYER_FILES if layer_files is None else layer_files
    pkg_rel = package_root.name
    findings: List[Finding] = []
    parsed: Dict[str, Tuple[str, Set[str]]] = {}

    for layer, rel in layer_files.items():
        path = package_root / rel
        rel_repo = f"{pkg_rel}/{rel}"
        result = _PARSERS[layer](path)
        if result is None:
            findings.append(
                Finding(
                    rule=RULE_LAYER_UNPARSEABLE,
                    severity=SEVERITY_ERROR,
                    path=rel_repo,
                    line=1,
                    message=(
                        f"wiring layer '{layer}' could not be parsed from "
                        f"{rel} (file missing, syntax error, or registry "
                        f"structure changed — update analysis/wiring_pass.py)"
                    ),
                    key=f"{RULE_LAYER_UNPARSEABLE}:{rel_repo}:{layer}",
                )
            )
            continue
        aliases = ALIASES.get(layer, {})
        ignored = IGNORED.get(layer, set())
        canonical = {
            aliases.get(name, name)
            for name in result
            if name not in ignored
        }
        parsed[layer] = (rel_repo, canonical)

    # TLW001: a layer carries a domain the contract has never heard of
    for layer, (rel_repo, domains) in sorted(parsed.items()):
        for d in sorted(domains - set(contract)):
            findings.append(
                Finding(
                    rule=RULE_UNDECLARED_DOMAIN,
                    severity=SEVERITY_ERROR,
                    path=rel_repo,
                    line=1,
                    message=(
                        f"domain '{d}' appears in the {layer} layer but is "
                        f"not declared in the wiring contract "
                        f"(analysis/wiring_pass.py CONTRACT) — declare it "
                        f"and wire the remaining layers"
                    ),
                    key=f"{RULE_UNDECLARED_DOMAIN}:{layer}:{d}",
                )
            )

    # TLW002: the contract requires a layer the domain is missing from
    for domain, required in sorted(contract.items()):
        for layer in sorted(required):
            if layer not in parsed:
                continue  # TLW000 already reported
            rel_repo, domains = parsed[layer]
            if domain not in domains:
                findings.append(
                    Finding(
                        rule=RULE_MISSING_LAYER,
                        severity=SEVERITY_ERROR,
                        path=rel_repo,
                        line=1,
                        message=(
                            f"domain '{domain}' is declared in the wiring "
                            f"contract but missing from the {layer} layer "
                            f"({LAYER_FILES.get(layer, layer)})"
                        ),
                        key=f"{RULE_MISSING_LAYER}:{layer}:{domain}",
                    )
                )
    return findings
