"""Async SQLite writer
(reference: src/traceml_ai/aggregator/sqlite_writer.py:112-647).

One dedicated writer thread owns the connection (sqlite is
single-writer anyway): bounded ingest queue (50k), per-batch
transactions, WAL + ``synchronous=NORMAL``, periodic per-rank retention
pruning to ``1.5×summary_window_rows`` via ``ROW_NUMBER() OVER
(PARTITION BY ...)``, flush barriers for read-your-writes, and
``finalize()`` = drain → prune → ``wal_checkpoint(TRUNCATE)`` → close.
"""

from __future__ import annotations

import queue
import sqlite3
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from traceml_tpu.aggregator.sqlite_writers import ALL_WRITERS, writer_for
from traceml_tpu.telemetry.envelope import TelemetryEnvelope
from traceml_tpu.utils.error_log import get_error_log

_QUEUE_MAX = 50_000
_PRUNE_EVERY_BATCHES = 50


class _FlushBarrier:
    __slots__ = ("event",)

    def __init__(self) -> None:
        self.event = threading.Event()


class SQLiteWriter:
    def __init__(
        self,
        db_path: Path,
        summary_window_rows: int = 10_000,
        retention_factor: float = 1.5,
    ) -> None:
        self.db_path = Path(db_path)
        self._retention_rows = int(summary_window_rows * retention_factor)
        self._queue: "queue.Queue[object]" = queue.Queue(maxsize=_QUEUE_MAX)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._finalized = threading.Event()
        self.enqueued = 0
        self.dropped = 0
        self.written = 0
        self._batches = 0

    # -- producer side (aggregator loop) --------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self.db_path.parent.mkdir(parents=True, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="traceml-sqlite-writer", daemon=True
        )
        self._thread.start()

    def ingest(self, env: TelemetryEnvelope) -> bool:
        try:
            self._queue.put_nowait(env)
            self.enqueued += 1
            return True
        except queue.Full:
            self.dropped += 1
            return False

    def force_flush(self, timeout: float = 10.0) -> bool:
        """Barrier: returns once everything enqueued so far is committed
        (reference: sqlite_writer.py:168)."""
        if self._thread is None or self._finalized.is_set():
            return False
        barrier = _FlushBarrier()
        try:
            self._queue.put(barrier, timeout=timeout)
        except queue.Full:
            return False
        return barrier.event.wait(timeout)

    def finalize(self, timeout: float = 30.0) -> bool:
        """Drain, prune, checkpoint, close (reference: 206-272, 554-622)."""
        if self._thread is None:
            return True
        ok = self.force_flush(timeout)
        self._stop_evt.set()
        try:
            self._queue.put_nowait(None)  # wake
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)
        alive = self._thread.is_alive()
        self._thread = None
        return ok and not alive

    # -- writer thread ---------------------------------------------------
    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(str(self.db_path))
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        for w in ALL_WRITERS:
            w.init_schema(conn)
        conn.commit()
        return conn

    def _run(self) -> None:
        try:
            conn = self._connect()
        except Exception as exc:
            get_error_log().error("sqlite writer failed to open db", exc)
            self._finalized.set()
            return
        try:
            while True:
                batch: List[TelemetryEnvelope] = []
                barriers: List[_FlushBarrier] = []
                try:
                    item = self._queue.get(timeout=0.25)
                except queue.Empty:
                    if self._stop_evt.is_set():
                        break
                    continue
                # greedily drain available items into one transaction
                while item is not None or not self._queue.empty():
                    if item is None:
                        pass
                    elif isinstance(item, _FlushBarrier):
                        barriers.append(item)
                    else:
                        batch.append(item)
                    try:
                        item = self._queue.get_nowait()
                    except queue.Empty:
                        item = None
                        break
                if batch:
                    self._write_batch(conn, batch)
                for b in barriers:
                    b.event.set()
                if self._stop_evt.is_set() and self._queue.empty():
                    break
            self._prune(conn)
            try:
                conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                conn.commit()
            except sqlite3.Error:
                pass
        except Exception as exc:  # pragma: no cover
            get_error_log().error("sqlite writer thread crashed", exc)
        finally:
            try:
                conn.close()
            except Exception:
                pass
            self._finalized.set()

    def _write_batch(self, conn: sqlite3.Connection, batch: List[TelemetryEnvelope]) -> None:
        # Build parameter tuples for the WHOLE batch first, grouped by
        # insert statement, so each (table, batch) costs exactly one
        # executemany inside one transaction — never per-row, and never
        # per-envelope when many ranks ship the same table.
        grouped: Dict[str, List[tuple]] = {}
        for env in batch:
            writer = writer_for(env.sampler)
            if writer is None:
                continue
            try:
                table_rows = writer.build_rows(env)
            except Exception as exc:
                get_error_log().warning(
                    f"projection build failed for {env.sampler}", exc
                )
                continue
            for table, rows in table_rows.items():
                if rows:
                    grouped.setdefault(writer.insert_sql(table), []).extend(rows)
        try:
            conn.execute("BEGIN")
            for sql, rows in grouped.items():
                conn.executemany(sql, rows)
                self.written += len(rows)
            conn.commit()
        except sqlite3.Error as exc:
            get_error_log().warning("sqlite batch write failed", exc)
            try:
                conn.rollback()
            except sqlite3.Error:
                pass
        self._batches += 1
        if self._batches % _PRUNE_EVERY_BATCHES == 0:
            self._prune(conn)

    def _prune(self, conn: sqlite3.Connection) -> None:
        """Keep the newest ``retention`` rows per (session, rank) per table
        (reference: sqlite_writer.py:416-509)."""
        for w in ALL_WRITERS:
            for table in getattr(w, "RETENTION_TABLES", ()):
                try:
                    conn.execute(
                        f"""DELETE FROM {table} WHERE id IN (
                            SELECT id FROM (
                                SELECT id, ROW_NUMBER() OVER (
                                    PARTITION BY session_id, global_rank
                                    ORDER BY id DESC
                                ) AS rn FROM {table}
                            ) WHERE rn > ?
                        )""",
                        (self._retention_rows,),
                    )
                    conn.commit()
                except sqlite3.Error as exc:
                    get_error_log().warning(f"prune failed for {table}", exc)
