"""Direct unit coverage for two load-bearing-but-indirectly-tested
modules: the declarative sampler registry (profiles/rank gating) and
the final-summary request service (request → settle → generate →
response → clear)."""

from traceml_tpu.aggregator.summary_service import FinalSummaryService
from traceml_tpu.runtime.identity import RuntimeIdentity
from traceml_tpu.runtime.sampler_registry import (
    SAMPLER_REGISTRY,
    build_samplers,
    register_default_samplers,
)
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.sdk import protocol


def _settings(tmp_path, mode="summary"):
    return TraceMLSettings(session_id="s", logs_dir=tmp_path, mode=mode)


def test_default_registry_contents():
    register_default_samplers()
    for key in ("system", "process", "step_time", "step_memory"):
        assert key in SAMPLER_REGISTRY
    assert SAMPLER_REGISTRY.get("system").node_primary_only
    assert SAMPLER_REGISTRY.get("step_time").drain_on_recording_stop


def test_node_primary_only_gating(tmp_path):
    primary = build_samplers(
        _settings(tmp_path), RuntimeIdentity(global_rank=0, local_rank=0)
    )
    secondary = build_samplers(
        _settings(tmp_path), RuntimeIdentity(global_rank=1, local_rank=1)
    )
    names_primary = {s.name for s in primary}
    names_secondary = {s.name for s in secondary}
    assert "system" in names_primary      # node-primary samples the host
    assert "system" not in names_secondary  # other local ranks don't
    for key in ("process", "step_time", "step_memory"):
        assert key in names_primary and key in names_secondary
    for s in primary + secondary:
        s.stop()


def test_summary_service_serves_request(tmp_path):
    settings = _settings(tmp_path)
    settings.session_dir.mkdir(parents=True, exist_ok=True)
    settled, generated = [], []
    svc = FinalSummaryService(
        settings,
        generate=lambda: generated.append(1) or True,
        settle=lambda: settled.append(1),
        poll_interval=0.0,
    )
    svc.poll()  # no request yet
    assert not generated
    protocol.write_summary_request(settings.session_dir, requester_rank=0)
    svc.poll()
    assert settled and generated
    assert svc.requests_served == 1
    resp = protocol.read_summary_response(settings.session_dir)
    assert resp and resp["ok"] is True
    # request cleared → no double-serve
    svc.poll()
    assert svc.requests_served == 1


def test_summary_service_failure_writes_error(tmp_path):
    settings = _settings(tmp_path)
    settings.session_dir.mkdir(parents=True, exist_ok=True)

    def boom():
        raise RuntimeError("db corrupt")

    svc = FinalSummaryService(settings, generate=boom, poll_interval=0.0)
    protocol.write_summary_request(settings.session_dir, requester_rank=0)
    svc.poll()  # must not raise
    resp = protocol.read_summary_response(settings.session_dir)
    assert resp and resp["ok"] is False
    assert "db corrupt" in resp.get("error", "")
