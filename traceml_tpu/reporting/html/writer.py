"""Dependency-free self-contained HTML summary
(reference: src/traceml_ai/reporting/html/ — no JS frameworks, inline
SVG charts, one file that opens anywhere).
"""

from __future__ import annotations

import html
from pathlib import Path
from typing import Any, Dict, List

from traceml_tpu.utils.atomic_io import atomic_write_text
from traceml_tpu.utils.formatting import fmt_bytes, fmt_ms

_SEV_COLOR = {"critical": "#c0392b", "warning": "#e67e22", "info": "#2d7dd2"}

_CSS = """
body{font-family:system-ui,-apple-system,sans-serif;margin:2rem auto;
     max-width:960px;color:#1a1a2e;background:#fafafa;padding:0 1rem}
h1{font-size:1.4rem} h2{font-size:1.1rem;margin-top:2rem;
   border-bottom:1px solid #ddd;padding-bottom:.3rem}
.verdict{border-radius:8px;padding:1rem 1.25rem;color:#fff;margin:1rem 0}
.verdict small{opacity:.85}
table{border-collapse:collapse;width:100%;font-size:.9rem}
th,td{text-align:left;padding:.35rem .6rem;border-bottom:1px solid #eee}
th{background:#f0f0f5;font-weight:600}
.bar{height:18px;border-radius:3px;display:inline-block;vertical-align:middle}
.muted{color:#777;font-size:.85rem}
code{background:#eee;padding:.05rem .3rem;border-radius:3px}
"""

_PHASE_COLORS = {
    "input": "#e74c3c",
    "h2d": "#e67e22",
    "forward": "#2d7dd2",
    "backward": "#2255a4",
    "optimizer": "#7d3dd2",
    "compute": "#2d7dd2",
    "compile": "#f1c40f",
    "collective": "#16a085",
    "checkpoint": "#8e5a2b",
    "residual": "#95a5a6",
}


def _esc(x: Any) -> str:
    return html.escape(str(x))


def _phase_bar(phases: Dict[str, Any]) -> str:
    """One stacked horizontal share bar (inline SVG-ish via divs)."""
    parts: List[str] = []
    total = 0.0
    for key, info in phases.items():
        if key == "step_time":
            continue
        share = info.get("share_of_step")
        if not share or share <= 0:
            continue
        share = min(share, 1.0 - total)
        total += share
        color = _PHASE_COLORS.get(key, "#888")
        parts.append(
            f'<span class="bar" title="{_esc(key)}: {share * 100:.1f}%" '
            f'style="width:{share * 100:.2f}%;background:{color}"></span>'
        )
    legend = " ".join(
        f'<span class="muted"><span class="bar" style="width:10px;'
        f'background:{_PHASE_COLORS.get(k, "#888")}"></span> {_esc(k)}</span>'
        for k in phases
        if k != "step_time"
    )
    return (
        f'<div style="width:100%;background:#eee;border-radius:3px">{"".join(parts)}</div>'
        f"<div>{legend}</div>"
    )


def _step_series_svg(series: Dict[str, Any], width: int = 900, height: int = 120) -> str:
    """Inline SVG polylines: one line per rank, shared scale."""
    all_vals = [v for vs in series.values() for v in vs if v is not None]
    if not all_vals:
        return ""
    vmax = max(all_vals) or 1.0
    lines = []
    hues = [210, 0, 120, 280, 30, 170, 330, 60]
    for i, (rank, vs) in enumerate(sorted(series.items(), key=lambda kv: int(kv[0]))):
        if not vs:
            continue
        n = len(vs)
        pts = " ".join(
            f"{(j / max(1, n - 1)) * width:.1f},"
            f"{height - 4 - (v / vmax) * (height - 10):.1f}"
            for j, v in enumerate(vs)
        )
        hue = hues[i % len(hues)]
        lines.append(
            f'<polyline fill="none" stroke="hsl({hue},65%,45%)" '
            f'stroke-width="1.2" points="{pts}"><title>rank {_esc(rank)}'
            f"</title></polyline>"
        )
    legend = " ".join(
        f'<tspan fill="hsl({hues[i % len(hues)]},65%,45%)">rank {_esc(r)}</tspan>'
        for i, r in enumerate(sorted(series, key=int))
    )
    return (
        f'<svg viewBox="0 0 {width} {height}" '
        f'style="width:100%;height:{height}px;background:#f4f4f8;'
        f'border-radius:6px">{"".join(lines)}'
        f'<text x="6" y="14" font-size="11">{legend} · max {vmax:.1f} ms</text>'
        f"</svg>"
    )


def render_html_summary(payload: Dict[str, Any]) -> str:
    meta = payload.get("meta") or {}
    primary = payload.get("primary_diagnosis") or {}
    color = _SEV_COLOR.get(primary.get("severity", "info"), "#2d7dd2")
    out = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>TraceML-TPU — {_esc(meta.get('session_id', 'summary'))}</title>",
        f"<style>{_CSS}</style></head><body>",
        "<h1>TraceML-TPU — final training summary</h1>",
        f"<p class='muted'>session <code>{_esc(meta.get('session_id'))}</code>"
        f" · mode {_esc((meta.get('topology') or {}).get('mode'))}"
        f" · world size {_esc((meta.get('topology') or {}).get('world_size'))}</p>",
        f"<div class='verdict' style='background:{color}'>"
        f"<strong>{_esc(primary.get('kind'))}</strong>"
        f" <small>[{_esc(primary.get('severity'))}]</small><br>"
        f"{_esc(primary.get('summary', ''))}"
        + (
            f"<br><small>→ {_esc(primary.get('action'))}</small>"
            if primary.get("action")
            else ""
        )
        + "</div>",
    ]

    st = (payload.get("sections") or {}).get("step_time") or {}
    g = st.get("global") or {}
    phases = g.get("phases") or {}
    series = g.get("step_series_ms") or {}
    if series:
        out.append("<h2>Step time per step</h2>")
        out.append(_step_series_svg(series))
    if phases:
        out.append("<h2>Step time</h2>")
        sub = (
            f"{_esc(g.get('n_steps'))} steps, {_esc(g.get('clock'))} clock"
        )
        occ = g.get("median_occupancy")
        if occ is not None:
            sub += f", chip busy {occ * 100:.0f}%"
        steady = g.get("steady_state") or {}
        if steady.get("median_ms") is not None:
            sub += f" · steady-state median {fmt_ms(steady['median_ms'])}"
            infl = steady.get("warmup_inflation_pct")
            if infl is not None and infl > 0.02:
                sub += f" (warmup inflated {infl * 100:.0f}%)"
        out.append(f"<p class='muted'>{sub}</p>")
        out.append(_phase_bar(phases))
        out.append(
            "<table><tr><th>phase</th><th>median</th><th>share</th>"
            "<th>worst rank</th><th>skew</th></tr>"
        )
        for key, info in phases.items():
            share = info.get("share_of_step")
            out.append(
                f"<tr><td>{_esc(key)}</td><td>{fmt_ms(info.get('median_ms'))}</td>"
                f"<td>{'' if share is None else f'{share * 100:.1f}%'}</td>"
                f"<td>{_esc(info.get('worst_rank'))}</td>"
                f"<td>{(info.get('skew_pct') or 0) * 100:.1f}%</td></tr>"
            )
        out.append("</table>")

    # per-rank phase matrix (small worlds)
    rank_cards = g.get("per_rank") or {}
    if 1 < len(rank_cards) <= 8 and phases:
        phase_keys = [k for k in phases if k != "step_time"]
        show_host = any(
            (c.get("identity") or {}).get("hostname") for c in rank_cards.values()
        )
        out.append("<h2>Per-rank breakdown (window avg, ms)</h2><table><tr>"
                   "<th>rank</th>" + ("<th>host</th>" if show_host else "")
                   + "<th>step</th>"
                   + "".join(f"<th>{_esc(k)}</th>" for k in phase_keys)
                   + "<th>busy</th></tr>")
        for rank, card in sorted(rank_cards.items(), key=lambda kv: int(kv[0])):
            avgs = card.get("avg_ms") or {}
            occ_r = card.get("occupancy")
            ident = card.get("identity") or {}
            if show_host:
                host_cell = (
                    f"<td>{_esc(ident.get('hostname'))}"
                    f"#{_esc(ident.get('node_rank'))}</td>"
                    if ident.get("hostname")
                    else "<td></td>"
                )
            else:
                host_cell = ""
            out.append(
                f"<tr><td>{_esc(rank)}</td>" + host_cell
                + f"<td>{avgs.get('step_time', 0):.1f}</td>"
                + "".join(f"<td>{avgs.get(k, 0):.1f}</td>" for k in phase_keys)
                + f"<td>{'' if occ_r is None else f'{occ_r * 100:.0f}%'}</td></tr>"
            )
        out.append("</table>")

    sm = (payload.get("sections") or {}).get("step_memory") or {}
    per_rank = (sm.get("global") or {}).get("per_rank") or {}
    if per_rank:
        out.append("<h2>Device memory</h2><table><tr><th>rank</th>"
                   "<th>current</th><th>peak</th><th>limit</th>"
                   "<th>pressure</th><th>growth</th></tr>")
        for rank, info in sorted(per_rank.items(), key=lambda kv: int(kv[0])):
            pressure = info.get("pressure")
            growth = info.get("growth_bytes")
            out.append(
                f"<tr><td>{_esc(rank)}</td>"
                f"<td>{fmt_bytes(info.get('current_bytes'))}</td>"
                f"<td>{fmt_bytes(info.get('step_peak_bytes'))}</td>"
                f"<td>{fmt_bytes(info.get('limit_bytes'))}</td>"
                f"<td>{'' if pressure is None else f'{pressure * 100:.0f}%'}</td>"
                f"<td>{'' if not growth else ('+' if growth > 0 else '') + fmt_bytes(growth)}</td>"
                f"</tr>"
            )
        out.append("</table>")
        rollup = (sm.get("global") or {}).get("rollup") or {}
        if rollup:
            out.append(
                f"<p class='muted'>total {fmt_bytes(rollup.get('total_current_bytes'))}"
                f" · max peak {fmt_bytes(rollup.get('max_peak_bytes'))}</p>"
            )

    sysg = ((payload.get("sections") or {}).get("system") or {}).get("global") or {}
    nodes = sysg.get("nodes") or {}
    if nodes:
        out.append("<h2>System</h2><table><tr><th>node</th><th>cpu mean/max</th>"
                   "<th>host mem</th><th>load</th></tr>")
        def _node_key(kv):
            try:
                return (0, int(kv[0]))
            except (TypeError, ValueError):
                return (1, kv[0])

        for node, info in sorted(nodes.items(), key=_node_key):
            cpu_m, cpu_x = info.get("cpu_pct_mean"), info.get("cpu_pct_max")
            load = info.get("load_1m")
            out.append(
                f"<tr><td>{_esc(info.get('hostname'))} (#{_esc(node)})</td>"
                f"<td>{'' if cpu_m is None else f'{cpu_m:.0f}%'}/"
                f"{'' if cpu_x is None else f'{cpu_x:.0f}%'}</td>"
                f"<td>{fmt_bytes(info.get('memory_used_bytes'))} / "
                f"{fmt_bytes(info.get('memory_total_bytes'))}</td>"
                f"<td>{'—' if load is None else _esc(load)}</td></tr>"
            )
        out.append("</table>")
        cluster = sysg.get("cluster")
        if cluster:
            out.append(
                f"<p class='muted'>cluster: {cluster['n_nodes']} nodes · host "
                f"CPU {cluster['cpu_pct_min']:.0f}/"
                f"{cluster['cpu_pct_median']:.0f}/{cluster['cpu_pct_max']:.0f}% "
                f"(min/median/max, busiest {_esc(cluster.get('busiest_node'))})</p>"
            )

    procg = ((payload.get("sections") or {}).get("process") or {}).get("global") or {}
    pranks = procg.get("per_rank") or {}
    if pranks:
        out.append("<h2>Processes</h2><table><tr><th>rank</th><th>pid</th>"
                   "<th>cpu mean/max</th><th>rss / peak</th><th>threads</th></tr>")
        for rank, info in sorted(pranks.items(), key=lambda kv: int(kv[0])):
            cpu_m, cpu_x = info.get("cpu_pct_mean"), info.get("cpu_pct_max")
            out.append(
                f"<tr><td>{_esc(rank)}</td><td>{_esc(info.get('pid') or '—')}</td>"
                f"<td>{'' if cpu_m is None else f'{cpu_m:.0f}%'}/"
                f"{'' if cpu_x is None else f'{cpu_x:.0f}%'}</td>"
                f"<td>{fmt_bytes(info.get('rss_bytes'))} / "
                f"{fmt_bytes(info.get('rss_peak_bytes'))}</td>"
                f"<td>{_esc(info.get('num_threads') or '—')}</td></tr>"
            )
        out.append("</table>")

    out.append("<h2>All findings</h2><table><tr><th>domain</th><th>kind</th>"
               "<th>severity</th><th>summary</th></tr>")
    for key, sec in (payload.get("sections") or {}).items():
        for issue in sec.get("issues") or []:
            out.append(
                f"<tr><td>{_esc(key)}</td><td>{_esc(issue.get('kind'))}</td>"
                f"<td style='color:{_SEV_COLOR.get(issue.get('severity'), '#333')}'>"
                f"{_esc(issue.get('severity'))}</td>"
                f"<td>{_esc(issue.get('summary'))}</td></tr>"
            )
    out.append("</table>")
    stats = meta.get("telemetry_stats") or {}
    if stats:
        out.append(
            "<p class='muted'>telemetry: "
            + " · ".join(f"{_esc(k)} {_esc(v)}" for k, v in stats.items())
            + "</p>"
        )
    out.append("</body></html>")
    return "".join(out)


def write_html_summary(payload: Dict[str, Any], path: Path) -> None:
    atomic_write_text(path, render_html_summary(payload))
