"""Chaos E2E: deterministic fault injection (``TRACEML_FAULT_PLAN``,
dev/chaos.py) through the REAL pipeline — launcher, rank executors,
aggregator over TCP.

The two pillars of the fault-tolerance contract
(docs/developer_guide/fault-tolerance.md):

* aggregator SIGKILL mid-run → supervised restart on the pinned port,
  rank-side spool replay, writer-side seq dedup: the final DB holds the
  SAME per-rank step coverage as a fault-free run — no silent loss, no
  duplicates.
* rank SIGKILL mid-run → the world notices: RANK_LOST verdict in the
  final report's liveness section, a data-gap annotation, and the
  settle-end warning naming the never-finished rank.
"""

import json
import os
import sqlite3
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

# paced training loop: slow enough that an early-run kill leaves a long
# post-restart tail (the replay + live-resume window the test is about)
SCRIPT = """
import time
import numpy as np
import jax, jax.numpy as jnp
import traceml_tpu

def step_fn(w, x):
    return w - 0.01 * jax.grad(lambda w, x: jnp.sum((x @ w) ** 2))(w, x)

step = traceml_tpu.wrap_step_fn(step_fn)
w = jnp.ones((16, 16))
rng = np.random.default_rng(0)
for i in range({steps}):
    with traceml_tpu.trace_step():
        x = jax.device_put(rng.normal(size=(4, 16)).astype(np.float32))
        w = step(w, x)
    time.sleep(0.04)
print("training finished fine")
"""


def _run(tmp_path, name, steps, nprocs=2, extra_env=None, check=True,
         finalize_timeout=45):
    script = tmp_path / f"{name}.py"
    script.write_text(SCRIPT.format(steps=steps))
    logs = tmp_path / f"logs_{name}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TRACEML_FAULT_PLAN", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    env.update(extra_env or {})
    proc = subprocess.run(
        [
            sys.executable, "-m", "traceml_tpu", "run",
            "--mode", "summary", "--logs-dir", str(logs),
            "--run-name", name, "--sampler-interval", "0.25",
            "--finalize-timeout", str(finalize_timeout),
            "--nprocs", str(nprocs), str(script),
        ],
        env=env, capture_output=True, text=True, timeout=300, cwd=str(tmp_path),
    )
    if check:
        assert proc.returncode == 0, proc.stderr[-3000:] + proc.stdout[-2000:]
    session = next(p for p in logs.iterdir() if p.is_dir())
    return session, proc


def _step_coverage(session):
    """{(rank, step), ...} plus the raw row count (rows > |set| means
    a replayed envelope double-inserted — the dedup failed)."""
    conn = sqlite3.connect(session / "telemetry.sqlite")
    try:
        rows = conn.execute(
            "SELECT global_rank, step FROM step_time_samples"
        ).fetchall()
    finally:
        conn.close()
    return {(r, s) for r, s in rows}, len(rows)


def _kill9_restart_case(tmp_path, transport):
    """Shared body: fault-free baseline vs aggregator-kill9 run over the
    given transport must land IDENTICAL (rank, step) coverage with zero
    duplicate rows."""
    env = {"TRACEML_TRANSPORT": transport}
    baseline_session, _ = _run(
        tmp_path, f"baseline_{transport}", steps=60, extra_env=env
    )
    base_cov, base_rows = _step_coverage(baseline_session)
    assert base_rows == len(base_cov)  # sanity: fault-free has no dupes

    plan = json.dumps(
        [{"point": "aggregator.ingest", "action": "kill9", "after": 40}]
    )
    chaos_session, proc = _run(
        tmp_path, f"aggkill_{transport}", steps=60,
        extra_env=dict(env, TRACEML_FAULT_PLAN=plan),
    )
    manifest = json.loads((chaos_session / "manifest.json").read_text())
    assert manifest["status"] == "completed"
    assert manifest["telemetry_status"] == "restarted", manifest
    assert manifest["aggregator_restarts"] == 1
    assert "restarting" in proc.stdout, proc.stdout[-2000:]

    cov, rows = _step_coverage(chaos_session)
    assert rows == len(cov), f"{rows - len(cov)} duplicate (rank, step) rows"
    # same workload, same coverage: everything in flight at the kill was
    # spooled rank-side and replayed into the restarted incarnation
    assert cov == base_cov, (
        f"missing={sorted(base_cov - cov)[:10]} extra={sorted(cov - base_cov)[:10]}"
    )
    # the report survived the crash too
    summary = json.loads((chaos_session / "final_summary.json").read_text())
    assert sorted(summary["meta"]["topology"]["ranks_seen"]) == [0, 1]
    return chaos_session


def test_aggregator_kill9_restart_no_loss_no_duplicates(tmp_path):
    # pinned to tcp: the pre-transport-tier golden arm
    _kill9_restart_case(tmp_path, "tcp")


def test_aggregator_kill9_restart_over_shm_ring(tmp_path):
    """The r12 contract over the shm fast path: the restarted aggregator
    re-attaches the rings (consumer-generation flip → one failed send →
    spooled replay), and coverage stays exactly-once."""
    session = _kill9_restart_case(tmp_path, "shm")
    # prove the run actually rode the ring, not a silent tcp fallback
    stats = json.loads((session / "ingest_stats.json").read_text())
    transports = stats["transports"]
    assert transports["frames_by_kind"].get("shm", 0) > 0, transports
    assert all(
        h["transport"] == "shm" for h in transports["ranks"].values()
    ), transports["ranks"]


def test_rank_sigkill_reported_lost_with_data_gap(tmp_path):
    plan = json.dumps(
        [{"point": "rank.tick", "action": "kill9", "after": 8, "rank": 1}]
    )
    session, proc = _run(
        tmp_path, "rankkill", steps=400, check=False, finalize_timeout=8,
        extra_env={
            "TRACEML_FAULT_PLAN": plan,
            # tightened so the 8s settle window crosses the LOST line
            "TRACEML_HEARTBEAT_INTERVAL_SEC": "0.5",
            "TRACEML_LIVENESS_STALE_SEC": "1",
            "TRACEML_LIVENESS_LOST_SEC": "3",
        },
    )
    assert proc.returncode != 0  # a SIGKILLed rank is a failed run
    manifest = json.loads((session / "manifest.json").read_text())
    assert manifest["status"] == "failed"

    # the final report still exists and names the dead rank
    summary = json.loads((session / "final_summary.json").read_text())
    sec = summary["sections"]["liveness"]
    assert sec["diagnosis"]["kind"] == "RANK_LOST", sec["diagnosis"]
    assert sec["diagnosis"]["severity"] == "critical"
    assert 1 in sec["diagnosis"]["ranks"], sec["diagnosis"]
    # telemetry from rank 1 is trustworthy only up to the kill
    assert "1" in sec.get("data_gaps", {}), sec.get("data_gaps")
    # settle-end bookkeeping: rank 1 never sent its finish marker
    assert 1 in sec["unfinished_ranks"]
    assert sec["unfinished_rank_states"]["1"] == "lost"
    # a dead world member outranks every perf finding
    assert summary["primary_diagnosis"]["kind"] in (
        "RANK_LOST", "LIKELY_PREEMPTED",
    ), summary["primary_diagnosis"]
