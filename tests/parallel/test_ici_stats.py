import jax
import numpy as np
import pytest

from traceml_tpu.parallel import IciStatAggregator, StatVector, make_mesh
from traceml_tpu.parallel.ici_stats import N_FIELDS, STAT_FIELDS, gathered_to_stat_vectors


def test_make_mesh_default_and_shapes():
    mesh = make_mesh()
    assert mesh.shape["fsdp"] == len(jax.devices())
    mesh = make_mesh({"data": 2, "fsdp": -1})
    assert mesh.shape["data"] == 2
    assert mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape["tensor"] == len(
        jax.devices()
    )
    with pytest.raises(ValueError):
        make_mesh({"data": 3})  # 3 doesn't divide 8


def test_stat_vector_roundtrip():
    sv = StatVector({"step": 5, "step_ms": 100.5, "input_ms": 20.0})
    arr = sv.to_array()
    assert arr.shape == (N_FIELDS,)
    back = StatVector.from_array(arr)
    assert back.values["step"] == 5
    assert abs(back.values["step_ms"] - 100.5) < 1e-3
    assert back.values["compute_ms"] == 0.0


def test_ici_all_gather_over_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 2, "fsdp": 4})
    agg = IciStatAggregator(mesh)
    assert agg.n_participants == 8
    out = agg.aggregate(StatVector({"step": 7, "step_ms": 42.0}))
    assert out.shape == (8, N_FIELDS)
    # single-controller: every row carries this process's vector
    np.testing.assert_allclose(out[:, STAT_FIELDS.index("step_ms")], 42.0)
    vecs = gathered_to_stat_vectors(out)
    assert len(vecs) == 8
    assert vecs[3].values["step"] == 7


def test_rank_skew_math():
    mesh = make_mesh({"fsdp": -1})
    agg = IciStatAggregator(mesh)
    gathered = np.zeros((4, N_FIELDS), dtype=np.float32)
    idx = STAT_FIELDS.index("step_ms")
    gathered[:, idx] = [100.0, 100.0, 100.0, 130.0]
    skew = agg.rank_skew(gathered, "step_ms")
    assert skew["worst_rank"] == 3
    assert abs(skew["skew_pct"] - 0.30) < 1e-6
    assert skew["median"] == 100.0
