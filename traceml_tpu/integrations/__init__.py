"""Framework integrations (reference: src/traceml_ai/integrations/)."""
