#!/usr/bin/env python
"""Execute .github/workflows/ci.yml's lanes locally and write CI_RUN.md
(VERDICT r4 item 5: the workflow had never demonstrably run green).

Each lane's `run:` steps execute verbatim where the tool exists
offline; documented substitutions otherwise (this host has no network):

* lint — ruff is not installed: the E9 class (syntax errors) is
  covered by ``compileall`` over the same paths; F63/F7/F82
  (undefined names / comparison bugs) have no offline substitute and
  are marked SKIPPED-OFFLINE.
* test-fast — the 4-version matrix needs setup-python; the host's
  3.12 runs the exact pytest command (one matrix cell).
* smoke-install — ``python -m build`` is not installed: the wheel is
  produced by ``pip wheel --no-build-isolation`` (same setuptools
  backend, same artifact), installed into a fresh venv with
  ``--no-index`` (offline), and the documented CLI surface asserted
  with the workflow's exact greps.

Usage::

    python -m traceml_tpu.dev.ci_local [--out CI_RUN.md]
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time
from pathlib import Path

from traceml_tpu.config import flags

REPO = Path(__file__).resolve().parents[2]


ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def _env(clean: bool = False) -> dict:
    """Lane env.  ``clean`` drops PYTHONPATH — the smoke lane's venv
    must not see the repo (with it, pip finds traceml_tpu.egg-info via
    the path entry, declares the wheel already installed, and skips
    the console-script generation the lane exists to verify)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(ENV)
    if clean:
        env.pop("PYTHONPATH", None)
    else:
        env["PYTHONPATH"] = str(REPO)
    return env


def _run(cmd, timeout=3600, clean_env=False, **kw):
    t0 = time.monotonic()
    proc = subprocess.run(
        cmd, env=_env(clean=clean_env), cwd=str(REPO), timeout=timeout,
        capture_output=True, text=True, **kw,
    )
    return proc, time.monotonic() - t0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=str(REPO / "CI_RUN.md"))
    parser.add_argument("--skip", default="",
                        help="comma-separated lane names to skip")
    args = parser.parse_args()
    skip = set(filter(None, args.skip.split(",")))

    rows = []  # (lane, step, status, seconds, note)

    def record(lane, step, proc, dt, note="", ok=None):
        if ok is None:
            ok = proc is None or proc.returncode == 0
        status = "PASS" if ok else (
            f"FAIL rc={proc.returncode}" if proc is not None else "FAIL"
        )
        rows.append((lane, step, status, dt, note))
        print(f"[ci-local] {lane:14s} {step:34s} {status:10s} {dt:7.1f}s",
              file=sys.stderr)
        if not ok:
            tail = (proc.stdout or "")[-2000:] + (proc.stderr or "")[-2000:]
            print(tail, file=sys.stderr)
        return ok

    all_ok = True

    # -- lane: lint -------------------------------------------------------
    if "lint" not in skip:
        targets = ["traceml_tpu/", "tests/", "bench.py", "__graft_entry__.py"]
        if shutil.which("ruff"):
            proc, dt = _run(
                ["ruff", "check", "--select", "E9,F63,F7,F82", *targets]
            )
            all_ok &= record("lint", "ruff E9,F63,F7,F82", proc, dt)
        else:
            proc, dt = _run(
                [sys.executable, "-m", "compileall", "-q", *targets]
            )
            all_ok &= record(
                "lint", "compileall (E9 substitute)", proc, dt,
                "ruff offline-unavailable; F63/F7/F82 skipped",
            )

    # -- lane: test-fast --------------------------------------------------
    if "test-fast" not in skip:
        proc, dt = _run([
            sys.executable, "-m", "pytest", "tests/", "-q",
            "--ignore=tests/launcher",
            "--ignore=tests/integrations",
            "--ignore=tests/benchmarks",
        ])
        all_ok &= record(
            "test-fast", "pytest unit+contract (py3.12 cell)", proc, dt,
            "matrix versions need setup-python",
        )

    # -- lane: test-e2e ---------------------------------------------------
    if "test-e2e" not in skip:
        proc, dt = _run(
            [sys.executable, "-m", "pytest", "tests/launcher",
             "tests/integrations", "-q"],
            timeout=2700,
        )
        all_ok &= record("test-e2e", "pytest launcher+integrations", proc, dt)
        proc, dt = _run([
            sys.executable, "-c",
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)",
        ], timeout=600)
        all_ok &= record("test-e2e", "dryrun_multichip(8)", proc, dt)
        env = _env()
        env[flags.BENCH_NO_PROBE.name] = "1"
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "bench.py", "--rounds", "2", "--steps", "4"],
            env=env, cwd=str(REPO), timeout=1200,
            capture_output=True, text=True,
        )
        dt = time.monotonic() - t0
        ok = proc.returncode == 0
        if ok:
            import json as _json

            try:
                row = _json.loads(proc.stdout.strip().splitlines()[-1])
                ok = "metric" in row and "value" in row
            except (IndexError, ValueError):
                # empty/non-JSON stdout must record a RED row, not
                # crash before CI_RUN.md is written
                ok = False
        all_ok &= record("test-e2e", "bench contract (one JSON line)",
                         proc, dt, "" if ok else "JSON contract violated",
                         ok=ok)

    # -- lane: smoke-install ---------------------------------------------
    if "smoke-install" not in skip:
        dist = REPO / "dist"
        shutil.rmtree(dist, ignore_errors=True)
        # pyproject-build exists on some hosts but needs network for its
        # isolated build env; the offline-capable path is pip wheel with
        # isolation off (same setuptools backend, same artifact)
        proc, dt = _run([
            sys.executable, "-m", "pip", "wheel", ".", "-w", "dist",
            "--no-deps", "--no-build-isolation", "--quiet",
        ])
        all_ok &= record("smoke-install", "build wheel", proc, dt,
                         "pip wheel substitute (python -m build needs net)")
        wheels = sorted(dist.glob("*.whl"))
        if wheels:
            venv = REPO / ".ci_smoke_env"
            shutil.rmtree(venv, ignore_errors=True)
            proc, dt = _run([sys.executable, "-m", "venv", str(venv)])
            all_ok &= record("smoke-install", "create venv", proc, dt)
            vpy = venv / "bin" / "python"
            proc, dt = _run([
                str(vpy), "-m", "pip", "install", "--no-index",
                "--no-deps", str(wheels[0]), "--quiet",
            ], clean_env=True)
            all_ok &= record("smoke-install", "install wheel (offline)",
                             proc, dt)
            vcli = venv / "bin" / "traceml-tpu"
            checks = (
                f"{vcli} --help | grep -q compare && "
                f"{vcli} run --help | grep -q mode && "
                f"{vpy} -c 'import traceml_tpu, traceml'"
            )
            t0 = time.monotonic()
            proc = subprocess.run(
                ["bash", "-c", checks], env=_env(clean=True),
                cwd=str(REPO), capture_output=True, text=True,
                timeout=120,
            )
            dt = time.monotonic() - t0
            all_ok &= record("smoke-install", "documented CLI surface",
                             proc, dt)
            shutil.rmtree(venv, ignore_errors=True)
        else:
            rows.append(("smoke-install", "install wheel", "FAIL", 0.0,
                         "no wheel built"))
            all_ok = False

    # -- write CI_RUN.md --------------------------------------------------
    lines = [
        "# CI_RUN — local execution of .github/workflows/ci.yml",
        "",
        f"Host: 1-core CPU, Python {sys.version.split()[0]}, "
        "offline (no package installs).  Every lane's `run:` steps were "
        "executed; substitutions (tooling unavailable offline) are noted "
        "per step and in traceml_tpu/dev/ci_local.py's docstring.",
        "",
        "| lane | step | status | time |  note |",
        "|---|---|---|---|---|",
    ]
    for lane, step, status, dt, note in rows:
        lines.append(f"| {lane} | {step} | {status} | {dt:.1f}s | {note} |")
    lines += [
        "",
        f"Overall: {'GREEN' if all_ok else 'RED'} "
        f"({time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime())})",
        "",
        "Reproduce: `python -m traceml_tpu.dev.ci_local`",
    ]
    Path(args.out).write_text("\n".join(lines) + "\n")
    print(f"[ci-local] wrote {args.out}", file=sys.stderr)
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
